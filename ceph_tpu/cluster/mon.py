"""Monitor: the cluster-map authority (single mon or Paxos quorum).

Mirrors the reference monitor's OSD-map service (src/mon/OSDMonitor.cc):
boot/failure handling with reporter thresholds (can_mark_down,
OSDMonitor.cc:1761), beacon-staleness + down-out ticks, map-epoch
broadcast to subscribers (MonClient subscription model,
src/mon/MonClient.cc:354), and pool-create commands that build CRUSH
rules through the EC-profile seam (ErasureCode::create_rule analog).

Multi-mon mode replicates every map delta through the Paxos machinery in
cluster/paxos.py (reference src/mon/Paxos.cc + Elector.cc): the elected
leader proposes, peons accept/commit and forward client commands to the
leader, leases detect leader death, and any quorum member serves map
subscriptions from its replicated state.
"""

from __future__ import annotations

import asyncio
import copy
import pickle
import time
from typing import Dict, List, Optional, Set, Tuple

from ceph_tpu.cluster import messages as M
from ceph_tpu.cluster.messenger import Addr, Connection, Dispatcher, EntityName, Messenger
from ceph_tpu.crush.types import (
    CRUSH_ITEM_NONE,
    RULE_CHOOSELEAF_FIRSTN,
    RULE_CHOOSELEAF_INDEP,
    RULE_EMIT,
    RULE_TAKE,
    Rule,
)
from ceph_tpu.osdmap.osdmap import (
    Incremental,
    OSDMap,
    PGid,
    PGPool,
    POOL_TYPE_ERASURE,
    POOL_TYPE_REPLICATED,
)
from ceph_tpu.utils import Config, DepLock, PerfCounters


class Monitor(Dispatcher):
    def __init__(self, osdmap: OSDMap, config: Optional[Config] = None,
                 rank: int = 0, n_mons: int = 1, store=None):
        """``store``: an ObjectStore backing the MonitorDBStore analog
        (reference src/mon/MonitorDBStore.h: mon state as a kv database);
        committed map state persists and start() resumes from it."""
        self.rank = rank
        self.n_mons = n_mons
        self.store = store
        self.db = None
        # per-daemon config copy: injectargs on one daemon must never
        # leak into another (each reference daemon owns its md_config_t)
        self.config = Config(**config.show()) if config else Config()
        self.osdmap = osdmap
        self.messenger = Messenger(
            EntityName("mon", rank),
            secret=self.config.auth_secret(),
            auth=self.config.cephx_context(f"mon.{rank}"),
            config=self.config)
        self.messenger.add_dispatcher(self)
        # cephx ticket service (reference CephxServiceHandler): clients
        # prove their entity key, the mon issues time-limited tickets;
        # revoked entities are refused renewal
        self._revoked_entities: Set[str] = set()
        if self.messenger.auth is not None:
            self.messenger.auth_server = self._handle_auth_request
        self.subscribers: Set[Addr] = set()
        # subscriber bind-addr -> the connection its subscribe rode in on
        self._sub_conns: Dict[Tuple, Connection] = {}
        # per-subscriber map-push state (round 14 backpressure): pushes
        # are serialized per subscriber by ONE pusher task each, and a
        # churn burst coalesces into "send (last, current]" instead of
        # queuing one delta message per epoch behind a slow peer
        self._push_state: Dict[Tuple, Dict] = {}
        # self-discarding background tasks (map pushers, failure flush)
        self._mon_tasks: Set[asyncio.Task] = set()
        self.failure_reports: Dict[int, Set[int]] = {}
        # markdowns past the reporter threshold awaiting the coalesce
        # window (round 14): N simultaneous failures -> ONE epoch
        self._pending_failed: Set[int] = set()
        self._failure_flush_task: Optional[asyncio.Task] = None
        self.down_since: Dict[int, float] = {}
        # last beacon per osd (reference MOSDBeacon/last_osd_report): lets
        # the tick mark OSDs down even when no reporters remain (e.g. the
        # whole cluster stopped at once)
        self.last_beacon: Dict[int, float] = {}
        # per-osd (total, used) bytes from beacons ('ceph df' feed)
        self.osd_statfs: Dict[int, Tuple[int, int]] = {}
        # per-osd blocked-op telemetry from beacons: feeds the SLOW_OPS
        # health warning and clears as soon as beacons report drain
        self.osd_slow_ops: Dict[int, Tuple[int, float]] = {}
        # per-osd event-loop lag from beacons (graft-trace loop
        # profiler): feeds the LOOP_LAG health warning the same way
        self.osd_loop_lag: Dict[int, Tuple[float, float]] = {}
        # per-osd (unrepaired inconsistent objects, pgs) from beacons
        # (round 16): feeds PG_INCONSISTENT / OSD_SCRUB_ERRORS, raised
        # while any primary holds unrepaired damage, cleared by the
        # next clean beacon — the SLOW_OPS raise/clear shape
        self.osd_scrub_stats: Dict[int, Tuple[int, int]] = {}
        # per-osd (unclean primary pgs, beacon map epoch) — the round-21
        # PG_RECOVERING feed: a PG is unclean while its primary still
        # owes it a peering/backfill round, and a beacon OLDER than the
        # last placement-changing epoch cannot yet vouch for that
        # epoch's reshuffle (pessimistic-until-reported, the misplaced-
        # ratio gate the balancer/reshaper throttle on)
        self.osd_unclean: Dict[int, Tuple[int, int]] = {}
        self._placement_epoch = 0
        self.perf = PerfCounters("mon")
        # chaos-skewable per-daemon time source: lease staleness, beacon
        # grace, and the down-out tick all judge from THIS clock, so a
        # skewed monitor really does fire early elections / false downs
        from ceph_tpu.chaos.clock import ChaosClock

        self.clock = ChaosClock.from_config(self.config)
        # graft-blackbox: flight ring + the bounded health-transition
        # history (the postmortem timeline's health spine) — raise and
        # clear records diffed from _health_data() each tick
        from collections import deque as _deque

        from ceph_tpu.trace import FlightRecorder

        self.flight = FlightRecorder.from_config(
            f"mon.{rank}", self.config, clock=self.clock)
        self.health_history: _deque = _deque(
            maxlen=max(1, int(getattr(self.config,
                                      "mon_health_history", 128))))
        self._last_health_checks: Dict[str, str] = {}
        self._last_health_status = "HEALTH_OK"
        # vstart arms this: fired once per edge INTO HEALTH_ERR with the
        # active checks (the postmortem trigger seam)
        self._blackbox_health_cb = None
        self.asok = self._build_admin_socket()
        self._tick_task: Optional[asyncio.Task] = None
        self._log: List[Tuple[str, object]] = []  # committed proposal log
        # cluster log (reference LogMonitor, src/mon/LogMonitor.h:39): a
        # Paxos-replicated event log every quorum member applies in order;
        # daemons feed it with MLog, the mon's own state changes append
        # directly, and 'log last' reads it back
        self.cluster_log: List[Tuple[str, float, str, str]] = []
        self._pending_clog: List[Tuple[str, float, str, str]] = []
        self.CLUSTER_LOG_MAX = 10_000
        # recent incrementals by resulting epoch (reference: mon keeps a
        # window of full+inc maps; subscribers behind the window get a full
        # map).  Size mirrors osd_map_cache_size.
        self._inc_log: Dict[int, Incremental] = {}
        # -- quorum state (multi-mon) --
        self.mon_addrs: List[Addr] = []
        self.elector = None
        self.paxos = None
        self.is_leader = n_mons == 1
        self.leader_rank: Optional[int] = 0 if n_mons == 1 else None
        self._map_mutex = DepLock("mon.map_mutex")
        self._lease_task: Optional[asyncio.Task] = None
        self._last_lease = 0.0
        self._fwd: Dict[int, Tuple[Connection, int]] = {}
        self._fwd_tid = 0
        self._boot_instances: Dict[int, int] = {}
        self.stopped = False

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> Addr:
        if self.store is not None:
            from ceph_tpu.cluster.kv import StoreDB

            self.store.mount()
            self.db = StoreDB(self.store)
            blob = self.db.get("osdmap", "latest")
            if blob is not None:
                # resume the committed map (MonitorDBStore refresh)
                self.osdmap = pickle.loads(blob)
                self.perf.inc("mon_store_resumes")
            clog_blob = self.db.get("clog", "recent")
            if clog_blob is not None:
                self.cluster_log = pickle.loads(clog_blob)
        addr = await self.messenger.bind(host, port)
        if self.n_mons == 1:
            self._tick_task = asyncio.get_event_loop().create_task(
                self._tick())
        return addr

    def set_monmap(self, addrs: List[Addr]) -> None:
        """Install the monmap + consensus machinery (multi-mon vstart
        calls this once every monitor is bound)."""
        from ceph_tpu.cluster.paxos import Elector, Paxos

        self.mon_addrs = [tuple(a) for a in addrs]
        self.is_leader = False
        self.leader_rank = None
        self.elector = Elector(
            self.rank, self.n_mons, self._send_mon, self._on_elected,
            timeout=self.config.mon_election_timeout,
            state_version=lambda: self.paxos.last_committed
            if self.paxos else 0)
        self.paxos = Paxos(
            self.rank, self.n_mons, self._send_mon, self._apply_committed,
            timeout=self.config.mon_paxos_timeout)

    async def begin_elections(self) -> None:
        if self.elector:
            await self.elector.start_election()

    async def stop(self) -> None:
        self.is_leader = False
        self.stopped = True
        if self.elector:
            self.elector.stop()
        if self.paxos:
            self.paxos.step_down()
        for t in (self._tick_task, self._lease_task,
                  self._failure_flush_task):
            if t:
                t.cancel()
        for t in list(self._mon_tasks):
            t.cancel()
        await self.messenger.shutdown()
        # umount LAST: an in-flight commit draining above must still be
        # able to persist its delta
        if self.store is not None:
            self.db = None
            self.store.umount()

    def _health_data(self) -> Dict:
        """Reference health checks (OSD_DOWN, OSD_OUT, OSD_FULL,
        SLOW_OPS): the SLOW_OPS warning is fed by the OSD beacon stream
        and clears on drain exactly like the reference's
        'N slow ops, oldest one blocked for X sec' check
        (OSDMap::check_health SLOW_OPS)."""
        m = self.osdmap
        checks = {}
        down = [o for o in range(m.max_osd)
                if m.osd_exists[o] and not m.osd_up[o]]
        out = [o for o in range(m.max_osd)
               if m.osd_exists[o] and m.osd_weight[o] == 0]
        if down:
            checks["OSD_DOWN"] = f"{len(down)} osds down: {down}"
        if out:
            checks["OSD_OUT"] = f"{len(out)} osds out: {out}"
        # utilization tiers against the configured mon_osd_*full_ratio
        # thresholds (round 16): nearfull warns, backfillfull blocks
        # backfill, full rejects client writes (HEALTH_ERR).  ONE
        # classifier serves this and the flag-commit tick, so health
        # reporting can never desynchronize from flag enforcement.
        tiers = self._full_tiers()
        nearfull = tiers["nearfull"]
        backfillfull = tiers["backfillfull"]
        full = tiers["full"]
        if full:
            checks["OSD_FULL"] = (
                f"{len(full)} osd(s) full: {full} — client writes "
                f"rejected ENOSPC until space frees")
        if backfillfull:
            checks["OSD_BACKFILLFULL"] = (
                f"{len(backfillfull)} osd(s) backfillfull: "
                f"{backfillfull}")
        if nearfull:
            checks["OSD_NEARFULL"] = \
                f"{len(nearfull)} osd(s) nearfull: {nearfull}"
        inconsistent = {o: s for o, s in self.osd_scrub_stats.items()
                        if o < m.max_osd and m.osd_up[o]}
        if inconsistent:
            objs = sum(n for n, _ in inconsistent.values())
            pgs = sum(p for _, p in inconsistent.values())
            checks["PG_INCONSISTENT"] = (
                f"{pgs} pg(s) inconsistent, {objs} unrepaired "
                f"object(s) (osds: {sorted(inconsistent)})")
            checks["OSD_SCRUB_ERRORS"] = \
                f"{objs} unrepaired scrub/read errors"
        slow = {o: s for o, s in self.osd_slow_ops.items()
                if o < m.max_osd and m.osd_up[o]}
        if slow:
            total = sum(n for n, _ in slow.values())
            oldest = max(age for _, age in slow.values())
            checks["SLOW_OPS"] = (
                f"{total} slow ops, oldest age {oldest:.2f}s "
                f"(osds: {sorted(slow)})")
        # PG_RECOVERING (round 21): data is still chasing placement.
        # Three feeds, all pessimistic: live pg_temp entries (a reshape
        # handoff in flight), any up OSD reporting unclean primary PGs,
        # and any up OSD whose last beacon predates the last placement-
        # changing epoch (it hasn't re-peered that reshuffle yet, so
        # its "clean" claim is stale).  The balancer's require_clean
        # gate and the reshaper's wait-clean both key off this check —
        # it is what stops a round-N+1 upmap or a daemon stop from
        # yanking a member that is still the sole holder of acked bytes.
        if m.pools:
            ups = [o for o in range(m.max_osd)
                   if m.osd_exists[o] and m.osd_up[o]]
            unclean = {o: self.osd_unclean[o][0] for o in ups
                       if self.osd_unclean.get(o, (0, 0))[0] > 0}
            behind = [o for o in ups
                      if self.osd_unclean.get(o, (0, -1))[1]
                      < self._placement_epoch]
            parts = []
            if m.pg_temp:
                parts.append(f"{len(m.pg_temp)} pg(s) on temp acting "
                             f"(reshape handoff)")
            if unclean:
                parts.append(f"{sum(unclean.values())} pg(s) "
                             f"recovering (osds: {sorted(unclean)})")
            if behind:
                parts.append(f"{len(behind)} osd(s) not yet reported "
                             f"since epoch {self._placement_epoch}")
            if parts:
                checks["PG_RECOVERING"] = "; ".join(parts)
        lagged = {o: ll for o, ll in self.osd_loop_lag.items()
                  if o < m.max_osd and m.osd_up[o]}
        if lagged:
            worst = max(mx for _, mx in lagged.values())
            checks["LOOP_LAG"] = (
                f"event-loop lag up to {worst * 1e3:.0f}ms "
                f"(osds: {sorted(lagged)}); something is blocking "
                f"the daemon's asyncio loop")
        status = "HEALTH_OK" if not checks else (
            "HEALTH_ERR" if full or len(down) >= m.max_osd
            else "HEALTH_WARN")
        return {"status": status, "checks": checks}

    def _full_tiers(self) -> Dict[str, List[int]]:
        """Classify every up OSD's beacon utilization into EXCLUSIVE
        tiers against the mon_osd_*full_ratio thresholds — the single
        source both the health checks and the flag-commit tick read
        (round 16), so the warning an operator sees and the flag the
        OSDs enforce can never drift apart."""
        m = self.osdmap
        out: Dict[str, List[int]] = {"nearfull": [], "backfillfull": [],
                                     "full": []}
        for o, (tot, used) in sorted(self.osd_statfs.items()):
            if not tot or o >= m.max_osd or not m.osd_up[o]:
                continue
            frac = used / tot
            if frac >= self.config.mon_osd_full_ratio > 0:
                out["full"].append(o)
            elif frac >= self.config.mon_osd_backfillfull_ratio > 0:
                out["backfillfull"].append(o)
            elif frac >= self.config.mon_osd_nearfull_ratio > 0:
                out["nearfull"].append(o)
        return out

    def _note_health(self) -> None:
        """Health-transition bookkeeping, run each tick: diff the live
        checks against the last tick's view and append raise/clear
        records to the bounded history ring (satellite: the postmortem
        timeline's health spine).  An edge INTO HEALTH_ERR fires the
        vstart-armed blackbox callback — the fourth trigger kind."""
        data = self._health_data()
        checks, status = data["checks"], data["status"]
        now = round(self.clock.time(), 6)
        epoch = self.osdmap.epoch
        for name, msg in checks.items():
            if name not in self._last_health_checks:
                sev = "ERR" if name == "OSD_FULL" else "WRN"
                rec = {"check": name, "severity": sev, "op": "raise",
                       "epoch": epoch, "time": now, "detail": msg}
                self.health_history.append(rec)
                if self.flight:
                    self.flight.record("health", **rec)
        for name in self._last_health_checks:
            if name not in checks:
                rec = {"check": name, "severity": "INF", "op": "clear",
                       "epoch": epoch, "time": now, "detail": ""}
                self.health_history.append(rec)
                if self.flight:
                    self.flight.record("health", **rec)
        if status != self._last_health_status:
            self.health_history.append(
                {"check": "STATUS", "severity": status, "op": "status",
                 "epoch": epoch, "time": now,
                 "detail": f"{self._last_health_status} -> {status}"})
            if self.flight:
                self.flight.record("health_status",
                                   prev=self._last_health_status,
                                   status=status, epoch=epoch)
            cb = self._blackbox_health_cb
            if status == "HEALTH_ERR" and cb is not None:
                cb(dict(checks))
        self._last_health_checks = dict(checks)
        self._last_health_status = status

    def _build_admin_socket(self):
        """The mon's 'ceph daemon mon.X' command table (reference
        Monitor::_add_bootstrap_peer_hint et al. asok registration)."""
        from ceph_tpu.utils import AdminSocket

        asok = AdminSocket()
        asok.register_common(self.perf, self.config,
                             flight=self.flight)
        asok.register("health", lambda cmd: self._health_data(),
                      "cluster health status + checks")
        asok.register("health history",
                      lambda cmd: list(self.health_history),
                      "bounded ring of health-transition records "
                      "(check, severity, raise/clear epoch + time)")
        asok.register("quorum_status",
                      lambda cmd: {"rank": self.rank,
                                   "leader": self.leader_rank,
                                   "is_leader": self.is_leader,
                                   "n_mons": self.n_mons},
                      "this monitor's view of the quorum")
        return asok

    @staticmethod
    def _placement_path(m) -> str:
        """'batched' when the map's shape runs on the TensorMapper, else
        'scalar_fallback(<why>)' — the operator-visible answer to "is my
        1M-PG map silently a Python loop?".  Uses the cheap shape probe:
        status must never build device tables inside the mon loop."""
        from ceph_tpu.crush.mapper import TensorMapper

        why = TensorMapper.unsupported_reason(m.crush)
        return "batched" if why is None else f"scalar_fallback({why})"

    # -- cephx ticket service ---------------------------------------------

    def _handle_auth_request(self, msg):
        """Verify the entity-key proof and issue a ticket (reference
        CephxServiceHandler::handle_request)."""
        import hashlib as _hl
        import hmac as _hm

        from ceph_tpu.cluster import auth as authmod
        from ceph_tpu.cluster.messenger import SIG_LEN, _MsgAuthReply

        master = self.config.auth_secret()
        if master is None:
            return _MsgAuthReply(result=-22, error="no cluster key")
        if msg.entity in self.osdmap.revoked_entities or \
                msg.entity in self._revoked_entities:
            self.perf.inc("mon_auth_refused")
            return _MsgAuthReply(result=-13, error="entity revoked")
        ek = authmod.entity_key(master, msg.entity)
        want = _hm.new(ek, b"authreq:" + msg.entity.encode() + msg.nonce,
                       _hl.sha256).digest()[:SIG_LEN]
        if not _hm.compare_digest(want, msg.proof):
            self.perf.inc("mon_auth_refused")
            return _MsgAuthReply(result=-13, error="bad key proof")
        ttl = self.config.auth_ticket_ttl
        blob, sealed, _ = authmod.issue_ticket(
            master, msg.entity, authmod.default_caps_for(msg.entity), ttl)
        self.perf.inc("mon_tickets_issued")
        return _MsgAuthReply(result=0, ticket_blob=blob, sealed_key=sealed,
                             ttl=ttl)

    # -- quorum plumbing ---------------------------------------------------

    async def _send_mon(self, rank: int, msg) -> None:
        await self.messenger.send_message(msg, self.mon_addrs[rank])

    async def _on_elected(self, leader: int, quorum: List[int],
                          epoch: int) -> None:
        self.leader_rank = leader
        was_leader = self.is_leader
        self.is_leader = leader == self.rank
        self.perf.inc("mon_elections_won" if self.is_leader
                      else "mon_elections_lost")
        if self.is_leader:
            await self.paxos.leader_init(quorum)
            if self._tick_task is None or self._tick_task.done():
                self._tick_task = asyncio.get_event_loop().create_task(
                    self._tick())
            if self._lease_task is None or self._lease_task.done():
                self._lease_task = asyncio.get_event_loop().create_task(
                    self._lease_loop())
        else:
            if self.paxos:
                self.paxos.step_down()
            if was_leader and self._tick_task:
                self._tick_task.cancel()
                self._tick_task = None
            self._last_lease = self.clock.monotonic()
            if self._lease_task is None or self._lease_task.done():
                self._lease_task = asyncio.get_event_loop().create_task(
                    self._lease_watch())

    async def _lease_loop(self) -> None:
        """Leader: extend the quorum lease (reference Paxos lease)."""
        while self.is_leader:
            for r in range(self.n_mons):
                if r != self.rank:
                    try:
                        await self._send_mon(r, M.MMonPaxos(
                            op="lease", rank=self.rank,
                            epoch=(self.elector.epoch
                                   if self.elector else 0),
                            last_committed=self.paxos.last_committed))
                    except (ConnectionError, OSError):
                        pass
            await asyncio.sleep(self.config.mon_lease_interval)

    async def _lease_watch(self) -> None:
        """Peon: call an election when the leader's lease goes stale."""
        while not self.is_leader and self.elector is not None:
            await asyncio.sleep(self.config.mon_lease_interval)
            if self.is_leader:
                return
            stale = self.clock.monotonic() - self._last_lease
            if stale > self.config.mon_lease_ack_timeout:
                self.perf.inc("mon_lease_timeouts")
                await self.elector.start_election()
                self._last_lease = self.clock.monotonic()

    async def _apply_committed(self, version: int, value: bytes) -> None:
        """Paxos apply callback: every quorum member applies committed
        map deltas in order (the PaxosService refresh).  Restart skew is
        tolerated: deltas already covered by a store-resumed map are
        skipped, and a map GAP (this mon's persisted map older than the
        quorum's) triggers a full-map sync from the leader instead of
        wedging on apply_incremental's contiguity check."""
        inc = pickle.loads(value)
        if inc.epoch <= self.osdmap.epoch:
            return  # resumed store already contains this delta
        if inc.epoch > self.osdmap.epoch + 1:
            await self._request_map_sync()
            return
        await self._apply_inc_local(inc)

    async def _request_map_sync(self) -> None:
        """Ask the leader's map service for our missing epochs (mon-to-mon
        subscription; the reply lands in ms_dispatch below)."""
        if self.leader_rank is None or self.leader_rank == self.rank:
            return
        try:
            await self._send_mon(self.leader_rank, M.MMonSubscribe(
                what="osdmap", addr=self.messenger.my_addr,
                since=self.osdmap.epoch))
        except (ConnectionError, OSError):
            pass

    # -- proposal/commit ---------------------------------------------------

    def _propose(self, what: str, payload) -> None:
        self._log.append((what, payload))
        self.perf.inc("mon_proposals")

    def clog(self, prio: str, msg: str) -> None:
        """Buffer a cluster-log event from this mon (leader side); the
        tick flushes the buffer through a Paxos round."""
        self._pending_clog.append(
            (f"mon.{self.rank}", time.time(), prio, msg))

    def _pool_by_name(self, name):
        return next((p for p, po in self.osdmap.pools.items()
                     if po.name == name or p == name), None)

    async def _handle_tier_command(self, prefix: str, cmd):
        """Cache-tier admin (reference OSDMonitor 'osd tier *' handlers):
        add/remove a cache pool over a base, set the cache mode, and
        point the base's overlay (read/write redirect) at the cache."""
        import dataclasses as _dc

        # snapshot + inc construction INSIDE the map mutex like every
        # other mutation path: two concurrent tier commands must never
        # commit deltas derived from the same stale pool state
        async with self._map_mutex:
            base_id = self._pool_by_name(cmd.get("pool"))
            if base_id is None:
                return -2, f"pool {cmd.get('pool')!r} not found"
            base = self.osdmap.pools[base_id]
            inc = None
            if prefix == "osd tier add":
                tid = self._pool_by_name(cmd.get("tierpool"))
                if tid is None:
                    return -2, f"pool {cmd.get('tierpool')!r} not found"
                if tid == base_id:
                    return -22, "a pool cannot be its own tier"
                tier = self.osdmap.pools[tid]
                if tier.is_tier():
                    return -22, f"{tier.name} is already a tier"
                if tier.tiers or base.is_tier():
                    return -22, "tier chains are not allowed"
                inc = self._new_inc()
                inc.new_pools[base_id] = _dc.replace(
                    base, tiers=tuple(base.tiers) + (tid,))
                inc.new_pools[tid] = _dc.replace(tier, tier_of=base_id)
            elif prefix == "osd tier remove":
                tid = self._pool_by_name(cmd.get("tierpool"))
                if tid is None or tid not in base.tiers:
                    return -2, "no such tier"
                if base.read_tier == tid or base.write_tier == tid:
                    return -16, ("tier is an active overlay; "
                                 "remove-overlay first")
                tier = self.osdmap.pools[tid]
                inc = self._new_inc()
                inc.new_pools[base_id] = _dc.replace(
                    base, tiers=tuple(t for t in base.tiers if t != tid))
                inc.new_pools[tid] = _dc.replace(tier, tier_of=-1,
                                                 cache_mode="none")
            elif prefix == "osd tier cache-mode":
                # here 'pool' names the CACHE pool
                mode = cmd.get("mode")
                if mode not in ("none", "writeback", "readproxy",
                                "forward"):
                    return -22, f"invalid cache mode {mode!r}"
                if not base.is_tier():
                    return -22, f"{base.name} is not a tier"
                inc = self._new_inc()
                inc.new_pools[base_id] = _dc.replace(base,
                                                     cache_mode=mode)
            elif prefix == "osd tier set-overlay":
                tid = self._pool_by_name(cmd.get("overlaypool"))
                if tid is None or tid not in base.tiers:
                    return -2, "overlay pool is not a tier of this pool"
                inc = self._new_inc()
                inc.new_pools[base_id] = _dc.replace(
                    base, read_tier=tid, write_tier=tid)
            elif prefix == "osd tier remove-overlay":
                inc = self._new_inc()
                inc.new_pools[base_id] = _dc.replace(
                    base, read_tier=-1, write_tier=-1)
            if not await self._commit_inc(inc):
                return -11, "quorum lost"
        self.clog("INF", f"tier command '{prefix}' on pool "
                         f"'{base.name}' applied")
        return 0, None

    async def _pool_set_pgnum(self, pid: int, var: str, val):
        """'osd pool set pg_num/pgp_num' (reference OSDMonitor pg_num
        checks + PG splitting on the OSDs).  pg_num may only GROW, and
        pgp_num stays put until set separately, so freshly-split children
        place with their parents (osd_types pps folding) and migrate on
        the later pgp_num bump — the reference's split-then-move design."""
        import dataclasses as _dc

        po = self.osdmap.pools[pid]
        try:
            ival = int(val)
        except (TypeError, ValueError):
            return -22, f"invalid {var}={val!r}"
        if var == "pg_num":
            if po.is_erasure():
                return -95, "pg_num change on erasure pools not supported"
            if ival <= po.pg_num:
                return -22, (f"pg_num {ival} must exceed current "
                             f"{po.pg_num} (merging unsupported)")
            new_pool = _dc.replace(po, pg_num=ival)
        else:
            if not (1 <= ival <= po.pg_num):
                return -22, f"need 1 <= pgp_num <= pg_num ({po.pg_num})"
            new_pool = _dc.replace(po, pgp_num=ival)
        async with self._map_mutex:
            inc = self._new_inc()
            inc.new_pools[pid] = new_pool
            if not await self._commit_inc(inc):
                return -11, "quorum lost"
        return 0, ival

    def _new_inc(self) -> Incremental:
        return Incremental(epoch=self.osdmap.epoch + 1)

    async def _commit_inc(self, inc: Incremental) -> bool:
        """Commit a map delta: direct in single-mon mode, through a Paxos
        round (begin/accept/commit on the quorum) otherwise."""
        self._mint_pg_temp(inc)
        if self.paxos is None:
            await self._apply_inc_local(inc)
            return True
        return await self.paxos.propose(pickle.dumps(inc))

    def _mint_pg_temp(self, inc: Incremental) -> None:
        """Conservative temp mappings for wholesale remaps (round 21).

        The reference's primaries request pg_temp themselves when they
        discover a backfill interval; here the leader derives the same
        entries AT COMMIT TIME, before the delta ships: any PG whose
        new up set shares NO member with its current acting set would
        strand its only copies on daemons the new map no longer names —
        an elastic drain (weight->0) or a big upmap batch can replace a
        whole acting set in one epoch.  Such PGs keep serving from the
        old holders (pg_temp = old acting) until the acting primary
        backfills the up members and requests the clear (MOSDPGTemp
        with empty osds).  Minted entries ride IN the same Incremental,
        so every quorum member and subscriber applies one atomic view.

        Also sweeps the opposite edge: a temp entry whose members were
        ALL purged from the map pins the PG to ids that can never come
        back — clear it and let acting fall back to up.  Down-but-
        existing members are NOT grounds to sweep: down is transient
        (a beacon blip marks every OSD down at once), and a swept
        handoff strands the data when the donors return."""
        placement = (inc.new_up or inc.new_weights or inc.new_pools
                     or inc.new_pg_upmap_items or inc.new_crush_hosts
                     or inc.old_osds or inc.new_primary_affinity)
        if not placement and not inc.new_down:
            return
        old = self.osdmap
        new = copy.deepcopy(old)
        new.apply_incremental(copy.deepcopy(inc))
        if placement:
            for pid, pool in new.pools.items():
                for seed in range(pool.pg_num):
                    pgid = PGid(pid, seed)
                    if pgid in inc.new_pg_temp:
                        continue   # an explicit request wins
                    cur = old.pg_temp.get(pgid)
                    if cur is not None and any(
                            o < new.max_osd and new.osd_exists[o]
                            for o in cur if o >= 0):
                        # a handoff is already armed for this PG — never
                        # re-derive it: a mid-blip re-mint computes its
                        # donor list from a DEGRADED acting view and
                        # overwrites the entry that names the real
                        # data-bearers (observed: [4,5,0] -> [5,1])
                        continue
                    # DOWN-BLIND on both sides: mint reasons about data
                    # LOCATION, and a beacon blip marking an OSD down
                    # does not move its bytes.  Up-filtered views here
                    # were the observed failure mode — an out committed
                    # mid-blip saw empty donors (no mint, data stranded)
                    # or degraded newcomers (a crippled entry).
                    new_raw = new.pg_raw_up(pgid)
                    new_set = {o for o in new_raw if o >= 0}
                    if not new_set:
                        continue
                    old_raw = old.pg_raw_up(pgid)
                    donors = [o for o in old_raw
                              if o >= 0 and o < new.max_osd
                              and new.osd_exists[o]]
                    if not donors or new_set & set(donors):
                        continue   # a survivor carries the data
                    if pool.can_shift_osds():
                        # replicated: acting = donors FIRST (the primary
                        # stays data-bearing) + the incoming up members.
                        # Newcomers joining acting immediately is the
                        # race-closer: every write acked during the
                        # handoff replicates to them too, so the clear
                        # can land at any moment without stranding a
                        # just-acked mutation on the donors.
                        inc.new_pg_temp[pgid] = donors + [
                            o for o in new_raw
                            if o >= 0 and o not in donors]
                    else:
                        # erasure: acting positions are shard slots —
                        # splicing newcomers in would scramble them.
                        # Donors-only keeps the data reachable; the
                        # primary's handoff backfill covers the rest.
                        inc.new_pg_temp[pgid] = [
                            o if (o >= 0 and o < new.max_osd
                                  and new.osd_exists[o])
                            else CRUSH_ITEM_NONE for o in old_raw]
                    self.perf.inc("mon_pg_temp_minted")
        for pgid, temp in new.pg_temp.items():
            if pgid in inc.new_pg_temp:
                continue
            if not any(o < new.max_osd and new.osd_exists[o]
                       for o in temp if o >= 0):
                inc.new_pg_temp[pgid] = []
                self.perf.inc("mon_pg_temp_swept")

    async def _apply_inc_local(self, inc: Incremental) -> None:
        """Apply a delta to the replicated map, log it, broadcast it."""
        self.osdmap.apply_incremental(inc)
        if (inc.new_up or inc.new_down or inc.new_weights or inc.new_pools
                or inc.new_pg_temp or getattr(inc, "new_pg_upmap_items", None)
                or getattr(inc, "new_crush_hosts", None)
                or getattr(inc, "old_osds", None)
                or getattr(inc, "new_max_osd", 0)
                or inc.new_primary_affinity):
            # any epoch that can move a PG re-arms the PG_RECOVERING
            # pessimism: beacons older than this can't vouch for it
            self._placement_epoch = self.osdmap.epoch
        # cluster-log events ride the delta stream: every quorum member
        # appends the same entries in the same order (LogMonitor refresh)
        new_clog = getattr(inc, "new_log_entries", ())
        if new_clog:
            self.cluster_log.extend(tuple(e) for e in new_clog)
            del self.cluster_log[:-self.CLUSTER_LOG_MAX]
            self.perf.inc("mon_clog_entries", len(new_clog))
        self._inc_log[inc.epoch] = inc
        cutoff = inc.epoch - self.config.osd_map_cache_size
        for e in [e for e in self._inc_log if e <= cutoff]:
            del self._inc_log[e]
        self.perf.inc("mon_map_epochs")
        if self.db is not None:
            from ceph_tpu.cluster.kv import KVTransaction

            txn = (KVTransaction()
                   .set("osdmap", f"inc_{inc.epoch:010d}", pickle.dumps(inc))
                   .set("osdmap", "latest", pickle.dumps(self.osdmap)))
            # trim the persisted inc window like the in-memory one
            txn.rmkey("osdmap", f"inc_{cutoff:010d}")
            if new_clog:
                txn.set("clog", "recent",
                        pickle.dumps(self.cluster_log[-1000:]))
            self.db.submit_transaction(txn)
        await self._broadcast_map()

    async def _persist_latest(self) -> None:
        if self.db is not None:
            from ceph_tpu.cluster.kv import KVTransaction

            self.db.submit_transaction(KVTransaction().set(
                "osdmap", "latest", pickle.dumps(self.osdmap)))

    # -- dispatch ----------------------------------------------------------

    async def ms_dispatch(self, conn: Connection, msg) -> bool:
        if isinstance(msg, M.MMonElection):
            if self.elector:
                await self.elector.handle(msg)
            return True
        if isinstance(msg, M.MMonPaxos):
            if msg.op == "lease":
                # fence stale ex-leaders: a lease from an older election
                # epoch must not refresh the timeout or flip forwarding
                # (reference Paxos::handle_lease epoch check)
                if self.elector is not None and msg.epoch < self.elector.epoch:
                    return True
                self._last_lease = self.clock.monotonic()
                self.leader_rank = msg.rank
            elif self.paxos:
                await self.paxos.handle(msg)
            return True
        if isinstance(msg, M.MLog):
            if not self.is_leader:
                if self.leader_rank is not None and \
                        self.leader_rank != self.rank:
                    try:
                        await self._send_mon(self.leader_rank, msg)
                    except (ConnectionError, OSError):
                        pass
                return True
            self._pending_clog.extend(tuple(e) for e in msg.entries)
            return True
        if isinstance(msg, (M.MOSDBoot, M.MOSDFailure, M.MOSDAlive,
                            M.MOSDPGTemp)):
            if not self.is_leader:
                # peon: relay to the leader (reference forward_request)
                if self.leader_rank is not None and \
                        self.leader_rank != self.rank:
                    try:
                        await self._send_mon(self.leader_rank, msg)
                    except (ConnectionError, OSError):
                        pass
                return True
            if isinstance(msg, M.MOSDBoot):
                await self._handle_boot(msg)
            elif isinstance(msg, M.MOSDFailure):
                await self._handle_failure(msg)
            elif isinstance(msg, M.MOSDPGTemp):
                await self._handle_pg_temp(msg)
            elif 0 <= msg.osd_id < self.osdmap.max_osd:
                self.last_beacon[msg.osd_id] = self.clock.monotonic()
                if getattr(msg, "statfs", None) is not None:
                    self.osd_statfs[msg.osd_id] = tuple(msg.statfs)
                slow = getattr(msg, "slow_ops", None)
                if slow is not None:
                    if slow[0]:
                        self.osd_slow_ops[msg.osd_id] = tuple(slow)
                    else:
                        # drained: the health warning clears with the
                        # next 'health' evaluation
                        self.osd_slow_ops.pop(msg.osd_id, None)
                ss = getattr(msg, "scrub_stats", None)
                if ss is not None and ss[0]:
                    self.osd_scrub_stats[msg.osd_id] = tuple(ss)
                else:
                    # repaired (or a restarted daemon with nothing
                    # flagged): PG_INCONSISTENT clears like SLOW_OPS
                    self.osd_scrub_stats.pop(msg.osd_id, None)
                uc = getattr(msg, "unclean_pgs", None)
                if uc is not None:
                    self.osd_unclean[msg.osd_id] = (
                        int(uc), int(getattr(msg, "map_epoch", 0)))
                lag = getattr(msg, "loop_lag", None)
                warn_at = self.config.loop_lag_warn
                if lag is not None and warn_at > 0 and lag[1] >= warn_at:
                    self.osd_loop_lag[msg.osd_id] = tuple(lag)
                else:
                    # drained below the threshold — or the daemon's
                    # profiler is off (lag None, e.g. restarted with
                    # the default config): LOOP_LAG clears like
                    # SLOW_OPS; a non-reporting OSD must never hold a
                    # stale warning
                    self.osd_loop_lag.pop(msg.osd_id, None)
            return True
        if isinstance(msg, M.MOSDMapMsg):
            newmap = pickle.loads(msg.osdmap_blob)
            if newmap.epoch > self.osdmap.epoch:
                self.osdmap = newmap
                self.perf.inc("mon_map_syncs")
                await self._persist_latest()
            return True
        if isinstance(msg, M.MOSDIncMapMsg):
            if msg.prev_epoch == self.osdmap.epoch:
                for blob in msg.inc_blobs:
                    await self._apply_inc_local(pickle.loads(blob))
            elif msg.epoch > self.osdmap.epoch:
                await self._request_map_sync()
            return True
        if isinstance(msg, M.MMgrBeacon):
            if not self.is_leader:
                if self.leader_rank is not None and \
                        self.leader_rank != self.rank:
                    try:
                        await self._send_mon(self.leader_rank, msg)
                    except (ConnectionError, OSError):
                        pass
                return True
            async with self._map_mutex:
                if self.osdmap.mgr_addr != tuple(msg.addr):
                    inc = self._new_inc()
                    inc.new_mgr_addr = tuple(msg.addr)
                    self.perf.inc("mon_mgr_beacons")
                    await self._commit_inc(inc)
            return True
        if type(msg).__name__ == "MMDSBeacon":
            # active-MDS registration (MDSMap-lite, like the mgr's)
            if not self.is_leader:
                if self.leader_rank is not None and \
                        self.leader_rank != self.rank:
                    try:
                        await self._send_mon(self.leader_rank, msg)
                    except (ConnectionError, OSError):
                        pass
                return True
            async with self._map_mutex:
                rank = getattr(msg, "rank", 0) or 0
                known = getattr(self.osdmap, "mds_addrs", {})
                if known.get(rank) != tuple(msg.addr):
                    inc = self._new_inc()
                    inc.new_mds_addrs = {rank: tuple(msg.addr)}
                    if rank == 0:
                        inc.new_mds_addr = tuple(msg.addr)
                    self.perf.inc("mon_mds_beacons")
                    await self._commit_inc(inc)
            return True
        if isinstance(msg, M.MMonSubscribe):
            self.subscribers.add(tuple(msg.addr))
            # remember the subscriber's OWN connection: cephx clients
            # cannot verify daemon authorizers (they hold no master
            # key), so pushes must ride the session the client opened —
            # exactly the reference model, where clients never accept
            # inbound connections
            self._sub_conns[tuple(msg.addr)] = conn
            covered = await self._send_map(tuple(msg.addr),
                                           since=msg.since)
            # the direct subscribe reply counts as a push: the pusher
            # must not re-send epochs the refresh just covered
            ps = self._push_state.setdefault(tuple(msg.addr), {})
            ps["last"] = max(ps.get("last", 0), covered)
            ps.setdefault("target", covered)
            return True
        if isinstance(msg, M.MCommand):
            # daemon-directed admin command ('ceph daemon mon.X ...'):
            # served from the local admin socket, never Paxos-forwarded
            result, data = await self.asok.dispatch(msg.cmd)
            try:
                await conn.send(M.MCommandReply(
                    tid=msg.tid, result=result, data=data))
            except (ConnectionError, OSError):
                pass
            return True
        if isinstance(msg, M.MMonCommand):
            await self._handle_command(conn, msg)
            return True
        if isinstance(msg, M.MMonCommandReply):
            # reply for a command we forwarded to the leader: relay it
            entry = self._fwd.pop(msg.tid, None)
            if entry is not None:
                client_conn, client_tid = entry
                try:
                    await client_conn.send(M.MMonCommandReply(
                        tid=client_tid, result=msg.result, data=msg.data))
                except (ConnectionError, OSError):
                    pass
            return True
        return False

    async def _handle_boot(self, msg: M.MOSDBoot) -> None:
        self._propose("boot", (msg.osd_id, msg.addr))
        if msg.osd_id >= self.osdmap.max_osd:
            return
        async with self._map_mutex:
            cur_addr = self.osdmap.osd_addrs.get(msg.osd_id)
            prev_instance = self._boot_instances.get(msg.osd_id)
            new_incarnation = (
                (cur_addr is not None and
                 tuple(cur_addr) != tuple(msg.addr)) or
                (prev_instance is not None and msg.instance and
                 prev_instance != msg.instance))
            self._boot_instances[msg.osd_id] = msg.instance
            if self.osdmap.osd_up[msg.osd_id] and new_incarnation:
                # a NEW incarnation of an osd we still think is up (it
                # bounced faster than failure detection): mark it down
                # first so the acting sets change and primaries run a
                # peering pass — otherwise the rejoiner silently keeps
                # whatever writes it missed (reference preprocess_boot
                # marks a booting-but-up osd down before the new up)
                down = self._new_inc()
                down.new_down.append(msg.osd_id)
                self.perf.inc("mon_osd_boot_fenced")
                await self._commit_inc(down)
            inc = self._new_inc()
            inc.new_up[msg.osd_id] = tuple(msg.addr)
            self.down_since.pop(msg.osd_id, None)
            self.failure_reports.pop(msg.osd_id, None)
            self.last_beacon[msg.osd_id] = self.clock.monotonic()
            self.perf.inc("mon_osd_boot")
            self.clog("INF", f"osd.{msg.osd_id} boot")
            await self._commit_inc(inc)

    async def _handle_pg_temp(self, msg: M.MOSDPGTemp) -> None:
        """Primary-requested temp-mapping change.  Today the only sender
        is a recovered primary asking for a CLEAR (osds=()): every
        up-member is backfilled current, so the conservative mon-minted
        pg_temp entry can drop and the map's real up set take over."""
        pgid = msg.pgid
        if pgid is None:
            return
        pool = self.osdmap.pools.get(pgid.pool)
        if pool is None or pgid.seed >= pool.pg_num:
            return
        async with self._map_mutex:
            cur = self.osdmap.pg_temp.get(pgid)
            want = [int(o) for o in msg.osds]
            # idempotent: a clear for an absent entry (or a set request
            # matching the current one) commits nothing
            if cur is None and not want:
                return
            if cur is not None and list(cur) == want:
                return
            # a CLEAR is only honored from a member of the live entry:
            # under a beacon blip an OSD whose degraded map shows every
            # donor down computes itself sole primary of an EMPTY pg,
            # finds nothing to hand off, and asks for the clear — honoring
            # it drops the only pointer to the data-bearing donors
            if cur is not None and not want and \
                    getattr(msg, "osd_id", -1) not in cur:
                self.perf.inc("mon_pg_temp_clear_rejected")
                return
            inc = self._new_inc()
            inc.new_pg_temp[pgid] = want
            self.perf.inc("mon_pg_temp_requests")
            await self._commit_inc(inc)

    async def _handle_failure(self, msg: M.MOSDFailure) -> None:
        m = self.osdmap
        osd = msg.failed_osd
        if osd < 0 or osd >= m.max_osd or not m.osd_up[osd]:
            return
        reporters = self.failure_reports.setdefault(osd, set())
        reporters.add(msg.reporter)
        # can_mark_down analog: enough distinct reporters
        if len(reporters) < self.config.mon_osd_min_down_reporters:
            return
        self._propose("down", osd)
        window = self.config.mon_osd_failure_coalesce
        if window <= 0:
            # immediate per-failure commit (the pre-round-14 anchor:
            # one Paxos round per markdown)
            async with self._map_mutex:
                if not self.osdmap.osd_up[osd]:
                    return
                inc = self._new_inc()
                inc.new_down.append(osd)
                self.down_since[osd] = self.clock.monotonic()
                nrep = len(self.failure_reports.pop(osd, ()))
                self.perf.inc("mon_osd_marked_down")
                self.clog("ERR", f"osd.{osd} failed "
                                 f"({nrep} reporters) -> marked down")
                await self._commit_inc(inc)
            return
        # round 14: failure-report aggregation — every markdown that
        # crosses the threshold inside one coalesce window rides ONE
        # incremental, so a mass outage costs a handful of epochs (and
        # Paxos rounds), not one per OSD
        self._pending_failed.add(osd)
        t = self._failure_flush_task
        if t is None or t.done():
            from ceph_tpu.utils.tasks import track_task

            self._failure_flush_task = track_task(
                self._mon_tasks, asyncio.get_event_loop().create_task(
                    self._flush_failures(window)))

    async def _flush_failures(self, window: float) -> None:
        """Commit every pending markdown as one map epoch per coalesce
        window, LOOPING until the pending set drains: a report that
        crosses the threshold while a commit is in flight lands in
        _pending_failed with this task still alive (so no new flush
        spawns), and OSD reporters send each failure only once
        (osd._reported) — without the re-check that markdown would
        strand until the beacon-grace backstop."""
        while not self.stopped:
            await asyncio.sleep(window)
            async with self._map_mutex:
                batch = sorted(o for o in self._pending_failed
                               if self.osdmap.osd_up[o])
                self._pending_failed.clear()
                if not batch:
                    return
                inc = self._new_inc()
                now = self.clock.monotonic()
                for osd in batch:
                    inc.new_down.append(osd)
                    self.down_since[osd] = now
                    nrep = len(self.failure_reports.pop(osd, ()))
                    self.perf.inc("mon_osd_marked_down")
                    self.clog("ERR", f"osd.{osd} failed "
                                     f"({nrep} reporters) -> marked down")
                if len(batch) > 1:
                    self.perf.inc("mon_failures_coalesced",
                                  len(batch) - 1)
                if not await self._commit_inc(inc):
                    # quorum lost mid-markdown: drop the batch — the
                    # beacon-grace tick (ours or the next leader's)
                    # redoes the detection from live state
                    for osd in batch:
                        self.down_since.pop(osd, None)

    # commands that mutate cluster state need mon "rw" caps (MonCap)
    _MUTATING_PREFIXES = frozenset({
        "osd pool create", "osd out", "osd in", "injectargs",
        "osd pool mksnap", "osd pool rmsnap",
        "osd pool selfmanaged_snap_create",
        "osd pool selfmanaged_snap_remove", "auth revoke",
        "osd pool delete", "osd pool rename", "osd pool set",
        "osd tier add", "osd tier remove", "osd tier cache-mode",
        "osd tier set-overlay", "osd tier remove-overlay",
        "osd pg-upmap-items", "osd rm-pg-upmap-items",
        "osd grow", "osd purge"})

    async def _handle_command(self, conn: Connection, msg: M.MMonCommand) -> None:
        cmd = msg.cmd
        result, data = 0, None
        prefix = cmd.get("prefix")
        caps = getattr(conn, "peer_caps", None)
        if caps is not None and prefix in self._MUTATING_PREFIXES:
            from ceph_tpu.cluster import auth as authmod

            if not authmod.allows(caps, "mon", "rw"):
                self.perf.inc("mon_eperm")
                await conn.send(M.MMonCommandReply(
                    tid=msg.tid, result=-1,
                    data=f"EPERM: mon rw caps required for {prefix!r}"))
                return
        mutating = prefix in (
            "osd pool create", "osd out", "osd in",
            "osd pool mksnap", "osd pool rmsnap",
            "osd pool selfmanaged_snap_create",
            "osd pool selfmanaged_snap_remove", "auth revoke",
            "osd pool delete", "osd pool rename", "osd pool set",
            "osd tier add", "osd tier remove", "osd tier cache-mode",
            "osd tier set-overlay", "osd tier remove-overlay",
            "osd pg-upmap-items", "osd rm-pg-upmap-items",
            "osd grow", "osd purge")
        if mutating and not self.is_leader:
            # forward to the leader, relay its reply (reference
            # Monitor::forward_request_leader)
            if self.leader_rank is None or self.leader_rank == self.rank:
                await conn.send(M.MMonCommandReply(
                    tid=msg.tid, result=-11, data="no leader"))
                return
            self._fwd_tid += 1
            self._fwd[self._fwd_tid] = (conn, msg.tid)
            await self._send_mon(self.leader_rank, M.MMonCommand(
                cmd=cmd, tid=self._fwd_tid))
            self.perf.inc("mon_commands_forwarded")
            return
        try:
            if prefix == "osd pool create":
                # idempotent by name: a retried create (client failed over
                # mid-commit) returns the existing pool
                existing = next(
                    (pid for pid, p in self.osdmap.pools.items()
                     if p.name == cmd["pool"]), None)
                if existing is not None:
                    data = existing
                else:
                    async with self._map_mutex:
                        data, inc = self._create_pool(cmd)
                        if not await self._commit_inc(inc):
                            result, data = -11, "quorum lost"
            elif prefix in ("osd pool mksnap", "osd pool rmsnap",
                            "osd pool selfmanaged_snap_create",
                            "osd pool selfmanaged_snap_remove"):
                result, data = await self._handle_snap_command(prefix, cmd)
            elif prefix == "osd pool delete":
                # reference OSDMonitor: name must repeat + the sure flag
                pid = next((p for p, po in self.osdmap.pools.items()
                            if po.name == cmd["pool"] or p == cmd["pool"]),
                           None)
                if pid is None:
                    result, data = -2, f"pool {cmd['pool']!r} not found"
                elif cmd.get("pool2") != cmd["pool"] or \
                        not cmd.get("sure"):
                    result, data = -1, (
                        "EPERM: pass the pool name twice and sure=True "
                        "to really delete (this is irreversible)")
                else:
                    async with self._map_mutex:
                        inc = self._new_inc()
                        inc.old_pools = (pid,)
                        if not await self._commit_inc(inc):
                            result, data = -11, "quorum lost"
                        else:
                            data = pid
            elif prefix == "osd pool rename":
                pid = next((p for p, po in self.osdmap.pools.items()
                            if po.name == cmd["srcpool"]), None)
                if pid is None:
                    result, data = -2, "source pool not found"
                elif any(po.name == cmd["destpool"]
                         for po in self.osdmap.pools.values()):
                    result, data = -17, "destination name exists"
                else:
                    import dataclasses as _dc

                    async with self._map_mutex:
                        inc = self._new_inc()
                        inc.new_pools[pid] = _dc.replace(
                            self.osdmap.pools[pid],
                            name=cmd["destpool"])
                        if not await self._commit_inc(inc):
                            result, data = -11, "quorum lost"
                        else:
                            data = pid
            elif prefix == "osd pool set":
                pid = next((p for p, po in self.osdmap.pools.items()
                            if po.name == cmd["pool"] or p == cmd["pool"]),
                           None)
                var, val = cmd.get("var"), cmd.get("val")
                if pid is None:
                    result, data = -2, f"pool {cmd['pool']!r} not found"
                elif var in ("pg_num", "pgp_num"):
                    result, data = await self._pool_set_pgnum(
                        pid, var, val)
                elif var in ("target_max_objects", "hit_set_count",
                             "hit_set_period"):
                    # cache-tier agent/hit-set knobs (reference
                    # OSDMonitor pool opts)
                    import dataclasses as _dc

                    caster = float if var == "hit_set_period" else int
                    try:
                        tval = caster(val)
                        if tval < 0:
                            raise ValueError
                    except (TypeError, ValueError):
                        result, data = -22, f"invalid {var}={val!r}"
                    else:
                        async with self._map_mutex:
                            inc = self._new_inc()
                            inc.new_pools[pid] = _dc.replace(
                                self.osdmap.pools[pid], **{var: tval})
                            if not await self._commit_inc(inc):
                                result, data = -11, "quorum lost"
                            else:
                                data = tval
                elif var not in ("size", "min_size"):
                    result, data = -22, f"cannot set {var!r}"
                else:
                    import dataclasses as _dc

                    # validate like the reference OSDMonitor: size >= 1
                    # and 1 <= min_size <= size, else committing through
                    # Paxos can wedge every write on the pool
                    po = self.osdmap.pools[pid]
                    try:
                        ival = int(val)
                    except (TypeError, ValueError):
                        ival = -1
                    new_size = ival if var == "size" else po.size
                    new_min = ival if var == "min_size" else po.min_size
                    if ival < 1 or new_min > new_size:
                        result, data = -22, (
                            f"invalid {var}={val!r}: need size >= 1 and "
                            f"1 <= min_size <= size "
                            f"(size={new_size}, min_size={new_min})")
                    else:
                        async with self._map_mutex:
                            inc = self._new_inc()
                            inc.new_pools[pid] = _dc.replace(
                                po, **{var: ival})
                            if not await self._commit_inc(inc):
                                result, data = -11, "quorum lost"
                            else:
                                data = ival
            elif prefix in ("osd tier add", "osd tier remove",
                            "osd tier cache-mode", "osd tier set-overlay",
                            "osd tier remove-overlay"):
                result, data = await self._handle_tier_command(prefix, cmd)
            elif prefix == "auth revoke":
                # refuse future ticket issuance/renewal for the entity
                # (existing tickets die at their TTL); committed through
                # Paxos so every mon enforces it and restarts keep it
                async with self._map_mutex:
                    inc = self._new_inc()
                    inc.new_revoked = (cmd["entity"],)
                    if not await self._commit_inc(inc):
                        result, data = -11, "quorum lost"
                    else:
                        data = sorted(self.osdmap.revoked_entities)
            elif prefix in ("osd out", "osd in"):
                # 'ids' batches the whole set into ONE epoch.  That is
                # load-bearing for drain safety: outing N OSDs as N
                # epochs lets the acting set WALK — each epoch keeps a
                # one-member overlap with the last, but the survivor it
                # keeps may itself be a just-added, not-yet-backfilled
                # member, so N quick epochs can strand every current
                # copy with no pg_temp ever minted.  One epoch makes the
                # wholesale replacement visible to _mint_pg_temp.
                ids = cmd.get("ids")
                ids = [int(i) for i in ids] if ids is not None \
                    else [int(cmd["id"])]
                w = 0 if prefix == "osd out" else 0x10000
                async with self._map_mutex:
                    inc = self._new_inc()
                    for i in ids:
                        inc.new_weights[i] = w
                    if not await self._commit_inc(inc):
                        result, data = -11, "quorum lost"
            elif prefix == "osd pg-upmap-items":
                # the balancer's commit edge: a BATCH of upmap exception
                # pairs as one Incremental (reference OSDMonitor
                # 'osd pg-upmap-items', one pg per command there; batched
                # here so a whole balancer round is one map epoch)
                result, data = await self._handle_upmap_items(cmd)
            elif prefix == "osd rm-pg-upmap-items":
                result, data = await self._handle_rm_upmap_items(cmd)
            elif prefix == "osd grow":
                result, data = await self._handle_grow(cmd)
            elif prefix == "osd purge":
                result, data = await self._handle_purge(cmd)
            elif prefix == "injectargs":
                # fan the config mutation out to the targeted daemons
                # (reference injectargs via mon 'ceph tell')
                who = cmd.get("who", "osd.*")
                args = cmd.get("args", {})
                sent = 0
                for o, addr in list(self.osdmap.osd_addrs.items()):
                    if who not in ("osd.*", f"osd.{o}"):
                        continue
                    if not self.osdmap.osd_up[o]:
                        continue
                    try:
                        await self.messenger.send_message(M.MCommand(
                            cmd={"prefix": "injectargs", "args": args}),
                            tuple(addr))
                        sent += 1
                    except (ConnectionError, OSError):
                        pass
                data = {"notified": sent}
            elif prefix == "status":
                m = self.osdmap
                data = {
                    "epoch": m.epoch,
                    "num_osds": m.max_osd,
                    "num_up": sum(m.osd_up),
                    "num_in": sum(1 for w in m.osd_weight if w > 0),
                    "pools": {p.name or pid: {
                        "id": pid, "size": p.size,
                        "pg_num": p.pg_num, "pgp_num": p.pgp_num,
                        "type": p.type,
                        **({"tier_of": p.tier_of,
                            "cache_mode": p.cache_mode}
                           if p.is_tier() else {}),
                        **({"tiers": list(p.tiers),
                            "read_tier": p.read_tier,
                            "write_tier": p.write_tier}
                           if p.tiers else {}),
                    } for pid, p in m.pools.items()},
                    "mds_ranks": {r: list(a) for r, a in
                                  sorted(getattr(m, "mds_addrs",
                                                 {}).items())},
                    "clog_entries": len(self.cluster_log),
                    # surfaced per round-3 verdict weakness #5: probing
                    # the MAP SHAPE (cached on the map) tells the truth
                    # even though batched placement runs in tools/OSDs,
                    # not in this process
                    "placement_path": self._placement_path(m),
                }
            elif prefix == "health":
                data = self._health_data()
            elif prefix == "df":
                # 'ceph df' analog from beacon statfs
                per = {o: {"total": t, "used": u, "avail": t - u}
                       for o, (t, u) in sorted(self.osd_statfs.items())}
                data = {
                    "total_bytes": sum(t for t, _ in
                                       self.osd_statfs.values()),
                    "used_bytes": sum(u for _, u in
                                      self.osd_statfs.values()),
                    "osds": per,
                }
            elif prefix == "perf dump":
                data = self.perf.dump()
            elif prefix == "log last":
                # 'ceph log last [n]' (reference LogMonitor command)
                try:
                    n = int(cmd.get("num", 20))
                except (TypeError, ValueError):
                    n = 20
                tail = self.cluster_log[-n:] if n > 0 else []
                data = [
                    {"who": who, "stamp": stamp, "prio": prio, "msg": m_}
                    for who, stamp, prio, m_ in tail]
            else:
                result = -22  # EINVAL
        except Exception as e:  # surface errors to the caller
            result, data = -22, repr(e)
        reply = M.MMonCommandReply(tid=msg.tid, result=result, data=data)
        await conn.send(reply)

    def _parse_pgid(self, s: str) -> Optional[PGid]:
        try:
            pool_s, seed_s = str(s).split(".", 1)
            pgid = PGid(int(pool_s), int(seed_s))
        except (TypeError, ValueError):
            return None
        pool = self.osdmap.pools.get(pgid.pool)
        if pool is None or not (0 <= pgid.seed < pool.pg_num):
            return None
        return pgid

    async def _handle_upmap_items(self, cmd: Dict):
        """Batched 'osd pg-upmap-items': validate every pair against the
        CURRENT map, commit the whole set as one Incremental.  An empty
        pair list clears the pg's entry."""
        items = cmd.get("items") or {}
        m = self.osdmap
        new_items: Dict[PGid, list] = {}
        for key, pairs in items.items():
            pgid = self._parse_pgid(key)
            if pgid is None:
                return -22, f"bad pgid {key!r}"
            clean = []
            for pair in pairs or []:
                try:
                    src, dst = int(pair[0]), int(pair[1])
                except (TypeError, ValueError, IndexError):
                    return -22, f"bad pair {pair!r} for {key}"
                # destination must be a live, in OSD — committing a map
                # that remaps onto an out/absent OSD would undo the
                # balancer's own safety story
                if not (0 <= dst < m.max_osd and m.osd_exists[dst]
                        and m.osd_weight[dst] > 0):
                    return -22, f"osd.{dst} not usable as upmap target"
                if not (0 <= src < m.max_osd):
                    return -22, f"bad source osd.{src}"
                clean.append((src, dst))
            new_items[pgid] = clean
        if not new_items:
            return -22, "no items"
        async with self._map_mutex:
            inc = self._new_inc()
            inc.new_pg_upmap_items = dict(new_items)
            if not await self._commit_inc(inc):
                return -11, "quorum lost"
        self.perf.inc("mon_upmap_commits")
        self.perf.inc("mon_upmap_items", len(new_items))
        return 0, {"applied": len(new_items)}

    async def _handle_rm_upmap_items(self, cmd: Dict):
        pgids = cmd.get("pgids") or []
        clear: Dict[PGid, list] = {}
        for key in pgids:
            pgid = self._parse_pgid(key)
            if pgid is None:
                return -22, f"bad pgid {key!r}"
            clear[pgid] = []
        if not clear:
            return -22, "no pgids"
        async with self._map_mutex:
            inc = self._new_inc()
            inc.new_pg_upmap_items = clear
            if not await self._commit_inc(inc):
                return -11, "quorum lost"
        return 0, {"removed": len(clear)}

    async def _handle_grow(self, cmd: Dict):
        """'osd grow': mint count new OSD ids and their CRUSH hosts in
        ONE Incremental (the reference's 'osd crush add-bucket' + 'osd
        crush move' + ids choreography, collapsed).  New ids start
        exists/down/in; daemons boot into them like any revived OSD."""
        try:
            count = int(cmd.get("count", 0))
            per_host = int(cmd.get("osds_per_host", 1) or 1)
        except (TypeError, ValueError):
            return -22, "count/osds_per_host must be ints"
        if count <= 0 or per_host <= 0 or count % per_host:
            return -22, (f"need count > 0 divisible by osds_per_host "
                         f"(got {count}/{per_host})")
        root = cmd.get("root", "default")
        if root not in self.osdmap.crush.item_names.values():
            return -2, f"crush root {root!r} not found"
        async with self._map_mutex:
            m = self.osdmap
            base = m.max_osd
            taken = set(m.crush.item_names.values())
            hosts = []
            hno = sum(1 for b in m.crush.buckets.values() if b.type == 1)
            for i in range(count // per_host):
                name = f"host{hno + i}"
                while name in taken:
                    name += "x"
                taken.add(name)
                ids = tuple(range(base + i * per_host,
                                  base + (i + 1) * per_host))
                hosts.append((name, ids, (0x10000,) * per_host, root))
            inc = self._new_inc()
            inc.new_max_osd = base + count
            inc.new_crush_hosts = tuple(hosts)
            if not await self._commit_inc(inc):
                return -11, "quorum lost"
        self.clog("INF", f"osd grow: +{count} osds "
                         f"({base}..{base + count - 1})")
        return 0, {"new_osds": list(range(base, base + count)),
                   "max_osd": base + count,
                   "hosts": [h[0] for h in hosts]}

    async def _handle_purge(self, cmd: Dict):
        """'osd purge': remove a DRAINED osd from existence (reference
        OSDMonitor 'osd purge' = rm + crush remove + auth del).  Refused
        unless the osd is already down AND out — purging a live or
        still-weighted osd silently degrades PGs."""
        try:
            osd = int(cmd["id"])
        except (KeyError, TypeError, ValueError):
            return -22, "need id=<osd>"
        m = self.osdmap
        if not (0 <= osd < m.max_osd) or not m.osd_exists[osd]:
            return -2, f"osd.{osd} does not exist"
        if not cmd.get("sure"):
            return -1, "EPERM: pass sure=True to really purge"
        if m.osd_up[osd] or m.osd_weight[osd] > 0:
            return -16, (f"osd.{osd} must be down+out before purge "
                         f"(up={bool(m.osd_up[osd])}, "
                         f"weight={m.osd_weight[osd]})")
        async with self._map_mutex:
            inc = self._new_inc()
            inc.old_osds = (osd,)
            if not await self._commit_inc(inc):
                return -11, "quorum lost"
        self.down_since.pop(osd, None)
        self.osd_statfs.pop(osd, None)
        self.clog("INF", f"osd.{osd} purged")
        return 0, {"purged": osd}

    def _create_pool(self, cmd: Dict) -> Tuple[int, Incremental]:
        """Build the pool + rule delta (committed by the caller)."""
        name = cmd["pool"]
        pool_type = POOL_TYPE_ERASURE if cmd.get("pool_type") == "erasure" \
            else POOL_TYPE_REPLICATED
        m = self.osdmap
        root = None
        for bid, b in m.crush.buckets.items():
            if b.type == max(bb.type for bb in m.crush.buckets.values()):
                root = bid
                break
        ec_profile = dict(cmd.get("ec_profile") or {})
        ruleno = len(m.crush.rules)  # appended by apply_incremental
        if pool_type == POOL_TYPE_ERASURE:
            from ceph_tpu.ec import factory

            if not ec_profile:
                ec_profile = {"plugin": "jerasure",
                              "technique": "reed_sol_van",
                              "k": "2", "m": "1"}
            codec = factory(ec_profile)
            size = codec.get_chunk_count()
            min_size = codec.get_data_chunk_count()
            # compose the stripe unit with the codec's layout constraints
            # (packet-interleaved codecs need w*packetsize multiples) so
            # default profiles never EINVAL deep in the data path
            ec_profile["stripe_unit"] = str(codec.stripe_unit(
                int(ec_profile.get("stripe_unit",
                                   self.config.osd_ec_stripe_unit))))
            # ErasureCode::create_rule analog: indep chooseleaf rule
            rule = Rule(steps=[
                (RULE_TAKE, root, 0),
                (RULE_CHOOSELEAF_INDEP, size, 1),
                (RULE_EMIT, 0, 0)], type=POOL_TYPE_ERASURE)
        else:
            size = int(cmd.get("size", self.config.osd_pool_default_size))
            min_size = max(1, size - 1)
            rule = Rule(steps=[
                (RULE_TAKE, root, 0),
                (RULE_CHOOSELEAF_FIRSTN, size, 1),
                (RULE_EMIT, 0, 0)])
        pg_num = int(cmd.get("pg_num", self.config.osd_pool_default_pg_num))
        # derive from the REPLICATED map, not local state: a failed-over
        # leader must never reuse an id committed by its predecessor
        pool_id = max(self.osdmap.pools, default=0) + 1
        inc = self._new_inc()
        inc.new_rules.append(rule)
        inc.new_pools[pool_id] = PGPool(
            pool_id=pool_id, type=pool_type, size=size, min_size=min_size,
            pg_num=pg_num, pgp_num=pg_num, crush_rule=ruleno,
            ec_profile=ec_profile, name=name)
        self._propose("pool_create", (pool_id, name))
        self.clog("INF", f"pool '{name}' created (id {pool_id})")
        self.perf.inc("mon_pool_create")
        return pool_id, inc

    async def _handle_snap_command(self, prefix: str, cmd):
        """Pool/selfmanaged snapshot lifecycle (reference
        OSDMonitor::prepare_pool_op on POOL_OP_CREATE_SNAP /
        POOL_OP_CREATE_UNMANAGED_SNAP / the delete twins): every variant
        commits an updated pg_pool_t through Paxos so OSDs learn snap ids
        and removed_snaps from the map."""
        import dataclasses as _dc

        ref = cmd.get("pool")
        pool_id = next((pid for pid, p in self.osdmap.pools.items()
                        if p.name == ref or pid == ref), None)
        if pool_id is None:
            return -2, f"pool {ref!r} not found"
        async with self._map_mutex:
            pool = self.osdmap.pools[pool_id]
            newp = _dc.replace(pool, snaps=dict(pool.snaps),
                               removed_snaps=tuple(pool.removed_snaps))
            data = None
            if prefix == "osd pool mksnap":
                name = cmd["snap"]
                if name in newp.snaps.values():
                    return 0, next(i for i, n in newp.snaps.items()
                                   if n == name)  # idempotent retry
                newp.snap_seq += 1
                newp.snaps[newp.snap_seq] = name
                data = newp.snap_seq
            elif prefix == "osd pool rmsnap":
                name = cmd["snap"]
                sid = next((i for i, n in newp.snaps.items() if n == name),
                           None)
                if sid is None:
                    return -2, f"snap {name!r} not found"
                del newp.snaps[sid]
                newp.removed_snaps = tuple(newp.removed_snaps) + (sid,)
                data = sid
            elif prefix == "osd pool selfmanaged_snap_create":
                newp.snap_seq += 1
                data = newp.snap_seq
            else:  # selfmanaged_snap_remove
                sid = int(cmd["snapid"])
                if sid in newp.removed_snaps:
                    return 0, sid  # idempotent retry
                newp.removed_snaps = tuple(newp.removed_snaps) + (sid,)
                data = sid
            inc = self._new_inc()
            inc.new_pools[pool_id] = newp
            if not await self._commit_inc(inc):
                return -11, "quorum lost"
            self.perf.inc("mon_snap_commands")
            return 0, data

    # -- map distribution --------------------------------------------------

    async def _broadcast_map(self) -> None:
        """Mark every subscriber dirty; their pusher tasks deliver.

        Round 14 backpressure: one serialized pusher per subscriber —
        while a push awaits a slow peer's socket, further commits only
        advance that subscriber's target epoch, so a churn burst
        coalesces into one (last, current] chain per subscriber instead
        of queueing a delta message per epoch (unbounded on a slow OSD),
        and a slow subscriber no longer head-of-line blocks the commit
        path for everyone else."""
        for addr in list(self.subscribers):
            self._kick_map_pusher(addr)

    def _kick_map_pusher(self, addr: Addr) -> None:
        key = tuple(addr)
        st = self._push_state.get(key)
        if st is None:
            st = self._push_state[key] = {"last": self.osdmap.epoch - 1}
        st["target"] = self.osdmap.epoch
        task = st.get("task")
        if task is None or task.done():
            from ceph_tpu.utils.tasks import track_task

            st["task"] = track_task(
                self._mon_tasks, asyncio.get_event_loop().create_task(
                    self._push_maps(key, st)))

    async def _push_maps(self, key: Tuple, st: Dict) -> None:
        while not self.stopped:
            target = st["target"]
            since = st["last"]
            if since >= target:
                return
            if target - since > 1:
                # epochs delivered in one chain that the per-commit
                # broadcast would have sent as separate messages
                self.perf.inc("mon_map_pushes_coalesced",
                              target - since - 1)
            try:
                covered = await self._send_map(key, since=since)
            except (ConnectionError, OSError):
                self.subscribers.discard(key)
                self._push_state.pop(key, None)
                return
            # against the LIVE watermark, not the loop-local `since`: a
            # subscribe-refresh reply racing this push may have already
            # advanced it past what this chain covered
            st["last"] = max(st["last"], covered)

    async def _map_push(self, msg, addr: Addr) -> None:
        """Deliver a map message: over the subscriber's own connection
        when one is alive (required for cephx clients), else by dialing
        the addr (daemon peers)."""
        conn = self._sub_conns.get(tuple(addr))
        if conn is not None and not conn.closed:
            try:
                await conn.send(msg)
                return
            except (ConnectionError, OSError, RuntimeError):
                self._sub_conns.pop(tuple(addr), None)
        await self.messenger.send_message(msg, addr)

    async def _send_map(self, addr: Addr, since: int = 0) -> int:
        """Send incrementals covering (since, current] when the window
        has them AND the chain stays under mon_osd_map_max_incs, else
        the full map (reference OSDMonitor send_incremental; skipping
        to a full map bounds both ends of a churn burst).  Returns the
        epoch the message covered."""
        epoch = self.osdmap.epoch
        if 0 < since <= epoch:
            chain = []
            e = since + 1
            limit = self.config.mon_osd_map_max_incs
            while e <= epoch and e in self._inc_log and \
                    len(chain) < limit:
                chain.append(pickle.dumps(self._inc_log[e]))
                e += 1
            if e > epoch:
                # complete chain (possibly empty when already current; the
                # empty message still acks the subscriber's refresh)
                self.perf.inc("mon_inc_maps_sent")
                await self._map_push(
                    M.MOSDIncMapMsg(prev_epoch=since, epoch=epoch,
                                    inc_blobs=chain), addr)
                return epoch
            if len(chain) >= limit:
                # the subscriber fell outside the bounded delta window
                # under churn: skip to the full map
                self.perf.inc("mon_skip_to_full_sends")
        self.perf.inc("mon_full_maps_sent")
        blob = pickle.dumps(self.osdmap)
        await self._map_push(
            M.MOSDMapMsg(epoch=epoch, osdmap_blob=blob), addr)
        return epoch

    async def _tick(self) -> None:
        """Down-out + beacon-staleness tick (reference OSDMonitor tick:
        auto-out and mark-down of osds whose beacons went silent)."""
        while True:
            await asyncio.sleep(self.config.mon_tick_interval)
            now = self.clock.monotonic()
            self._note_health()
            async with self._map_mutex:
                inc = self._new_inc()
                out_restore: Dict[int, float] = {}
                for osd, since in list(self.down_since.items()):
                    if now - since > self.config.mon_osd_down_out_interval \
                            and self.osdmap.osd_weight[osd] > 0:
                        inc.new_weights[osd] = 0
                        out_restore[osd] = self.down_since.pop(osd)
                down_restore: Dict[int, float] = {}
                for osd, last in list(self.last_beacon.items()):
                    if self.osdmap.osd_up[osd] and \
                            now - last > self.config.mon_osd_beacon_grace:
                        inc.new_down.append(osd)
                        self.down_since[osd] = now
                        down_restore[osd] = self.last_beacon.pop(osd)
                        self.perf.inc("mon_osd_marked_down")
                for osd in inc.new_down:
                    self.clog("WRN", f"osd.{osd} marked down "
                                     "(beacon grace expired)")
                for osd in inc.new_weights:
                    self.clog("WRN", f"osd.{osd} marked out "
                                     "(down past the out interval)")
                # full-ratio protection (round 16): judge per-OSD
                # utilization from beacon statfs against the configured
                # ratios and commit flag transitions into the map —
                # OSDs enforce from their own copy (ENOSPC on client
                # writes under "full", backfill deferred under
                # "backfillfull"); flags CLEAR here too as deletes
                # drain space and beacons report it
                tiers = self._full_tiers()   # shared with health
                want = set()
                if tiers["full"]:
                    want |= {"full", "backfillfull", "nearfull"}
                if tiers["backfillfull"]:
                    want |= {"backfillfull", "nearfull"}
                if tiers["nearfull"]:
                    want.add("nearfull")
                for flag in ("nearfull", "backfillfull", "full"):
                    have = flag in self.osdmap.flags
                    if (flag in want) == have:
                        continue
                    inc.new_flags[flag] = flag in want
                    if flag in want:
                        self.clog("ERR" if flag == "full" else "WRN",
                                  f"cluster is {flag} "
                                  f"(mon_osd_{flag}_ratio)")
                    else:
                        self.clog("INF", f"{flag} flag cleared")
                # flush buffered cluster-log events through Paxos so the
                # whole quorum (and the persisted store) agree on the log
                if self._pending_clog:
                    inc.new_log_entries = tuple(self._pending_clog)
                    self._pending_clog = []
                if inc.new_weights or inc.new_down or \
                        inc.new_log_entries or inc.new_flags:
                    if not await self._commit_inc(inc):
                        # quorum lost mid-tick (leader killed under
                        # churn): the detection state must survive the
                        # failed commit, or an up-but-dead OSD whose
                        # beacon entry was already popped would never
                        # be marked down by anyone
                        self.down_since.update(out_restore)
                        for osd, last in down_restore.items():
                            self.last_beacon[osd] = last
                            self.down_since.pop(osd, None)
                        self._pending_clog = \
                            list(inc.new_log_entries) + self._pending_clog
