"""Monitor: the cluster-map authority.

Mirrors the reference monitor's OSD-map service (src/mon/OSDMonitor.cc):
boot/failure handling with reporter thresholds (can_mark_down,
OSDMonitor.cc:1761), down-out ticks, map-epoch broadcast to subscribers
(MonClient subscription model, src/mon/MonClient.cc:354), and pool-create
commands that build CRUSH rules through the EC-profile seam
(ErasureCode::create_rule analog).  Map mutations go through a
single-authority proposal log (the Paxos seam — multi-mon quorum is the
next stage; the propose/commit structure is kept so Paxos slots in).
"""

from __future__ import annotations

import asyncio
import pickle
import time
from typing import Dict, List, Optional, Set, Tuple

from ceph_tpu.cluster import messages as M
from ceph_tpu.cluster.messenger import Addr, Connection, Dispatcher, EntityName, Messenger
from ceph_tpu.crush.types import (
    RULE_CHOOSELEAF_FIRSTN,
    RULE_CHOOSELEAF_INDEP,
    RULE_EMIT,
    RULE_TAKE,
    Rule,
)
from ceph_tpu.osdmap.osdmap import (
    Incremental,
    OSDMap,
    PGPool,
    POOL_TYPE_ERASURE,
    POOL_TYPE_REPLICATED,
)
from ceph_tpu.utils import Config, PerfCounters


class Monitor(Dispatcher):
    def __init__(self, osdmap: OSDMap, config: Optional[Config] = None,
                 rank: int = 0):
        self.rank = rank
        self.config = config or Config()
        self.osdmap = osdmap
        self.messenger = Messenger(EntityName("mon", rank))
        self.messenger.add_dispatcher(self)
        self.subscribers: Set[Addr] = set()
        self.failure_reports: Dict[int, Set[int]] = {}
        self.down_since: Dict[int, float] = {}
        # last beacon per osd (reference MOSDBeacon/last_osd_report): lets
        # the tick mark OSDs down even when no reporters remain (e.g. the
        # whole cluster stopped at once)
        self.last_beacon: Dict[int, float] = {}
        self.perf = PerfCounters("mon")
        self._tick_task: Optional[asyncio.Task] = None
        self._log: List[Tuple[str, object]] = []  # proposal log (Paxos seam)
        self._next_pool_id = max(self.osdmap.pools, default=0) + 1
        # recent incrementals by resulting epoch (reference: mon keeps a
        # window of full+inc maps; subscribers behind the window get a full
        # map).  Size mirrors osd_map_cache_size.
        self._inc_log: Dict[int, Incremental] = {}

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> Addr:
        addr = await self.messenger.bind(host, port)
        self._tick_task = asyncio.get_event_loop().create_task(self._tick())
        return addr

    async def stop(self) -> None:
        if self._tick_task:
            self._tick_task.cancel()
        await self.messenger.shutdown()

    # -- proposal log (single-authority; Paxos slots in here) --------------

    def _propose(self, what: str, payload) -> None:
        self._log.append((what, payload))
        self.perf.inc("mon_proposals")

    def _new_inc(self) -> Incremental:
        return Incremental(epoch=self.osdmap.epoch + 1)

    async def _commit_inc(self, inc: Incremental) -> None:
        """Apply a delta to the authoritative map, log it, broadcast it."""
        self.osdmap.apply_incremental(inc)
        self._inc_log[inc.epoch] = inc
        cutoff = inc.epoch - self.config.osd_map_cache_size
        for e in [e for e in self._inc_log if e <= cutoff]:
            del self._inc_log[e]
        self.perf.inc("mon_map_epochs")
        await self._broadcast_map()

    # -- dispatch ----------------------------------------------------------

    async def ms_dispatch(self, conn: Connection, msg) -> bool:
        if isinstance(msg, M.MOSDBoot):
            await self._handle_boot(msg)
            return True
        if isinstance(msg, M.MOSDFailure):
            await self._handle_failure(msg)
            return True
        if isinstance(msg, M.MOSDAlive):
            if 0 <= msg.osd_id < self.osdmap.max_osd:
                self.last_beacon[msg.osd_id] = time.monotonic()
            return True
        if isinstance(msg, M.MMonSubscribe):
            self.subscribers.add(tuple(msg.addr))
            await self._send_map(tuple(msg.addr), since=msg.since)
            return True
        if isinstance(msg, M.MMonCommand):
            await self._handle_command(conn, msg)
            return True
        return False

    async def _handle_boot(self, msg: M.MOSDBoot) -> None:
        self._propose("boot", (msg.osd_id, msg.addr))
        m = self.osdmap
        if msg.osd_id >= m.max_osd:
            return
        inc = self._new_inc()
        inc.new_up[msg.osd_id] = tuple(msg.addr)
        self.down_since.pop(msg.osd_id, None)
        self.failure_reports.pop(msg.osd_id, None)
        self.last_beacon[msg.osd_id] = time.monotonic()
        self.perf.inc("mon_osd_boot")
        await self._commit_inc(inc)

    async def _handle_failure(self, msg: M.MOSDFailure) -> None:
        m = self.osdmap
        osd = msg.failed_osd
        if osd < 0 or osd >= m.max_osd or not m.osd_up[osd]:
            return
        reporters = self.failure_reports.setdefault(osd, set())
        reporters.add(msg.reporter)
        # can_mark_down analog: enough distinct reporters
        if len(reporters) >= self.config.mon_osd_min_down_reporters:
            self._propose("down", osd)
            inc = self._new_inc()
            inc.new_down.append(osd)
            self.down_since[osd] = time.monotonic()
            self.failure_reports.pop(osd, None)
            self.perf.inc("mon_osd_marked_down")
            await self._commit_inc(inc)

    async def _handle_command(self, conn: Connection, msg: M.MMonCommand) -> None:
        cmd = msg.cmd
        result, data = 0, None
        try:
            prefix = cmd.get("prefix")
            if prefix == "osd pool create":
                data, inc = self._create_pool(cmd)
                await self._commit_inc(inc)
            elif prefix == "osd out":
                inc = self._new_inc()
                inc.new_weights[int(cmd["id"])] = 0
                await self._commit_inc(inc)
            elif prefix == "osd in":
                inc = self._new_inc()
                inc.new_weights[int(cmd["id"])] = 0x10000
                await self._commit_inc(inc)
            elif prefix == "status":
                m = self.osdmap
                data = {
                    "epoch": m.epoch,
                    "num_osds": m.max_osd,
                    "num_up": sum(m.osd_up),
                    "num_in": sum(1 for w in m.osd_weight if w > 0),
                    "pools": {p.name or pid: {"id": pid, "size": p.size,
                                              "pg_num": p.pg_num,
                                              "type": p.type}
                              for pid, p in m.pools.items()},
                }
            elif prefix == "perf dump":
                data = self.perf.dump()
            else:
                result = -22  # EINVAL
        except Exception as e:  # surface errors to the caller
            result, data = -22, repr(e)
        reply = M.MMonCommandReply(tid=msg.tid, result=result, data=data)
        await conn.send(reply)

    def _create_pool(self, cmd: Dict) -> Tuple[int, Incremental]:
        """Build the pool + rule delta (committed by the caller)."""
        name = cmd["pool"]
        pool_type = POOL_TYPE_ERASURE if cmd.get("pool_type") == "erasure" \
            else POOL_TYPE_REPLICATED
        m = self.osdmap
        root = None
        for bid, b in m.crush.buckets.items():
            if b.type == max(bb.type for bb in m.crush.buckets.values()):
                root = bid
                break
        ec_profile = dict(cmd.get("ec_profile") or {})
        ruleno = len(m.crush.rules)  # appended by apply_incremental
        if pool_type == POOL_TYPE_ERASURE:
            from ceph_tpu.ec import factory

            if not ec_profile:
                ec_profile = {"plugin": "jerasure",
                              "technique": "reed_sol_van",
                              "k": "2", "m": "1"}
            codec = factory(ec_profile)
            size = codec.get_chunk_count()
            min_size = codec.get_data_chunk_count()
            # compose the stripe unit with the codec's layout constraints
            # (packet-interleaved codecs need w*packetsize multiples) so
            # default profiles never EINVAL deep in the data path
            ec_profile["stripe_unit"] = str(codec.stripe_unit(
                int(ec_profile.get("stripe_unit",
                                   self.config.osd_ec_stripe_unit))))
            # ErasureCode::create_rule analog: indep chooseleaf rule
            rule = Rule(steps=[
                (RULE_TAKE, root, 0),
                (RULE_CHOOSELEAF_INDEP, size, 1),
                (RULE_EMIT, 0, 0)], type=POOL_TYPE_ERASURE)
        else:
            size = int(cmd.get("size", self.config.osd_pool_default_size))
            min_size = max(1, size - 1)
            rule = Rule(steps=[
                (RULE_TAKE, root, 0),
                (RULE_CHOOSELEAF_FIRSTN, size, 1),
                (RULE_EMIT, 0, 0)])
        pg_num = int(cmd.get("pg_num", self.config.osd_pool_default_pg_num))
        pool_id = self._next_pool_id
        self._next_pool_id += 1
        inc = self._new_inc()
        inc.new_rules.append(rule)
        inc.new_pools[pool_id] = PGPool(
            pool_id=pool_id, type=pool_type, size=size, min_size=min_size,
            pg_num=pg_num, pgp_num=pg_num, crush_rule=ruleno,
            ec_profile=ec_profile, name=name)
        self._propose("pool_create", (pool_id, name))
        self.perf.inc("mon_pool_create")
        return pool_id, inc

    # -- map distribution --------------------------------------------------

    async def _broadcast_map(self) -> None:
        """Push the newest delta to subscribers (O(delta), not O(map))."""
        for addr in list(self.subscribers):
            try:
                await self._send_map(addr, since=self.osdmap.epoch - 1)
            except (ConnectionError, OSError):
                self.subscribers.discard(addr)

    async def _send_map(self, addr: Addr, since: int = 0) -> None:
        """Send incrementals covering (since, current] when the window has
        them, else the full map (reference OSDMonitor send_incremental)."""
        epoch = self.osdmap.epoch
        if 0 < since <= epoch:
            chain = []
            e = since + 1
            while e <= epoch and e in self._inc_log:
                chain.append(pickle.dumps(self._inc_log[e]))
                e += 1
            if e > epoch:
                # complete chain (possibly empty when already current; the
                # empty message still acks the subscriber's refresh)
                self.perf.inc("mon_inc_maps_sent")
                await self.messenger.send_message(
                    M.MOSDIncMapMsg(prev_epoch=since, epoch=epoch,
                                    inc_blobs=chain), addr)
                return
        self.perf.inc("mon_full_maps_sent")
        blob = pickle.dumps(self.osdmap)
        await self.messenger.send_message(
            M.MOSDMapMsg(epoch=epoch, osdmap_blob=blob), addr)

    async def _tick(self) -> None:
        """Down-out + beacon-staleness tick (reference OSDMonitor tick:
        auto-out and mark-down of osds whose beacons went silent)."""
        while True:
            await asyncio.sleep(self.config.mon_tick_interval)
            now = time.monotonic()
            inc = self._new_inc()
            for osd, since in list(self.down_since.items()):
                if now - since > self.config.mon_osd_down_out_interval and \
                        self.osdmap.osd_weight[osd] > 0:
                    inc.new_weights[osd] = 0
                    self.down_since.pop(osd)
            for osd, last in list(self.last_beacon.items()):
                if self.osdmap.osd_up[osd] and \
                        now - last > self.config.mon_osd_beacon_grace:
                    inc.new_down.append(osd)
                    self.down_since[osd] = now
                    self.last_beacon.pop(osd)
                    self.perf.inc("mon_osd_marked_down")
            if inc.new_weights or inc.new_down:
                await self._commit_inc(inc)
