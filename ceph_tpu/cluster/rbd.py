"""RBD: block images striped over RADOS objects.

Behavioral analog of the reference librbd core data path
(src/librbd/: images are a header object holding metadata plus
"rbd_data.<id>.%016x" objects laid out by the Striper; src/osdc/Striper
drives the extent math).  Subset implemented: create/open/remove,
size/resize, striped read/write at arbitrary offsets, snapshot ids
recorded in the header (metadata-level snapshots), stats.  The data path
rides IoCtx, so EC pools, recovery, and scrub all apply to images
unchanged.
"""

from __future__ import annotations

import asyncio
import pickle
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ceph_tpu.cluster.objecter import IoCtx
from ceph_tpu.cluster.striper import (
    FileLayout,
    StripedReader,
    file_to_extents,
)


@dataclass
class ImageHeader:
    """rbd_header.<name> contents (librbd image metadata analog)."""

    name: str
    size: int
    layout: FileLayout
    snaps: Dict[str, int] = field(default_factory=dict)  # name -> snap id
    next_snap_id: int = 1


class RBD:
    """Image admin surface (reference librbd::RBD)."""

    def __init__(self, ioctx: IoCtx):
        self.ioctx = ioctx

    @staticmethod
    def _header_oid(name: str) -> str:
        return f"rbd_header.{name}"

    async def create(self, name: str, size: int,
                     stripe_unit: int = 1 << 20,
                     stripe_count: int = 1,
                     object_size: int = 1 << 22) -> None:
        layout = FileLayout(stripe_unit=stripe_unit,
                            stripe_count=stripe_count,
                            object_size=object_size)
        layout.validate()
        hdr = ImageHeader(name=name, size=size, layout=layout)
        try:
            await self.ioctx.stat(self._header_oid(name))
            raise FileExistsError(name)
        except FileNotFoundError:
            pass
        await self.ioctx.write_full(self._header_oid(name),
                                    pickle.dumps(hdr))

    async def remove(self, name: str) -> None:
        img = await self.open(name)
        await img._remove_data()
        await self.ioctx.remove(self._header_oid(name))

    async def list(self) -> List[str]:
        return sorted(
            oid[len("rbd_header."):]
            for oid in await self.ioctx.list_objects()
            if oid.startswith("rbd_header."))

    async def open(self, name: str) -> "Image":
        try:
            blob = await self.ioctx.read(self._header_oid(name))
        except FileNotFoundError:
            raise FileNotFoundError(f"image {name}")
        hdr: ImageHeader = pickle.loads(blob)
        return Image(self.ioctx, hdr)


class Image:
    """Open image handle (reference librbd::Image)."""

    def __init__(self, ioctx: IoCtx, header: ImageHeader):
        self.ioctx = ioctx
        self.header = header
        self._fmt = f"rbd_data.{header.name}.%016x"

    # -- metadata -----------------------------------------------------------

    def size(self) -> int:
        return self.header.size

    async def _save_header(self) -> None:
        await self.ioctx.write_full(
            RBD._header_oid(self.header.name), pickle.dumps(self.header))

    async def resize(self, new_size: int) -> None:
        """Grow or shrink; shrinking removes whole dead OBJECT SETS and
        zeroes the partially-live tail, so a later grow reads zeros, not
        resurrected bytes (librbd resize + trim)."""
        old = self.header.size
        if new_size < old:
            layout = self.header.layout
            period = layout.object_size * layout.stripe_count
            live_sets = (new_size + period - 1) // period
            old_sets = (old + period - 1) // period
            # zero the live tail of the last partially-used period
            tail_end = min(old, live_sets * period)
            if tail_end > new_size:
                zeros = b"\0" * (tail_end - new_size)
                await self.write(new_size, zeros, _size_check=old)
            # drop every object of fully-dead sets
            for objno in range(live_sets * layout.stripe_count,
                               old_sets * layout.stripe_count):
                try:
                    await self.ioctx.remove(self._fmt % objno)
                except (IOError, FileNotFoundError):
                    pass
        self.header.size = new_size
        await self._save_header()

    async def snap_create(self, snap_name: str) -> int:
        """Metadata-level snapshot id (SnapContext bookkeeping analog;
        data cloning is future work)."""
        sid = self.header.next_snap_id
        self.header.next_snap_id += 1
        self.header.snaps[snap_name] = sid
        await self._save_header()
        return sid

    async def snap_remove(self, snap_name: str) -> None:
        del self.header.snaps[snap_name]
        await self._save_header()

    def snap_list(self) -> Dict[str, int]:
        return dict(self.header.snaps)

    # -- data path ----------------------------------------------------------

    async def write(self, offset: int, data: bytes,
                    _size_check: int = None) -> None:
        limit = self.header.size if _size_check is None else _size_check
        if offset + len(data) > limit:
            raise ValueError("write past end of image")
        extents = file_to_extents(self._fmt, self.header.layout,
                                  offset, len(data))
        per_object = StripedReader.scatter(extents, data)
        # per-object writes run concurrently; each is an atomic OSD op
        await asyncio.gather(*[
            self.ioctx.write(oid, blob, offset=obj_off)
            for oid, parts in per_object.items()
            for obj_off, blob in parts])

    async def read(self, offset: int, length: int) -> bytes:
        length = min(length, max(0, self.header.size - offset))
        if length == 0:
            return b""
        extents = file_to_extents(self._fmt, self.header.layout,
                                  offset, length)

        async def fetch(ex):
            try:
                return ex.oid, await self.ioctx.read(
                    ex.oid, offset=ex.offset, length=ex.length)
            except FileNotFoundError:
                return ex.oid, b""  # sparse: never written

        got = dict(await asyncio.gather(*[fetch(ex) for ex in extents]))
        return StripedReader.assemble(extents, got, length, relative=True)

    async def _remove_data(self) -> None:
        layout = self.header.layout
        period = layout.object_size * layout.stripe_count
        n_sets = (self.header.size + period - 1) // period
        n_objs = n_sets * layout.stripe_count
        for objno in range(n_objs):
            try:
                await self.ioctx.remove(self._fmt % objno)
            except (IOError, FileNotFoundError):
                pass

    async def stat(self) -> Dict:
        return {"size": self.header.size,
                "stripe_unit": self.header.layout.stripe_unit,
                "stripe_count": self.header.layout.stripe_count,
                "object_size": self.header.layout.object_size,
                "snaps": self.snap_list()}
