"""RBD: block images striped over RADOS objects.

Behavioral analog of the reference librbd core data path
(src/librbd/: images are a header object holding metadata plus
"rbd_data.<id>.%016x" objects laid out by the Striper; src/osdc/Striper
drives the extent math).  Implemented: create/open/remove, size/resize,
striped read/write at arbitrary offsets, REAL snapshots (selfmanaged
RADOS snaps + clone-on-write at the OSD: snap reads are point-in-time,
reference librbd snap_create -> ioctx selfmanaged snaps + SnapContext),
clone with copy-on-write copy-up from the parent snap (reference
librbd::CloneRequest / CopyupRequest), and stats.  The data path rides
IoCtx, so EC pools, recovery, and scrub all apply to images unchanged.
"""

from __future__ import annotations

import asyncio
import pickle
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ceph_tpu.utils.deadline import deadline_of, remaining
from ceph_tpu.utils.lockdep import DepLock

from ceph_tpu.cluster.objecter import IoCtx
from ceph_tpu.cluster.striper import (
    FileLayout,
    StripedReader,
    file_to_extents,
)


def _chaos(io: IoCtx, name: str) -> None:
    """Client-library chaos seam (round 15): interrupt this front-door
    transaction AT THIS INSTANT when the client config arms ``name``
    (the application "died" mid-op; a retry models its restart).  One
    falsy test when unarmed — the no-op contract."""
    if not io.objecter.config.chaos_crash_point:
        return
    from ceph_tpu.chaos.points import maybe_interrupt

    maybe_interrupt(io.objecter.config, name)


@dataclass
class ImageHeader:
    """rbd_header.<name> contents (librbd image metadata analog)."""

    name: str
    size: int
    layout: FileLayout
    snaps: Dict[str, int] = field(default_factory=dict)  # name -> rados snap
    snap_sizes: Dict[int, int] = field(default_factory=dict)  # id -> size
    # clone parentage (librbd parent_info): (parent image, parent snapid);
    # reads of unwritten child extents fall through to the parent snap
    parent: Optional[tuple] = None
    # clone children per snap (the reference's rbd_children registry):
    # snap name -> [(child image name, registration stamp)].  A snap
    # with live children is pinned — snap_remove refuses it (reference:
    # protected snapshots), which is what keeps clone parents immutable
    # while children still copy-up from them.  The stamp bounds the
    # dangling-child prune: a registration whose header is missing may
    # be a clone that CRASHED mid-create (prunable) or one still in
    # flight between registration and header write — only entries older
    # than the grace window are deemed dead.
    children: Dict[str, List[tuple]] = field(default_factory=dict)
    # journaling feature (reference RBD_FEATURE_JOURNALING,
    # src/journal/): mutations append to the image journal BEFORE the
    # data write, so rbd-mirror can replay them elsewhere
    journaling: bool = False


class RBD:
    """Image admin surface (reference librbd::RBD)."""

    def __init__(self, ioctx: IoCtx):
        self.ioctx = ioctx

    @staticmethod
    def _header_oid(name: str) -> str:
        return f"rbd_header.{name}"

    async def create(self, name: str, size: int,
                     stripe_unit: int = 1 << 20,
                     stripe_count: int = 1,
                     object_size: int = 1 << 22,
                     journaling: bool = False) -> None:
        layout = FileLayout(stripe_unit=stripe_unit,
                            stripe_count=stripe_count,
                            object_size=object_size)
        layout.validate()
        hdr = ImageHeader(name=name, size=size, layout=layout,
                          journaling=journaling)
        try:
            await self.ioctx.stat(self._header_oid(name))
            raise FileExistsError(name)
        except FileNotFoundError:
            pass
        await self.ioctx.write_full(self._header_oid(name),
                                    pickle.dumps(hdr))

    async def remove(self, name: str) -> None:
        img = await self.open(name)
        await img._remove_data()
        try:
            # the image journal dies with the image, or a recreated
            # same-name image would inherit (and mirrors would replay)
            # the dead image's events
            await self.ioctx.remove(f"rbd_journal.{name}")
        except FileNotFoundError:
            pass
        await self.ioctx.remove(self._header_oid(name))

    async def list(self) -> List[str]:
        return sorted(
            oid[len("rbd_header."):]
            for oid in await self.ioctx.list_objects()
            if oid.startswith("rbd_header."))

    async def open(self, name: str) -> "Image":
        try:
            blob = await self.ioctx.read(self._header_oid(name))
        except FileNotFoundError:
            raise FileNotFoundError(f"image {name}")
        hdr: ImageHeader = pickle.loads(blob)
        return Image(self.ioctx, hdr)

    async def clone(self, parent_name: str, snap_name: str,
                    child_name: str, timeout: float = None) -> None:
        """COW clone of a parent snapshot (reference librbd::CloneRequest):
        the child starts with NO data objects; reads fall through to the
        parent snap, writes copy-up the touched object first.

        Two-step transaction, crash-consistent: (1) register the child
        in the parent's children table — the snap is now pinned against
        removal BEFORE any child can depend on it; (2) write the child
        header.  A client dying between the two (``rbd_clone_mid``)
        leaves a dangling child entry, which ``snap_remove`` prunes (a
        registered child whose header never landed pins nothing); a
        retry is idempotent (re-registering is a set-insert)."""
        dl = deadline_of(timeout)
        parent = await self.open(parent_name)
        psid = parent.header.snaps.get(snap_name)
        if psid is None:
            raise FileNotFoundError(f"{parent_name}@{snap_name}")
        size = parent.header.snap_sizes.get(psid, parent.header.size)
        hdr = ImageHeader(name=child_name, size=size,
                          layout=parent.header.layout,
                          parent=(parent_name, psid))
        try:
            await self.ioctx.stat(self._header_oid(child_name),
                                  timeout=remaining(dl))
            raise FileExistsError(child_name)
        except FileNotFoundError:
            pass
        kids = parent.header.children.setdefault(snap_name, [])
        if child_name not in [c for c, _ in kids]:
            kids.append((child_name, time.time()))
            await parent._save_header(timeout=remaining(dl))
        _chaos(self.ioctx, "rbd_clone_mid")
        await self.ioctx.write_full(self._header_oid(child_name),
                                    pickle.dumps(hdr),
                                    timeout=remaining(dl))


class Image:
    """Open image handle (reference librbd::Image).

    Data ops run through a private IoCtx carrying this image's
    SnapContext (librbd keeps its own per-image snapc the same way), so
    snapshots of one image never affect another image's writes.

    ``CLONE_PRUNE_GRACE``: how old a header-less child registration
    must be before ``snap_remove`` deems the cloning client dead and
    prunes its pin (younger registrations may be clones mid-create)."""

    CLONE_PRUNE_GRACE = 30.0

    def __init__(self, ioctx: IoCtx, header: ImageHeader):
        self.ioctx = ioctx
        self._io = IoCtx(ioctx.objecter, ioctx.pool_id)
        self.header = header
        self._fmt = f"rbd_data.{header.name}.%016x"
        self._parent: Optional["Image"] = None
        # per-object copy-up serialization (librbd CopyupRequest holds the
        # object context lock): without it, a second concurrent writer's
        # copy-up write_full could land AFTER the first writer's partial
        # write and clobber its acknowledged bytes with parent data
        self._copyup_locks: Dict[int, asyncio.Lock] = {}
        self._apply_snapc()

    def _apply_snapc(self) -> None:
        sids = sorted(self.header.snaps.values(), reverse=True)
        if sids:
            self._io.set_snap_context(sids[0], sids)
        else:
            self._io._snapc = None

    # -- image journal (reference src/journal JournalRecorder) -------------

    @property
    def _journal_oid(self) -> str:
        return f"rbd_journal.{self.header.name}"

    async def _journal_event(self, event: tuple,
                             timeout: float = None) -> None:
        """Append one replayable event BEFORE applying it (the librbd
        journaling contract: the journal is authoritative for replay)."""
        if not self.header.journaling:
            return
        reply = await self._io.objecter.op_submit(
            self._io.pool_id, self._journal_oid,
            [("exec", {"cls": "rbd_journal", "method": "append",
                       "indata": pickle.dumps(event)})],
            timeout=timeout)
        if reply.result != 0:
            raise IOError(f"journal append -> {reply.result}")

    async def _get_parent(self) -> Optional["Image"]:
        if self.header.parent is None:
            return None
        if self._parent is None:
            pname, _ = self.header.parent
            self._parent = await RBD(self.ioctx).open(pname)
        return self._parent

    # -- metadata -----------------------------------------------------------

    def size(self) -> int:
        return self.header.size

    async def _save_header(self, timeout: float = None) -> None:
        await self.ioctx.write_full(
            RBD._header_oid(self.header.name), pickle.dumps(self.header),
            timeout=timeout)

    async def _refresh_header(self, timeout: float = None) -> None:
        """Re-read the header from RADOS (librbd refresh on header
        watch).  Snapshot mutations refresh FIRST: a stale handle
        otherwise cannot see children a clone registered through its
        own freshly-opened parent handle — and would happily remove a
        snapshot those clones still copy-up from (found by the
        round-15 no-op proof)."""
        blob = await self.ioctx.read(RBD._header_oid(self.header.name),
                                     timeout=timeout)
        self.header = pickle.loads(blob)
        self._apply_snapc()

    async def resize(self, new_size: int) -> None:
        """Grow or shrink; shrinking removes whole dead OBJECT SETS and
        zeroes the partially-live tail, so a later grow reads zeros, not
        resurrected bytes (librbd resize + trim)."""
        await self._journal_event(("resize", new_size))
        old = self.header.size
        if new_size < old:
            layout = self.header.layout
            period = layout.object_size * layout.stripe_count
            live_sets = (new_size + period - 1) // period
            old_sets = (old + period - 1) // period
            # zero the live tail of the last partially-used period
            tail_end = min(old, live_sets * period)
            if tail_end > new_size:
                zeros = b"\0" * (tail_end - new_size)
                await self.write(new_size, zeros, _size_check=old,
                                 _journal=False)
            # drop every object of fully-dead sets (through the snapc io:
            # a snapshotted image's shrink must clone-on-write, so snaps
            # keep reading the pre-shrink bytes)
            for objno in range(live_sets * layout.stripe_count,
                               old_sets * layout.stripe_count):
                try:
                    await self._io.remove(self._fmt % objno)
                except (IOError, FileNotFoundError):
                    pass
        self.header.size = new_size
        await self._save_header()

    async def snap_create(self, snap_name: str,
                          timeout: float = None) -> int:
        """Point-in-time snapshot (reference librbd snap_create:
        selfmanaged RADOS snap id + SnapContext on subsequent writes, so
        the OSD clone-on-writes every later mutation).

        Crash-consistency: the snap only EXISTS once the header save
        lands — a client dying between the id allocation and the save
        (``rbd_snap_pre_header``) leaks one snap id and nothing else
        (no header lists it, no SnapContext carries it, so no read can
        ever resolve to it and no write COWs against it); the retried
        create allocates a fresh id and is the one that counts."""
        dl = deadline_of(timeout)
        await self._refresh_header(timeout=remaining(dl))
        if snap_name in self.header.snaps:
            raise FileExistsError(snap_name)
        sid = await self._io.selfmanaged_snap_create()
        self.header.snaps[snap_name] = sid
        self.header.snap_sizes[sid] = self.header.size
        _chaos(self._io, "rbd_snap_pre_header")
        self._apply_snapc()
        await self._save_header(timeout=remaining(dl))
        return sid

    async def snap_remove(self, snap_name: str,
                          timeout: float = None) -> None:
        """Drops the snap and lets the OSD trimmer reclaim its clones.
        Refused while clone children depend on it (reference: a
        protected snapshot with children returns -EBUSY) — that pin is
        what keeps clone parents immutable.  Children registered by a
        clone that died before its header landed (``rbd_clone_mid``)
        are pruned here: a header-less child pins nothing."""
        dl = deadline_of(timeout)
        await self._refresh_header(timeout=remaining(dl))
        kids = self.header.children.get(snap_name, [])
        if kids:
            live = []
            now = time.time()
            for child, stamp in kids:
                try:
                    await self.ioctx.stat(RBD._header_oid(child),
                                          timeout=remaining(dl))
                    live.append((child, stamp))
                except FileNotFoundError:
                    # header missing: either the cloning client died
                    # mid-create (prunable) or it is STILL IN FLIGHT
                    # between registration and header write — inside
                    # the grace window the registration keeps its pin
                    # (removing the snap under a live clone would be
                    # silent child data loss)
                    if now - stamp <= self.CLONE_PRUNE_GRACE:
                        live.append((child, stamp))
            if live != kids:
                if live:
                    self.header.children[snap_name] = live
                else:
                    self.header.children.pop(snap_name, None)
                await self._save_header(timeout=remaining(dl))
            if live:
                raise OSError(16, f"snapshot {snap_name} has clone "
                                  f"children "
                                  f"{[c for c, _ in live]}")
        sid = self.header.snaps.pop(snap_name)
        self.header.snap_sizes.pop(sid, None)
        self.header.children.pop(snap_name, None)
        self._apply_snapc()
        await self._save_header(timeout=remaining(dl))
        await self._io.selfmanaged_snap_remove(sid)

    def snap_list(self) -> Dict[str, int]:
        return dict(self.header.snaps)

    # -- data path ----------------------------------------------------------

    async def write(self, offset: int, data: bytes,
                    _size_check: int = None,
                    _journal: bool = True,
                    timeout: float = None) -> None:
        dl = deadline_of(timeout)
        limit = self.header.size if _size_check is None else _size_check
        if offset + len(data) > limit:
            raise ValueError("write past end of image")
        if _journal:
            # internal writes (resize tail-zeroing) must NOT journal:
            # they are implied by the journaled resize event, and their
            # pre-shrink offsets would make the mirror re-grow the
            # secondary past the shrunken size
            await self._journal_event(("write", offset, bytes(data)),
                                      timeout=remaining(dl))
        extents = file_to_extents(self._fmt, self.header.layout,
                                  offset, len(data))
        per_object = StripedReader.scatter(extents, data)
        if self.header.parent is not None:
            # COW copy-up (librbd CopyupRequest): a partial write to an
            # object the child has never written must first materialize
            # the parent snap's bytes, or the untouched part of the
            # object would read back as zeros
            objno_of = {ex.oid: ex.objectno for ex in extents}
            await asyncio.gather(*[
                self._copyup(oid, objno_of[oid], deadline=dl)
                for oid in per_object])
        # per-object writes run concurrently; each is an atomic OSD op
        await asyncio.gather(*[
            self._io.write(oid, blob, offset=obj_off,
                           timeout=remaining(dl))
            for oid, parts in per_object.items()
            for obj_off, blob in parts])

    async def _copyup(self, oid: str, objno: int,
                      deadline: float = None) -> None:
        """Idempotent by construction, which is what makes a client
        dying at ``rbd_copyup_mid`` (parent bytes read, child object
        not yet written) safe to retry: the stat re-checks the child,
        the parent snap read is immutable, and the write_full lands the
        identical bytes — a half-done copy-up is indistinguishable from
        one that never started."""
        lock = self._copyup_locks.setdefault(objno, DepLock("rbd.copyup"))
        async with lock:
            try:
                await self._io.stat(oid, timeout=remaining(deadline))
                return  # child already has this object
            except FileNotFoundError:
                pass
            parent = await self._get_parent()
            if parent is None:
                return
            _, psid = self.header.parent
            try:
                pdata = await parent._io.read(parent._fmt % objno,
                                              snapid=psid,
                                              timeout=remaining(deadline))
            except FileNotFoundError:
                return  # parent sparse here too
            _chaos(self._io, "rbd_copyup_mid")
            if pdata:
                await self._io.write_full(oid, pdata,
                                          timeout=remaining(deadline))

    async def read(self, offset: int, length: int,
                   snap_name: str = None,
                   timeout: float = None) -> bytes:
        """Point-in-time read when ``snap_name`` is given (reference
        librbd snap_set + read: each object read resolves to the clone
        covering the snap at the OSD); unwritten extents of a cloned
        child fall through to the parent snap."""
        dl = deadline_of(timeout)
        snapid = None
        size = self.header.size
        if snap_name is not None:
            snapid = self.header.snaps[snap_name]
            size = self.header.snap_sizes.get(snapid, size)
        length = min(length, max(0, size - offset))
        if length == 0:
            return b""
        extents = file_to_extents(self._fmt, self.header.layout,
                                  offset, length)

        async def fetch(ex):
            try:
                return ex.oid, await self._io.read(
                    ex.oid, offset=ex.offset, length=ex.length,
                    snapid=snapid, timeout=remaining(dl))
            except FileNotFoundError:
                pass
            parent = await self._get_parent()
            if parent is not None:
                _, psid = self.header.parent
                try:
                    return ex.oid, await parent._io.read(
                        parent._fmt % ex.objectno, offset=ex.offset,
                        length=ex.length, snapid=psid,
                        timeout=remaining(dl))
                except FileNotFoundError:
                    pass
            return ex.oid, b""  # sparse: never written

        got = dict(await asyncio.gather(*[fetch(ex) for ex in extents]))
        return StripedReader.assemble(extents, got, length, relative=True)

    async def _remove_data(self) -> None:
        layout = self.header.layout
        period = layout.object_size * layout.stripe_count
        n_sets = (self.header.size + period - 1) // period
        n_objs = n_sets * layout.stripe_count
        for objno in range(n_objs):
            try:
                await self._io.remove(self._fmt % objno)
            except (IOError, FileNotFoundError):
                pass

    async def stat(self) -> Dict:
        return {"size": self.header.size,
                "stripe_unit": self.header.layout.stripe_unit,
                "stripe_count": self.header.layout.stripe_count,
                "object_size": self.header.layout.object_size,
                "snaps": self.snap_list()}
