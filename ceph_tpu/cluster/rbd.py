"""RBD: block images striped over RADOS objects.

Behavioral analog of the reference librbd core data path
(src/librbd/: images are a header object holding metadata plus
"rbd_data.<id>.%016x" objects laid out by the Striper; src/osdc/Striper
drives the extent math).  Implemented: create/open/remove, size/resize,
striped read/write at arbitrary offsets, REAL snapshots (selfmanaged
RADOS snaps + clone-on-write at the OSD: snap reads are point-in-time,
reference librbd snap_create -> ioctx selfmanaged snaps + SnapContext),
clone with copy-on-write copy-up from the parent snap (reference
librbd::CloneRequest / CopyupRequest), and stats.  The data path rides
IoCtx, so EC pools, recovery, and scrub all apply to images unchanged.
"""

from __future__ import annotations

import asyncio
import pickle
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ceph_tpu.utils.lockdep import DepLock

from ceph_tpu.cluster.objecter import IoCtx
from ceph_tpu.cluster.striper import (
    FileLayout,
    StripedReader,
    file_to_extents,
)


@dataclass
class ImageHeader:
    """rbd_header.<name> contents (librbd image metadata analog)."""

    name: str
    size: int
    layout: FileLayout
    snaps: Dict[str, int] = field(default_factory=dict)  # name -> rados snap
    snap_sizes: Dict[int, int] = field(default_factory=dict)  # id -> size
    # clone parentage (librbd parent_info): (parent image, parent snapid);
    # reads of unwritten child extents fall through to the parent snap
    parent: Optional[tuple] = None
    # journaling feature (reference RBD_FEATURE_JOURNALING,
    # src/journal/): mutations append to the image journal BEFORE the
    # data write, so rbd-mirror can replay them elsewhere
    journaling: bool = False


class RBD:
    """Image admin surface (reference librbd::RBD)."""

    def __init__(self, ioctx: IoCtx):
        self.ioctx = ioctx

    @staticmethod
    def _header_oid(name: str) -> str:
        return f"rbd_header.{name}"

    async def create(self, name: str, size: int,
                     stripe_unit: int = 1 << 20,
                     stripe_count: int = 1,
                     object_size: int = 1 << 22,
                     journaling: bool = False) -> None:
        layout = FileLayout(stripe_unit=stripe_unit,
                            stripe_count=stripe_count,
                            object_size=object_size)
        layout.validate()
        hdr = ImageHeader(name=name, size=size, layout=layout,
                          journaling=journaling)
        try:
            await self.ioctx.stat(self._header_oid(name))
            raise FileExistsError(name)
        except FileNotFoundError:
            pass
        await self.ioctx.write_full(self._header_oid(name),
                                    pickle.dumps(hdr))

    async def remove(self, name: str) -> None:
        img = await self.open(name)
        await img._remove_data()
        try:
            # the image journal dies with the image, or a recreated
            # same-name image would inherit (and mirrors would replay)
            # the dead image's events
            await self.ioctx.remove(f"rbd_journal.{name}")
        except FileNotFoundError:
            pass
        await self.ioctx.remove(self._header_oid(name))

    async def list(self) -> List[str]:
        return sorted(
            oid[len("rbd_header."):]
            for oid in await self.ioctx.list_objects()
            if oid.startswith("rbd_header."))

    async def open(self, name: str) -> "Image":
        try:
            blob = await self.ioctx.read(self._header_oid(name))
        except FileNotFoundError:
            raise FileNotFoundError(f"image {name}")
        hdr: ImageHeader = pickle.loads(blob)
        return Image(self.ioctx, hdr)

    async def clone(self, parent_name: str, snap_name: str,
                    child_name: str) -> None:
        """COW clone of a parent snapshot (reference librbd::CloneRequest):
        the child starts with NO data objects; reads fall through to the
        parent snap, writes copy-up the touched object first."""
        parent = await self.open(parent_name)
        psid = parent.header.snaps.get(snap_name)
        if psid is None:
            raise FileNotFoundError(f"{parent_name}@{snap_name}")
        size = parent.header.snap_sizes.get(psid, parent.header.size)
        hdr = ImageHeader(name=child_name, size=size,
                          layout=parent.header.layout,
                          parent=(parent_name, psid))
        try:
            await self.ioctx.stat(self._header_oid(child_name))
            raise FileExistsError(child_name)
        except FileNotFoundError:
            pass
        await self.ioctx.write_full(self._header_oid(child_name),
                                    pickle.dumps(hdr))


class Image:
    """Open image handle (reference librbd::Image).

    Data ops run through a private IoCtx carrying this image's
    SnapContext (librbd keeps its own per-image snapc the same way), so
    snapshots of one image never affect another image's writes."""

    def __init__(self, ioctx: IoCtx, header: ImageHeader):
        self.ioctx = ioctx
        self._io = IoCtx(ioctx.objecter, ioctx.pool_id)
        self.header = header
        self._fmt = f"rbd_data.{header.name}.%016x"
        self._parent: Optional["Image"] = None
        # per-object copy-up serialization (librbd CopyupRequest holds the
        # object context lock): without it, a second concurrent writer's
        # copy-up write_full could land AFTER the first writer's partial
        # write and clobber its acknowledged bytes with parent data
        self._copyup_locks: Dict[int, asyncio.Lock] = {}
        self._apply_snapc()

    def _apply_snapc(self) -> None:
        sids = sorted(self.header.snaps.values(), reverse=True)
        if sids:
            self._io.set_snap_context(sids[0], sids)
        else:
            self._io._snapc = None

    # -- image journal (reference src/journal JournalRecorder) -------------

    @property
    def _journal_oid(self) -> str:
        return f"rbd_journal.{self.header.name}"

    async def _journal_event(self, event: tuple) -> None:
        """Append one replayable event BEFORE applying it (the librbd
        journaling contract: the journal is authoritative for replay)."""
        if not self.header.journaling:
            return
        reply = await self._io.objecter.op_submit(
            self._io.pool_id, self._journal_oid,
            [("exec", {"cls": "rbd_journal", "method": "append",
                       "indata": pickle.dumps(event)})])
        if reply.result != 0:
            raise IOError(f"journal append -> {reply.result}")

    async def _get_parent(self) -> Optional["Image"]:
        if self.header.parent is None:
            return None
        if self._parent is None:
            pname, _ = self.header.parent
            self._parent = await RBD(self.ioctx).open(pname)
        return self._parent

    # -- metadata -----------------------------------------------------------

    def size(self) -> int:
        return self.header.size

    async def _save_header(self) -> None:
        await self.ioctx.write_full(
            RBD._header_oid(self.header.name), pickle.dumps(self.header))

    async def resize(self, new_size: int) -> None:
        """Grow or shrink; shrinking removes whole dead OBJECT SETS and
        zeroes the partially-live tail, so a later grow reads zeros, not
        resurrected bytes (librbd resize + trim)."""
        await self._journal_event(("resize", new_size))
        old = self.header.size
        if new_size < old:
            layout = self.header.layout
            period = layout.object_size * layout.stripe_count
            live_sets = (new_size + period - 1) // period
            old_sets = (old + period - 1) // period
            # zero the live tail of the last partially-used period
            tail_end = min(old, live_sets * period)
            if tail_end > new_size:
                zeros = b"\0" * (tail_end - new_size)
                await self.write(new_size, zeros, _size_check=old,
                                 _journal=False)
            # drop every object of fully-dead sets (through the snapc io:
            # a snapshotted image's shrink must clone-on-write, so snaps
            # keep reading the pre-shrink bytes)
            for objno in range(live_sets * layout.stripe_count,
                               old_sets * layout.stripe_count):
                try:
                    await self._io.remove(self._fmt % objno)
                except (IOError, FileNotFoundError):
                    pass
        self.header.size = new_size
        await self._save_header()

    async def snap_create(self, snap_name: str) -> int:
        """Point-in-time snapshot (reference librbd snap_create:
        selfmanaged RADOS snap id + SnapContext on subsequent writes, so
        the OSD clone-on-writes every later mutation)."""
        if snap_name in self.header.snaps:
            raise FileExistsError(snap_name)
        sid = await self._io.selfmanaged_snap_create()
        self.header.snaps[snap_name] = sid
        self.header.snap_sizes[sid] = self.header.size
        self._apply_snapc()
        await self._save_header()
        return sid

    async def snap_remove(self, snap_name: str) -> None:
        """Drops the snap and lets the OSD trimmer reclaim its clones."""
        sid = self.header.snaps.pop(snap_name)
        self.header.snap_sizes.pop(sid, None)
        self._apply_snapc()
        await self._save_header()
        await self._io.selfmanaged_snap_remove(sid)

    def snap_list(self) -> Dict[str, int]:
        return dict(self.header.snaps)

    # -- data path ----------------------------------------------------------

    async def write(self, offset: int, data: bytes,
                    _size_check: int = None,
                    _journal: bool = True) -> None:
        limit = self.header.size if _size_check is None else _size_check
        if offset + len(data) > limit:
            raise ValueError("write past end of image")
        if _journal:
            # internal writes (resize tail-zeroing) must NOT journal:
            # they are implied by the journaled resize event, and their
            # pre-shrink offsets would make the mirror re-grow the
            # secondary past the shrunken size
            await self._journal_event(("write", offset, bytes(data)))
        extents = file_to_extents(self._fmt, self.header.layout,
                                  offset, len(data))
        per_object = StripedReader.scatter(extents, data)
        if self.header.parent is not None:
            # COW copy-up (librbd CopyupRequest): a partial write to an
            # object the child has never written must first materialize
            # the parent snap's bytes, or the untouched part of the
            # object would read back as zeros
            objno_of = {ex.oid: ex.objectno for ex in extents}
            await asyncio.gather(*[
                self._copyup(oid, objno_of[oid]) for oid in per_object])
        # per-object writes run concurrently; each is an atomic OSD op
        await asyncio.gather(*[
            self._io.write(oid, blob, offset=obj_off)
            for oid, parts in per_object.items()
            for obj_off, blob in parts])

    async def _copyup(self, oid: str, objno: int) -> None:
        lock = self._copyup_locks.setdefault(objno, DepLock("rbd.copyup"))
        async with lock:
            try:
                await self._io.stat(oid)
                return  # child already has this object
            except FileNotFoundError:
                pass
            parent = await self._get_parent()
            if parent is None:
                return
            _, psid = self.header.parent
            try:
                pdata = await parent._io.read(parent._fmt % objno,
                                              snapid=psid)
            except FileNotFoundError:
                return  # parent sparse here too
            if pdata:
                await self._io.write_full(oid, pdata)

    async def read(self, offset: int, length: int,
                   snap_name: str = None) -> bytes:
        """Point-in-time read when ``snap_name`` is given (reference
        librbd snap_set + read: each object read resolves to the clone
        covering the snap at the OSD); unwritten extents of a cloned
        child fall through to the parent snap."""
        snapid = None
        size = self.header.size
        if snap_name is not None:
            snapid = self.header.snaps[snap_name]
            size = self.header.snap_sizes.get(snapid, size)
        length = min(length, max(0, size - offset))
        if length == 0:
            return b""
        extents = file_to_extents(self._fmt, self.header.layout,
                                  offset, length)

        async def fetch(ex):
            try:
                return ex.oid, await self._io.read(
                    ex.oid, offset=ex.offset, length=ex.length,
                    snapid=snapid)
            except FileNotFoundError:
                pass
            parent = await self._get_parent()
            if parent is not None:
                _, psid = self.header.parent
                try:
                    return ex.oid, await parent._io.read(
                        parent._fmt % ex.objectno, offset=ex.offset,
                        length=ex.length, snapid=psid)
                except FileNotFoundError:
                    pass
            return ex.oid, b""  # sparse: never written

        got = dict(await asyncio.gather(*[fetch(ex) for ex in extents]))
        return StripedReader.assemble(extents, got, length, relative=True)

    async def _remove_data(self) -> None:
        layout = self.header.layout
        period = layout.object_size * layout.stripe_count
        n_sets = (self.header.size + period - 1) // period
        n_objs = n_sets * layout.stripe_count
        for objno in range(n_objs):
            try:
                await self._io.remove(self._fmt % objno)
            except (IOError, FileNotFoundError):
                pass

    async def stat(self) -> Dict:
        return {"size": self.header.size,
                "stripe_unit": self.header.layout.stripe_unit,
                "stripe_count": self.header.layout.stripe_count,
                "object_size": self.header.layout.object_size,
                "snaps": self.snap_list()}
