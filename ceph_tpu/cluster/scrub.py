"""Scrub: background integrity verification + repair routing
(reference PG scrub / ecbackend.rst:86-99)."""

from __future__ import annotations

import asyncio
from typing import Dict, List, Tuple

from ceph_tpu.cluster import messages as M
from ceph_tpu.crush.types import CRUSH_ITEM_NONE
from ceph_tpu.osdmap.osdmap import PGid
from ceph_tpu.cluster.pg import PGMETA, PGState, _coll
from ceph_tpu.ec import planar_store
from ceph_tpu.ops import crc32c as crcmod


class ScrubMixin:

    # --------------------------------------------------------------- scrub
    #
    # Background integrity verification (reference PG scrub +
    # ecbackend.rst:86-99): the primary collects per-member scrub maps
    # (oid -> computed crc32c over the bytes, batched on the device where
    # object sizes group), detects divergent replicas / corrupt EC shards
    # WITHOUT a client read, and repairs through the recovery machinery.

    def _build_scrub_map(self, pgid: PGid) -> Dict[str, Tuple]:
        """oid -> (version, size, computed_crc, stored_crc).  Equal-size
        objects CRC in ONE device dispatch (crc32c_batch); odd sizes fall
        back to the host path.

        Round 19 (planar at rest): planar shard objects deep-scrub over
        their PLANE-MAJOR rows — equal-size planar blobs stack into one
        crc32c_planar_rows pass whose column-spread crcs are
        bit-identical to the byte anchor's, so mixed-layout members
        agree on every verdict and the byte view is never
        materialized."""
        import numpy as np

        coll = _coll(pgid)
        oids = self._list_pg_objects(pgid)
        pset = {oid for oid in oids
                if self.store.object_layout(coll, oid)
                == planar_store.LAYOUT_PLANAR}
        blobs = {oid: (self.store.read_planar(coll, oid)
                       if oid in pset else self.store.read(coll, oid))
                 for oid in oids}
        by_len: Dict[Tuple[int, bool], List[str]] = {}
        for oid, b in blobs.items():
            by_len.setdefault((len(b), oid in pset), []).append(oid)
        crcs: Dict[str, int] = {}
        for (ln, planar), group in by_len.items():
            if planar and ln > 0:
                planes = np.vstack([planar_store.blob_to_planes(blobs[o])
                                    for o in group])
                for o, v in zip(group,
                                crcmod.crc32c_planar_rows(planes)):
                    crcs[o] = int(v)
            elif not planar and len(group) >= 2 and ln > 0:
                arr = np.stack([
                    np.frombuffer(blobs[o], dtype=np.uint8) for o in group])
                vals = np.asarray(crcmod.crc32c_batch(arr))
                for o, v in zip(group, vals):
                    crcs[o] = int(v)
            else:
                for o in group:
                    crcs[o] = crcmod.crc32c(0xFFFFFFFF, blobs[o])
        out = {}
        for oid in oids:
            stored = self.store.getattr(coll, oid, "hinfo_crc")
            out[oid] = (self.store.get_version(coll, oid),
                        len(blobs[oid]), crcs[oid],
                        int(stored) if stored is not None else None)
        return out

    async def scrub_pg(self, st: PGState) -> Dict[str, List[str]]:
        """Primary-driven scrub of one PG; returns
        {"inconsistent": [...], "repaired": [...]}."""
        async with st.lock:
            report = await self._scrub_pg_locked(st)
        # inconsistent -> clean health flow (round 16): a scrub pass
        # scans EVERY object of the PG, so its verdict REPLACES the
        # set — unrepaired findings stay flagged (beacon-fed
        # PG_INCONSISTENT / OSD_SCRUB_ERRORS raise), repaired ones and
        # stale entries (healed by recovery/read-repair out-of-band,
        # or deleted since) clear, so a single transient repair
        # failure can never pin the health warning forever.  (If a
        # read detection races this pass and its repair then fails,
        # the next detecting read or scrub pass re-flags the oid.)
        repaired = set(report["repaired"])
        bad = set(report["inconsistent"]) - repaired
        st.inconsistent.intersection_update(bad)
        st.inconsistent.update(bad)
        if repaired:
            self.perf.inc("osd_scrub_errors_repaired", len(repaired))
        if report["inconsistent"]:
            # cluster-log the scrub result (reference clog error stream)
            self.clog(
                "ERR",
                f"pg {st.pgid} scrub: "
                f"{len(report['inconsistent'])} inconsistent "
                f"({len(report['repaired'])} repaired): "
                f"{report['inconsistent'][:5]}")
        return report

    async def _scrub_pg_locked(self, st: PGState) -> Dict[str, List[str]]:
        pool = self.osdmap.pools[st.pgid.pool]
        members = [o for o in st.acting
                   if o not in (self.osd_id, CRUSH_ITEM_NONE)]
        maps: Dict[int, Dict[str, Tuple]] = {
            self.osd_id: self._build_scrub_map(st.pgid)}
        for osd in members:
            reqid = self._next_reqid()
            fut = self._make_waiter(reqid, 1)
            try:
                await self._send_osd(osd, M.MOSDScrub(
                    reqid=reqid, pgid=st.pgid))
                acc = await asyncio.wait_for(fut, timeout=5.0)
                _, reply = acc[0]
                if reply is not None:
                    maps[osd] = reply.objects
            except (asyncio.TimeoutError, ConnectionError):
                pass
            finally:
                self._pending.pop(reqid, None)
        inconsistent: List[str] = []
        repaired: List[str] = []
        if pool.is_erasure():
            # every shard is distinct: a member is corrupt when the crc of
            # its bytes no longer matches its stored hinfo crc
            for osd, smap in maps.items():
                for oid, (_ver, _size, crc, stored) in smap.items():
                    if stored is not None and crc != stored:
                        inconsistent.append(oid)
                        self.perf.inc("osd_scrub_errors")
                        bad_shard = {i for i, o in enumerate(st.acting)
                                     if o == osd}
                        ok = await self._recover_ec_object(
                            pool, st, oid, targets=[osd],
                            exclude_sources=bad_shard)
                        if ok:
                            repaired.append(oid)
            # generation divergence: a shard can be bitwise-clean against
            # its OWN crc yet belong to an older committed generation
            # (an interrupted recovery left it behind).  Such a shard
            # must never feed a decode; rebuild it from the newest
            # committed group (surfaced by graft-chaos: a stale primary
            # shard served torn reads and crc-scrub saw nothing wrong)
            from ceph_tpu.cluster import snaps as snapmod

            handled = set(inconsistent)
            all_oids = set()
            for smap in maps.values():
                all_oids.update(smap)
            committed = st.last_complete[1]
            for oid in sorted(all_oids):
                if oid in handled or oid.endswith(snapmod._SNAPDIR):
                    continue  # snapdirs replicate; handled oids repaired
                vers = {osd: smap[oid][0] for osd, smap in maps.items()
                        if oid in smap}
                cvers = [v for v in vers.values() if v <= committed]
                if not cvers:
                    continue  # only un-acked generations: peering's call
                auth_v = max(cvers)
                stale = sorted(o for o, v in vers.items() if v < auth_v)
                if not stale:
                    continue
                inconsistent.append(oid)
                self.perf.inc("osd_scrub_errors")
                stale_shards = {i for i, o in enumerate(st.acting)
                                if o in stale}
                ok = await self._recover_ec_object(
                    pool, st, oid, targets=stale,
                    exclude_sources=stale_shards)
                if ok:
                    repaired.append(oid)
        else:
            # replicated: majority crc wins, divergent members get the
            # authoritative copy re-pushed
            all_oids = set()
            for smap in maps.values():
                all_oids.update(smap)
            for oid in sorted(all_oids):
                votes: Dict[Tuple[int, int], List[int]] = {}
                for osd, smap in maps.items():
                    if oid in smap:
                        ver, size, crc, _ = smap[oid]
                        votes.setdefault((size, crc), []).append(osd)
                if len(votes) <= 1 and all(oid in m for m in maps.values()):
                    continue
                inconsistent.append(oid)
                self.perf.inc("osd_scrub_errors")
                # only auto-repair with a strict-majority authoritative
                # copy; on a tie (e.g. 1-1 on size-2 pools) repairing
                # would arbitrarily overwrite a possibly-good replica —
                # the reference marks the object inconsistent instead
                sizes = sorted((len(v) for v in votes.values()),
                               reverse=True)
                if len(sizes) > 1 and sizes[0] == sizes[1]:
                    self.perf.inc("osd_scrub_ties")
                    continue
                winner = max(votes.values(), key=len)
                if self.osd_id not in winner:
                    if not await self._pull_rep_object(st, winner[0], oid):
                        continue
                data = self.store.read(_coll(st.pgid), oid)
                ver = self.store.get_version(_coll(st.pgid), oid)
                fixed = True
                for osd in members:
                    if osd in winner:
                        continue
                    try:
                        await self._send_osd(osd, M.MOSDPGPush(
                            pgid=st.pgid, oid=oid, op="repair",
                            data=data, version=ver))
                        self.perf.inc("osd_pushes_sent")
                    except ConnectionError:
                        fixed = False
                if fixed:
                    repaired.append(oid)
        self.perf.inc("osd_scrubs")
        return {"inconsistent": inconsistent, "repaired": repaired}

    async def _scrub_loop(self) -> None:
        """Scheduled deep scrub (round 16, reference OSD::sched_scrub):
        each primary PG carries its own next-due deadline, seeded-
        jittered inside ``osd_scrub_jitter * interval`` so a daemon's
        PGs (and a cluster's daemons, via per-daemon streams) never
        scrub in lockstep — the reference spreads deep scrubs across
        the interval for the same reason.  Due PGs scrub one at a time,
        yielding to client admission pressure (the round-10 QoS seam);
        the interval is re-read every pass so injectargs can enable or
        retune a running daemon.  Interval 0 parks the loop."""
        from ceph_tpu.chaos.rng import stream as _stream

        rng = _stream(self.config.chaos_seed,
                      f"scrub:osd.{self.osd_id}") \
            if self.config.chaos_seed else None
        if rng is None:
            import random as _random

            rng = _random.Random(self.osd_id * 2654435761 + 1)
        next_due: Dict = {}
        while not self._stopped:
            interval = self.config.osd_scrub_interval
            if not interval:
                next_due.clear()
                await asyncio.sleep(0.5)
                continue
            await asyncio.sleep(min(max(interval / 4.0, 0.05), 1.0))
            now = self.clock.monotonic()
            jitter = self.config.osd_scrub_jitter
            for pgid, st in list(self.pgs.items()):
                if self._stopped:
                    return
                if st.primary != self.osd_id:
                    next_due.pop(pgid, None)
                    continue
                due = next_due.get(pgid)
                if due is None:
                    # first sight: spread the initial scrub across the
                    # jitter band instead of stampeding at one beat
                    next_due[pgid] = now + interval * (
                        1.0 + jitter * (rng.random() - 1.0))
                    continue
                if now < due:
                    continue
                # re-arm BEFORE scrubbing (a slow scrub must not
                # compress the next period), wobbling +/- jitter/2
                next_due[pgid] = now + interval * (
                    1.0 + jitter * (rng.random() - 0.5))
                try:
                    # background scrub yields to client admission
                    # pressure, like recovery (QoS class demotion)
                    await self._yield_under_pressure()
                    self.perf.inc("osd_scrubs_scheduled")
                    await self.scrub_pg(st)
                except asyncio.CancelledError:
                    raise
                except Exception:
                    self.perf.inc("osd_scrub_errors")
            for pgid in [p for p in next_due if p not in self.pgs]:
                del next_due[pgid]
