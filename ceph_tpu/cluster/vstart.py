"""vstart: in-process dev cluster launcher.

Analog of the reference's src/vstart.sh dev-cluster bootstrap: spin up one
monitor and N OSD daemons on loopback, build the initial CRUSH map/OSDMap,
and hand back a connected client.  Used as the fixture for the tier-3-style
cluster tests (reference qa/standalone/ceph-helpers.sh run the same
daemons-on-loopback shape) and runnable as a module for interactive use:

    python -m ceph_tpu.cluster.vstart --osds 3
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ceph_tpu.cluster.mgr import MgrDaemon
from ceph_tpu.cluster.mon import Monitor
from ceph_tpu.cluster.objecter import RadosClient
from ceph_tpu.cluster.osd import OSDDaemon
from ceph_tpu.crush.types import build_hierarchy
from ceph_tpu.osdmap.osdmap import OSDMap
from ceph_tpu.utils import Config


@dataclass
class Cluster:
    """A running mini cluster: mon quorum, N OSDs, loopback messengers."""

    mons: List[Monitor]
    osds: Dict[int, OSDDaemon]
    config: Config
    mon_addrs: List[tuple] = field(default_factory=list)
    clients: List[RadosClient] = field(default_factory=list)
    mgr: Optional[MgrDaemon] = None
    mgr_addr: Optional[tuple] = None
    mds: Optional[object] = None       # rank-0 MDSDaemon (cluster/mds.py)
    mds_addr: Optional[tuple] = None
    mdss: Optional[dict] = None        # rank -> MDSDaemon (multi-active)
    # per-daemon config copies of killed OSDs: a revive must resume the
    # daemon's OWN config (injected fault options survive kill/revive
    # within a chaos scenario), not the cluster template
    osd_configs: Dict[int, Config] = field(default_factory=dict)
    # durable stores of killed/crashed OSDs: a crash-revive remounts the
    # same store and replays its journal (MemStore kills stay lost-RAM)
    osd_stores: Dict[int, object] = field(default_factory=dict)
    # chaos crash-point teardown tasks (round 12): a daemon that
    # self-crashes at an armed seam hands its teardown HERE — the dying
    # daemon cannot own the task (its stop() would cancel the crash
    # mid-flight).  Self-discarding; drain_chaos() awaits stragglers so
    # a scenario's heal phase never races a crash still in progress.
    _chaos_tasks: set = field(default_factory=set)
    # per-rank config copies of crashed MDS ranks (round 15): like
    # osd_configs, a restarted rank resumes its OWN config so injected
    # fault options (e.g. an armed replay-seam crash point) survive the
    # bounce; the rank's pools ride along so a babysitter can restart
    # it without re-deriving them
    mds_configs: Dict[int, Config] = field(default_factory=dict)
    mds_pools: Dict[int, tuple] = field(default_factory=dict)
    # graft-blackbox (round 17): triggered postmortem bundles.  Every
    # produced bundle record lands here ({kind, reason, path, bundle});
    # _bb_seen dedups triggers (one bundle per (kind, reason) — a
    # flapping HEALTH_ERR edge or a re-judged gate must not spray
    # bundles), _bb_tasks tracks async trigger collection spawned from
    # sync seams (the mon health callback), drained by stop().
    postmortems: List[Dict] = field(default_factory=list)
    _bb_seen: set = field(default_factory=set)
    _bb_tasks: set = field(default_factory=set)
    # the boot-time store factory, kept so elastically-grown OSDs
    # (add_osds) get the same backing-store flavor as the original set
    store_factory: Optional[object] = None

    async def blackbox_trigger(self, kind: str, reason: str,
                               detail: Optional[Dict] = None,
                               clients=()) -> Optional[Dict]:
        """Fire a postmortem trigger: snapshot every daemon's flight
        ring + historic ops + mgr scrape + mon health history into ONE
        bundle (ceph_tpu/trace/postmortem.py), write POSTMORTEM_*.json
        when blackbox_dir is set, and remember the record.  One falsy
        test when blackbox_enabled=0 (the no-op contract); deduped per
        (kind, reason)."""
        if not getattr(self.config, "blackbox_enabled", 0):
            return None
        key = (kind, reason)
        if key in self._bb_seen:
            return None
        self._bb_seen.add(key)
        from ceph_tpu.trace import postmortem as pm

        bundle = await pm.collect_bundle(self, kind, reason,
                                         detail=detail, clients=clients)
        path = None
        out_dir = getattr(self.config, "blackbox_dir", "")
        if out_dir:
            path = pm.write_bundle(bundle, out_dir)
        rec = {"kind": kind, "reason": reason, "path": path,
               "bundle": bundle}
        self.postmortems.append(rec)
        return rec

    def _arm_blackbox(self, mon: Monitor) -> None:
        """Install the mon's HEALTH_ERR trigger seam: the edge INTO
        HEALTH_ERR (detected by the mon's tick) spawns a bundle
        collection task owned by the cluster (the mon's tick loop must
        not block on collecting a cluster-wide snapshot)."""
        if not getattr(self.config, "blackbox_enabled", 0):
            return
        from ceph_tpu.utils.tasks import track_task

        def fire(checks: Dict) -> None:
            async def _collect():
                await self.blackbox_trigger(
                    "health_err", f"mon.{mon.rank} HEALTH_ERR",
                    detail={"checks": checks})

            track_task(self._bb_tasks,
                       asyncio.get_event_loop().create_task(_collect()))

        mon._blackbox_health_cb = fire

    async def drain_blackbox(self) -> None:
        """Wait out in-flight trigger collections (stop() calls this
        first so a bundle never races the teardown)."""
        while self._bb_tasks:
            # collection drain: each task's outcome is its bundle record
            await asyncio.gather(*list(self._bb_tasks),  # graftlint: ignore[swallowed-async-error]
                                 return_exceptions=True)

    def _arm_chaos_crash(self, osd: OSDDaemon) -> None:
        """Install the crash-point callback: when the daemon's write
        path trips an armed chaos_crash_point, the cluster performs the
        same bookkeeping as an injector-driven crash_osd (config +
        durable store remembered for revive)."""
        from ceph_tpu.utils.tasks import track_task

        def fire(point: str) -> None:
            async def _crash():
                if self.osds.get(osd.osd_id) is osd:
                    await self.crash_osd(osd.osd_id)
                # a fired crash point is a postmortem trigger: the
                # bundle is taken with the victim already down (its
                # flight ring's tail IS the evidence of interest, and
                # collection tolerates the dead daemon)
                await self.blackbox_trigger(
                    "crash_point",
                    f"osd.{osd.osd_id} crash point {point!r}",
                    detail={"osd": osd.osd_id, "point": point})

            track_task(self._chaos_tasks,
                       asyncio.get_event_loop().create_task(_crash()))

        osd._chaos_crash_cb = fire

    async def drain_chaos(self) -> None:
        """Wait out in-flight crash-point teardowns (scenario runner
        calls this before healing/reviving)."""
        while self._chaos_tasks:
            # teardown drain: each task's outcome is the crash itself
            await asyncio.gather(*list(self._chaos_tasks),  # graftlint: ignore[swallowed-async-error]
                                 return_exceptions=True)

    async def start_mds(self, meta_pool: int, data_pool: int,
                        rank: int = 0):
        """Start (or restart) an active MDS rank over existing pools
        (multiple ranks = multi-active, subtree-partitioned).  A rank
        crashed at a chaos seam resumes its own per-rank config copy
        (mds_configs), like an OSD revive."""
        from ceph_tpu.cluster.mds import MDSDaemon

        cfg = self.mds_configs.pop(rank, None) or self.config
        daemon = MDSDaemon(self.mon_addr, meta_pool, data_pool,
                           config=cfg, rank=rank)
        self._arm_chaos_crash_mds(daemon)
        self.mds_pools[rank] = (meta_pool, data_pool)
        addr = await daemon.start()
        if self.mdss is None:
            self.mdss = {}
        self.mdss[rank] = daemon
        if rank == 0 or self.mds is None:
            self.mds = daemon
            self.mds_addr = addr
        return daemon

    def _arm_chaos_crash_mds(self, daemon) -> None:
        """Install the MDS crash-point callback: when the rank's serve
        or replay path trips an armed chaos_crash_point, the cluster
        performs the same bookkeeping as crash_mds (per-rank config
        remembered; the rank's durable state already lives in RADOS)."""
        from ceph_tpu.utils.tasks import track_task

        def fire(point: str) -> None:
            async def _crash():
                if (self.mdss or {}).get(daemon.rank) is daemon:
                    await self.crash_mds(daemon.rank)
                else:
                    # crashed during boot, before registration: remember
                    # the config and put the half-started daemon down
                    self.mds_configs.setdefault(daemon.rank,
                                                daemon.config)
                    await daemon.stop()

            track_task(self._chaos_tasks,
                       asyncio.get_event_loop().create_task(_crash()))

        daemon._chaos_crash_cb = fire

    async def crash_mds(self, rank: int) -> None:
        """Power-cut an MDS rank (round 15): stop it at this instant,
        remembering its per-rank config for the restart.  The MDS holds
        no local store — its journal and dirfrags live in RADOS — so
        the restarted rank's boot replay is the recovery path."""
        daemon = (self.mdss or {}).pop(rank, None)
        if daemon is None:
            return
        self.mds_configs[rank] = daemon.config
        if self.mds is daemon:
            self.mds = next(iter((self.mdss or {}).values()), None)
        daemon._stopped = True
        await daemon.stop()

    @property
    def mon(self) -> Monitor:
        """The authoritative monitor: the quorum leader (or the only one)."""
        for m in self.mons:
            if m.is_leader:
                return m
        return self.mons[0]

    @property
    def mon_addr(self):
        return self.mon_addrs[0] if len(self.mon_addrs) == 1 \
            else self.mon_addrs

    async def client(self, name: str = "admin") -> RadosClient:
        c = RadosClient(self.mon_addr, name=name, config=self.config)
        await c.connect()
        self.clients.append(c)
        return c

    def daemon_addr(self, name: str):
        """Resolve a daemon name ('osd.2', 'mon', 'mon.1', 'mgr',
        'mds.0') to its messenger address — the 'ceph daemon <name>'
        target-resolution seam."""
        kind, _, num = name.partition(".")
        if kind == "mon":
            rank = int(num) if num else self.mons[0].rank
            return self.mon_addrs[rank]
        if kind == "osd":
            osd = self.osds.get(int(num))
            if osd is None:
                raise KeyError(f"no such daemon {name}")
            return osd.messenger.my_addr
        if kind == "mgr":
            if self.mgr_addr is None:
                raise KeyError("no mgr running")
            return self.mgr_addr
        if kind == "mds":
            rank = int(num) if num else 0
            daemon = (self.mdss or {}).get(rank)
            if daemon is None:
                raise KeyError(f"no such daemon {name}")
            return daemon.messenger.my_addr
        raise KeyError(f"unknown daemon kind {kind!r}")

    async def daemon_command(self, name: str, cmd, timeout: float = 30.0):
        """'ceph daemon <name> <cmd>' against this cluster: route an
        MCommand to the daemon's admin socket (cmd: prefix string or
        full command dict)."""
        if isinstance(cmd, str):
            cmd = {"prefix": cmd}
        if not self.clients:
            await self.client()
        return await self.clients[0].objecter.daemon_command(
            self.daemon_addr(name), cmd, timeout=timeout)

    # serialized pickle of the cluster's INITIAL blank osdmap: the seed
    # a revived in-memory monitor reboots from (committed state comes
    # back from the quorum, like a reference mon resyncing from peers)
    _initial_map_blob: bytes = b""

    async def kill_mon(self, rank: int) -> None:
        """Hard-stop a monitor (mon_thrash analog)."""
        await self.mons[rank].stop()

    async def revive_mon(self, rank: int) -> Monitor:
        """Start a fresh monitor for a killed rank (mon_thrash revive):
        binds the ORIGINAL monmap address, rejoins elections, and
        catches up — paxos state through the collect/catch-up path
        (the election's last_committed guard keeps the blank rejoiner
        from winning before it has), the osdmap through an explicit
        subscription to the leader (paxos catch-up alone can be trimmed
        past a long-dead rejoiner's horizon)."""
        import pickle as _pickle

        mon = Monitor(_pickle.loads(self._initial_map_blob),
                      config=self.config, rank=rank,
                      n_mons=len(self.mons))
        host, port = self.mon_addrs[rank]
        await mon.start(host, port)
        self.mons[rank] = mon
        self._arm_blackbox(mon)
        if len(self.mons) > 1:
            mon.set_monmap(self.mon_addrs)
            await mon.begin_elections()
            for _ in range(100):
                if mon.leader_rank is not None and \
                        mon.leader_rank != rank:
                    await mon._request_map_sync()
                    break
                await asyncio.sleep(0.05)
        return mon

    async def wait_for_leader(self, timeout: float = 10.0,
                              exclude: int = -1) -> Monitor:
        deadline = asyncio.get_event_loop().time() + timeout
        while asyncio.get_event_loop().time() < deadline:
            for m in self.mons:
                if m.rank != exclude and m.is_leader:
                    return m
            await asyncio.sleep(0.05)
        raise TimeoutError("no mon leader elected")

    async def kill_osd(self, osd_id: int) -> None:
        """Hard-stop an OSD (thrasher kill_osd analog).  The daemon's
        per-daemon config is remembered for revive; a durable store
        (FileStore/BlueStore — anything with a crash/mount cycle) is
        remembered too, since a dead host's disks survive it."""
        osd = self.osds.pop(osd_id)
        self.osd_configs[osd_id] = osd.config
        if hasattr(osd.store, "crash"):
            self.osd_stores[osd_id] = osd.store
        await osd.stop()

    async def crash_osd(self, osd_id: int, torn_tail: bool = False,
                        lose_frames: int = 0) -> None:
        """Power-cut an OSD (chaos disk injector): no clean store
        shutdown; a durable store may tear/lose its journal tail and is
        kept for a revive that must replay it."""
        osd = self.osds.pop(osd_id)
        self.osd_configs[osd_id] = osd.config
        if hasattr(osd.store, "crash"):
            self.osd_stores[osd_id] = osd.store
        await osd.stop(crash=True, torn_tail=torn_tail,
                       lose_frames=lose_frames)

    async def revive_osd(self, osd_id: int,
                         with_store: bool = False) -> OSDDaemon:
        """Start a fresh daemon for the id (revive_osd analog; empty
        store by default — recovery must repopulate it).  It resumes the
        killed daemon's OWN config copy, so fault options injected
        before the kill survive the bounce; ``with_store`` remounts the
        remembered durable store (journal replay) instead of booting
        empty."""
        cfg = self.osd_configs.pop(osd_id, None) or self.config
        # the remembered store is consumed either way: reviving empty
        # must not leave a stale pre-crash store behind for a later
        # ``osd_id in osd_stores`` check to remount over recovered data
        store = self.osd_stores.pop(osd_id, None)
        if not with_store:
            store = None
        osd = OSDDaemon(osd_id, self.mon_addr, config=cfg, store=store)
        await osd.start()
        self.osds[osd_id] = osd
        self._arm_chaos_crash(osd)
        return osd

    async def restart_osd(self, osd_id: int) -> OSDDaemon:
        """Stop + start an OSD KEEPING its object store (daemon restart:
        the persisted pg log lets peering delta-resync instead of
        backfilling, reference OSD.cc:2556 superblock resume) AND its
        per-daemon config (injected fault options survive the bounce)."""
        old = self.osds.pop(osd_id)
        store = old.store
        await old.stop()
        osd = OSDDaemon(osd_id, self.mon_addr, config=old.config,
                        store=store)
        await osd.start()
        self.osds[osd_id] = osd
        self._arm_chaos_crash(osd)
        return osd

    async def add_osds(self, count: int, osds_per_host: int = 1,
                       timeout: float = 15.0) -> List[int]:
        """Elastic growth (graft-balance round 21): mint ``count`` new
        OSD ids + CRUSH hosts through the mon ('osd grow', one
        Incremental), boot daemons into them, and wait until the map
        shows them up — the live N->2N expansion primitive."""
        if not self.clients:
            await self.client()
        data = await self.clients[0].objecter.mon_command(
            {"prefix": "osd grow", "count": count,
             "osds_per_host": osds_per_host})
        new_ids = [int(o) for o in data["new_osds"]]
        await self.boot_osds(new_ids, timeout=timeout)
        return new_ids

    async def boot_osds(self, osd_ids: List[int],
                        timeout: float = 15.0) -> None:
        """Boot daemons into already-minted ids (the mgr reshape path
        mints them via 'balance grow'; this is the operator's side of
        the handshake) and wait until the mon map shows them up."""
        for o in osd_ids:
            factory = self.store_factory
            osd = OSDDaemon(o, self.mon_addr, config=self.config,
                            store=factory(o) if factory else None)
            await osd.start()
            self.osds[o] = osd
            self._arm_chaos_crash(osd)
        deadline = asyncio.get_event_loop().time() + timeout
        while asyncio.get_event_loop().time() < deadline:
            if all(self.mon.osdmap.osd_up[o] for o in osd_ids):
                return
            await asyncio.sleep(0.02)
        raise TimeoutError(f"grown osds never booted: {osd_ids}")

    async def remove_osd(self, osd_id: int,
                         timeout: float = 20.0) -> None:
        """Finish a drain: stop the daemon, wait for the mon to see it
        down, purge it from the maps.  The caller is responsible for
        having drained data first ('osd out' + wait-clean — the
        mgr Reshaper's drain op); this is the stop-and-purge tail."""
        if osd_id in self.osds:
            await self.kill_osd(osd_id)
        self.osd_configs.pop(osd_id, None)
        self.osd_stores.pop(osd_id, None)
        await self.wait_down(osd_id, timeout=timeout)
        if not self.clients:
            await self.client()
        await self.clients[0].objecter.mon_command(
            {"prefix": "osd purge", "id": osd_id, "sure": True})

    async def wait_for_epoch(self, epoch: int, timeout: float = 10.0) -> None:
        deadline = asyncio.get_event_loop().time() + timeout
        while asyncio.get_event_loop().time() < deadline:
            if all(o.osdmap is not None and o.osdmap.epoch >= epoch
                   for o in self.osds.values()):
                return
            await asyncio.sleep(0.02)
        raise TimeoutError(f"epoch {epoch} not reached")

    async def wait_down(self, osd_id: int, timeout: float = 20.0) -> None:
        deadline = asyncio.get_event_loop().time() + timeout
        while asyncio.get_event_loop().time() < deadline:
            if not self.mon.osdmap.osd_up[osd_id]:
                return
            await asyncio.sleep(0.05)
        raise TimeoutError(f"osd.{osd_id} never marked down")

    async def stop(self) -> None:
        await self.drain_blackbox()
        for c in self.clients:
            await c.shutdown()
        for d in (self.mdss or {}).values():
            await d.stop()
        if self.mds is not None and self.mds not in \
                (self.mdss or {}).values():
            await self.mds.stop()
        if self.mgr is not None:
            await self.mgr.stop()
        for osd in self.osds.values():
            await osd.stop()
        for m in self.mons:
            await m.stop()


def _fast_config() -> Config:
    """Test-speed timings (the vstart analog of ceph.conf overrides)."""
    return Config(
        osd_heartbeat_interval=0.1,
        osd_heartbeat_grace=1.5,
        mon_tick_interval=0.1,
        mon_osd_down_out_interval=2.0,
        mon_osd_min_down_reporters=1,
        mon_osd_beacon_grace=1.5,
        osd_recovery_delay_start=0.05,
        osd_client_op_timeout=5.0,
        # XLA first-compiles of codec shapes can take tens of seconds on a
        # loaded CPU; client retries must outlast them
        rados_osd_op_timeout=90.0,
        # batched data plane (round 11): vstart clusters run the sharded
        # dispatch + per-tick stripe-batch coalescing path — the plain
        # Config() zero-defaults remain the per-op bisection anchor
        osd_op_shards=2,
        osd_batch_tick_ops=16,
        # client-edge batching (round 18): the objecter coalesces a
        # tick's ops per (session, OSD) into MOSDOpBatch frames with
        # batched replies; objecter_batch_tick_ops=0 stays the per-op
        # frame anchor for bit-exactness and same-host A/B
        objecter_batch_tick_ops=16,
        # planar at rest (round 19): vstart clusters store EC shards as
        # packed bit-planes end-to-end; osd_ec_planar_at_rest=0 (the
        # plain Config() default) stays the byte-at-rest bit-exactness
        # anchor for bisection and same-session A/B
        osd_ec_planar_at_rest=1,
    )


async def start_cluster(n_osds: int = 3, osds_per_host: int = 1,
                        config: Optional[Config] = None,
                        store_factory=None, n_mons: int = 1,
                        with_mgr: bool = False,
                        mon_store_factory=None) -> Cluster:
    """Boot the mon quorum + OSDs and wait for everything up in the map.

    ``store_factory(osd_id) -> ObjectStore`` selects the backing store
    (default MemStore; pass a FileStore factory for a durable cluster —
    the vstart.sh --bluestore/--filestore switch analog).  ``n_mons`` > 1
    runs a Paxos quorum with leader election."""
    import pickle as _pickle

    config = config or _fast_config()
    if getattr(config, "race_check_enabled", 0):
        # arm the process-global write-after-read tracker (graft-race);
        # race_run installs its own tracker+shim pair, so only arm when
        # nothing is installed yet — a boot must not wipe a run's state
        from ceph_tpu.analysis import racecheck
        if not racecheck.TRACKER:
            racecheck.install(racecheck.from_config(config))
    n_hosts = (n_osds + osds_per_host - 1) // osds_per_host
    cmap, _ = build_hierarchy(n_hosts, osds_per_host, numrep=3)
    osdmap = OSDMap(cmap, max_osd=n_osds)
    # OSDs boot "down" until they report in (reference: superblock boot flow)
    for o in range(n_osds):
        osdmap.osd_up[o] = False
    map_blob = _pickle.dumps(osdmap)
    mons: List[Monitor] = []
    mon_addrs: List[tuple] = []
    for r in range(n_mons):
        mon = Monitor(_pickle.loads(map_blob), config=config, rank=r,
                      n_mons=n_mons,
                      store=mon_store_factory(r) if mon_store_factory
                      else None)
        mon_addrs.append(await mon.start())
        mons.append(mon)
    cluster = Cluster(mons=mons, osds={}, config=config,
                      mon_addrs=mon_addrs, store_factory=store_factory)
    cluster._initial_map_blob = map_blob
    for mon in mons:
        cluster._arm_blackbox(mon)
    if n_mons > 1:
        for mon in mons:
            mon.set_monmap(mon_addrs)
        await mons[0].begin_elections()
        await cluster.wait_for_leader()
    if with_mgr:
        cluster.mgr = MgrDaemon(cluster.mon_addr, config=config)
        cluster.mgr_addr = await cluster.mgr.start()
    for o in range(n_osds):
        osd = OSDDaemon(o, cluster.mon_addr, config=config,
                        store=store_factory(o) if store_factory else None)
        await osd.start()
        cluster.osds[o] = osd
        cluster._arm_chaos_crash(osd)
    deadline = asyncio.get_event_loop().time() + 10
    while asyncio.get_event_loop().time() < deadline:
        if all(cluster.mon.osdmap.osd_up[o] for o in range(n_osds)):
            break
        await asyncio.sleep(0.02)
    else:
        raise TimeoutError("OSDs never booted")
    await cluster.wait_for_epoch(cluster.mon.osdmap.epoch)
    return cluster


async def _main(n_osds: int) -> None:
    cluster = await start_cluster(n_osds)
    client = await cluster.client()
    status = await client.status()
    print(f"cluster up: {status}")
    pool = await client.pool_create("rbd", "replicated", pg_num=8, size=2)
    io = client.ioctx(pool)
    await io.write_full("hello", b"world")
    print("hello ->", await io.read("hello"))
    await cluster.stop()


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--osds", type=int, default=3)
    args = ap.parse_args()
    asyncio.run(_main(args.osds))
