"""rbd-mirror: journal-based asynchronous image replication.

Behavioral analog of the reference rbd-mirror daemon
(/root/reference/src/tools/rbd_mirror/ + src/journal/): images with the
journaling feature append every mutation to a per-image journal
(cls-atomic sequence allocation, cluster/objclass.py rbd_journal);
this daemon tails those journals and REPLAYS the events onto a peer
pool/cluster image (ImageReplayer::handle_replay analog), tracks its
committed position, and TRIMS the source journal behind it (the
reference's client-commit + object trim).

One-directional primary->secondary replication of all journaled images
in the source pool; the secondary image is created on first sight.
Failover = stop mirroring and promote (open the secondary read/write) —
the reference's promote/demote dance is an orchestration layer above
this replay core.
"""

from __future__ import annotations

import asyncio
import pickle
from typing import Dict, Optional

from ceph_tpu.cluster.rbd import RBD, Image


class MirrorDaemon:
    """Replays source-pool image journals onto the destination pool."""

    def __init__(self, src_ioctx, dst_ioctx, poll_interval: float = 0.1):
        self.src = RBD(src_ioctx)
        self.dst = RBD(dst_ioctx)
        self.poll = poll_interval
        # image -> committed (replayed + trimmed) journal position
        self.positions: Dict[str, int] = {}
        self._dst_images: Dict[str, Image] = {}
        self._task: Optional[asyncio.Task] = None
        self._stopped = False
        self.replayed = 0

    def start(self) -> None:
        self._task = asyncio.get_event_loop().create_task(self._run())

    async def stop(self) -> None:
        self._stopped = True
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass

    async def _run(self) -> None:
        while not self._stopped:
            try:
                await self.sync_once()
            except asyncio.CancelledError:
                raise
            except Exception:
                # hiccup OR poison entry: the daemon must outlive it —
                # a dead replay task is silent replication loss.  Count
                # so operators can see a stuck mirror.
                self.errors = getattr(self, "errors", 0) + 1
            await asyncio.sleep(self.poll)

    async def sync_once(self) -> int:
        """One replay pass over every journaled source image; returns
        the number of events applied."""
        n = 0
        for name in await self.src.list():
            img = await self.src.open(name)
            if not img.header.journaling:
                continue
            n += await self._replay_image(img)
        return n

    async def _replay_image(self, src_img: Image) -> int:
        name = src_img.header.name
        journal_oid = f"rbd_journal.{name}"
        try:
            omap = await self.src.ioctx.omap_get(journal_oid)
        except (IOError, FileNotFoundError):
            return 0
        pos = self.positions.get(name, 0)
        pending = sorted(
            (int(k), v) for k, v in omap.items()
            if not k.startswith("_") and int(k) > pos)
        if not pending:
            return 0
        dst_img = await self._dst_image(src_img)
        for seq, blob in pending:
            event = pickle.loads(blob)
            await self._apply(dst_img, event)
            pos = seq
            self.replayed += 1
        self.positions[name] = pos
        # commit: trim the source journal behind the replayed position
        await self.src.ioctx.execute(journal_oid, "rbd_journal", "trim",
                                     str(pos).encode())
        return len(pending)

    async def _dst_image(self, src_img: Image) -> Image:
        name = src_img.header.name
        img = self._dst_images.get(name)
        if img is not None:
            return img
        try:
            img = await self.dst.open(name)
        except FileNotFoundError:
            lay = src_img.header.layout
            await self.dst.create(name, size=src_img.header.size,
                                  stripe_unit=lay.stripe_unit,
                                  stripe_count=lay.stripe_count,
                                  object_size=lay.object_size)
            img = await self.dst.open(name)
        self._dst_images[name] = img
        return img

    async def _apply(self, dst_img: Image, event) -> None:
        kind = event[0]
        if kind == "write":
            _, offset, data = event
            if offset + len(data) > dst_img.header.size:
                await dst_img.resize(offset + len(data))
            await dst_img.write(offset, data)
        elif kind == "resize":
            await dst_img.resize(event[1])
        else:
            raise IOError(f"unreplayable journal event {kind!r}")
