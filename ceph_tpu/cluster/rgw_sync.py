"""RGW multisite sync: replay a peer zone's bucket-index logs.

Behavioral analog of the reference multisite machinery (src/rgw/
rgw_sync.cc metadata sync, rgw_data_sync.cc data sync): zones are
independent RGW deployments (here: separate pools or clusters); each
zone's gateway appends every index mutation to a per-bucket index log
(cls_rgw bilog) and registers changed buckets in a zone datalog.  An
RGWSyncAgent in the DESTINATION zone polls the source datalog, replays
bilog entries past its persisted per-bucket marker (incremental sync),
and falls back to a FULL bucket sync when its marker has been trimmed
out of the source's log window — the same full/incremental split as
RGWDataSyncCR.  Active-active pairs run one agent in each direction;
entries carry their ORIGIN zone, and an agent skips entries that
originated in its own zone, which is what terminates the replication
loop (the reference tags ops with zone short-ids for the same reason).

Conflict policy is last-writer-wins by entry order per bucket key —
the reference resolves with object mtime/epoch squashing; documented
simplification.
"""

from __future__ import annotations

import asyncio
import pickle
from typing import Dict, Optional

from ceph_tpu.cluster.rgw import RGW

SYNC_STATUS_OID = ".sync.status"   # per-source-zone markers (omap)


class RGWSyncAgent:
    """One-direction sync: pull changes from ``src`` into ``dst``
    (run a second agent for the reverse direction = active-active)."""

    def __init__(self, src: RGW, dst: RGW, interval: float = 0.5):
        self.src = src
        self.dst = dst
        self.interval = interval
        self._task: Optional[asyncio.Task] = None
        self._stopped = False
        self.stats = {"applied": 0, "full_syncs": 0, "skipped_echo": 0}
        # full-sync delete guard: dst-only keys younger than this are
        # kept (a peer's reverse agent may not have shipped them yet)
        self.full_sync_delete_grace = 60.0

    # -- markers (persisted in the DESTINATION zone) ------------------------

    async def _markers(self) -> Dict[str, int]:
        try:
            om = await self.dst.ioctx.omap_get(SYNC_STATUS_OID)
        except (FileNotFoundError, IOError):
            return {}
        pref = f"{self.src.zone}/"
        return {k[len(pref):]: int(v) for k, v in om.items()
                if k.startswith(pref)}

    async def _set_marker(self, bucket: str, seq: int) -> None:
        # omap_set auto-creates (the meta txn touches the object)
        await self.dst.ioctx.omap_set(
            SYNC_STATUS_OID,
            {f"{self.src.zone}/{bucket}": str(seq).encode()})

    # -- sync ---------------------------------------------------------------

    async def sync_once(self) -> int:
        """One pass over the source datalog; returns entries applied."""
        applied = 0
        datalog = await self.src.datalog()
        markers = await self._markers()
        # metadata sync-lite: peer buckets exist here too
        src_buckets = set(await self.src.list_buckets())
        dst_buckets = set(await self.dst.list_buckets())
        for b in src_buckets - dst_buckets:
            try:
                await self.dst.create_bucket(b)
            except FileExistsError:
                pass
        for bucket, head in datalog.items():
            marker = markers.get(bucket, 0)
            if head <= marker:
                continue
            tail, _ = await self.src.bilog_window(bucket)
            if marker < tail:
                applied += await self._full_sync(bucket)
                marker = tail
            applied += await self._incremental(bucket, marker)
        return applied

    async def _incremental(self, bucket: str, marker: int) -> int:
        n = 0
        last = None
        for seq, e in await self.src.bilog_entries(bucket, marker):
            if e.get("origin") == self.dst.zone:
                # our own change reflected back: consume without applying
                self.stats["skipped_echo"] += 1
            else:
                await self._apply(bucket, e)
                n += 1
            last = seq
        if last is not None:
            # ONE marker write per pass: _apply is idempotent under
            # re-replay, so a crash mid-pass only re-applies this page
            await self._set_marker(bucket, last)
        return n

    async def _apply(self, bucket: str, e: Dict) -> None:
        key = e["key"]
        if e["op"] == "put":
            try:
                meta, data = await self.src.get_object(bucket, key)
            except FileNotFoundError:
                return  # deleted again since; a later entry covers it
            await self.dst.put_object(bucket, key, data, meta=meta,
                                      origin=e.get("origin",
                                                   self.src.zone))
        elif e["op"] == "delete":
            try:
                await self.dst.delete_object(
                    bucket, key, origin=e.get("origin", self.src.zone))
            except FileNotFoundError:
                pass
        self.stats["applied"] += 1

    async def _full_sync(self, bucket: str) -> int:
        """Marker fell out of the source log window: reconcile the whole
        bucket against the source listing (reference full-sync shard
        sweep) — upserting changed objects AND deleting destination keys
        the source no longer has (their delete entries were trimmed)."""
        self.stats["full_syncs"] += 1
        n = 0
        marker = ""
        src_keys = set()
        while True:
            res = await self.src.list_objects(bucket, marker=marker,
                                              max_keys=256)
            for meta in res.keys:
                src_keys.add(meta.key)
                cur = None
                try:
                    cur = await self.dst.head_object(bucket, meta.key)
                except FileNotFoundError:
                    pass
                if cur is None or cur.etag != meta.etag:
                    _, data = await self.src.get_object(bucket, meta.key)
                    await self.dst.put_object(
                        bucket, meta.key, data, meta=meta,
                        origin=self.src.zone)
                    n += 1
            if not res.is_truncated:
                break
            marker = res.next_marker
        # deletes: reconcile dst-only keys, but NEVER recent local writes
        # (an active-active peer's reverse agent may not have shipped
        # them to the source yet — the reference squashes by object
        # version; we guard by mtime, documented simplification)
        import time as _time

        grace = _time.time() - self.full_sync_delete_grace
        dres = await self.dst.list_objects(bucket, max_keys=1_000_000)
        for meta in dres.keys:
            if meta.key not in src_keys and meta.mtime < grace:
                try:
                    await self.dst.delete_object(bucket, meta.key,
                                                 origin=self.src.zone)
                    n += 1
                except FileNotFoundError:
                    pass
        return n

    # -- daemon -------------------------------------------------------------

    def start(self) -> None:
        self._task = asyncio.get_event_loop().create_task(self._loop())

    async def _loop(self) -> None:
        while not self._stopped:
            try:
                await self.sync_once()
            except Exception:
                # transient (peer down); next tick retries — counted so
                # a permanently-failing agent is visible in its stats
                self.stats["errors"] = self.stats.get("errors", 0) + 1
            await asyncio.sleep(self.interval)

    async def stop(self) -> None:
        self._stopped = True
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
