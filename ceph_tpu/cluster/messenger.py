"""Async messenger: Connection / Dispatcher / sessions over asyncio TCP.

Structural mirror of the reference messenger abstraction (src/msg/
Messenger.h, Dispatcher.h; AsyncMessenger event loops): entity-named
endpoints, per-peer Connections with ordered delivery and reconnect,
dispatchers receiving typed messages.  Transport is asyncio TCP on
loopback (the reference's tier-3 standalone tests run the same way:
N daemons x 1 host over real sockets).  Frames are length-prefixed and
typed: ordinary messages are pickles — an internal trust boundary, like
the reference's cephx-signed native encoding is within a cluster —
while the cephx handshake frames use FIXED struct encodings so that no
unauthenticated byte ever reaches the deserializer (in cephx mode, data
frames on a connection without a session key are rejected outright).

Integrity (reference cephx message signing, src/auth/cephx/): when the
messenger holds a cluster secret, every frame carries a truncated
HMAC-SHA256 over the payload; receivers verify before unpickling and
reset the connection on mismatch, so a byte-flipped or forged frame can
never reach a dispatcher.  auth "none" (no secret) stays the default,
like the reference's auth_supported=none dev mode.

Reliability (reference AsyncConnection reconnect/replay semantics):
outgoing traffic runs over per-peer SESSIONS with monotonically
increasing sequence numbers; sent frames stay buffered until the peer
acks them, and a dropped TCP connection is transparently re-opened with
the unacked tail replayed IN ORDER.  Delivery is therefore ordered
at-least-once — handlers are idempotent by design (absolute-offset
writes, versioned log appends), exactly like the reference's lossless
osd-osd policy replaying out_q after a session reset.
"""

from __future__ import annotations

import asyncio
import itertools
import pickle
import struct
import hmac as _hmac
import hashlib
import time as _time

from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Set, Tuple

from ceph_tpu.cluster.optracker import mark_current
from ceph_tpu.utils.lockdep import DepLock

Addr = Tuple[str, int]

_SID = itertools.count(1)

# stream buffer limit: asyncio's 64 KiB default pauses/resumes the
# transport several times inside EVERY 1 MiB data frame (flow-control
# churn per sub-write); sized to hold a whole large frame.  Socket
# buffers get the same treatment so a burst of shard sub-writes drains
# in few syscalls (TCP_NODELAY is asyncio's default already).
_STREAM_LIMIT = 4 << 20
_SOCK_BUF = 2 << 20


def _tune_socket(writer) -> None:
    import socket as _socket

    sock = writer.get_extra_info("socket")
    if sock is None:
        return
    try:
        sock.setsockopt(_socket.SOL_SOCKET, _socket.SO_SNDBUF, _SOCK_BUF)
        sock.setsockopt(_socket.SOL_SOCKET, _socket.SO_RCVBUF, _SOCK_BUF)
    except OSError:  # pragma: no cover - exotic transports
        pass


@dataclass(frozen=True)
class EntityName:
    type: str  # mon | osd | client | mgr
    num: int

    def __str__(self):
        return f"{self.type}.{self.num}"


@dataclass
class Message:
    """Base message; src/seq/sid are stamped by the sending messenger.

    ``trace`` is the op-lifecycle trace header (round 6 telemetry): a
    {"id", "events": [(name, wall_ts), ...]} dict minted by the objecter
    and stamped by each messenger hop, absorbed into the receiving
    daemon's TrackedOp so dump_historic_ops shows the op's cross-daemon
    timeline (reference: the OpRequest's event list + blkin-style trace
    propagation)."""

    src: Optional[EntityName] = field(default=None, init=False)
    seq: int = field(default=0, init=False)
    sid: int = field(default=0, init=False)
    trace: Optional[dict] = field(default=None, init=False)


@dataclass
class _MsgAck(Message):
    """Transport-level ack: trims the sender's replay buffer."""

    acked: int = 0


@dataclass
class _MsgAuth(Message):
    """Connection authorizer (cephx mode): MUST be the first frame on a
    connection; carries the sealed ticket + session-key possession proof
    (reference CephXAuthorizer in the connection handshake)."""

    authorizer: bytes = b""


@dataclass
class _MsgAuthRequest(Message):
    """Client -> mon ticket request (reference CEPH_AUTH_CEPHX
    MAuth): entity + proof of the per-entity key."""

    entity: str = ""
    nonce: bytes = b""
    proof: bytes = b""


@dataclass
class _MsgAuthReply(Message):
    """Mon -> client: sealed ticket + session key sealed under the
    entity key (result != 0 -> refused)."""

    result: int = 0
    ticket_blob: bytes = b""
    sealed_key: bytes = b""
    ttl: float = 3600.0
    error: str = ""


class _Session:
    """Per-peer outgoing session: seq numbering + unacked replay buffer
    (reference AsyncConnection out_seq/out_q)."""

    MAX_UNACKED = 512

    def __init__(self):
        self.conn: Optional["Connection"] = None
        self.seq = 0
        self.unacked: "OrderedDict[int, bytes]" = OrderedDict()
        self.overflowed = False
        # set by a chaos frame drop: NO later frame may go out until the
        # tail is replayed — the peer's acks are CUMULATIVE (ack of N
        # trims everything <= N), which is only sound while delivery is
        # in-order, so a skipped frame must block the session until
        # retransmission restores order
        self.needs_replay = False
        # unique attribute name on purpose: graftlint's static lock
        # resolver binds attr -> lock name, and PGState already owns
        # the bare attr `lock`
        self.order_lock = DepLock("messenger.session")

    def buffer(self, seq: int, frame: bytes) -> None:
        self.unacked[seq] = frame
        while len(self.unacked) > self.MAX_UNACKED:
            # cannot trim silently and still promise at-least-once: mark
            # the session broken so the next reconnect FAILS loudly
            # instead of replaying an incomplete tail
            self.overflowed = True
            self.unacked.popitem(last=False)

    def ack(self, seq: int) -> None:
        for s in [s for s in self.unacked if s <= seq]:
            del self.unacked[s]
        if not self.unacked:
            self.overflowed = False  # fully acked: contract restored


class Connection:
    def __init__(self, messenger: "Messenger", reader, writer,
                 peer: Optional[EntityName] = None,
                 peer_addr: Optional[Addr] = None):
        self.messenger = messenger
        self.reader = reader
        self.writer = writer
        self.peer = peer
        self.peer_addr = peer_addr
        self._send_lock = DepLock("messenger.conn_send")
        self._seq = 0
        self.closed = False
        # cephx session state (set by the authorizer handshake):
        # subsequent frames both ways sign with the session key, and
        # dispatchers consult peer_caps for authorization
        self.session_key: Optional[bytes] = None
        self.peer_entity: Optional[str] = None
        self.peer_caps: Optional[Dict[str, str]] = None

    def _sign_key(self) -> Optional[bytes]:
        return self.session_key if self.session_key is not None \
            else self.messenger.secret

    async def send(self, msg: Message) -> None:
        msg.src = self.messenger.name
        async with self._send_lock:
            self._seq += 1
            msg.seq = self._seq
            if msg.trace is not None:
                # hop stamp for replies riding raw connections (the
                # reply-leg half of op attribution; send_message stamps
                # session traffic the same way)
                msg.trace.setdefault("events", []).append(
                    (f"msgr:{self.messenger.name}:send", _time.time()))
            hs = _encode_hs(msg)
            if hs is not None:
                # handshake: fixed struct, pre-session, unsigned
                bufs = [struct.pack("<I", len(hs)), hs]
            else:
                payload = pickle.dumps(msg)
                secret = self._sign_key()
                sig = _sign(secret, payload) if secret is not None \
                    else b""
                # zero-copy framing: header/payload/signature go to the
                # transport as separate buffers — a 1 MiB payload is
                # never re-materialized into a fresh frame bytes
                bufs = [struct.pack("<IB",
                                    1 + len(payload) + len(sig),
                                    _FT_MSG), payload]
                if sig:
                    bufs.append(sig)
            try:
                for b in bufs:
                    self.writer.write(b)
                await self.writer.drain()
            except (ConnectionError, RuntimeError):
                self.closed = True
                raise

    async def close(self) -> None:
        self.closed = True
        try:
            self.writer.close()
            await self.writer.wait_closed()
        except (ConnectionError, OSError, RuntimeError,
                asyncio.TimeoutError):
            pass  # best-effort close of an already-dying transport


class Dispatcher:
    async def ms_dispatch(self, conn: Connection, msg: Message) -> bool:
        """Return True if handled."""
        return False

    async def ms_handle_reset(self, conn: Connection) -> None:
        ...


class Throttle:
    """Byte-budget backpressure (reference Throttle bound to the
    messenger policies, src/ceph_osd.cc:511-525 client-throttler): a
    reader acquires its frame's bytes before dispatch and releases
    after; when the budget is exhausted the reader WAITS — it stops
    draining its socket, so TCP backpressure propagates to the peer
    instead of the daemon queueing unboundedly."""

    def __init__(self, max_bytes: int):
        self.max = max_bytes
        self.cur = 0
        self.waiting = 0
        self._cond = asyncio.Condition()

    async def acquire(self, n: int) -> bool:
        """Returns True when the caller had to WAIT for budget — the
        signal the read loop stamps into the op's trace header so
        throttle wait shows up in per-stage attribution."""
        n = min(n, self.max)  # a single oversized frame must not wedge
        waited = False
        async with self._cond:
            self.waiting += 1
            try:
                while self.cur + n > self.max:
                    waited = True
                    await self._cond.wait()
            finally:
                self.waiting -= 1
            self.cur += n
        return waited

    async def release(self, n: int) -> None:
        n = min(n, self.max)
        async with self._cond:
            self.cur = max(0, self.cur - n)
            self._cond.notify_all()


@dataclass
class Policy:
    """Per-peer-type connection policy (reference Messenger::Policy):
    ``lossy`` sessions do NOT replay their unacked tail across a reset —
    the send fails and the peer re-requests (stateless client policy;
    enforced in _reconnect_replay); ``throttle`` bounds bytes
    concurrently in dispatch from peers of this type (backpressure in
    _read_loop)."""

    lossy: bool = False
    throttle: Optional[Throttle] = None


SIG_LEN = 16

# frame-type bytes: every frame is <u32 len><type><body>.  Type 0 is a
# pickled Message (signed when a key is bound); types 1-3 are the cephx
# handshake in FIXED struct encodings, so no unauthenticated byte ever
# reaches the pickle deserializer (the r4 advisor's high finding: the
# old handshake pickled first and authenticated after).
_FT_MSG, _FT_AUTH, _FT_AUTH_REQ, _FT_AUTH_REPLY = 0, 1, 2, 3


def _sign(secret: bytes, payload: bytes) -> bytes:
    return _hmac.new(secret, payload, hashlib.sha256).digest()[:SIG_LEN]


def _encode_hs(msg: Message) -> Optional[bytes]:
    """Handshake frame body (type byte + fixed struct), or None for
    ordinary messages."""
    if isinstance(msg, _MsgAuth):
        return bytes([_FT_AUTH]) + msg.authorizer
    if isinstance(msg, _MsgAuthRequest):
        e = msg.entity.encode()
        return (bytes([_FT_AUTH_REQ]) + struct.pack("<H", len(e)) + e +
                struct.pack("<B", len(msg.nonce)) + msg.nonce +
                struct.pack("<B", len(msg.proof)) + msg.proof)
    if isinstance(msg, _MsgAuthReply):
        err = msg.error.encode()
        return (bytes([_FT_AUTH_REPLY]) +
                struct.pack("<idII", msg.result, msg.ttl,
                            len(msg.ticket_blob), len(msg.sealed_key)) +
                msg.ticket_blob + msg.sealed_key +
                struct.pack("<H", len(err)) + err)
    return None


def _decode_hs(ftype: int, body: bytes) -> Message:
    try:
        if ftype == _FT_AUTH:
            return _MsgAuth(authorizer=body)
        if ftype == _FT_AUTH_REQ:
            (el,) = struct.unpack_from("<H", body)
            off = 2
            entity = body[off:off + el].decode()
            off += el
            nl = body[off]
            nonce = body[off + 1:off + 1 + nl]
            off += 1 + nl
            pl = body[off]
            proof = body[off + 1:off + 1 + pl]
            if off + 1 + pl != len(body):
                raise ValueError("trailing bytes")
            return _MsgAuthRequest(entity=entity, nonce=nonce, proof=proof)
        if ftype == _FT_AUTH_REPLY:
            result, ttl, tl, kl = struct.unpack_from("<idII", body)
            off = struct.calcsize("<idII")
            blob = body[off:off + tl]
            key = body[off + tl:off + tl + kl]
            off += tl + kl
            (el,) = struct.unpack_from("<H", body, off)
            err = body[off + 2:off + 2 + el].decode()
            if off + 2 + el != len(body) or len(blob) != tl or len(key) != kl:
                raise ValueError("trailing bytes")
            return _MsgAuthReply(result=result, ttl=ttl, ticket_blob=blob,
                                 sealed_key=key, error=err)
    except (struct.error, IndexError, UnicodeDecodeError, ValueError) as e:
        raise ConnectionError(f"malformed handshake frame: {e}")
    raise ConnectionError(f"unknown frame type {ftype}")


class Messenger:
    def __init__(self, name: EntityName, secret: bytes = None, auth=None,
                 config=None):
        self.name = name
        self.secret = secret
        # cephx mode (auth = auth.CephxContext): per-connection session
        # keys replace the global secret; secret must be None then
        self.auth = auth
        if auth is not None:
            self.secret = None
        # chaos net injector (ceph_tpu/chaos/net.py), rebuilt whenever
        # the owning daemon's chaos_net_* options change (injectargs
        # seam, like the reference's ms_inject_socket_failures).  None
        # when disabled: the send path pays one `is None` test.
        self.config = config
        self.chaos = None
        if config is not None:
            config.add_observer(self._chaos_observer)
            self._chaos_reconfig()
        # mon-side hook: callable(_MsgAuthRequest) -> _MsgAuthReply
        self.auth_server = None
        self.sid = next(_SID)
        self.dispatchers: List[Dispatcher] = []
        self._server: Optional[asyncio.base_events.Server] = None
        self._out: Dict[Addr, Connection] = {}
        self._sessions: Dict[Addr, _Session] = {}
        self._accepted: List[Connection] = []
        # live-task registry: completed tasks self-discard, or a chaos
        # run would grow one dead Task per dropped/reordered frame for
        # the daemon's lifetime
        self._tasks: Set[asyncio.Task] = set()
        self._auth_waiters: Dict[int, asyncio.Future] = {}
        self._closing = False
        self.my_addr: Optional[Addr] = None
        # per-peer-type policies (reference Messenger::set_policy, bound
        # in ceph_osd.cc:511-525); key None = default
        self._policies: Dict[Optional[str], Policy] = {}

    def _chaos_observer(self, name: str, value) -> None:
        if name.startswith("chaos_net") or name == "chaos_seed":
            self._chaos_reconfig()

    def _chaos_reconfig(self) -> None:
        from ceph_tpu.chaos.net import NetInjector

        keep = self.chaos.partitions if self.chaos is not None else None
        self.chaos = NetInjector.from_config(
            self.config, str(self.name), keep_partitions=keep)

    def set_policy(self, peer_type: Optional[str], policy: Policy) -> None:
        """Bind a Policy for connections whose peer entity has ``type``
        (e.g. 'client', 'osd'); ``None`` sets the default."""
        self._policies[peer_type] = policy

    def policy_for(self, conn: "Connection") -> Optional[Policy]:
        ptype = conn.peer.type if conn.peer is not None else None
        return self._policies.get(ptype, self._policies.get(None))

    def add_dispatcher(self, d: Dispatcher) -> None:
        self.dispatchers.append(d)

    async def bind(self, host: str = "127.0.0.1", port: int = 0) -> Addr:
        self._server = await asyncio.start_server(
            self._accept, host, port, limit=_STREAM_LIMIT)
        self.my_addr = self._server.sockets[0].getsockname()[:2]
        return self.my_addr

    async def _accept(self, reader, writer) -> None:
        _tune_socket(writer)
        conn = Connection(self, reader, writer)
        if self._closing:
            # a peer raced our shutdown: refuse, or the read loop would
            # keep Server.wait_closed() (which since py3.12 awaits every
            # handler) hanging until the PEER closes — a distributed
            # shutdown deadlock when that peer stops after us
            await conn.close()
            return
        self._accepted.append(conn)
        task = asyncio.current_task()
        if task is not None:
            self._track(task)
        await self._read_loop(conn)

    async def _read_loop(self, conn: Connection) -> None:
        try:
            while True:
                hdr = await conn.reader.readexactly(4)
                (n,) = struct.unpack("<I", hdr)
                if n < 1:
                    raise ConnectionError("empty frame")
                frame = await conn.reader.readexactly(n)
                # memoryview slicing: verification, signature strip, and
                # unpickle all run on views of the one received buffer —
                # no per-frame payload re-materialization (round 11)
                ftype, payload = frame[0], memoryview(frame)[1:]
                if ftype != _FT_MSG:
                    # handshake frames: fixed struct decode, no pickle
                    # (tiny; decoded from a plain bytes copy)
                    msg = _decode_hs(ftype, bytes(payload))
                    if self.auth is None or not await \
                            self._handle_auth_frame(conn, msg):
                        raise ConnectionError(
                            f"unexpected handshake frame type {ftype}")
                    continue
                if self.auth is not None and conn.session_key is None:
                    # cephx mode: nothing but the handshake may ride an
                    # unauthenticated connection — reject BEFORE any
                    # deserialization
                    raise ConnectionError("unauthenticated data frame")
                verify_key = conn.session_key if conn.session_key \
                    is not None else self.secret
                if verify_key is not None:
                    # verify BEFORE unpickling: unauthenticated bytes
                    # must never reach the deserializer
                    if len(payload) < SIG_LEN or not _hmac.compare_digest(
                            _sign(verify_key, payload[:-SIG_LEN]),
                            payload[-SIG_LEN:]):
                        raise ConnectionError("bad message signature")
                    payload = payload[:-SIG_LEN]
                msg = pickle.loads(payload)
                if conn.peer is None:
                    conn.peer = msg.src
                if msg.trace is not None:
                    # receive-side hop stamp: the trace header records
                    # when this endpoint took the message off the wire
                    # (arrival, before any dispatch queueing) — the
                    # "wire" stage boundary in op attribution
                    msg.trace.setdefault("events", []).append(
                        (f"msgr:{self.name}:recv", _time.time()))
                if isinstance(msg, _MsgAck):
                    sess = self._sessions.get(conn.peer_addr)
                    if sess is not None:
                        sess.ack(msg.acked)
                    continue
                if msg.sid:
                    # session traffic: ack so the sender can trim replay
                    try:
                        await conn.send(_MsgAck(acked=msg.seq))
                    except (ConnectionError, OSError, RuntimeError):
                        pass
                pol = self.policy_for(conn)
                thr = pol.throttle if pol is not None else None
                if thr is not None:
                    # byte-budget backpressure: waiting here stops this
                    # socket's drain, pushing TCP backpressure to the peer
                    if await thr.acquire(n) and msg.trace is not None:
                        # the wait was real: stamp it so attribution
                        # books the delta as throttle_wait, not wire
                        msg.trace.setdefault("events", []).append(
                            (f"throttle:{self.name}:acquired",
                             _time.time()))
                    # dispatch handoff seam: a dispatcher that QUEUES the
                    # message (the OSD's ShardedOpWQ analog) takes
                    # ownership by setting _throttle_held and releases
                    # after serving — the cap then bounds bytes in
                    # dispatch, not merely in enqueue
                    msg._throttle = thr
                    msg._throttle_bytes = n
                try:
                    for d in self.dispatchers:
                        if await d.ms_dispatch(conn, msg):
                            break
                finally:
                    if thr is not None and \
                            not getattr(msg, "_throttle_held", False):
                        await thr.release(n)
        except (asyncio.IncompleteReadError, ConnectionError,
                asyncio.CancelledError):
            # actually CLOSE the socket (not just flag it): a signature
            # mismatch must tear the TCP stream down so the peer's session
            # sees the failure and reconnect+replay engages, instead of
            # writing into a blackholed socket until overflow
            await conn.close()
            for d in self.dispatchers:
                try:
                    await d.ms_handle_reset(conn)
                except Exception:
                    # a broken reset hook must not kill the read loop,
                    # but it is a BUG in the dispatcher — surface it
                    import logging

                    logging.getLogger("ceph_tpu.msgr").exception(
                        "%s: ms_handle_reset hook failed", self.name)

    async def _handle_auth_frame(self, conn: Connection, msg) -> bool:
        """cephx transport frames (already struct-decoded — the pickle
        deserializer never sees unauthenticated bytes; the authorizer's
        pickled interior sits behind the sealed ticket's MAC)."""
        from ceph_tpu.cluster import auth as authmod

        if isinstance(msg, _MsgAuth):
            if self.auth.master is None:
                raise ConnectionError("no master key to verify authorizer")
            try:
                t = authmod.verify_authorizer(self.auth.master,
                                              msg.authorizer)
            except ValueError as e:
                # malformed/forged authorizer must tear the connection
                # down through the normal reset path (close +
                # ms_handle_reset), not kill the read-loop task
                raise ConnectionError(f"bad authorizer: {e}")
            conn.session_key = t.session_key
            conn.peer_entity = t.entity
            conn.peer_caps = t.caps
            return True
        if isinstance(msg, _MsgAuthRequest):
            if self.auth_server is None:
                raise ConnectionError("not an auth server")
            reply = self.auth_server(msg)
            await conn.send(reply)
            return True
        if isinstance(msg, _MsgAuthReply):
            fut = self._auth_waiters.pop(id(conn), None)
            if fut is not None and not fut.done():
                fut.set_result(msg)
            return True
        return False

    async def cephx_bootstrap(self, mon_addr: Addr) -> None:
        """Client ticket bootstrap (reference MAuth round-trip): prove
        the entity key to a monitor, adopt the returned ticket."""
        import os as _os

        from ceph_tpu.cluster import auth as authmod

        nonce = _os.urandom(16)
        proof = _hmac.new(self.auth.entity_secret,
                          b"authreq:" + self.auth.entity.encode() + nonce,
                          hashlib.sha256).digest()[:SIG_LEN]
        reader, writer = await asyncio.open_connection(
            mon_addr[0], mon_addr[1], limit=_STREAM_LIMIT)
        conn = Connection(self, reader, writer, peer_addr=tuple(mon_addr))
        fut = asyncio.get_event_loop().create_future()
        self._auth_waiters[id(conn)] = fut
        task = asyncio.get_event_loop().create_task(self._read_loop(conn))
        self._track(task)
        try:
            await conn.send(_MsgAuthRequest(entity=self.auth.entity,
                                            nonce=nonce, proof=proof))
            reply = await asyncio.wait_for(fut, timeout=10.0)
            if reply.result != 0:
                raise PermissionError(
                    f"auth refused for {self.auth.entity}: {reply.error}")
            self.auth.adopt(reply.ticket_blob, reply.sealed_key,
                            ttl_hint=getattr(reply, "ttl", 3600.0))
        finally:
            self._auth_waiters.pop(id(conn), None)
            await conn.close()

    async def connect(self, addr: Addr) -> Connection:
        if self.chaos is not None:
            # asymmetric partition: OUR connects to that peer fail like
            # a blackholed TCP connect; their path to us is untouched
            self.chaos.check_connect(addr)
        conn = self._out.get(tuple(addr))
        if conn is not None and not conn.closed:
            return conn
        reader, writer = await asyncio.open_connection(
            addr[0], addr[1], limit=_STREAM_LIMIT)
        _tune_socket(writer)
        conn = Connection(self, reader, writer, peer_addr=tuple(addr))
        if self.auth is not None:
            # authorizer-first (reference connection handshake): present
            # the ticket before any session traffic; the session key
            # signs everything after
            from ceph_tpu.cluster import auth as authmod

            self.auth.ensure_ticket()
            await conn.send(_MsgAuth(authorizer=authmod.make_authorizer(
                self.auth.ticket_blob, self.auth.session_key)))
            conn.session_key = self.auth.session_key
        self._out[tuple(addr)] = conn
        task = asyncio.get_event_loop().create_task(self._read_loop(conn))
        self._track(task)
        return conn

    async def send_message(self, msg: Message, addr: Addr) -> None:
        """Session send: ordered at-least-once with reconnect + replay of
        the unacked tail (reference AsyncConnection replay)."""
        addr = tuple(addr)
        sess = self._sessions.get(addr)
        if sess is None:
            sess = self._sessions[addr] = _Session()
        async with sess.order_lock:
            sess.seq += 1
            msg.src = self.name
            msg.seq = sess.seq
            msg.sid = self.sid
            if msg.trace is not None:
                # messenger hop stamp: the trace header records when this
                # endpoint put the message on the wire
                msg.trace.setdefault("events", []).append(
                    (f"msgr:{self.name}:send", _time.time()))
            if self.chaos is not None:
                # batch-frame faults mutate the message BEFORE pickling
                # so the buffered replay frame carries the same partial
                # tick — the item loss is real, not racing replay
                self.chaos.mutate_batch(msg)
            payload = pickle.dumps(msg)
            # buffer the UNSIGNED payload and sign at write time with the
            # connection's key: a cephx ticket renewal mints a new session
            # key for NEW connections, while frames replayed over a fresh
            # connection must carry the fresh key's signature (signing at
            # buffer time would wedge the replay after every renewal)
            sess.buffer(sess.seq, payload)
            fate = None
            if self.chaos is not None:
                fate = self.chaos.on_frame(addr)
                if fate.delay:
                    await asyncio.sleep(fate.delay)
                if fate.drop:
                    # drop + socket failure (reference
                    # ms_inject_socket_failures): the frame stays in
                    # unacked, the connection dies, and the session is
                    # GATED (needs_replay) until a retransmission timer
                    # or the next send replays the tail in order —
                    # packet loss under retransmission, not silent
                    # erasure (under a partition the replayed reconnect
                    # fails too and the loss is real)
                    sess.needs_replay = True
                    old = self._out.pop(addr, None)
                    if old is not None:
                        await old.close()
                    self._track(
                        asyncio.get_event_loop().create_task(
                            self._replay_later(sess, addr,
                                               fate.retransmit)))
                    return
                if fate.reorder and not sess.needs_replay:
                    # a gated session must not leak frames around the
                    # replay: the peer's acks are cumulative, so a late
                    # frame delivered past the gate would trim the
                    # still-undelivered dropped frame from the replay
                    # buffer — silent erasure, not reordering
                    self._track(
                        asyncio.get_event_loop().create_task(
                            self._late_send(sess, addr, sess.seq,
                                            payload, fate.reorder)))
                    return
            try:
                if sess.needs_replay:
                    # a chaos drop gated this session: replay the whole
                    # unacked tail (this frame is buffered, so it rides
                    # the replay) before anything newer goes out
                    await self._reconnect_replay(sess, addr)
                    return
                conn = await self.connect(addr)
                bufs = self._frame_bufs(conn, payload)
                self._write_frame(conn, bufs)
                if fate is not None and fate.dup:
                    self._write_frame(conn, bufs)  # duplicate delivery:
                    # handlers are idempotent by contract — prove it
                await conn.writer.drain()
                # flush boundary on the CURRENT op's timeline (sub-op
                # fan-out runs under the op context; no-op otherwise)
                mark_current("msgr:flushed")
                if fate is not None and fate.reset:
                    # injected session reset AFTER the bytes left: the
                    # peer sees a clean close; our next send reconnects
                    # and replays the unacked tail
                    self._out.pop(addr, None)
                    await conn.close()
            except (ConnectionError, OSError, RuntimeError):
                if self._closing:
                    raise
                await self._reconnect_replay(sess, addr)

    async def _replay_later(self, sess: _Session, addr: Addr,
                            delay: float) -> None:
        """Chaos retransmission timer: replay the session's unacked tail
        after a dropped frame gated the session.  A failure here leaves
        the gate set — the next send retries the replay."""
        await asyncio.sleep(delay)
        if self._closing or not sess.needs_replay:
            return
        try:
            async with sess.order_lock:
                if sess.needs_replay:
                    await self._reconnect_replay(sess, addr, retries=1)
        except (ConnectionError, OSError, RuntimeError):
            pass

    async def _late_send(self, sess: _Session, addr: Addr, seq: int,
                         payload: bytes, delay: float) -> None:
        """Chaos reorder: this frame goes out AFTER traffic that was
        sent later (ordered-delivery violation, deliberately).  A
        failure here is a DROP, and by then the cumulative ack of later
        traffic may already have trimmed the frame from the replay
        buffer — so it is re-buffered (in seq order) and the session
        gated, turning the failure into packet loss under
        retransmission rather than silent erasure."""
        await asyncio.sleep(delay)
        try:
            conn = await self.connect(addr)
            self._write_frame(conn, self._frame_bufs(conn, payload))
            await conn.writer.drain()
        except (ConnectionError, OSError, RuntimeError):
            if self._closing:
                return
            async with sess.order_lock:
                if seq not in sess.unacked:
                    sess.unacked[seq] = payload
                    for s in sorted(sess.unacked):
                        sess.unacked.move_to_end(s)
                sess.needs_replay = True
            self._track(
                asyncio.get_event_loop().create_task(
                    self._replay_later(sess, addr, delay)))

    def _track(self, task: asyncio.Task) -> asyncio.Task:
        from ceph_tpu.utils.tasks import track_task

        return track_task(self._tasks, task)

    def _frame_bufs(self, conn: Connection, payload: bytes) -> list:
        """Frame as a buffer list (header, payload, signature), written
        sequentially: large payloads pass straight to the transport
        instead of being copied into a fresh frame bytes per hop (the
        round-11 zero-copy framing; replay buffers still hold only the
        single pickled payload)."""
        key = conn._sign_key()
        sig = _sign(key, payload) if key is not None else b""
        bufs = [struct.pack("<IB", 1 + len(payload) + len(sig),
                            _FT_MSG), payload]
        if sig:
            bufs.append(sig)
        return bufs

    @staticmethod
    def _write_frame(conn: Connection, bufs: list) -> None:
        for b in bufs:
            conn.writer.write(b)

    async def _reconnect_replay(self, sess: _Session, addr: Addr,
                                retries: int = 3) -> None:
        """Re-open the peer connection and replay every unacked frame in
        order; raises when the peer stays unreachable."""
        if sess.overflowed:
            # frames were evicted while unacked: an in-order replay is no
            # longer possible — fail the send and reset the session so
            # future traffic starts from a clean (acked-empty) state
            sess.unacked.clear()
            sess.overflowed = False
            sess.needs_replay = False
            raise ConnectionError(
                f"session to {addr} lost unacked frames (overflow); "
                "cannot replay")
        old_conn = self._out.get(addr)
        if old_conn is not None:
            pol = self.policy_for(old_conn)
            if pol is not None and pol.lossy:
                # lossy peer policy (reference stateless client policy):
                # no replay across a reset — drop the unacked tail and
                # surface the failure so the caller re-requests
                sess.unacked.clear()
                sess.needs_replay = False
                raise ConnectionError(
                    f"lossy session to {addr} reset; not replaying")
        last: Optional[Exception] = None
        # capped exponential backoff with jitter between attempts (was:
        # immediate linear retry) — seeded via chaos_seed so scenario
        # retry timing replays with the fault schedule
        from ceph_tpu.utils.backoff import ExpBackoff

        backoff = ExpBackoff(base=0.02, cap=0.5, rng=self._backoff_rng())
        for attempt in range(retries):
            old = self._out.pop(addr, None)
            if old is not None:
                await old.close()
            try:
                conn = await self.connect(addr)
                for payload in sess.unacked.values():
                    self._write_frame(conn, self._frame_bufs(conn,
                                                             payload))
                await conn.writer.drain()
                sess.needs_replay = False
                return
            except (ConnectionError, OSError, RuntimeError) as e:
                last = e
                await asyncio.sleep(backoff.next())
        # keep the session gated while undelivered frames remain: a later
        # send must replay them BEFORE anything newer, or the peer's
        # cumulative acks could trim a frame it never saw
        sess.needs_replay = bool(sess.unacked)
        raise last or ConnectionError(f"reconnect to {addr} failed")

    def _backoff_rng(self):
        """Seeded jitter stream when the daemon carries a chaos seed
        (deterministic scenario replay); fresh entropy otherwise."""
        if self.config is not None and self.config.chaos_seed:
            from ceph_tpu.chaos.rng import stream

            return stream(self.config.chaos_seed,
                          f"backoff:{self.name}:{self.sid}")
        return None

    async def shutdown(self) -> None:
        self._closing = True
        if self.config is not None:
            # the config outlives this messenger (daemon bounces reuse
            # it): leave no observer behind to pin dead incarnations
            self.config.remove_observer(self._chaos_observer)
        if self._server:
            self._server.close()
        for conn in list(self._out.values()) + list(self._accepted):
            await conn.close()
        # cancel + drain reader/handler tasks BEFORE wait_closed: since
        # py3.12 wait_closed() awaits every connection handler, and a
        # handler blocked in its read loop only exits via EOF or cancel
        pending = [t for t in self._tasks if not t.done()]
        for t in pending:
            t.cancel()
        if pending:
            # teardown drain of just-cancelled reader tasks; their
            # results are void by definition
            await asyncio.gather(*pending, return_exceptions=True)  # graftlint: ignore[swallowed-async-error]
        if self._server:
            await self._server.wait_closed()
