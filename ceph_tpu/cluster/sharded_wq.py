"""ShardedOpWQ analog: PG-affine client-op dispatch shards.

Structural mirror of the reference's ShardedOpWQ (src/osd/OSD.cc: ops
land in one of N shards by PG hash; each shard's own lock + queue serve
dequeues).  A PG always maps to one shard, so per-PG ordering survives
sharding by construction; within a shard, ops dequeue on a bounded
DISPATCH TICK and execute concurrently (per-(connection, PG) arrival
order preserved through per-group FIFOs — exactly the legacy
guarantee), which is what lines concurrent EC writes up at the encode
coalescer (cluster/batcher.py): tick alignment turns N per-op device
dispatches into one.

The round-10 scheduling machinery moves INSIDE the shard: with
osd_op_queue=mclock every shard owns its own DmClockQueue (the
reference plugs mClockClientQueue into each ShardedOpWQ shard the same
way), and deadline purging, stale-attempt drops, and QoS-enforced
eviction run per shard.  FIFO mode keeps per-(conn, PG) group FIFOs;
mclock mode spawns a task per dequeued op (QoS decides order, the
legacy global-mclock semantics).

``osd_op_shards=0`` (the config default) bypasses this module entirely
— the round-10 per-(conn, PG) FIFO / global-mclock path is preserved
verbatim as the bisection anchor.
"""

from __future__ import annotations

import asyncio
import time
from collections import deque
from typing import Deque, Dict, Optional, Set, Tuple


class _Shard:
    __slots__ = ("idx", "fifo", "opq", "event", "groups", "active")

    def __init__(self, idx: int, opq=None):
        self.idx = idx
        self.fifo: Deque = deque()
        self.opq = opq                      # DmClockQueue under mclock
        self.event = asyncio.Event()
        self.groups: Dict[Tuple, Deque] = {}
        self.active: Set[Tuple] = set()

    def __len__(self) -> int:
        n = len(self.opq) if self.opq is not None else len(self.fifo)
        return n + sum(len(q) for q in self.groups.values())


class ShardedOpWQ:
    def __init__(self, osd, nshards: int):
        from ceph_tpu.cluster.dmclock import DmClockQueue

        self.osd = osd
        self.use_mclock = osd.config.osd_op_queue == "mclock"
        self.shards = [
            _Shard(i, DmClockQueue() if self.use_mclock else None)
            for i in range(max(1, nshards))]

    def start(self) -> None:
        for sh in self.shards:
            self.osd._track(asyncio.get_event_loop().create_task(
                self.osd.loopmon.wrap(self._drain(sh))))

    # --------------------------------------------------------- enqueue

    def shard_for(self, pgid) -> _Shard:
        # PG-affine: a PG's ops always land in the same shard, so the
        # shard queue is the per-object ordering domain (golden-ratio
        # mix keeps sequential seeds from clumping on one shard)
        h = (pgid.pool * 0x9E3779B1 + pgid.seed * 0x85EBCA77) & 0xFFFFFFFF
        return self.shards[h % len(self.shards)]

    def enqueue(self, conn, msg, qos_client: Optional[str] = None,
                qos_default=None) -> None:
        sh = self.shard_for(msg.pgid)
        if msg.trace is not None:
            # shard-queue stamp: attribution books recv->here as
            # dispatch_queue and here->tick as batch_wait
            msg.trace.setdefault("events", []).append(
                (f"shard:{sh.idx}:queued", time.time()))
        item = (conn, msg, time.monotonic())
        if sh.opq is not None:
            sh.opq.ensure_client(qos_client, qos_default)
            sh.opq.enqueue(qos_client, item)
            self.osd.perf.inc("osd_ops_queued_mclock")
        else:
            sh.fifo.append(item)
        self.osd._queued_depth += 1
        self.osd.perf.set("osd_dispatch_queue_depth",
                          self.osd._queued_depth)
        sh.event.set()

    # ------------------------------------------- QoS eviction (mclock)

    def peek_evict(self, match):
        for sh in self.shards:
            if sh.opq is not None:
                v = sh.opq.peek_evict(match)
                if v is not None:
                    return v
        return None

    def evict(self, match):
        for sh in self.shards:
            if sh.opq is not None:
                v = sh.opq.evict(match)
                if v is not None:
                    return v
        return None

    def evicted_total(self) -> int:
        return sum(sh.opq.stats["evicted"] for sh in self.shards
                   if sh.opq is not None)

    def set_client(self, client: str, spec) -> None:
        for sh in self.shards:
            if sh.opq is not None:
                sh.opq.set_client(client, spec)

    def dump(self) -> Dict:
        out: Dict = {"shards": len(self.shards), "per_shard": []}
        for sh in self.shards:
            row = {"depth": len(sh)}
            if sh.opq is not None:
                row.update(sh.opq.dump())
            out["per_shard"].append(row)
        return out

    # ----------------------------------------------------------- drain

    def _dec_depth(self) -> None:
        self.osd._queued_depth = max(0, self.osd._queued_depth - 1)
        self.osd.perf.set("osd_dispatch_queue_depth",
                          self.osd._queued_depth)

    def _pop(self, sh: _Shard):
        if sh.opq is not None:
            return sh.opq.dequeue()
        return sh.fifo.popleft() if sh.fifo else None

    async def _idle(self, sh: _Shard) -> None:
        """Nothing eligible: purge dead queued work (mclock), then park
        until the next enqueue or the earliest L-tag."""
        osd = self.osd
        if sh.opq is not None:
            now = osd.clock.time()
            expired = sh.opq.purge(
                lambda it: getattr(it[1], "deadline", None) is not None
                and now > it[1].deadline
                and not osd._is_control_op(it[1]))
            for _e_conn, e_msg, _stamp in expired:
                self._dec_depth()
                osd._shed_if_expired(e_msg)
                await osd._admit_release(e_msg)
            wait = sh.opq.next_eligible_in()
            if wait is not None:
                # throttled: sleep until the earliest L-tag matures
                await asyncio.sleep(min(max(wait, 0.002), 0.25))
                return
        sh.event.clear()
        try:
            await asyncio.wait_for(sh.event.wait(), 5.0)
        except asyncio.TimeoutError:
            pass

    async def _drain(self, sh: _Shard) -> None:
        """One shard's dispatch loop: each iteration is a TICK — pop up
        to the bounded batch, hand every op to execution, yield.  Ops of
        one tick reach the encode coalescer together."""
        osd = self.osd
        while not osd._stopped:
            item = self._pop(sh)
            if item is None:
                await self._idle(sh)
                continue
            tick = [item]
            cap = max(1, osd.config.osd_batch_tick_ops or 64)
            while len(tick) < cap:
                nxt = self._pop(sh)
                if nxt is None:
                    break
                tick.append(nxt)
            tick_wall = time.time()
            for conn, msg, stamp in tick:
                if msg.trace is not None:
                    msg.trace.setdefault("events", []).append(
                        (f"shard:{sh.idx}:tick", tick_wall))
                if sh.opq is not None:
                    # legacy-mclock semantics per op: stale-attempt
                    # drop, conformance gauges, a free-running task
                    # (QoS already decided the order)
                    self._dec_depth()
                    if time.monotonic() - stamp > \
                            osd.config.osd_client_op_timeout:
                        osd.perf.inc("osd_ops_dropped_stale")
                        await osd._admit_release(msg)
                        continue
                    t = asyncio.get_event_loop().create_task(
                        osd.loopmon.wrap(osd._serve_admitted(conn, msg)))
                    osd._opq_running.add(t)
                    t.add_done_callback(osd._opq_running.discard)
                else:
                    self._queue_group(sh, conn, msg)
            if sh.opq is not None:
                osd.perf.set(
                    "osd_qos_served_reservation",
                    sum(s.opq.stats["served_reservation"]
                        for s in self.shards))
                osd.perf.set(
                    "osd_qos_served_spare",
                    sum(s.opq.stats["served_spare"]
                        for s in self.shards))
                osd.perf.set("osd_qos_evicted", self.evicted_total())
            # tick boundary: let the dispatched ops run (and the next
            # arrivals land) before draining more
            await asyncio.sleep(0)

    def _queue_group(self, sh: _Shard, conn, msg) -> None:
        """FIFO mode: per-(connection, PG, object) arrival order — a
        pipelined A-then-B to one object must apply as A then B, while
        DIFFERENT objects of one PG dispatch concurrently (they meet
        again at the encode tick and the ordered commit section)."""
        key = (id(conn), msg.pgid, msg.oid)
        q = sh.groups.get(key)
        if q is None:
            q = sh.groups[key] = deque()
        q.append((conn, msg))
        if key not in sh.active:
            self._spawn_group(sh, key, q)

    def _spawn_group(self, sh: _Shard, key, q) -> None:
        sh.active.add(key)
        t = asyncio.get_event_loop().create_task(
            self.osd.loopmon.wrap(self._drain_group(sh, key, q)))
        self.osd._opq_running.add(t)
        t.add_done_callback(self.osd._opq_running.discard)

    async def _drain_group(self, sh: _Shard, key, q) -> None:
        try:
            while q:
                conn, msg = q.popleft()
                self._dec_depth()
                await self.osd._serve_admitted(conn, msg)
        finally:
            sh.active.discard(key)
            if q and not self.osd._stopped:
                # drainer died mid-queue (cancellation): respawn so the
                # queued ops are not stranded
                self._spawn_group(sh, key, q)
            elif sh.groups.get(key) is q:
                del sh.groups[key]
