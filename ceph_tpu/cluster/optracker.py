"""OpTracker: in-flight + historic op tracing.

Behavioral mirror of the reference's TrackedOp machinery
(src/common/TrackedOp.cc, src/osd/OpRequest.cc): every tracked op records
timestamped events from arrival to completion; the tracker keeps the
in-flight set plus ring buffers of the most recent and the slowest
completed ops, served by the admin commands dump_ops_in_flight /
dump_historic_ops / dump_historic_slow_ops.
"""

from __future__ import annotations

import itertools
import time
from collections import deque
from typing import Deque, Dict, List, Optional


class TrackedOp:
    def __init__(self, tracker: "OpTracker", desc: str):
        self._tracker = tracker
        self.seq = next(tracker._seq)
        self.desc = desc
        self.start = time.monotonic()
        self.events: List[tuple] = [(0.0, "initiated")]
        self.duration: Optional[float] = None

    def mark(self, event: str) -> None:
        self.events.append((time.monotonic() - self.start, event))

    def finish(self) -> None:
        if self.duration is None:
            self.mark("done")
            self.duration = time.monotonic() - self.start
            self._tracker._finished(self)

    def dump(self) -> Dict:
        return {
            "seq": self.seq,
            "description": self.desc,
            "age": time.monotonic() - self.start,
            "duration": self.duration,
            "type_data": {"events": [
                {"time": round(t, 6), "event": e} for t, e in self.events]},
        }


class OpTracker:
    def __init__(self, history_size: int = 20, slow_size: int = 20,
                 slow_threshold: float = 0.0):
        self._seq = itertools.count(1)
        self._in_flight: Dict[int, TrackedOp] = {}
        self._history: Deque[TrackedOp] = deque(maxlen=history_size)
        self._slowest: List[TrackedOp] = []
        self._slow_size = slow_size
        self.slow_threshold = slow_threshold

    def create(self, desc: str) -> TrackedOp:
        op = TrackedOp(self, desc)
        self._in_flight[op.seq] = op
        return op

    def _finished(self, op: TrackedOp) -> None:
        self._in_flight.pop(op.seq, None)
        self._history.append(op)
        if op.duration is not None and \
                op.duration >= self.slow_threshold:
            self._slowest.append(op)
            self._slowest.sort(key=lambda o: -(o.duration or 0))
            del self._slowest[self._slow_size:]

    # -- admin-command surfaces (reference dump_historic_ops et al.) --------

    def dump_ops_in_flight(self) -> Dict:
        ops = sorted(self._in_flight.values(), key=lambda o: o.seq)
        return {"num_ops": len(ops), "ops": [o.dump() for o in ops]}

    def dump_historic_ops(self) -> Dict:
        return {"num_ops": len(self._history),
                "ops": [o.dump() for o in self._history]}

    def dump_historic_slow_ops(self) -> Dict:
        return {"num_ops": len(self._slowest),
                "ops": [o.dump() for o in self._slowest]}
