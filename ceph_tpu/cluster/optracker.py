"""OpTracker: in-flight + historic op tracing.

Behavioral mirror of the reference's TrackedOp machinery
(src/common/TrackedOp.cc, src/osd/OpRequest.cc): every tracked op records
timestamped events from arrival to completion; the tracker keeps the
in-flight set plus ring buffers of the most recent and the slowest
completed ops, served by the admin commands dump_ops_in_flight /
dump_historic_ops / dump_historic_slow_ops.

Cross-layer tracing (round 6): an op minted client-side carries a trace
header (id + pre-arrival events stamped by the objecter and each
messenger hop); TrackedOp absorbs it so one ``dump_historic_ops`` entry
shows the op's whole life — objecter submit, messenger send, OSD
dispatch, encode/journal/commit — across daemons.  ``CURRENT_OP`` lets
deep layers (backends, stores) mark the op being served without
threading the handle through every call.

Slow-op semantics (reference osd_op_complaint_time, default 30s): the
slowest-completed ring only admits ops at/above ``slow_threshold``
(0 disables it entirely — the old behavior of 0 admitting EVERY op made
the ring a second history buffer), and ``slow_in_flight()`` reports
currently-blocked ops past the threshold for the health-warning path
("N slow ops, oldest age X").
"""

from __future__ import annotations

import contextvars
import itertools
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

# the op currently being served on this task's context (reference: the
# OpRequest threaded through do_op/do_osd_ops; a contextvar keeps the
# deep layers' signatures unchanged)
CURRENT_OP: contextvars.ContextVar[Optional["TrackedOp"]] = \
    contextvars.ContextVar("ceph_tpu_current_op", default=None)


def mark_current(event: str) -> None:
    """Record an event on the op being served, if any (no-op outside a
    tracked dispatch — recovery, scrub, internal ops)."""
    op = CURRENT_OP.get()
    if op is not None:
        op.mark(event)


def _lock_trace(name: str, phase: str) -> None:
    """DepLock trace hook: lock wait/acquire pairs land on the current
    op's timeline (pg.lock / messenger.session wait become first-class
    attribution stages) with one ContextVar read per acquisition."""
    op = CURRENT_OP.get()
    if op is not None:
        op.mark(f"lock_{phase}:{name}")


# install at import: every daemon that tracks ops pulls this module in,
# and the hook itself is a no-op outside a tracked dispatch
from ceph_tpu.utils import lockdep as _lockdep  # noqa: E402

_lockdep.TRACE_HOOK = _lock_trace


class TrackedOp:
    def __init__(self, tracker: "OpTracker", desc: str,
                 trace: Optional[Dict] = None):
        self._tracker = tracker
        self._clock = tracker.clock
        self.seq = next(tracker._seq)
        self.desc = desc
        self.start = self._clock.monotonic()
        self.wall_start = self._clock.time()
        self.events: List[tuple] = []
        self.duration: Optional[float] = None
        self.trace_id: Optional[str] = None
        if trace:
            self.trace_id = trace.get("id")
            # inherited events carry wall-clock stamps from upstream
            # layers (objecter, messenger hops); rebase them onto this
            # op's clock — loopback daemons share the wall clock, so
            # negative offsets faithfully mean "before OSD arrival".
            # Clamp at 0.0: the wall and monotonic clocks are sampled at
            # different instants, so an inherited stamp can land
            # epsilon-PAST our start and would sort after "initiated" —
            # drifting the timeline (a pre-arrival hop rendered as if it
            # happened mid-dispatch).  Everything upstream happened
            # before this op existed, by causality.
            for name, ts in trace.get("events", ()):
                self.events.append((min(ts - self.wall_start, 0.0), name))
        self.events.append((0.0, "initiated"))

    def mark(self, event: str) -> None:
        self.events.append((self._clock.monotonic() - self.start, event))

    def mark_at(self, event: str, mono_ts: float) -> None:
        """Record an event at an explicit ``clock.monotonic()`` stamp —
        for shared timestamps computed elsewhere (the encode coalescer's
        tick window lands on every op of the batch)."""
        self.events.append((mono_ts - self.start, event))

    def finish(self) -> None:
        if self.duration is None:
            self.mark("done")
            self.duration = self._clock.monotonic() - self.start
            self._tracker._finished(self)

    def age(self) -> float:
        return self._clock.monotonic() - self.start

    def dump(self) -> Dict:
        # sorted() is stable: same-stamp events keep insertion (causal)
        # order, so the inherited client-side hops can never interleave
        # into the OSD-side marks (the round-9 event-ordering fix)
        ordered = sorted(self.events, key=lambda ev: ev[0])
        out = {
            "seq": self.seq,
            "description": self.desc,
            "age": self._clock.monotonic() - self.start,
            "duration": self.duration,
            "type_data": {"events": [
                {"time": round(t, 6), "event": e} for t, e in ordered]},
        }
        if self.trace_id is not None:
            out["trace_id"] = self.trace_id
        if self.duration is not None:
            # stage-labeled spans derived from the same timeline, so
            # dump_historic_ops and graft-trace agree on one op story
            from ceph_tpu.trace.attribution import spans_from_events

            out["spans"] = spans_from_events(ordered)
        return out


class OpTracker:
    def __init__(self, history_size: int = 20, slow_size: int = 20,
                 slow_threshold: float = 30.0, clock=None):
        """``slow_threshold`` mirrors osd_op_complaint_time (reference
        default 30s); 0 disables slow-op tracking.  ``clock`` is the
        owning daemon's (chaos-skewable) time source — op ages follow
        the daemon's view of time, so a clock-skew scenario makes slow-op
        warnings fire early/late exactly as NTP drift would."""
        from ceph_tpu.chaos.clock import ChaosClock

        self.clock = clock or ChaosClock()
        self._seq = itertools.count(1)
        self._in_flight: Dict[int, TrackedOp] = {}
        self._history: Deque[TrackedOp] = deque(maxlen=history_size)
        self._slowest: List[TrackedOp] = []
        self._slow_size = slow_size
        self.slow_threshold = slow_threshold

    def create(self, desc: str, trace: Optional[Dict] = None) -> TrackedOp:
        op = TrackedOp(self, desc, trace=trace)
        self._in_flight[op.seq] = op
        return op

    def _finished(self, op: TrackedOp) -> None:
        self._in_flight.pop(op.seq, None)
        self._history.append(op)
        if self.slow_threshold > 0 and op.duration is not None and \
                op.duration >= self.slow_threshold:
            self._slowest.append(op)
            self._slowest.sort(key=lambda o: -(o.duration or 0))
            del self._slowest[self._slow_size:]

    def resize(self, history_size: Optional[int] = None,
               slow_size: Optional[int] = None) -> None:
        """Apply runtime knob changes (injectargs on
        osd_op_history_size / osd_op_history_slow_op_size) to the live
        rings, keeping the newest entries."""
        if history_size is not None and \
                history_size != self._history.maxlen:
            self._history = deque(self._history, maxlen=history_size)
        if slow_size is not None:
            self._slow_size = slow_size
            del self._slowest[slow_size:]

    def slow_in_flight(self) -> Tuple[int, float]:
        """(count, oldest_age) of in-flight ops blocked past the
        complaint threshold — the 'N slow ops, oldest age X' health feed
        (reference OpTracker::check_ops_in_flight)."""
        if self.slow_threshold <= 0:
            return 0, 0.0
        ages = [op.age() for op in self._in_flight.values()]
        slow = [a for a in ages if a >= self.slow_threshold]
        return len(slow), max(slow) if slow else 0.0

    def history(self) -> List[TrackedOp]:
        """Completed ops, oldest first (the attribution aggregator's
        input — ceph_tpu.trace.attribution.aggregate_tracker)."""
        return list(self._history)

    # -- admin-command surfaces (reference dump_historic_ops et al.) --------

    def dump_ops_in_flight(self) -> Dict:
        ops = sorted(self._in_flight.values(), key=lambda o: o.seq)
        return {"num_ops": len(ops), "ops": [o.dump() for o in ops]}

    def dump_historic_ops(self) -> Dict:
        return {"num_ops": len(self._history),
                "ops": [o.dump() for o in self._history]}

    def dump_historic_slow_ops(self) -> Dict:
        return {"num_ops": len(self._slowest),
                "ops": [o.dump() for o in self._slowest]}
