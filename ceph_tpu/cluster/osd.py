"""OSD daemon: PGs, replicated and erasure-coded backends, recovery.

Structural mirror of the reference OSD (src/osd/OSD.cc dispatch ->
PrimaryLogPG op execution; ReplicatedBackend transaction fan-out;
ECBackend shard writes/reads, src/osd/ECBackend.cc:921,986,1141), with the
dense compute — erasure encode/decode, chunk crc32c — running through the
TPU codec engine.  Heartbeats/failure reports mirror OSD::heartbeat_check
(OSD.cc:4763) -> MOSDFailure -> monitor.  Recovery re-synchronizes PG
contents on map change (push recovery; EC shards reconstructed by decode,
ECBackend::run_recovery_op analog).
"""

from __future__ import annotations

import asyncio
import pickle
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ceph_tpu.cluster import messages as M
from ceph_tpu.cluster import pglog
from ceph_tpu.cluster.messenger import (
    Addr,
    Connection,
    Dispatcher,
    EntityName,
    Messenger,
)
from ceph_tpu.cluster.pglog import LogEntry, PGInfo, PGLog
from ceph_tpu.cluster.store import MemStore, ObjectStore, Transaction
from ceph_tpu.crush.types import CRUSH_ITEM_NONE
from ceph_tpu.ops import crc32c as crcmod
from ceph_tpu.osdmap.osdmap import OSDMap, PGid, PGPool
from ceph_tpu.utils import Config, PerfCounters

# the per-PG metadata object holding the persisted log + last_update
# (reference: the pgmeta ghobject, PG::_init / read_info)
PGMETA = "_pgmeta_"
# the daemon-level metadata collection: superblock with the current osdmap
# (reference OSDSuperblock, read at OSD::init, src/osd/OSD.cc:2556)
METACOLL = "meta"


@dataclass
class PGState:
    pgid: PGid
    up: List[int] = field(default_factory=list)
    acting: List[int] = field(default_factory=list)
    primary: int = -1
    # pg_info_t analog: every mutation advances last_update and appends to
    # the log (reference PG.h pg_log)
    last_update: pglog.Eversion = pglog.ZERO
    log: PGLog = field(default_factory=PGLog)
    # per-PG op serialization domain (reference PG lock / ShardedOpWQ,
    # src/osd/OSD.h:1599): mutations hold this across their whole
    # fan-out so concurrent writes order identically on all replicas
    lock: asyncio.Lock = field(default_factory=asyncio.Lock)
    # reqid -> cached replies of completed mutations (reference pg_log
    # dup tracking, osd_pg_log_dups_tracked): a resent non-idempotent op
    # (exec, delete, ...) returns its original reply instead of
    # re-executing.  In-memory only — a primary restart forgets dups the
    # way a reference OSD forgets dups past the trimmed log.
    reqid_replies: "OrderedDict[Tuple, List]" = field(
        default_factory=OrderedDict)
    # reqids currently executing: a dup that races its first instance
    # waits for that instance's replies rather than re-executing
    reqid_inflight: Dict[Tuple, asyncio.Future] = field(
        default_factory=dict)

    def info(self) -> PGInfo:
        return PGInfo(last_update=self.last_update, log_tail=self.log.tail)


@dataclass
class MOSDPGQuery(M.Message):
    pgid: Optional[PGid] = None


@dataclass
class MOSDPGQueryReply(M.Message):
    pgid: Optional[PGid] = None
    objects: Dict[str, int] = field(default_factory=dict)  # oid -> seq
    info: Optional[PGInfo] = None
    log: Optional[PGLog] = None


def _coll(pgid: PGid) -> str:
    return f"pg_{pgid.pool}_{pgid.seed}"


class OSDDaemon(Dispatcher):
    def __init__(self, osd_id: int, mon_addr,
                 config: Optional[Config] = None,
                 store: Optional[ObjectStore] = None):
        self.osd_id = osd_id
        # per-daemon config copy: injectargs on one daemon must never
        # leak into another (each reference daemon owns its md_config_t)
        self.config = Config(**config.show()) if config else Config()
        self.store = store or MemStore()
        self.messenger = Messenger(
            EntityName("osd", osd_id),
            secret=self.config.auth_secret())
        self.messenger.add_dispatcher(self)
        # monmap failover (shared MonClient hunting, cluster/monclient.py)
        from ceph_tpu.cluster.monclient import MonTargeter

        self.monc = MonTargeter(
            self.messenger, mon_addr,
            subscribe_since=lambda: self.osdmap.epoch if self.osdmap else 0)
        self.osdmap: Optional[OSDMap] = None
        self.pgs: Dict[PGid, PGState] = {}
        self.perf = PerfCounters(f"osd.{osd_id}")
        from ceph_tpu.cluster.optracker import OpTracker

        self.tracker = OpTracker()
        self._codecs: Dict[int, object] = {}
        self._pending: Dict[Tuple, Tuple[asyncio.Future, List]] = {}
        self._tid = 0
        self._tasks: List[asyncio.Task] = []
        self._hb_last: Dict[int, float] = {}
        self._reported: Set[int] = set()
        # dmClock op scheduling (reference mClockClientQueue plugged into
        # ShardedOpWQ): enabled by osd_op_queue=mclock; ops enqueue per
        # client and a drain task serves them by reservation/weight/limit
        self._opq = None
        self._opq_event = asyncio.Event()
        self._opq_running: Set[asyncio.Task] = set()
        if self.config.osd_op_queue == "mclock":
            from ceph_tpu.cluster.dmclock import DmClockQueue, QoSSpec

            self._opq = DmClockQueue()
            self._opq_default = QoSSpec(
                reservation=self.config.osd_mclock_default_reservation,
                weight=self.config.osd_mclock_default_weight,
                limit=self.config.osd_mclock_default_limit)
        # boot instance nonce: lets the mon fence a fast rebounce even if
        # the new daemon lands on the identical address
        import itertools as _it
        import secrets as _secrets

        self.boot_instance = _secrets.randbits(63)
        # watch/notify state: (pgid, oid) -> {(watcher, cookie): conn}
        # (reference Watch/Notify on PrimaryLogPG)
        self._watchers: Dict[Tuple, Dict[Tuple[str, int], Connection]] = {}
        self._notifies: Dict[int, Tuple[asyncio.Future, Set[str]]] = {}
        self._notify_id = 0
        self._stopped = False

    # ------------------------------------------------------------ lifecycle

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> Addr:
        self.store.mount()
        since = self._load_superblock()
        addr = await self.messenger.bind(host, port)
        # boot must surface unreachable monitors, not run unregistered
        await self._mon_send(M.MOSDBoot(osd_id=self.osd_id, addr=addr,
                                        instance=self.boot_instance),
                             raise_on_fail=True)
        await self._mon_send(
            M.MMonSubscribe(what="osdmap", addr=addr, since=since))
        loop = asyncio.get_event_loop()
        self._tasks.append(loop.create_task(self._heartbeat_loop()))
        self._tasks.append(loop.create_task(self._scrub_loop()))
        if self._opq is not None:
            self._tasks.append(loop.create_task(self._opq_drain()))
        return addr

    def _load_superblock(self) -> int:
        """Resume from the persisted osdmap + PG logs (reference
        read_superblock + load_pgs, OSD.cc:2556,2572).  Returns the epoch
        to subscribe from (0 = never booted)."""
        blob = self.store.getattr(METACOLL, "superblock", "osdmap")
        if blob is None:
            return 0
        self.osdmap = pickle.loads(blob)
        self.perf.set("osd_map_epoch", self.osdmap.epoch)
        self._advance_pgs()  # reloads per-PG logs from their pgmeta objects
        return self.osdmap.epoch

    def _save_superblock(self) -> None:
        self.store.queue_transaction(
            Transaction()
            .create_collection(METACOLL)
            .setattr(METACOLL, "superblock", "osdmap",
                     pickle.dumps(self.osdmap)))

    async def stop(self) -> None:
        self._stopped = True
        for t in list(self._tasks) + list(self._opq_running):
            t.cancel()
        if self._opq_running:
            await asyncio.gather(*self._opq_running,
                                 return_exceptions=True)
        await self.messenger.shutdown()
        self.store.umount()

    def _next_reqid(self) -> Tuple[str, int]:
        self._tid += 1
        return (f"osd.{self.osd_id}", self._tid)

    @property
    def mon_addr(self) -> Addr:
        return self.monc.current

    async def _mon_send(self, msg, raise_on_fail: bool = False) -> bool:
        return await self.monc.send(msg, raise_on_fail=raise_on_fail)

    # --------------------------------------------------------- pg log state

    def _next_version(self, st: PGState) -> pglog.Eversion:
        """eversion for the next mutation: (map epoch, next seq)."""
        return (self.osdmap.epoch if self.osdmap else 0, st.last_update[1] + 1)

    @staticmethod
    def _meta_key(version: pglog.Eversion) -> str:
        return f"{version[0]:010d}.{version[1]:012d}"

    def _log_mutation(self, st: PGState, op: str, oid: str,
                      version: pglog.Eversion,
                      entry: Optional[LogEntry] = None):
        """Append a log entry + persist it INCREMENTALLY to the pgmeta
        object (one omap key per entry + a head attr), so a restarted OSD
        peers from its on-store log instead of backfilling and the hot
        write path never re-serializes the whole log (reference: log
        entries ride the op's own transaction, PG::write_if_dirty).
        Replicas pass the primary's ``entry`` through verbatim so every
        member's log (incl. prior_version chains) stays byte-identical.
        Returns the appended LogEntry, or None for a replayed duplicate."""
        if version <= st.last_update:
            return None  # replayed/duplicate entry
        if entry is None:
            entry = LogEntry(op=op, oid=oid, version=version,
                             prior_version=st.last_update)
        st.log.append(entry)
        st.last_update = version
        dropped = st.log.trim()
        coll = _coll(st.pgid)
        txn = (Transaction()
               .omap_set(coll, PGMETA,
                         {self._meta_key(version): pickle.dumps(entry)})
               .setattr(coll, PGMETA, "last_update", pickle.dumps(version))
               .setattr(coll, PGMETA, "log_tail", pickle.dumps(st.log.tail)))
        if dropped:
            txn.omap_rmkeys(coll, PGMETA,
                            [self._meta_key(e.version) for e in dropped])
        self.store.queue_transaction(txn)
        return entry

    def _save_pg_meta(self, st: PGState) -> None:
        """Full rewrite of the persisted log (recovery-time adoption of an
        authoritative log; NOT on the per-op path)."""
        coll = _coll(st.pgid)
        old = list(self.store.omap_get(coll, PGMETA))
        txn = Transaction()
        if old:
            txn.omap_rmkeys(coll, PGMETA, old)
        txn.omap_set(coll, PGMETA,
                     {self._meta_key(e.version): pickle.dumps(e)
                      for e in st.log.entries})
        txn.setattr(coll, PGMETA, "last_update", pickle.dumps(st.last_update))
        txn.setattr(coll, PGMETA, "log_tail", pickle.dumps(st.log.tail))
        self.store.queue_transaction(txn)

    def _load_pg_meta(self, pgid: PGid) -> Tuple[pglog.Eversion, PGLog]:
        coll = _coll(pgid)
        lu = self.store.getattr(coll, PGMETA, "last_update")
        if lu is None:
            return pglog.ZERO, PGLog()
        last_update = pickle.loads(lu)
        tail_blob = self.store.getattr(coll, PGMETA, "log_tail")
        tail = pickle.loads(tail_blob) if tail_blob else pglog.ZERO
        entries = [pickle.loads(v) for _, v in
                   sorted(self.store.omap_get(coll, PGMETA).items())]
        entries = [e for e in entries if e.version > tail]
        return last_update, PGLog(tail=tail, entries=entries)

    def _list_pg_objects(self, pgid: PGid) -> List[str]:
        return [o for o in self.store.list_objects(_coll(pgid))
                if o != PGMETA]

    def _codec(self, pool: PGPool):
        codec = self._codecs.get(pool.pool_id)
        if codec is None:
            from ceph_tpu.ec import factory

            profile = pool.ec_profile or {
                "plugin": "jerasure", "technique": "reed_sol_van",
                "k": "2", "m": "1"}
            codec = factory(profile)
            self._codecs[pool.pool_id] = codec
        return codec

    def _sinfo(self, pool: PGPool, codec) -> "StripeInfo":
        """Stripe layout for a pool (ECUtil::stripe_info_t analog)."""
        from ceph_tpu.ec.stripe import StripeInfo

        unit = int((pool.ec_profile or {}).get(
            "stripe_unit", self.config.osd_ec_stripe_unit))
        return StripeInfo(codec.get_data_chunk_count(), unit)

    # ------------------------------------------------------------- dispatch

    async def ms_dispatch(self, conn: Connection, msg) -> bool:
        try:
            return await self._dispatch(conn, msg)
        except Exception as e:
            self.perf.inc("osd_dispatch_errors")
            if isinstance(msg, M.MOSDOp):
                await conn.send(M.MOSDOpReply(
                    reqid=msg.reqid, result=-5, data=repr(e)))
                return True
            raise

    async def _dispatch(self, conn: Connection, msg) -> bool:
        if isinstance(msg, M.MOSDMapMsg):
            await self._handle_map(msg)
            return True
        if isinstance(msg, M.MOSDIncMapMsg):
            await self._handle_inc_map(msg)
            return True
        if isinstance(msg, M.MOSDOp):
            await self._handle_client_op(conn, msg)
            return True
        if isinstance(msg, M.MOSDRepOp):
            txn = Transaction.decode(msg.txn_blob)
            self.store.queue_transaction(txn)
            st = self.pgs.get(msg.pgid)
            if st is not None and msg.entry is not None:
                self._log_mutation(st, msg.entry.op, msg.entry.oid,
                                   msg.entry.version, entry=msg.entry)
            self.perf.inc("osd_rep_ops")
            await conn.send(M.MOSDRepOpReply(reqid=msg.reqid, result=0))
            return True
        if isinstance(msg, M.MOSDRepOpReply) or \
                isinstance(msg, M.MOSDECSubOpWriteReply):
            self._ack(msg.reqid, msg.result)
            return True
        if isinstance(msg, M.MOSDECSubOpWrite):
            await self._handle_ec_write(conn, msg)
            return True
        if isinstance(msg, M.MOSDECSubOpRead):
            await self._handle_ec_read(conn, msg)
            return True
        if isinstance(msg, M.MOSDECSubOpReadReply):
            self._ack(msg.reqid, msg.result, msg)
            return True
        if isinstance(msg, M.MOSDScrub):
            await conn.send(M.MOSDScrubMap(
                reqid=msg.reqid, pgid=msg.pgid,
                objects=self._build_scrub_map(msg.pgid)))
            return True
        if isinstance(msg, M.MOSDScrubMap):
            self._ack(msg.reqid, 0, msg)
            return True
        if isinstance(msg, M.MOSDPGPush):
            self._handle_push(msg)
            await conn.send(M.MOSDPGPushReply(
                pgid=msg.pgid, oid=msg.oid, result=0))
            return True
        if isinstance(msg, M.MOSDPGPushReply):
            return True
        if isinstance(msg, MOSDPGQuery):
            objects = {
                oid: self.store.get_version(_coll(msg.pgid), oid)
                for oid in self._list_pg_objects(msg.pgid)
            }
            st = self.pgs.get(msg.pgid)
            await conn.send(MOSDPGQueryReply(
                pgid=msg.pgid, objects=objects,
                info=st.info() if st else None,
                log=st.log if st else None))
            return True
        if isinstance(msg, MOSDPGQueryReply):
            self._ack(("pgq", str(msg.pgid), msg.src.num), 0, msg)
            return True
        if isinstance(msg, M.MCommand):
            await self._handle_admin_command(conn, msg)
            return True
        if isinstance(msg, M.MPing):
            if msg.reply:
                if msg.src is not None:
                    self._hb_last[msg.src.num] = time.monotonic()
            else:
                await conn.send(M.MPing(stamp=msg.stamp, reply=True))
            return True
        return False

    async def _handle_admin_command(self, conn: Connection,
                                    msg: M.MCommand) -> None:
        """Admin-socket surface (reference AdminSocket commands: perf
        dump, dump_historic_ops, config show, injectargs, scrub)."""
        cmd = msg.cmd
        prefix = cmd.get("prefix")
        result, data = 0, None
        try:
            if prefix == "perf dump":
                data = self.perf.dump()
            elif prefix == "dump_ops_in_flight":
                data = self.tracker.dump_ops_in_flight()
            elif prefix == "dump_historic_ops":
                data = self.tracker.dump_historic_ops()
            elif prefix == "dump_historic_slow_ops":
                data = self.tracker.dump_historic_slow_ops()
            elif prefix == "config show":
                data = self.config.show()
            elif prefix == "injectargs":
                self.config.injectargs(cmd.get("args", {}))
                self.perf.inc("osd_injectargs")
            elif prefix == "scrub":
                reports = {}
                for pgid, st in list(self.pgs.items()):
                    if st.primary == self.osd_id:
                        reports[str(pgid)] = await self.scrub_pg(st)
                data = reports
            else:
                result = -22
        except Exception as e:
            result, data = -22, repr(e)
        if msg.tid or prefix != "injectargs":
            try:
                await conn.send(M.MCommandReply(
                    tid=msg.tid, result=result, data=data))
            except (ConnectionError, OSError):
                pass

    # -------------------------------------------------------------- helpers

    async def _compute(self, fn, *args):
        """Run codec compute (encode/decode, possibly a first-call jit
        compile) off the event loop.  Blocking the loop here starves
        heartbeat replies and triggers false failure reports — the reference
        isolates heartbeats on dedicated messengers for the same reason
        (src/ceph_osd.cc:459-486 creates 4 hb messengers)."""
        return await asyncio.get_event_loop().run_in_executor(
            None, lambda: fn(*args))

    def _ack(self, key, result, payload=None) -> None:
        entry = self._pending.get(tuple(key) if isinstance(key, tuple) else key)
        if entry is None:
            return
        fut, acc = entry
        acc.append((result, payload))
        if len(acc) >= fut.needed and not fut.done():  # type: ignore[attr-defined]
            fut.set_result(acc)

    def _make_waiter(self, key, needed: int) -> asyncio.Future:
        fut = asyncio.get_event_loop().create_future()
        fut.needed = needed  # type: ignore[attr-defined]
        self._pending[key] = (fut, [])
        return fut

    def _waiter_dec(self, key) -> None:
        """A planned responder became unreachable: lower the threshold AND
        re-check completion — acks that already arrived must be able to
        satisfy the waiter, or a durably-committed op reports failure."""
        entry = self._pending.get(key)
        if entry is None:
            return
        fut, acc = entry
        fut.needed -= 1  # type: ignore[attr-defined]
        if len(acc) >= fut.needed and not fut.done():  # type: ignore[attr-defined]
            fut.set_result(acc)

    async def _send_osd(self, osd: int, msg) -> None:
        addr = self.osdmap.osd_addrs.get(osd)
        if addr is None:
            raise ConnectionError(f"no address for osd.{osd}")
        await self.messenger.send_message(msg, addr)

    # ------------------------------------------------------------ map flow

    async def _handle_inc_map(self, msg: M.MOSDIncMapMsg) -> None:
        """Apply a delta chain (reference handle_osd_map incremental path).
        On an epoch gap, re-subscribe from our epoch to resync."""
        m = self.osdmap
        if m is None or msg.prev_epoch != m.epoch:
            if m is not None and msg.epoch <= m.epoch:
                return  # stale or duplicate
            await self._mon_send(
                M.MMonSubscribe(what="osdmap", addr=self.messenger.my_addr,
                                since=m.epoch if m else 0))
            return
        for blob in msg.inc_blobs:
            m.apply_incremental(pickle.loads(blob))
        self.perf.set("osd_map_epoch", m.epoch)
        await self._post_map_update()

    async def _handle_map(self, msg: M.MOSDMapMsg) -> None:
        newmap: OSDMap = pickle.loads(msg.osdmap_blob)
        old = self.osdmap
        if old is not None and newmap.epoch < old.epoch:
            return  # stale full map
        self.osdmap = newmap
        self.perf.set("osd_map_epoch", newmap.epoch)
        await self._post_map_update()

    async def _post_map_update(self) -> None:
        newmap = self.osdmap
        self._save_superblock()
        if not self._stopped and self.osd_id < newmap.max_osd and \
                not newmap.osd_up[self.osd_id]:
            # the map says we are down but we are alive: re-boot (reference
            # OSD::start_boot after _committed_osd_maps notices the same)
            self.perf.inc("osd_re_boots")
            await self._mon_send(M.MOSDBoot(osd_id=self.osd_id,
                                            addr=self.messenger.my_addr,
                                            instance=self.boot_instance))
        changed = self._advance_pgs()
        if changed and not self._stopped:
            self._tasks.append(asyncio.get_event_loop().create_task(
                self._recover_all()))

    def _advance_pgs(self) -> bool:
        """Recompute PG membership for this OSD; returns True if the set of
        primary PGs changed (triggering recovery).  PG log/last_update are
        preserved across map changes (and reloaded from the pgmeta object
        when the collection already exists on store — the load_pgs resume
        path, reference OSD.cc:2572)."""
        m = self.osdmap
        changed = False
        for pool_id, pool in m.pools.items():
            for pgid, up, upp, acting, actp in self._pool_memberships(
                    m, pool_id, pool):
                mine = self.osd_id in [o for o in acting if o != CRUSH_ITEM_NONE]
                old = self.pgs.get(pgid)
                if mine:
                    if old is None:
                        changed = True
                        self.store.queue_transaction(
                            Transaction().create_collection(_coll(pgid)))
                        st = PGState(pgid, up, acting, actp)
                        st.last_update, st.log = self._load_pg_meta(pgid)
                        self.pgs[pgid] = st
                    else:
                        if old.acting != acting:
                            changed = True
                        old.up, old.acting, old.primary = up, acting, actp
                elif old is not None:
                    del self.pgs[pgid]
                    changed = True
        return changed

    def _pool_memberships(self, m: OSDMap, pool_id: int, pool: PGPool):
        """Yield (pgid, up, upp, acting, actp) for every PG of a pool.

        Large pools go through the batched whole-pool placement (one TPU
        dispatch via OSDMap.pool_mapping, which falls back to the scalar
        mapper for map shapes the TensorMapper rejects); sparse pg_temp /
        primary_temp overrides re-run the scalar chain per affected PG.
        Small pools stay scalar — a per-epoch device dispatch costs more
        than it saves below a few hundred PGs."""
        batch_min = self.config.osd_map_batch_min_pgs
        if pool.pg_num < batch_min:
            for seed in range(pool.pg_num):
                pgid = PGid(pool_id, seed)
                yield (pgid, *m.pg_to_up_acting_osds(pgid))
            return
        up_arr, upp_arr = m.pool_mapping(pool_id)
        for seed in range(pool.pg_num):
            pgid = PGid(pool_id, seed)
            if pgid in m.pg_temp or pgid in m.primary_temp:
                yield (pgid, *m.pg_to_up_acting_osds(pgid))
                continue
            row = up_arr[seed]
            up = [int(o) for o in row if o != CRUSH_ITEM_NONE] \
                if pool.can_shift_osds() else [int(o) for o in row]
            upp = int(upp_arr[seed])
            yield pgid, up, upp, up, upp

    # -------------------------------------------------------- client ops

    async def _resolve_client_op(self, conn: Connection, msg: M.MOSDOp):
        """Map/pool/PG/primary checks for a client op; replies and
        returns None when the op cannot be served here."""
        m = self.osdmap
        if m is None:
            await conn.send(M.MOSDOpReply(reqid=msg.reqid, result=-11))
            return None
        pool = m.pools.get(msg.pgid.pool)
        if pool is None:
            await conn.send(M.MOSDOpReply(reqid=msg.reqid, result=-2))
            return None
        st = self.pgs.get(msg.pgid)
        if st is None or st.primary != self.osd_id:
            # not primary (anymore): tell client to refresh its map
            await conn.send(M.MOSDOpReply(
                reqid=msg.reqid, result=-11, epoch=m.epoch))
            self.perf.inc("osd_misdirected_ops")
            return None
        return m, pool, st

    async def _handle_client_op(self, conn: Connection, msg: M.MOSDOp) -> None:
        resolved = await self._resolve_client_op(conn, msg)
        if resolved is None:
            return
        m, pool, st = resolved
        if self._opq is not None:
            self._opq.ensure_client(msg.reqid[0], self._opq_default)
            # queue ONLY (conn, msg, stamp): map/pool/PG/primary state is
            # re-resolved at dequeue time, and ops that outlived the
            # client's attempt window are dropped (the client has already
            # resent; executing the stale copy would double-apply)
            self._opq.enqueue(msg.reqid[0],
                              (conn, msg, time.monotonic()))
            self.perf.inc("osd_ops_queued_mclock")
            self._opq_event.set()
            return
        await self._dispatch_client_op(conn, msg, m, pool, st)

    async def _opq_drain(self) -> None:
        """Serve the dmClock queue (the ShardedOpWQ dequeue loop): QoS
        decides WHEN an op starts; execution runs as its own task so one
        slow write never head-of-line blocks other clients/PGs."""
        while not self._stopped:
            item = self._opq.dequeue()
            if item is None:
                wait = self._opq.next_eligible_in()
                if wait is not None:
                    # throttled: sleep until the earliest L-tag matures
                    await asyncio.sleep(min(max(wait, 0.002), 0.25))
                else:
                    self._opq_event.clear()
                    try:
                        await asyncio.wait_for(self._opq_event.wait(), 5.0)
                    except asyncio.TimeoutError:
                        pass
                continue
            conn, msg, stamp = item
            if time.monotonic() - stamp > self.config.osd_client_op_timeout:
                # the client abandoned this attempt and resent: executing
                # the stale copy would double-apply the op
                self.perf.inc("osd_ops_dropped_stale")
                continue
            t = asyncio.get_event_loop().create_task(
                self._serve_queued_op(conn, msg))
            self._opq_running.add(t)
            t.add_done_callback(self._opq_running.discard)

    async def _serve_queued_op(self, conn, msg) -> None:
        try:
            resolved = await self._resolve_client_op(conn, msg)
            if resolved is None:
                return
            m, pool, st = resolved
            await self._dispatch_client_op(conn, msg, m, pool, st)
        except Exception as e:
            # mirror ms_dispatch's error contract: the client gets a
            # prompt EIO instead of a timeout
            self.perf.inc("osd_dispatch_errors")
            try:
                await conn.send(M.MOSDOpReply(
                    reqid=msg.reqid, result=-5, data=repr(e)))
            except (ConnectionError, OSError, RuntimeError):
                pass

    def set_qos(self, client: str, reservation: float = 0.0,
                weight: float = 1.0, limit: float = 0.0) -> None:
        """Live per-client QoS update (mclock profile analog)."""
        from ceph_tpu.cluster.dmclock import QoSSpec

        if self._opq is not None:
            self._opq.set_client(client, QoSSpec(
                reservation=reservation, weight=weight, limit=limit))

    # ops whose effects are not idempotent under at-least-once delivery;
    # a resend must return the cached original reply (reference pg_log
    # dup detection, PGLog dups / osd_pg_log_dups_tracked)
    _MUTATING_OPS = frozenset({
        "write_full", "write", "delete", "setxattr", "rmxattr",
        "omap_set", "omap_rmkeys", "exec"})
    _REQID_DUPS_TRACKED = 3000

    async def _dispatch_client_op(self, conn, msg, m, pool, st) -> None:
        self.perf.inc("osd_client_ops")
        top = self.tracker.create(
            f"osd_op({msg.reqid[0]}:{msg.reqid[1]} {msg.oid} "
            f"{[o[0] for o in msg.ops]})")
        top.mark("dispatched")
        try:
            if any(o[0] in self._MUTATING_OPS for o in msg.ops):
                await self._execute_mutation_dedup(conn, msg, m, pool, st,
                                                  top)
            else:
                await self._execute_client_ops(conn, msg, m, pool, st, top)
        finally:
            top.finish()

    async def _execute_mutation_dedup(self, conn, msg, m, pool, st, top):
        reqid = tuple(msg.reqid)
        cached = st.reqid_replies.get(reqid)
        if cached is None and reqid in st.reqid_inflight:
            # dup racing its first instance: wait for it, then answer
            # from its replies
            await asyncio.shield(st.reqid_inflight[reqid])
            cached = st.reqid_replies.get(reqid)
        if cached is not None:
            self.perf.inc("osd_dup_ops")
            top.mark("dup_reply_from_cache")
            for reply in cached:
                await conn.send(reply)
            return
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        st.reqid_inflight[reqid] = fut

        sent: List = []

        class _RecordingConn:
            """Forwards sends while capturing replies for the dup cache."""

            def __init__(self, inner):
                self._inner = inner

            def __getattr__(self, name):
                return getattr(self._inner, name)

            async def send(self, reply):
                sent.append(reply)
                await self._inner.send(reply)

        try:
            await self._execute_client_ops(
                _RecordingConn(conn), msg, m, pool, st, top)
            st.reqid_replies[reqid] = sent
            while len(st.reqid_replies) > self._REQID_DUPS_TRACKED:
                st.reqid_replies.popitem(last=False)
        finally:
            st.reqid_inflight.pop(reqid, None)
            if not fut.done():
                fut.set_result(None)

    async def _execute_client_ops(self, conn, msg, m, pool, st, top):
        for opname, args in msg.ops:
            if opname == "write_full":
                async with st.lock:
                    r = await self._op_write_full(
                        pool, st, msg.oid, args["data"])
                await conn.send(M.MOSDOpReply(
                    reqid=msg.reqid, result=r, epoch=m.epoch))
            elif opname == "write":
                async with st.lock:
                    r = await self._op_write(pool, st, msg.oid,
                                             args["offset"], args["data"])
                await conn.send(M.MOSDOpReply(
                    reqid=msg.reqid, result=r, epoch=m.epoch))
            elif opname == "read":
                try:
                    data = await self._op_read(
                        pool, st, msg.oid,
                        args.get("offset", 0), args.get("length"))
                    await conn.send(M.MOSDOpReply(
                        reqid=msg.reqid, result=0, data=data, epoch=m.epoch))
                except FileNotFoundError:
                    await conn.send(M.MOSDOpReply(
                        reqid=msg.reqid, result=-2, epoch=m.epoch))
            elif opname == "delete":
                async with st.lock:
                    r = await self._op_delete(pool, st, msg.oid)
                await conn.send(M.MOSDOpReply(
                    reqid=msg.reqid, result=r, epoch=m.epoch))
            elif opname == "stat":
                size = self.store.stat(_coll(st.pgid), msg.oid)
                if pool.is_erasure():
                    xs = self.store.getattr(_coll(st.pgid), msg.oid, "size")
                    size = int(xs) if xs else (None if size is None else size)
                await conn.send(M.MOSDOpReply(
                    reqid=msg.reqid,
                    result=0 if size is not None else -2,
                    data=size, epoch=m.epoch))
            elif opname == "list":
                names = self._list_pg_objects(st.pgid)
                await conn.send(M.MOSDOpReply(
                    reqid=msg.reqid, result=0, data=names, epoch=m.epoch))
            elif opname in ("getxattr", "getxattrs", "omap_get"):
                r, data = self._op_read_meta(st, msg.oid, opname, args)
                await conn.send(M.MOSDOpReply(
                    reqid=msg.reqid, result=r, data=data, epoch=m.epoch))
            elif opname in ("setxattr", "rmxattr", "omap_set",
                            "omap_rmkeys"):
                async with st.lock:
                    r = await self._op_write_meta(st, msg.oid, opname, args)
                await conn.send(M.MOSDOpReply(
                    reqid=msg.reqid, result=r, epoch=m.epoch))
            elif opname == "exec":
                async with st.lock:
                    r, data = await self._op_exec(st, msg.oid, args)
                await conn.send(M.MOSDOpReply(
                    reqid=msg.reqid, result=r, data=data, epoch=m.epoch))
            elif opname == "watch":
                self._watchers.setdefault((st.pgid, msg.oid), {})[
                    (str(msg.src), args["cookie"])] = conn
                self.perf.inc("osd_watches")
                await conn.send(M.MOSDOpReply(
                    reqid=msg.reqid, result=0, epoch=m.epoch))
            elif opname == "unwatch":
                self._watchers.get((st.pgid, msg.oid), {}).pop(
                    (str(msg.src), args["cookie"]), None)
                await conn.send(M.MOSDOpReply(
                    reqid=msg.reqid, result=0, epoch=m.epoch))
            elif opname == "notify":
                # off the connection's dispatch loop: a notifier that also
                # watches the object acks over this same connection, which
                # must keep reading while the notify gathers acks
                async def _notify_bg(reqid=msg.reqid, oid=msg.oid,
                                     a=args, epoch=m.epoch):
                    ackers = await self._op_notify(st, oid, a)
                    try:
                        await conn.send(M.MOSDOpReply(
                            reqid=reqid, result=0, data=ackers,
                            epoch=epoch))
                    except (ConnectionError, OSError):
                        pass

                self._tasks.append(
                    asyncio.get_event_loop().create_task(_notify_bg()))
            elif opname == "notify_ack":
                entry = self._notifies.get(args["notify_id"])
                if entry is not None:
                    fut, acked = entry
                    acked.add(str(msg.src))
                    if not fut.done() and len(acked) >= fut.needed:  # type: ignore[attr-defined]
                        fut.set_result(None)
                await conn.send(M.MOSDOpReply(
                    reqid=msg.reqid, result=0, epoch=m.epoch))
            else:
                await conn.send(M.MOSDOpReply(reqid=msg.reqid, result=-95))

    # ------------------------------------------------- xattr/omap/exec ops
    #
    # User xattrs are stored with a "_" prefix, exactly like the reference
    # object store's user-attr namespace, so they never collide with the
    # internal shard/size/hinfo attrs.

    def _op_read_meta(self, st: PGState, oid: str, opname: str, args):
        coll = _coll(st.pgid)
        if self.store.stat(coll, oid) is None:
            return -2, None
        if opname == "getxattr":
            v = self.store.getattr(coll, oid, "_" + args["name"])
            return (0, v) if v is not None else (-61, None)  # ENODATA
        if opname == "getxattrs":
            return 0, {k[1:]: v for k, v in
                       self.store.get_xattrs(coll, oid).items()
                       if k.startswith("_")}
        if opname == "omap_get":
            return 0, self.store.omap_get(coll, oid)
        return -95, None

    async def _op_write_meta(self, st: PGState, oid: str, opname: str,
                             args) -> int:
        """Metadata mutations ride the same logged+replicated transaction
        path as data writes (reference do_osd_ops xattr/omap cases write
        into the op's transaction, PrimaryLogPG.cc:4917)."""
        coll = _coll(st.pgid)
        txn = Transaction().touch(coll, oid)
        if opname == "setxattr":
            txn.setattr(coll, oid, "_" + args["name"], args["value"])
        elif opname == "rmxattr":
            txn.rmattr(coll, oid, "_" + args["name"])
        elif opname == "omap_set":
            txn.omap_set(coll, oid, args["kv"])
        elif opname == "omap_rmkeys":
            txn.omap_rmkeys(coll, oid, list(args["keys"]))
        version = self._next_version(st)
        txn.set_version(coll, oid, version[1])
        return await self._replicate_txn(st, txn, "modify", oid, version)

    async def _op_exec(self, st: PGState, oid: str, args):
        """Object-class execution (reference do_osd_ops CEPH_OSD_OP_CALL):
        the method's reads hit the store, its writes collect into a txn
        that commits + replicates atomically with the op."""
        from ceph_tpu.cluster.objclass import (
            ClassRegistry, ClsError, MethodContext,
        )

        coll = _coll(st.pgid)
        txn = Transaction().touch(coll, oid)
        ctx = MethodContext(self.store, coll, oid, txn)
        try:
            out = ClassRegistry.instance().call(
                args["cls"], args["method"], ctx, args.get("indata", b""))
        except ClsError as e:
            return e.errno, str(e)
        self.perf.inc("osd_cls_calls")
        if len(txn.ops) > 1:  # beyond the touch: mutations to commit
            version = self._next_version(st)
            txn.set_version(coll, oid, version[1])
            r = await self._replicate_txn(st, txn, "modify", oid, version)
            if r != 0:
                return r, None
        return 0, out

    async def _op_notify(self, st: PGState, oid: str, args):
        """Fan a notify out to every watcher and gather acks within the
        timeout (reference PrimaryLogPG::do_osd_op_effects + Notify)."""
        watchers = self._watchers.get((st.pgid, oid), {})
        live = {k: c for k, c in watchers.items() if not c.closed}
        self._watchers[(st.pgid, oid)] = live
        if not live:
            return []
        self._notify_id += 1
        nid = self._notify_id
        fut = asyncio.get_event_loop().create_future()
        fut.needed = len(live)  # type: ignore[attr-defined]
        acked: Set[str] = set()
        self._notifies[nid] = (fut, acked)
        for (watcher, cookie), conn in live.items():
            try:
                await conn.send(M.MWatchNotify(
                    pool=st.pgid.pool, oid=oid, notify_id=nid,
                    cookie=cookie, payload=args.get("payload", b"")))
            except (ConnectionError, OSError, RuntimeError):
                fut.needed -= 1  # type: ignore[attr-defined]
                if len(acked) >= fut.needed and not fut.done():  # type: ignore[attr-defined]
                    fut.set_result(None)
        try:
            if not fut.done() and fut.needed > 0:  # type: ignore[attr-defined]
                await asyncio.wait_for(
                    fut, timeout=args.get("timeout",
                                          self.config.osd_client_op_timeout))
        except asyncio.TimeoutError:
            pass
        finally:
            self._notifies.pop(nid, None)
        self.perf.inc("osd_notifies")
        return sorted(acked)

    # replicated write: local txn + MOSDRepOp fan-out (ReplicatedBackend)
    async def _op_write_full(self, pool: PGPool, st: PGState, oid: str,
                             data: bytes) -> int:
        if pool.is_erasure():
            return await self._ec_write(pool, st, oid, data, offset=None)
        version = self._next_version(st)
        txn = (Transaction()
               .remove(_coll(st.pgid), oid)
               .write(_coll(st.pgid), oid, 0, data)
               .set_version(_coll(st.pgid), oid, version[1]))
        return await self._replicate_txn(st, txn, "modify", oid, version)

    async def _op_write(self, pool: PGPool, st: PGState, oid: str,
                        offset: int, data: bytes) -> int:
        """Partial write at (offset, len) — the RMW path for EC pools
        (reference ECBackend::start_rmw, ECBackend.cc:1785)."""
        if pool.is_erasure():
            return await self._ec_write(pool, st, oid, data, offset=offset)
        version = self._next_version(st)
        txn = (Transaction()
               .write(_coll(st.pgid), oid, offset, data)
               .set_version(_coll(st.pgid), oid, version[1]))
        return await self._replicate_txn(st, txn, "modify", oid, version)

    async def _replicate_txn(self, st: PGState, txn: Transaction,
                             op: str, oid: str,
                             version: pglog.Eversion) -> int:
        """Apply locally + fan out with the log entry; commit when all
        acting replicas ack (reference PrimaryLogPG::issue_repop,
        PrimaryLogPG.cc:9173)."""
        self.store.queue_transaction(txn)
        entry = self._log_mutation(st, op, oid, version)
        peers = [o for o in st.acting
                 if o != self.osd_id and o != CRUSH_ITEM_NONE]
        if peers:
            reqid = self._next_reqid()
            fut = self._make_waiter(reqid, len(peers))
            rep = M.MOSDRepOp(reqid=reqid, pgid=st.pgid,
                              txn_blob=txn.encode(),
                              entry=entry,
                              epoch=self.osdmap.epoch)
            for o in peers:
                try:
                    await self._send_osd(o, rep)
                except (ConnectionError, OSError, RuntimeError):
                    # peer unreachable (map lag around a failure): the op
                    # proceeds on the reachable set; the logged entry
                    # delta-recovers the peer at rejoin (reference: the
                    # acting set shrinks, missing grows)
                    self._waiter_dec(reqid)
            try:
                if not fut.done():
                    await asyncio.wait_for(
                        fut, timeout=self.config.osd_client_op_timeout)
            except asyncio.TimeoutError:
                return -110
            finally:
                self._pending.pop(reqid, None)
        return 0

    async def _op_delete(self, pool: PGPool, st: PGState, oid: str) -> int:
        """Delete is ack-gated exactly like writes — fire-and-forget
        MOSDRepOps let a slow replica resurrect the object."""
        version = self._next_version(st)
        txn = Transaction().remove(_coll(st.pgid), oid)
        return await self._replicate_txn(st, txn, "delete", oid, version)

    async def _op_read(self, pool: PGPool, st: PGState, oid: str,
                       offset: int = 0, length: Optional[int] = None) -> bytes:
        if pool.is_erasure():
            return await self._ec_read(pool, st, oid, offset, length)
        return self.store.read(_coll(st.pgid), oid, offset, length)

    # ----------------------------------------------------------- EC backend
    #
    # Objects are striped (ECUtil::stripe_info_t math, ceph_tpu.ec.stripe):
    # shard s holds stripe-chunk s of every stripe, concatenated.  Encode /
    # decode of the whole touched stripe range happens in one batched TPU
    # dispatch; partial writes are read-modify-write over stripe bounds
    # (reference ECBackend::start_rmw, ECBackend.cc:1785-1886).

    async def _ec_write(self, pool: PGPool, st: PGState, oid: str,
                        data: bytes, offset: Optional[int]) -> int:
        """EC write incl. the RMW sequence (read old stripes, merge,
        re-encode, fan out shard writes).  Serialization: callers hold the
        PG-wide st.lock across the whole op, so overlapping RMWs to one
        object can never interleave (the reference serializes them in the
        ECBackend pipeline, ECBackend::start_rmw wait queue; our domain is
        the whole PG, like the reference's PG lock)."""
        from ceph_tpu.ec import stripe as stripemod

        codec = self._codec(pool)
        sinfo = self._sinfo(pool, codec)
        coll = _coll(st.pgid)
        eversion = self._next_version(st)
        version = eversion[1]

        if offset is None:
            # write_full: replace the object
            new_size = len(data)
            chunk_off = 0
            shards = await self._compute(
                stripemod.encode_stripes, codec, sinfo, data)
        else:
            sa = self.store.getattr(coll, oid, "size")
            old_size = int(sa) if sa else 0
            off0, len0 = sinfo.offset_len_to_stripe_bounds(offset, len(data))
            chunk_off = sinfo.aligned_logical_offset_to_chunk_offset(off0)
            old_in_range = max(0, min(old_size - off0, len0))
            old_bytes = b""
            if old_in_range:
                old_bytes = await self._ec_read_stripes(
                    pool, st, oid, chunk_off, old_in_range)
            merged = stripemod.merge_range(
                old_bytes, old_in_range, offset - off0, data)
            new_size = max(old_size, offset + len(data))
            shards = await self._compute(
                stripemod.encode_stripes, codec, sinfo, merged)

        shard_size = sinfo.shard_size(new_size)
        hinfo = {"size": new_size, "version": version}
        n = codec.get_chunk_count()
        reqid = self._next_reqid()
        peers = []
        my_shard = None
        for shard in range(n):
            osd = st.acting[shard] if shard < len(st.acting) else CRUSH_ITEM_NONE
            if osd == self.osd_id:
                my_shard = shard
            elif osd != CRUSH_ITEM_NONE:
                peers.append((osd, shard))
        if my_shard is not None:
            self._apply_shard(st.pgid, oid, my_shard,
                              shards[my_shard].tobytes(), chunk_off,
                              shard_size, hinfo)
        entry = self._log_mutation(st, "modify", oid, eversion)
        if peers:
            fut = self._make_waiter(reqid, len(peers))
            for osd, shard in peers:
                try:
                    await self._send_osd(osd, M.MOSDECSubOpWrite(
                        reqid=reqid, pgid=st.pgid, oid=oid, shard=shard,
                        data=shards[shard].tobytes(), chunk_off=chunk_off,
                        shard_size=shard_size, hinfo=hinfo, entry=entry,
                        epoch=self.osdmap.epoch))
                except (ConnectionError, OSError, RuntimeError):
                    self._waiter_dec(reqid)
            try:
                if not fut.done():
                    await asyncio.wait_for(
                        fut, timeout=self.config.osd_client_op_timeout)
            except asyncio.TimeoutError:
                return -110
            finally:
                self._pending.pop(reqid, None)
        return 0

    def _apply_shard(self, pgid: PGid, oid: str, shard: int, data: bytes,
                     chunk_off: int, shard_size: int, hinfo: Dict) -> None:
        """Apply a shard sub-range write with its crc in ONE atomic
        transaction (ECUtil::HashInfo analog, reference ECUtil.h:105-163:
        the crc is CUMULATIVE for appends/full rewrites — no whole-shard
        re-read on the hot path — and data+crc can never disagree)."""
        coll = _coll(pgid)
        old_size = self.store.stat(coll, oid)
        if chunk_off == 0 and len(data) >= shard_size:
            # full-shard rewrite: one pass over the payload
            crc = crcmod.crc32c(0xFFFFFFFF, data[:shard_size])
        elif old_size is not None and chunk_off == old_size and \
                shard_size == chunk_off + len(data):
            # append: combine the stored cumulative crc with the new
            # bytes' crc (GF(2) zero-extension, reference HashInfo append)
            stored = self.store.getattr(coll, oid, "hinfo_crc")
            if stored is not None:
                crc = crcmod.crc32c_combine(
                    int(stored), crcmod.crc32c(0, data), len(data))
            else:
                crc = crcmod.crc32c(0xFFFFFFFF,
                                    self.store.read(coll, oid) + data)
        else:
            # true mid-shard RMW: recompute over the merged bytes
            old = bytearray(self.store.read(coll, oid)) \
                if old_size is not None else bytearray()
            if len(old) < shard_size:
                old.extend(b"\0" * (shard_size - len(old)))
            old[chunk_off:chunk_off + len(data)] = data
            crc = crcmod.crc32c(0xFFFFFFFF, bytes(old[:shard_size]))
        txn = (Transaction()
               .write(coll, oid, chunk_off, data)
               .truncate(coll, oid, shard_size)
               .setattr(coll, oid, "shard", str(shard).encode())
               .setattr(coll, oid, "size", str(hinfo["size"]).encode())
               .setattr(coll, oid, "hinfo_crc", str(crc).encode())
               .set_version(coll, oid, hinfo["version"]))
        self.store.queue_transaction(txn)

    async def _handle_ec_write(self, conn: Connection,
                               msg: M.MOSDECSubOpWrite) -> None:
        shard_size = msg.shard_size if msg.shard_size is not None \
            else msg.chunk_off + len(msg.data)
        self._apply_shard(msg.pgid, msg.oid, msg.shard, msg.data,
                          msg.chunk_off, shard_size, msg.hinfo)
        st = self.pgs.get(msg.pgid)
        if st is not None and msg.entry is not None:
            self._log_mutation(st, msg.entry.op, msg.entry.oid,
                               msg.entry.version, entry=msg.entry)
        self.perf.inc("osd_ec_sub_writes")
        await conn.send(M.MOSDECSubOpWriteReply(reqid=msg.reqid, result=0))

    async def _handle_ec_read(self, conn: Connection,
                              msg: M.MOSDECSubOpRead) -> None:
        try:
            full = self.store.read(_coll(msg.pgid), msg.oid)
            stored_crc = self.store.getattr(_coll(msg.pgid), msg.oid,
                                            "hinfo_crc")
            # scrub-on-read: verify the shard crc (ecbackend.rst:86-99)
            if stored_crc is not None and \
                    int(stored_crc) != crcmod.crc32c(0xFFFFFFFF, full):
                raise IOError("chunk crc mismatch")
            data = full[msg.off: msg.off + msg.length] \
                if msg.length is not None else full[msg.off:]
            shard_attr = self.store.getattr(_coll(msg.pgid), msg.oid, "shard")
            shard = int(shard_attr) if shard_attr else msg.shard
            size = self.store.getattr(_coll(msg.pgid), msg.oid, "size")
            hinfo = {"size": int(size) if size else 0}
            if msg.shard == -1:
                # whole-object fetch (pull recovery): carry version +
                # xattrs so the puller stores a faithful copy
                hinfo["version"] = self.store.get_version(
                    _coll(msg.pgid), msg.oid)
                o = self.store._colls.get(_coll(msg.pgid), {}).get(msg.oid)
                hinfo["xattrs"] = dict(o.xattrs) if o else {}
            await conn.send(M.MOSDECSubOpReadReply(
                reqid=msg.reqid, result=0, shard=shard, data=data,
                hinfo=hinfo))
            self.perf.inc("osd_ec_sub_reads")
        except (FileNotFoundError, IOError):
            await conn.send(M.MOSDECSubOpReadReply(
                reqid=msg.reqid, result=-2, shard=msg.shard))

    async def _gather_shards(
        self, pool: PGPool, st: PGState, oid: str, need_k: int,
        off: int = 0, length: Optional[int] = None,
        exclude_shards: Optional[Set[int]] = None,
    ) -> Tuple[Dict[int, bytes], int]:
        """Collect >= k shard (ranges) from the acting set (own shard
        free).  ``exclude_shards``: shard ids known corrupt — they must
        never be decode sources (scrub repair would otherwise reconstruct
        FROM the corruption and bless it)."""
        exclude_shards = exclude_shards or set()
        shards: Dict[int, bytes] = {}
        size = 0
        my = self.store.stat(_coll(st.pgid), oid)
        if my is not None:
            data = self.store.read(_coll(st.pgid), oid, off, length)
            shard_attr = self.store.getattr(_coll(st.pgid), oid, "shard")
            if shard_attr is not None and                     int(shard_attr) not in exclude_shards:
                shards[int(shard_attr)] = data
            sa = self.store.getattr(_coll(st.pgid), oid, "size")
            size = int(sa) if sa else 0
        peers = [(shard, osd) for shard, osd in enumerate(st.acting)
                 if osd not in (self.osd_id, CRUSH_ITEM_NONE)
                 and shard not in shards and shard not in exclude_shards]
        if peers and len(shards) < need_k:
            reqid = self._next_reqid()
            fut = self._make_waiter(reqid, len(peers))
            for shard, osd in peers:
                try:
                    await self._send_osd(osd, M.MOSDECSubOpRead(
                        reqid=reqid, pgid=st.pgid, oid=oid, shard=shard,
                        off=off, length=length))
                except (ConnectionError, OSError, RuntimeError):
                    self._waiter_dec(reqid)
            try:
                if fut.done():
                    acc = fut.result()
                else:
                    acc = await asyncio.wait_for(
                        fut, timeout=self.config.osd_client_op_timeout)
            except asyncio.TimeoutError:
                acc = self._pending[reqid][1]
            finally:
                self._pending.pop(reqid, None)
            for result, reply in acc:
                if result == 0 and reply is not None:
                    shards[reply.shard] = reply.data
                    if reply.hinfo.get("size"):
                        size = reply.hinfo["size"]
        return shards, size

    async def _ec_read_stripes(self, pool: PGPool, st: PGState, oid: str,
                               chunk_off: int, logical_len: int) -> bytes:
        """Read a stripe-aligned logical range: gather the touched chunk
        range from >= k shards and decode it as a mini-object."""
        from ceph_tpu.ec import stripe as stripemod
        import numpy as np

        codec = self._codec(pool)
        sinfo = self._sinfo(pool, codec)
        k = codec.get_data_chunk_count()
        nstripes = sinfo.object_stripes(logical_len)
        chunk_len = nstripes * sinfo.chunk_size
        shards, _ = await self._gather_shards(
            pool, st, oid, k, off=chunk_off, length=chunk_len)
        avail = {s: np.frombuffer(d, dtype=np.uint8)
                 for s, d in shards.items()
                 if len(d) == chunk_len}
        if len(avail) < k:
            raise IOError(
                f"only {len(avail)} of {k} shard ranges for {oid}")
        return await self._compute(
            stripemod.decode_stripes, codec, sinfo, avail, logical_len)

    async def _ec_read(self, pool: PGPool, st: PGState, oid: str,
                       offset: int = 0, length: Optional[int] = None) -> bytes:
        """objects_read_async analog: min shards + batched TPU decode
        (ECBackend.cc:2111,1588,2262)."""
        coll = _coll(st.pgid)
        sa = self.store.getattr(coll, oid, "size")
        if sa is None:
            # primary lost its shard (or never had one): probe peers
            codec = self._codec(pool)
            shards, size = await self._gather_shards(
                pool, st, oid, codec.get_data_chunk_count(), 0, 0)
            if not shards and size == 0:
                raise FileNotFoundError(oid)
        else:
            size = int(sa)
        if length is None:
            length = max(0, size - offset)
        if length == 0 or offset >= size:
            return b""
        length = min(length, size - offset)
        codec = self._codec(pool)
        sinfo = self._sinfo(pool, codec)
        off0, len0 = sinfo.offset_len_to_stripe_bounds(offset, length)
        len0 = min(len0, max(0, size - off0))
        chunk_off = sinfo.aligned_logical_offset_to_chunk_offset(off0)
        out = await self._ec_read_stripes(pool, st, oid, chunk_off, len0)
        return out[offset - off0: offset - off0 + length]

    # ------------------------------------------------------------- recovery

    async def _recover_all(self) -> None:
        await asyncio.sleep(self.config.osd_recovery_delay_start)
        for pgid, st in list(self.pgs.items()):
            if st.primary == self.osd_id:
                try:
                    await self._recover_pg(st)
                except Exception:
                    # count AND surface: a silently-failing recovery loop
                    # means a pool that never re-protects itself
                    self.perf.inc("osd_recovery_errors")
                    import logging
                    logging.getLogger("ceph_tpu.osd").exception(
                        "osd.%d: recovery of pg %s failed", self.osd_id, pgid)

    async def _query_pg(self, osd: int, pgid: PGid):
        """GetInfo/GetLog exchange with one member (reference peering
        Query/Notify, PG.h RecoveryMachine GetInfo)."""
        key = ("pgq", str(pgid), osd)
        fut = self._make_waiter(key, 1)
        try:
            await self._send_osd(osd, MOSDPGQuery(pgid=pgid))
            acc = await asyncio.wait_for(fut, timeout=2.0)
            return acc[0][1]
        except (asyncio.TimeoutError, ConnectionError):
            return None
        finally:
            self._pending.pop(key, None)

    async def _recover_pg(self, st: PGState) -> None:
        """Primary-driven peering + recovery (flattened RecoveryMachine,
        reference src/osd/PG.h:1994-2498):

        1. GetInfo: collect (last_update, log) from every acting member.
        2. GetLog: the max last_update owns the authoritative log; if that
           is not us, bring ourselves up first (delta when our
           last_update is inside the auth log window, backfill otherwise).
        3. Active/Recovering: push ONLY the log delta to each stale
           member; full-inventory backfill when a member is behind the
           log tail.

        Runs under the PG lock: peering mutates st.log/st.last_update, and
        a client write interleaving with log adoption could regress
        last_update and reuse an eversion (the reference blocks ops during
        peering for the same reason)."""
        async with st.lock:
            await self._recover_pg_locked(st)

    async def _recover_pg_locked(self, st: PGState) -> None:
        m = self.osdmap
        pool = m.pools[st.pgid.pool]
        members = [o for o in st.acting
                   if o not in (self.osd_id, CRUSH_ITEM_NONE)]
        infos: Dict[int, PGInfo] = {self.osd_id: st.info()}
        logs: Dict[int, PGLog] = {self.osd_id: st.log}
        inventories: Dict[int, Dict[str, int]] = {}
        for osd in members:
            reply = await self._query_pg(osd, st.pgid)
            if reply is None:
                continue
            infos[osd] = reply.info or PGInfo()
            logs[osd] = reply.log or PGLog()
            inventories[osd] = reply.objects or {}

        auth = pglog.choose_authoritative(infos)
        if auth != self.osd_id and \
                infos[auth].last_update > st.last_update:
            await self._sync_self_from(
                pool, st, auth, logs[auth], inventories.get(auth, {}))

        for osd in members:
            if osd not in infos:
                continue
            peer_lu = infos[osd].last_update
            if peer_lu >= st.last_update:
                continue
            to_sync = st.log.objects_to_sync(peer_lu)
            if to_sync is None:
                await self._backfill_member(
                    pool, st, osd, inventories.get(osd, {}))
            else:
                # replay in VERSION order so the member's log advances
                # monotonically (out-of-order pushes would hit the
                # duplicate guard and leave silent log holes)
                for oid, entry in sorted(to_sync.items(),
                                         key=lambda kv: kv[1].version):
                    await self._push_object(pool, st, osd, oid, entry)
        self.perf.inc("osd_pg_recoveries")

    async def _sync_self_from(self, pool: PGPool, st: PGState, auth: int,
                              auth_log: PGLog,
                              auth_inventory: Dict[str, int]) -> None:
        """Bring the primary up to the authoritative member's state."""
        coll = _coll(st.pgid)
        to_sync = auth_log.objects_to_sync(st.last_update)
        if to_sync is None:
            # behind the log window: full backfill from auth's inventory
            mine = {oid: self.store.get_version(coll, oid)
                    for oid in self._list_pg_objects(st.pgid)}
            to_pull = [oid for oid, ver in auth_inventory.items()
                       if mine.get(oid, -1) < ver]
            # objects we hold that the authoritative member does not =
            # deletes we missed (possibly trimmed past the log tail);
            # without this, a rejoining primary resurrects deleted objects
            for oid in mine:
                if oid not in auth_inventory:
                    self.store.queue_transaction(
                        Transaction().remove(coll, oid))
        else:
            to_pull = []
            for oid, entry in to_sync.items():
                if entry.op == "delete":
                    self.store.queue_transaction(
                        Transaction().remove(coll, oid))
                else:
                    to_pull.append(oid)
        ok = True
        for oid in to_pull:
            if pool.is_erasure():
                ok &= await self._recover_ec_object(
                    pool, st, oid, targets=[self.osd_id])
            else:
                ok &= await self._pull_rep_object(st, auth, oid)
        if not ok:
            # a pull failed (auth unreachable mid-recovery): do NOT claim
            # the authoritative version — stay stale so the next peering
            # round retries instead of serving/pushing stale bytes as new
            self.perf.inc("osd_recovery_incomplete")
            return
        # adopt the authoritative log
        st.log = PGLog(tail=auth_log.tail,
                       entries=list(auth_log.entries),
                       max_entries=auth_log.max_entries)
        st.last_update = auth_log.head if auth_log.entries else \
            max(st.last_update, auth_log.tail)
        self._save_pg_meta(st)

    async def _pull_rep_object(self, st: PGState, source: int,
                               oid: str) -> bool:
        """Fetch a full replicated object from a member (pull recovery,
        reference ReplicatedBackend::prepare_pull).  Returns success: the
        caller must NOT claim the authoritative version for objects it
        failed to pull."""
        reqid = self._next_reqid()
        fut = self._make_waiter(reqid, 1)
        try:
            await self._send_osd(source, M.MOSDECSubOpRead(
                reqid=reqid, pgid=st.pgid, oid=oid, shard=-1))
            acc = await asyncio.wait_for(fut, timeout=2.0)
            result, reply = acc[0]
            if result == 0 and reply is not None:
                txn = (Transaction()
                       .remove(_coll(st.pgid), oid)
                       .write(_coll(st.pgid), oid, 0, reply.data)
                       .set_version(_coll(st.pgid), oid,
                                    reply.hinfo.get("version", 0)))
                for k, v in reply.hinfo.get("xattrs", {}).items():
                    txn.setattr(_coll(st.pgid), oid, k, v)
                self.store.queue_transaction(txn)
                return True
        except (asyncio.TimeoutError, ConnectionError):
            pass
        finally:
            self._pending.pop(reqid, None)
        return False

    async def _push_object(self, pool: PGPool, st: PGState, osd: int,
                           oid: str, entry: LogEntry) -> None:
        """Replay one log entry onto a stale member (delta recovery)."""
        if entry.op == "delete":
            try:
                await self._send_osd(osd, M.MOSDPGPush(
                    pgid=st.pgid, oid=oid, op="delete",
                    version=entry.version[1], entry=entry))
                self.perf.inc("osd_pushes_sent")
            except ConnectionError:
                pass
            return
        if pool.is_erasure():
            await self._recover_ec_object(pool, st, oid, targets=[osd],
                                          entry=entry)
            return
        coll = _coll(st.pgid)
        if self.store.stat(coll, oid) is None:
            return
        data = self.store.read(coll, oid)
        try:
            await self._send_osd(osd, M.MOSDPGPush(
                pgid=st.pgid, oid=oid, data=data,
                version=entry.version[1], entry=entry))
            self.perf.inc("osd_pushes_sent")
        except ConnectionError:
            pass

    async def _backfill_member(self, pool: PGPool, st: PGState, osd: int,
                               inventory: Dict[str, int]) -> None:
        """Full-inventory resync for a member behind the log tail
        (reference Backfilling state)."""
        for oid in self._list_pg_objects(st.pgid):
            ver = self.store.get_version(_coll(st.pgid), oid)
            if inventory.get(oid, -1) >= ver:
                continue
            if pool.is_erasure():
                await self._recover_ec_object(pool, st, oid, targets=[osd])
            else:
                data = self.store.read(_coll(st.pgid), oid)
                try:
                    await self._send_osd(osd, M.MOSDPGPush(
                        pgid=st.pgid, oid=oid, data=data, version=ver))
                    self.perf.inc("osd_pushes_sent")
                except ConnectionError:
                    pass
        # stale objects the member has but we (authoritative) don't
        mine = set(self._list_pg_objects(st.pgid))
        for oid in inventory:
            if oid not in mine:
                try:
                    await self._send_osd(osd, M.MOSDPGPush(
                        pgid=st.pgid, oid=oid, op="delete",
                        version=st.last_update[1]))
                    self.perf.inc("osd_pushes_sent")
                except ConnectionError:
                    pass
        # hand the member our log state so the next peering round sees it
        # as current instead of re-backfilling
        blob = pickle.dumps((st.last_update, st.log))
        try:
            await self._send_osd(osd, M.MOSDPGPush(
                pgid=st.pgid, op="log_sync", data=blob))
        except ConnectionError:
            pass

    async def _recover_ec_object(self, pool: PGPool, st: PGState, oid: str,
                                 targets: Optional[List[int]] = None,
                                 entry: Optional[LogEntry] = None,
                                 exclude_sources: Optional[Set[int]] = None,
                                 ) -> bool:
        """Reconstruct shards for the target members (batched TPU decode +
        encode, ECBackend::run_recovery_op analog).  targets=None rebuilds
        every acting member's shard; exclude_sources keeps known-corrupt
        shard ids out of the decode.  Returns False when the object is
        currently unrecoverable (fewer than k shard sources)."""
        from ceph_tpu.ec import stripe as stripemod
        import numpy as np

        codec = self._codec(pool)
        sinfo = self._sinfo(pool, codec)
        k = codec.get_data_chunk_count()
        shards, size = await self._gather_shards(
            pool, st, oid, k, exclude_shards=exclude_sources)
        shard_len = sinfo.shard_size(size)
        avail = {s: np.frombuffer(d, dtype=np.uint8)
                 for s, d in shards.items() if len(d) == shard_len}
        if len(avail) < k:
            self.perf.inc("osd_unrecoverable")
            return False
        data = await self._compute(
            stripemod.decode_stripes, codec, sinfo, avail, size)
        chunks = await self._compute(
            stripemod.encode_stripes, codec, sinfo, data)
        version = max((self.store.get_version(_coll(st.pgid), oid)), 1)
        hinfo = {"size": size, "version": version}
        for shard, osd in enumerate(st.acting):
            if osd == CRUSH_ITEM_NONE:
                continue
            if targets is not None and osd not in targets:
                continue
            blob = chunks[shard].tobytes()
            if osd == self.osd_id:
                self._apply_shard(st.pgid, oid, shard, blob, 0,
                                  shard_len, hinfo)
            else:
                try:
                    await self._send_osd(osd, M.MOSDECSubOpWrite(
                        reqid=self._next_reqid(), pgid=st.pgid, oid=oid,
                        shard=shard, data=blob, chunk_off=0,
                        shard_size=shard_len, hinfo=hinfo, entry=entry,
                        epoch=self.osdmap.epoch))
                    self.perf.inc("osd_pushes_sent")
                except ConnectionError:
                    pass
        return True

    # --------------------------------------------------------------- scrub
    #
    # Background integrity verification (reference PG scrub +
    # ecbackend.rst:86-99): the primary collects per-member scrub maps
    # (oid -> computed crc32c over the bytes, batched on the device where
    # object sizes group), detects divergent replicas / corrupt EC shards
    # WITHOUT a client read, and repairs through the recovery machinery.

    def _build_scrub_map(self, pgid: PGid) -> Dict[str, Tuple]:
        """oid -> (version, size, computed_crc, stored_crc).  Equal-size
        objects CRC in ONE device dispatch (crc32c_batch); odd sizes fall
        back to the host path."""
        import numpy as np

        coll = _coll(pgid)
        oids = self._list_pg_objects(pgid)
        blobs = {oid: self.store.read(coll, oid) for oid in oids}
        by_len: Dict[int, List[str]] = {}
        for oid, b in blobs.items():
            by_len.setdefault(len(b), []).append(oid)
        crcs: Dict[str, int] = {}
        for ln, group in by_len.items():
            if len(group) >= 2 and ln > 0:
                arr = np.stack([
                    np.frombuffer(blobs[o], dtype=np.uint8) for o in group])
                vals = np.asarray(crcmod.crc32c_batch(arr))
                for o, v in zip(group, vals):
                    crcs[o] = int(v)
            else:
                for o in group:
                    crcs[o] = crcmod.crc32c(0xFFFFFFFF, blobs[o])
        out = {}
        for oid in oids:
            stored = self.store.getattr(coll, oid, "hinfo_crc")
            out[oid] = (self.store.get_version(coll, oid),
                        len(blobs[oid]), crcs[oid],
                        int(stored) if stored is not None else None)
        return out

    async def scrub_pg(self, st: PGState) -> Dict[str, List[str]]:
        """Primary-driven scrub of one PG; returns
        {"inconsistent": [...], "repaired": [...]}."""
        async with st.lock:
            return await self._scrub_pg_locked(st)

    async def _scrub_pg_locked(self, st: PGState) -> Dict[str, List[str]]:
        pool = self.osdmap.pools[st.pgid.pool]
        members = [o for o in st.acting
                   if o not in (self.osd_id, CRUSH_ITEM_NONE)]
        maps: Dict[int, Dict[str, Tuple]] = {
            self.osd_id: self._build_scrub_map(st.pgid)}
        for osd in members:
            reqid = self._next_reqid()
            fut = self._make_waiter(reqid, 1)
            try:
                await self._send_osd(osd, M.MOSDScrub(
                    reqid=reqid, pgid=st.pgid))
                acc = await asyncio.wait_for(fut, timeout=5.0)
                _, reply = acc[0]
                if reply is not None:
                    maps[osd] = reply.objects
            except (asyncio.TimeoutError, ConnectionError):
                pass
            finally:
                self._pending.pop(reqid, None)
        inconsistent: List[str] = []
        repaired: List[str] = []
        if pool.is_erasure():
            # every shard is distinct: a member is corrupt when the crc of
            # its bytes no longer matches its stored hinfo crc
            for osd, smap in maps.items():
                for oid, (_ver, _size, crc, stored) in smap.items():
                    if stored is not None and crc != stored:
                        inconsistent.append(oid)
                        self.perf.inc("osd_scrub_errors")
                        bad_shard = {i for i, o in enumerate(st.acting)
                                     if o == osd}
                        ok = await self._recover_ec_object(
                            pool, st, oid, targets=[osd],
                            exclude_sources=bad_shard)
                        if ok:
                            repaired.append(oid)
        else:
            # replicated: majority crc wins, divergent members get the
            # authoritative copy re-pushed
            all_oids = set()
            for smap in maps.values():
                all_oids.update(smap)
            for oid in sorted(all_oids):
                votes: Dict[Tuple[int, int], List[int]] = {}
                for osd, smap in maps.items():
                    if oid in smap:
                        ver, size, crc, _ = smap[oid]
                        votes.setdefault((size, crc), []).append(osd)
                if len(votes) <= 1 and all(oid in m for m in maps.values()):
                    continue
                inconsistent.append(oid)
                self.perf.inc("osd_scrub_errors")
                # only auto-repair with a strict-majority authoritative
                # copy; on a tie (e.g. 1-1 on size-2 pools) repairing
                # would arbitrarily overwrite a possibly-good replica —
                # the reference marks the object inconsistent instead
                sizes = sorted((len(v) for v in votes.values()),
                               reverse=True)
                if len(sizes) > 1 and sizes[0] == sizes[1]:
                    self.perf.inc("osd_scrub_ties")
                    continue
                winner = max(votes.values(), key=len)
                if self.osd_id not in winner:
                    if not await self._pull_rep_object(st, winner[0], oid):
                        continue
                data = self.store.read(_coll(st.pgid), oid)
                ver = self.store.get_version(_coll(st.pgid), oid)
                fixed = True
                for osd in members:
                    if osd in winner:
                        continue
                    try:
                        await self._send_osd(osd, M.MOSDPGPush(
                            pgid=st.pgid, oid=oid, op="repair",
                            data=data, version=ver))
                        self.perf.inc("osd_pushes_sent")
                    except ConnectionError:
                        fixed = False
                if fixed:
                    repaired.append(oid)
        self.perf.inc("osd_scrubs")
        return {"inconsistent": inconsistent, "repaired": repaired}

    async def _scrub_loop(self) -> None:
        """Periodic background scrub of primary PGs (reference scrub
        scheduling; interval 0 disables)."""
        interval = self.config.osd_scrub_interval
        if not interval:
            return
        while not self._stopped:
            await asyncio.sleep(interval)
            for st in list(self.pgs.values()):
                if st.primary == self.osd_id and not self._stopped:
                    try:
                        await self.scrub_pg(st)
                    except Exception:
                        self.perf.inc("osd_scrub_errors")

    def _handle_push(self, msg: M.MOSDPGPush) -> None:
        coll = _coll(msg.pgid)
        st = self.pgs.get(msg.pgid)
        if msg.op == "log_sync":
            if st is not None:
                st.last_update, st.log = pickle.loads(msg.data)
                self._save_pg_meta(st)
            return
        if msg.op == "delete":
            # version-guarded like pushes: a stale delete (old primary's
            # backfill racing a newer primary's push) must not remove a
            # newer object
            cur = self.store.get_version(coll, msg.oid)
            if cur <= msg.version:
                self.store.queue_transaction(
                    Transaction().remove(coll, msg.oid))
        else:
            cur = self.store.get_version(coll, msg.oid)
            exists = self.store.stat(coll, msg.oid) is not None
            # op == "repair": scrub found silent corruption (same version,
            # wrong bytes) — apply unconditionally
            if msg.op == "repair" or not (exists and cur >= msg.version):
                txn = (Transaction()
                       .remove(coll, msg.oid)
                       .write(coll, msg.oid, 0, msg.data)
                       .set_version(coll, msg.oid, msg.version))
                for k, v in msg.xattrs.items():
                    txn.setattr(coll, msg.oid, k, v)
                self.store.queue_transaction(txn)
        if st is not None and msg.entry is not None:
            self._log_mutation(st, msg.entry.op, msg.entry.oid,
                               msg.entry.version, entry=msg.entry)
        self.perf.inc("osd_pushes_applied")

    # ------------------------------------------------------------ heartbeat

    async def _heartbeat_loop(self) -> None:
        while not self._stopped:
            await asyncio.sleep(self.config.osd_heartbeat_interval)
            m = self.osdmap
            if m is None:
                continue
            now = time.monotonic()
            # beacon to the mon (reference MOSDBeacon): lets the mon mark
            # us down even when no peer reporters survive; never let a
            # transport hiccup kill the heartbeat task
            try:
                await self._mon_send(M.MOSDAlive(osd_id=self.osd_id))
            except Exception:
                pass
            # perf-counter stream to the active mgr (MgrClient::send_report)
            mgr_addr = getattr(m, "mgr_addr", None)
            if mgr_addr:
                try:
                    await self.messenger.send_message(M.MMgrReport(
                        daemon=f"osd.{self.osd_id}",
                        counters=self.perf.dump()[f"osd.{self.osd_id}"],
                        stamp=now), tuple(mgr_addr))
                except (ConnectionError, OSError, RuntimeError):
                    pass
            for osd, addr in list(m.osd_addrs.items()):
                if osd == self.osd_id or not m.osd_up[osd]:
                    continue
                try:
                    await self.messenger.send_message(
                        M.MPing(stamp=now), addr)
                except (ConnectionError, OSError):
                    pass
                last = self._hb_last.get(osd)
                if last is not None and \
                        now - last > self.config.osd_heartbeat_grace and \
                        osd not in self._reported:
                    self._reported.add(osd)
                    if await self._mon_send(M.MOSDFailure(
                            failed_osd=osd, reporter=self.osd_id)):
                        self.perf.inc("osd_failure_reports")
                elif last is None:
                    self._hb_last[osd] = now
            # once the monitor marks a reported peer down, forget it so a
            # future reboot is tracked afresh
            for osd in list(self._reported):
                if not m.osd_up[osd]:
                    self._reported.discard(osd)
                    self._hb_last.pop(osd, None)
