"""OSD daemon: PGs, replicated and erasure-coded backends, recovery.

Structural mirror of the reference OSD (src/osd/OSD.cc dispatch ->
PrimaryLogPG op execution; ReplicatedBackend transaction fan-out;
ECBackend shard writes/reads, src/osd/ECBackend.cc:921,986,1141), with the
dense compute — erasure encode/decode, chunk crc32c — running through the
TPU codec engine.  Heartbeats/failure reports mirror OSD::heartbeat_check
(OSD.cc:4763) -> MOSDFailure -> monitor.  Recovery re-synchronizes PG
contents on map change (push recovery; EC shards reconstructed by decode,
ECBackend::run_recovery_op analog).
"""

from __future__ import annotations

import asyncio
import pickle
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ceph_tpu.cluster import messages as M
from ceph_tpu.cluster.messenger import (
    Addr,
    Connection,
    Dispatcher,
    EntityName,
    Messenger,
)
from ceph_tpu.cluster.store import MemStore, ObjectStore, Transaction
from ceph_tpu.crush.types import CRUSH_ITEM_NONE
from ceph_tpu.ops import crc32c as crcmod
from ceph_tpu.osdmap.osdmap import OSDMap, PGid, PGPool
from ceph_tpu.utils import Config, PerfCounters


@dataclass
class PGState:
    pgid: PGid
    up: List[int] = field(default_factory=list)
    acting: List[int] = field(default_factory=list)
    primary: int = -1


@dataclass
class MOSDPGQuery(M.Message):
    pgid: Optional[PGid] = None


@dataclass
class MOSDPGQueryReply(M.Message):
    pgid: Optional[PGid] = None
    objects: Dict[str, int] = field(default_factory=dict)  # oid -> version


def _coll(pgid: PGid) -> str:
    return f"pg_{pgid.pool}_{pgid.seed}"


class OSDDaemon(Dispatcher):
    def __init__(self, osd_id: int, mon_addr: Addr,
                 config: Optional[Config] = None,
                 store: Optional[ObjectStore] = None):
        self.osd_id = osd_id
        self.mon_addr = tuple(mon_addr)
        self.config = config or Config()
        self.store = store or MemStore()
        self.messenger = Messenger(EntityName("osd", osd_id))
        self.messenger.add_dispatcher(self)
        self.osdmap: Optional[OSDMap] = None
        self.pgs: Dict[PGid, PGState] = {}
        self.perf = PerfCounters(f"osd.{osd_id}")
        self._codecs: Dict[int, object] = {}
        self._obj_locks: Dict[Tuple[PGid, str], list] = {}  # [Lock, refcount]
        self._pending: Dict[Tuple, Tuple[asyncio.Future, List]] = {}
        self._tid = 0
        self._tasks: List[asyncio.Task] = []
        self._hb_last: Dict[int, float] = {}
        self._reported: Set[int] = set()
        self._stopped = False

    # ------------------------------------------------------------ lifecycle

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> Addr:
        addr = await self.messenger.bind(host, port)
        await self.messenger.send_message(
            M.MOSDBoot(osd_id=self.osd_id, addr=addr), self.mon_addr)
        await self.messenger.send_message(
            M.MMonSubscribe(what="osdmap", addr=addr), self.mon_addr)
        loop = asyncio.get_event_loop()
        self._tasks.append(loop.create_task(self._heartbeat_loop()))
        return addr

    async def stop(self) -> None:
        self._stopped = True
        for t in self._tasks:
            t.cancel()
        await self.messenger.shutdown()

    def _next_reqid(self) -> Tuple[str, int]:
        self._tid += 1
        return (f"osd.{self.osd_id}", self._tid)

    def _codec(self, pool: PGPool):
        codec = self._codecs.get(pool.pool_id)
        if codec is None:
            from ceph_tpu.ec import factory

            profile = pool.ec_profile or {
                "plugin": "jerasure", "technique": "reed_sol_van",
                "k": "2", "m": "1"}
            codec = factory(profile)
            self._codecs[pool.pool_id] = codec
        return codec

    def _sinfo(self, pool: PGPool, codec) -> "StripeInfo":
        """Stripe layout for a pool (ECUtil::stripe_info_t analog)."""
        from ceph_tpu.ec.stripe import StripeInfo

        unit = int((pool.ec_profile or {}).get(
            "stripe_unit", self.config.osd_ec_stripe_unit))
        return StripeInfo(codec.get_data_chunk_count(), unit)

    # ------------------------------------------------------------- dispatch

    async def ms_dispatch(self, conn: Connection, msg) -> bool:
        try:
            return await self._dispatch(conn, msg)
        except Exception as e:
            self.perf.inc("osd_dispatch_errors")
            if isinstance(msg, M.MOSDOp):
                await conn.send(M.MOSDOpReply(
                    reqid=msg.reqid, result=-5, data=repr(e)))
                return True
            raise

    async def _dispatch(self, conn: Connection, msg) -> bool:
        if isinstance(msg, M.MOSDMapMsg):
            await self._handle_map(msg)
            return True
        if isinstance(msg, M.MOSDIncMapMsg):
            await self._handle_inc_map(msg)
            return True
        if isinstance(msg, M.MOSDOp):
            await self._handle_client_op(conn, msg)
            return True
        if isinstance(msg, M.MOSDRepOp):
            txn = Transaction.decode(msg.txn_blob)
            self.store.queue_transaction(txn)
            self.perf.inc("osd_rep_ops")
            await conn.send(M.MOSDRepOpReply(reqid=msg.reqid, result=0))
            return True
        if isinstance(msg, M.MOSDRepOpReply) or \
                isinstance(msg, M.MOSDECSubOpWriteReply):
            self._ack(msg.reqid, msg.result)
            return True
        if isinstance(msg, M.MOSDECSubOpWrite):
            await self._handle_ec_write(conn, msg)
            return True
        if isinstance(msg, M.MOSDECSubOpRead):
            await self._handle_ec_read(conn, msg)
            return True
        if isinstance(msg, M.MOSDECSubOpReadReply):
            self._ack(msg.reqid, msg.result, msg)
            return True
        if isinstance(msg, M.MOSDPGPush):
            self._handle_push(msg)
            await conn.send(M.MOSDPGPushReply(
                pgid=msg.pgid, oid=msg.oid, result=0))
            return True
        if isinstance(msg, M.MOSDPGPushReply):
            return True
        if isinstance(msg, MOSDPGQuery):
            objects = {
                oid: self.store.get_version(_coll(msg.pgid), oid)
                for oid in self.store.list_objects(_coll(msg.pgid))
            }
            await conn.send(MOSDPGQueryReply(pgid=msg.pgid, objects=objects))
            return True
        if isinstance(msg, MOSDPGQueryReply):
            self._ack(("pgq", str(msg.pgid), msg.src.num), 0, msg)
            return True
        if isinstance(msg, M.MPing):
            if msg.reply:
                if msg.src is not None:
                    self._hb_last[msg.src.num] = time.monotonic()
            else:
                await conn.send(M.MPing(stamp=msg.stamp, reply=True))
            return True
        return False

    # -------------------------------------------------------------- helpers

    async def _compute(self, fn, *args):
        """Run codec compute (encode/decode, possibly a first-call jit
        compile) off the event loop.  Blocking the loop here starves
        heartbeat replies and triggers false failure reports — the reference
        isolates heartbeats on dedicated messengers for the same reason
        (src/ceph_osd.cc:459-486 creates 4 hb messengers)."""
        return await asyncio.get_event_loop().run_in_executor(
            None, lambda: fn(*args))

    def _ack(self, key, result, payload=None) -> None:
        entry = self._pending.get(tuple(key) if isinstance(key, tuple) else key)
        if entry is None:
            return
        fut, acc = entry
        acc.append((result, payload))
        if len(acc) >= fut.needed and not fut.done():  # type: ignore[attr-defined]
            fut.set_result(acc)

    def _make_waiter(self, key, needed: int) -> asyncio.Future:
        fut = asyncio.get_event_loop().create_future()
        fut.needed = needed  # type: ignore[attr-defined]
        self._pending[key] = (fut, [])
        return fut

    async def _send_osd(self, osd: int, msg) -> None:
        addr = self.osdmap.osd_addrs.get(osd)
        if addr is None:
            raise ConnectionError(f"no address for osd.{osd}")
        await self.messenger.send_message(msg, addr)

    # ------------------------------------------------------------ map flow

    async def _handle_inc_map(self, msg: M.MOSDIncMapMsg) -> None:
        """Apply a delta chain (reference handle_osd_map incremental path).
        On an epoch gap, re-subscribe from our epoch to resync."""
        m = self.osdmap
        if m is None or msg.prev_epoch != m.epoch:
            if m is not None and msg.epoch <= m.epoch:
                return  # stale or duplicate
            await self.messenger.send_message(
                M.MMonSubscribe(what="osdmap", addr=self.messenger.my_addr,
                                since=m.epoch if m else 0), self.mon_addr)
            return
        for blob in msg.inc_blobs:
            m.apply_incremental(pickle.loads(blob))
        self.perf.set("osd_map_epoch", m.epoch)
        await self._post_map_update()

    async def _handle_map(self, msg: M.MOSDMapMsg) -> None:
        newmap: OSDMap = pickle.loads(msg.osdmap_blob)
        old = self.osdmap
        if old is not None and newmap.epoch < old.epoch:
            return  # stale full map
        self.osdmap = newmap
        self.perf.set("osd_map_epoch", newmap.epoch)
        await self._post_map_update()

    async def _post_map_update(self) -> None:
        newmap = self.osdmap
        if not self._stopped and self.osd_id < newmap.max_osd and \
                not newmap.osd_up[self.osd_id]:
            # the map says we are down but we are alive: re-boot (reference
            # OSD::start_boot after _committed_osd_maps notices the same)
            self.perf.inc("osd_re_boots")
            await self.messenger.send_message(
                M.MOSDBoot(osd_id=self.osd_id,
                           addr=self.messenger.my_addr), self.mon_addr)
        changed = self._advance_pgs()
        if changed and not self._stopped:
            self._tasks.append(asyncio.get_event_loop().create_task(
                self._recover_all()))

    def _advance_pgs(self) -> bool:
        """Recompute PG membership for this OSD; returns True if the set of
        primary PGs changed (triggering recovery)."""
        m = self.osdmap
        changed = False
        for pool_id, pool in m.pools.items():
            for pgid, up, upp, acting, actp in self._pool_memberships(
                    m, pool_id, pool):
                mine = self.osd_id in [o for o in acting if o != CRUSH_ITEM_NONE]
                old = self.pgs.get(pgid)
                if mine:
                    st = PGState(pgid, up, acting, actp)
                    if old is None or old.acting != acting:
                        changed = True
                        self.store.queue_transaction(
                            Transaction().create_collection(_coll(pgid)))
                    self.pgs[pgid] = st
                elif old is not None:
                    del self.pgs[pgid]
                    changed = True
        return changed

    def _pool_memberships(self, m: OSDMap, pool_id: int, pool: PGPool):
        """Yield (pgid, up, upp, acting, actp) for every PG of a pool.

        Large pools go through the batched whole-pool placement (one TPU
        dispatch via OSDMap.pool_mapping, which falls back to the scalar
        mapper for map shapes the TensorMapper rejects); sparse pg_temp /
        primary_temp overrides re-run the scalar chain per affected PG.
        Small pools stay scalar — a per-epoch device dispatch costs more
        than it saves below a few hundred PGs."""
        batch_min = self.config.osd_map_batch_min_pgs
        if pool.pg_num < batch_min:
            for seed in range(pool.pg_num):
                pgid = PGid(pool_id, seed)
                yield (pgid, *m.pg_to_up_acting_osds(pgid))
            return
        up_arr, upp_arr = m.pool_mapping(pool_id)
        for seed in range(pool.pg_num):
            pgid = PGid(pool_id, seed)
            if pgid in m.pg_temp or pgid in m.primary_temp:
                yield (pgid, *m.pg_to_up_acting_osds(pgid))
                continue
            row = up_arr[seed]
            up = [int(o) for o in row if o != CRUSH_ITEM_NONE] \
                if pool.can_shift_osds() else [int(o) for o in row]
            upp = int(upp_arr[seed])
            yield pgid, up, upp, up, upp

    # -------------------------------------------------------- client ops

    async def _handle_client_op(self, conn: Connection, msg: M.MOSDOp) -> None:
        m = self.osdmap
        if m is None:
            await conn.send(M.MOSDOpReply(reqid=msg.reqid, result=-11))
            return
        pool = m.pools.get(msg.pgid.pool)
        if pool is None:
            await conn.send(M.MOSDOpReply(reqid=msg.reqid, result=-2))
            return
        st = self.pgs.get(msg.pgid)
        if st is None or st.primary != self.osd_id:
            # not primary (anymore): tell client to refresh its map
            await conn.send(M.MOSDOpReply(
                reqid=msg.reqid, result=-11, epoch=m.epoch))
            self.perf.inc("osd_misdirected_ops")
            return
        self.perf.inc("osd_client_ops")
        for opname, args in msg.ops:
            if opname == "write_full":
                r = await self._op_write_full(pool, st, msg.oid, args["data"])
                await conn.send(M.MOSDOpReply(
                    reqid=msg.reqid, result=r, epoch=m.epoch))
            elif opname == "write":
                r = await self._op_write(pool, st, msg.oid,
                                         args["offset"], args["data"])
                await conn.send(M.MOSDOpReply(
                    reqid=msg.reqid, result=r, epoch=m.epoch))
            elif opname == "read":
                try:
                    data = await self._op_read(
                        pool, st, msg.oid,
                        args.get("offset", 0), args.get("length"))
                    await conn.send(M.MOSDOpReply(
                        reqid=msg.reqid, result=0, data=data, epoch=m.epoch))
                except FileNotFoundError:
                    await conn.send(M.MOSDOpReply(
                        reqid=msg.reqid, result=-2, epoch=m.epoch))
            elif opname == "delete":
                r = await self._op_delete(pool, st, msg.oid)
                await conn.send(M.MOSDOpReply(
                    reqid=msg.reqid, result=r, epoch=m.epoch))
            elif opname == "stat":
                size = self.store.stat(_coll(st.pgid), msg.oid)
                if size is None and pool.is_erasure():
                    xs = self.store.getattr(_coll(st.pgid), msg.oid, "size")
                    size = int(xs) if xs else None
                elif pool.is_erasure():
                    xs = self.store.getattr(_coll(st.pgid), msg.oid, "size")
                    size = int(xs) if xs else size
                await conn.send(M.MOSDOpReply(
                    reqid=msg.reqid,
                    result=0 if size is not None else -2,
                    data=size, epoch=m.epoch))
            elif opname == "list":
                names = self.store.list_objects(_coll(st.pgid))
                await conn.send(M.MOSDOpReply(
                    reqid=msg.reqid, result=0, data=names, epoch=m.epoch))
            else:
                await conn.send(M.MOSDOpReply(reqid=msg.reqid, result=-95))

    # replicated write: local txn + MOSDRepOp fan-out (ReplicatedBackend)
    async def _op_write_full(self, pool: PGPool, st: PGState, oid: str,
                             data: bytes) -> int:
        if pool.is_erasure():
            return await self._ec_write(pool, st, oid, data, offset=None)
        version = self.store.get_version(_coll(st.pgid), oid) + 1
        txn = (Transaction()
               .remove(_coll(st.pgid), oid)
               .write(_coll(st.pgid), oid, 0, data)
               .set_version(_coll(st.pgid), oid, version))
        return await self._replicate_txn(st, txn)

    async def _op_write(self, pool: PGPool, st: PGState, oid: str,
                        offset: int, data: bytes) -> int:
        """Partial write at (offset, len) — the RMW path for EC pools
        (reference ECBackend::start_rmw, ECBackend.cc:1785)."""
        if pool.is_erasure():
            return await self._ec_write(pool, st, oid, data, offset=offset)
        version = self.store.get_version(_coll(st.pgid), oid) + 1
        txn = (Transaction()
               .write(_coll(st.pgid), oid, offset, data)
               .set_version(_coll(st.pgid), oid, version))
        return await self._replicate_txn(st, txn)

    async def _replicate_txn(self, st: PGState, txn: Transaction) -> int:
        self.store.queue_transaction(txn)
        peers = [o for o in st.acting
                 if o != self.osd_id and o != CRUSH_ITEM_NONE]
        if peers:
            reqid = self._next_reqid()
            fut = self._make_waiter(reqid, len(peers))
            rep = M.MOSDRepOp(reqid=reqid, pgid=st.pgid,
                              txn_blob=txn.encode(),
                              epoch=self.osdmap.epoch)
            for o in peers:
                await self._send_osd(o, rep)
            try:
                await asyncio.wait_for(
                    fut, timeout=self.config.osd_client_op_timeout)
            except asyncio.TimeoutError:
                return -110
            finally:
                self._pending.pop(reqid, None)
        return 0

    async def _op_delete(self, pool: PGPool, st: PGState, oid: str) -> int:
        txn = Transaction().remove(_coll(st.pgid), oid)
        self.store.queue_transaction(txn)
        peers = [o for o in st.acting
                 if o != self.osd_id and o != CRUSH_ITEM_NONE]
        for o in peers:
            await self._send_osd(o, M.MOSDRepOp(
                reqid=self._next_reqid(), pgid=st.pgid,
                txn_blob=txn.encode(), epoch=self.osdmap.epoch))
        return 0

    async def _op_read(self, pool: PGPool, st: PGState, oid: str,
                       offset: int = 0, length: Optional[int] = None) -> bytes:
        if pool.is_erasure():
            return await self._ec_read(pool, st, oid, offset, length)
        return self.store.read(_coll(st.pgid), oid, offset, length)

    # ----------------------------------------------------------- EC backend
    #
    # Objects are striped (ECUtil::stripe_info_t math, ceph_tpu.ec.stripe):
    # shard s holds stripe-chunk s of every stripe, concatenated.  Encode /
    # decode of the whole touched stripe range happens in one batched TPU
    # dispatch; partial writes are read-modify-write over stripe bounds
    # (reference ECBackend::start_rmw, ECBackend.cc:1785-1886).

    async def _ec_write(self, pool: PGPool, st: PGState, oid: str,
                        data: bytes, offset: Optional[int]) -> int:
        """Per-object write serialization: the EC RMW sequence (read old
        stripes, merge, re-encode, fan out shard writes) suspends at several
        awaits; two concurrent partial writes interleaving there would
        commit a mix of shard versions from both writers — parity
        inconsistent with data.  The reference serializes overlapping RMWs
        in the ECBackend pipeline (ECBackend::start_rmw wait queue).

        Locks are refcounted and pruned at zero so the dict doesn't grow
        with every distinct object ever written; the count is incremented
        synchronously (no await between lookup and increment), so a pruned
        entry can never race with a contender holding the old lock.
        """
        key = (st.pgid, oid)
        entry = self._obj_locks.get(key)
        if entry is None:
            entry = self._obj_locks[key] = [asyncio.Lock(), 0]
        entry[1] += 1
        try:
            async with entry[0]:
                return await self._ec_write_locked(pool, st, oid, data, offset)
        finally:
            entry[1] -= 1
            if entry[1] == 0:
                self._obj_locks.pop(key, None)

    async def _ec_write_locked(self, pool: PGPool, st: PGState, oid: str,
                               data: bytes, offset: Optional[int]) -> int:
        from ceph_tpu.ec import stripe as stripemod

        codec = self._codec(pool)
        sinfo = self._sinfo(pool, codec)
        coll = _coll(st.pgid)
        version = self.store.get_version(coll, oid) + 1

        if offset is None:
            # write_full: replace the object
            new_size = len(data)
            chunk_off = 0
            shards = await self._compute(
                stripemod.encode_stripes, codec, sinfo, data)
        else:
            sa = self.store.getattr(coll, oid, "size")
            old_size = int(sa) if sa else 0
            off0, len0 = sinfo.offset_len_to_stripe_bounds(offset, len(data))
            chunk_off = sinfo.aligned_logical_offset_to_chunk_offset(off0)
            old_in_range = max(0, min(old_size - off0, len0))
            old_bytes = b""
            if old_in_range:
                old_bytes = await self._ec_read_stripes(
                    pool, st, oid, chunk_off, old_in_range)
            merged = stripemod.merge_range(
                old_bytes, old_in_range, offset - off0, data)
            new_size = max(old_size, offset + len(data))
            shards = await self._compute(
                stripemod.encode_stripes, codec, sinfo, merged)

        shard_size = sinfo.shard_size(new_size)
        hinfo = {"size": new_size, "version": version}
        n = codec.get_chunk_count()
        reqid = self._next_reqid()
        peers = []
        my_shard = None
        for shard in range(n):
            osd = st.acting[shard] if shard < len(st.acting) else CRUSH_ITEM_NONE
            if osd == self.osd_id:
                my_shard = shard
            elif osd != CRUSH_ITEM_NONE:
                peers.append((osd, shard))
        if my_shard is not None:
            self._apply_shard(st.pgid, oid, my_shard,
                              shards[my_shard].tobytes(), chunk_off,
                              shard_size, hinfo)
        if peers:
            fut = self._make_waiter(reqid, len(peers))
            for osd, shard in peers:
                await self._send_osd(osd, M.MOSDECSubOpWrite(
                    reqid=reqid, pgid=st.pgid, oid=oid, shard=shard,
                    data=shards[shard].tobytes(), chunk_off=chunk_off,
                    shard_size=shard_size, hinfo=hinfo,
                    epoch=self.osdmap.epoch))
            try:
                await asyncio.wait_for(
                    fut, timeout=self.config.osd_client_op_timeout)
            except asyncio.TimeoutError:
                return -110
            finally:
                self._pending.pop(reqid, None)
        return 0

    def _apply_shard(self, pgid: PGid, oid: str, shard: int, data: bytes,
                     chunk_off: int, shard_size: int, hinfo: Dict) -> None:
        """Apply a shard sub-range write + refresh the shard crc
        (ECUtil::HashInfo analog; crc covers the whole shard)."""
        coll = _coll(pgid)
        txn = (Transaction()
               .write(coll, oid, chunk_off, data)
               .truncate(coll, oid, shard_size)
               .setattr(coll, oid, "shard", str(shard).encode())
               .setattr(coll, oid, "size", str(hinfo["size"]).encode())
               .set_version(coll, oid, hinfo["version"]))
        self.store.queue_transaction(txn)
        crc = crcmod.crc32c(0xFFFFFFFF, self.store.read(coll, oid))
        self.store.queue_transaction(
            Transaction().setattr(coll, oid, "hinfo_crc", str(crc).encode())
            .set_version(coll, oid, hinfo["version"]))

    async def _handle_ec_write(self, conn: Connection,
                               msg: M.MOSDECSubOpWrite) -> None:
        shard_size = msg.shard_size if msg.shard_size is not None \
            else msg.chunk_off + len(msg.data)
        self._apply_shard(msg.pgid, msg.oid, msg.shard, msg.data,
                          msg.chunk_off, shard_size, msg.hinfo)
        self.perf.inc("osd_ec_sub_writes")
        await conn.send(M.MOSDECSubOpWriteReply(reqid=msg.reqid, result=0))

    async def _handle_ec_read(self, conn: Connection,
                              msg: M.MOSDECSubOpRead) -> None:
        try:
            full = self.store.read(_coll(msg.pgid), msg.oid)
            stored_crc = self.store.getattr(_coll(msg.pgid), msg.oid,
                                            "hinfo_crc")
            # scrub-on-read: verify the shard crc (ecbackend.rst:86-99)
            if stored_crc is not None and \
                    int(stored_crc) != crcmod.crc32c(0xFFFFFFFF, full):
                raise IOError("chunk crc mismatch")
            data = full[msg.off: msg.off + msg.length] \
                if msg.length is not None else full[msg.off:]
            shard_attr = self.store.getattr(_coll(msg.pgid), msg.oid, "shard")
            shard = int(shard_attr) if shard_attr else msg.shard
            size = self.store.getattr(_coll(msg.pgid), msg.oid, "size")
            await conn.send(M.MOSDECSubOpReadReply(
                reqid=msg.reqid, result=0, shard=shard, data=data,
                hinfo={"size": int(size) if size else 0}))
            self.perf.inc("osd_ec_sub_reads")
        except (FileNotFoundError, IOError):
            await conn.send(M.MOSDECSubOpReadReply(
                reqid=msg.reqid, result=-2, shard=msg.shard))

    async def _gather_shards(
        self, pool: PGPool, st: PGState, oid: str, need_k: int,
        off: int = 0, length: Optional[int] = None,
    ) -> Tuple[Dict[int, bytes], int]:
        """Collect >= k shard (ranges) from the acting set (own shard free)."""
        shards: Dict[int, bytes] = {}
        size = 0
        my = self.store.stat(_coll(st.pgid), oid)
        if my is not None:
            data = self.store.read(_coll(st.pgid), oid, off, length)
            shard_attr = self.store.getattr(_coll(st.pgid), oid, "shard")
            if shard_attr is not None:
                shards[int(shard_attr)] = data
            sa = self.store.getattr(_coll(st.pgid), oid, "size")
            size = int(sa) if sa else 0
        peers = [(shard, osd) for shard, osd in enumerate(st.acting)
                 if osd not in (self.osd_id, CRUSH_ITEM_NONE)
                 and shard not in shards]
        if peers and len(shards) < need_k:
            reqid = self._next_reqid()
            fut = self._make_waiter(reqid, len(peers))
            for shard, osd in peers:
                try:
                    await self._send_osd(osd, M.MOSDECSubOpRead(
                        reqid=reqid, pgid=st.pgid, oid=oid, shard=shard,
                        off=off, length=length))
                except ConnectionError:
                    fut.needed -= 1  # type: ignore[attr-defined]
            try:
                acc = await asyncio.wait_for(
                    fut, timeout=self.config.osd_client_op_timeout)
            except asyncio.TimeoutError:
                acc = self._pending[reqid][1]
            finally:
                self._pending.pop(reqid, None)
            for result, reply in acc:
                if result == 0 and reply is not None:
                    shards[reply.shard] = reply.data
                    if reply.hinfo.get("size"):
                        size = reply.hinfo["size"]
        return shards, size

    async def _ec_read_stripes(self, pool: PGPool, st: PGState, oid: str,
                               chunk_off: int, logical_len: int) -> bytes:
        """Read a stripe-aligned logical range: gather the touched chunk
        range from >= k shards and decode it as a mini-object."""
        from ceph_tpu.ec import stripe as stripemod
        import numpy as np

        codec = self._codec(pool)
        sinfo = self._sinfo(pool, codec)
        k = codec.get_data_chunk_count()
        nstripes = sinfo.object_stripes(logical_len)
        chunk_len = nstripes * sinfo.chunk_size
        shards, _ = await self._gather_shards(
            pool, st, oid, k, off=chunk_off, length=chunk_len)
        avail = {s: np.frombuffer(d, dtype=np.uint8)
                 for s, d in shards.items()
                 if len(d) == chunk_len}
        if len(avail) < k:
            raise IOError(
                f"only {len(avail)} of {k} shard ranges for {oid}")
        return await self._compute(
            stripemod.decode_stripes, codec, sinfo, avail, logical_len)

    async def _ec_read(self, pool: PGPool, st: PGState, oid: str,
                       offset: int = 0, length: Optional[int] = None) -> bytes:
        """objects_read_async analog: min shards + batched TPU decode
        (ECBackend.cc:2111,1588,2262)."""
        coll = _coll(st.pgid)
        sa = self.store.getattr(coll, oid, "size")
        if sa is None:
            # primary lost its shard (or never had one): probe peers
            codec = self._codec(pool)
            shards, size = await self._gather_shards(
                pool, st, oid, codec.get_data_chunk_count(), 0, 0)
            if not shards and size == 0:
                raise FileNotFoundError(oid)
        else:
            size = int(sa)
        if length is None:
            length = max(0, size - offset)
        if length == 0 or offset >= size:
            return b""
        length = min(length, size - offset)
        codec = self._codec(pool)
        sinfo = self._sinfo(pool, codec)
        off0, len0 = sinfo.offset_len_to_stripe_bounds(offset, length)
        len0 = min(len0, max(0, size - off0))
        chunk_off = sinfo.aligned_logical_offset_to_chunk_offset(off0)
        out = await self._ec_read_stripes(pool, st, oid, chunk_off, len0)
        return out[offset - off0: offset - off0 + length]

    # ------------------------------------------------------------- recovery

    async def _recover_all(self) -> None:
        await asyncio.sleep(self.config.osd_recovery_delay_start)
        for pgid, st in list(self.pgs.items()):
            if st.primary == self.osd_id:
                try:
                    await self._recover_pg(st)
                except Exception:
                    # count AND surface: a silently-failing recovery loop
                    # means a pool that never re-protects itself
                    self.perf.inc("osd_recovery_errors")
                    import logging
                    logging.getLogger("ceph_tpu.osd").exception(
                        "osd.%d: recovery of pg %s failed", self.osd_id, pgid)

    async def _recover_pg(self, st: PGState) -> None:
        """Primary-driven resync: query members, reconstruct, push."""
        m = self.osdmap
        pool = m.pools[st.pgid.pool]
        members = [o for o in st.acting
                   if o not in (self.osd_id, CRUSH_ITEM_NONE)]
        # object inventory = union of members' lists + local
        names: Dict[str, int] = {
            oid: self.store.get_version(_coll(st.pgid), oid)
            for oid in self.store.list_objects(_coll(st.pgid))}
        for osd in members:
            key = ("pgq", str(st.pgid), osd)
            fut = self._make_waiter(key, 1)
            try:
                await self._send_osd(osd, MOSDPGQuery(pgid=st.pgid))
                acc = await asyncio.wait_for(fut, timeout=2.0)
                for _, reply in acc:
                    for oid, ver in reply.objects.items():
                        names[oid] = max(names.get(oid, 0), ver)
            except (asyncio.TimeoutError, ConnectionError):
                pass
            finally:
                self._pending.pop(key, None)
        for oid in names:
            if pool.is_erasure():
                await self._recover_ec_object(pool, st, oid)
            else:
                await self._recover_rep_object(pool, st, oid, names[oid])
        self.perf.inc("osd_pg_recoveries")

    async def _recover_rep_object(self, pool: PGPool, st: PGState,
                                  oid: str, version: int) -> None:
        if self.store.stat(_coll(st.pgid), oid) is None:
            # pull from any member that has it
            for osd in st.acting:
                if osd in (self.osd_id, CRUSH_ITEM_NONE):
                    continue
                key = ("pgq", str(st.pgid), osd)
                # reuse EC sub read as a generic object fetch
                reqid = self._next_reqid()
                fut = self._make_waiter(reqid, 1)
                try:
                    await self._send_osd(osd, M.MOSDECSubOpRead(
                        reqid=reqid, pgid=st.pgid, oid=oid, shard=-1))
                    acc = await asyncio.wait_for(fut, timeout=2.0)
                    result, reply = acc[0]
                    if result == 0:
                        self.store.queue_transaction(
                            Transaction().write(_coll(st.pgid), oid, 0,
                                                reply.data))
                        break
                except (asyncio.TimeoutError, ConnectionError):
                    continue
                finally:
                    self._pending.pop(reqid, None)
        if self.store.stat(_coll(st.pgid), oid) is None:
            return
        data = self.store.read(_coll(st.pgid), oid)
        for osd in st.acting:
            if osd in (self.osd_id, CRUSH_ITEM_NONE):
                continue
            try:
                await self._send_osd(osd, M.MOSDPGPush(
                    pgid=st.pgid, oid=oid, data=data, version=version))
            except ConnectionError:
                pass

    async def _recover_ec_object(self, pool: PGPool, st: PGState,
                                 oid: str) -> None:
        """Reconstruct and re-distribute shards (batched TPU decode + encode,
        ECBackend::run_recovery_op analog)."""
        from ceph_tpu.ec import stripe as stripemod
        import numpy as np

        codec = self._codec(pool)
        sinfo = self._sinfo(pool, codec)
        k = codec.get_data_chunk_count()
        shards, size = await self._gather_shards(pool, st, oid, k)
        shard_len = sinfo.shard_size(size)
        avail = {s: np.frombuffer(d, dtype=np.uint8)
                 for s, d in shards.items() if len(d) == shard_len}
        if len(avail) < k:
            self.perf.inc("osd_unrecoverable")
            return
        data = await self._compute(
            stripemod.decode_stripes, codec, sinfo, avail, size)
        chunks = await self._compute(
            stripemod.encode_stripes, codec, sinfo, data)
        version = max((self.store.get_version(_coll(st.pgid), oid)), 1)
        hinfo = {"size": size, "version": version}
        for shard, osd in enumerate(st.acting):
            if osd == CRUSH_ITEM_NONE:
                continue
            blob = chunks[shard].tobytes()
            if osd == self.osd_id:
                self._apply_shard(st.pgid, oid, shard, blob, 0,
                                  shard_len, hinfo)
            else:
                try:
                    await self._send_osd(osd, M.MOSDECSubOpWrite(
                        reqid=self._next_reqid(), pgid=st.pgid, oid=oid,
                        shard=shard, data=blob, chunk_off=0,
                        shard_size=shard_len, hinfo=hinfo,
                        epoch=self.osdmap.epoch))
                except ConnectionError:
                    pass

    def _handle_push(self, msg: M.MOSDPGPush) -> None:
        coll = _coll(msg.pgid)
        cur = self.store.get_version(coll, msg.oid)
        if self.store.stat(coll, msg.oid) is not None and cur >= msg.version:
            return
        txn = (Transaction()
               .remove(coll, msg.oid)
               .write(coll, msg.oid, 0, msg.data)
               .set_version(coll, msg.oid, msg.version))
        for k, v in msg.xattrs.items():
            txn.setattr(coll, msg.oid, k, v)
        self.store.queue_transaction(txn)
        self.perf.inc("osd_pushes_applied")

    # ------------------------------------------------------------ heartbeat

    async def _heartbeat_loop(self) -> None:
        while not self._stopped:
            await asyncio.sleep(self.config.osd_heartbeat_interval)
            m = self.osdmap
            if m is None:
                continue
            now = time.monotonic()
            for osd, addr in list(m.osd_addrs.items()):
                if osd == self.osd_id or not m.osd_up[osd]:
                    continue
                try:
                    await self.messenger.send_message(
                        M.MPing(stamp=now), addr)
                except (ConnectionError, OSError):
                    pass
                last = self._hb_last.get(osd)
                if last is not None and \
                        now - last > self.config.osd_heartbeat_grace and \
                        osd not in self._reported:
                    self._reported.add(osd)
                    try:
                        await self.messenger.send_message(
                            M.MOSDFailure(failed_osd=osd,
                                          reporter=self.osd_id),
                            self.mon_addr)
                        self.perf.inc("osd_failure_reports")
                    except (ConnectionError, OSError):
                        pass
                elif last is None:
                    self._hb_last[osd] = now
            # once the monitor marks a reported peer down, forget it so a
            # future reboot is tracked afresh
            for osd in list(self._reported):
                if not m.osd_up[osd]:
                    self._reported.discard(osd)
                    self._hb_last.pop(osd, None)
