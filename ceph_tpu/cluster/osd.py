"""OSD daemon: PGs, replicated and erasure-coded backends, recovery.

Structural mirror of the reference OSD (src/osd/OSD.cc dispatch ->
PrimaryLogPG op execution; ReplicatedBackend transaction fan-out;
ECBackend shard writes/reads, src/osd/ECBackend.cc:921,986,1141), with the
dense compute — erasure encode/decode, chunk crc32c — running through the
TPU codec engine.  Heartbeats/failure reports mirror OSD::heartbeat_check
(OSD.cc:4763) -> MOSDFailure -> monitor.  Recovery re-synchronizes PG
contents on map change (push recovery; EC shards reconstructed by decode,
ECBackend::run_recovery_op analog).
"""

from __future__ import annotations

import asyncio
import pickle
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ceph_tpu.analysis import racecheck
from ceph_tpu.cluster import messages as M
from ceph_tpu.cluster import pglog
from ceph_tpu.cluster.messenger import (
    Addr,
    Connection,
    Dispatcher,
    EntityName,
    Messenger,
)
from ceph_tpu.cluster.pglog import LogEntry, PGInfo, PGLog
from ceph_tpu.cluster.store import MemStore, ObjectStore, Transaction
from ceph_tpu.crush.types import CRUSH_ITEM_NONE
from ceph_tpu.ops import crc32c as crcmod
from ceph_tpu.osdmap.osdmap import OSDMap, PGid, PGPool
from ceph_tpu.utils import Config, PerfCounters
from ceph_tpu.cluster.backend_ec import ECBackendMixin
from ceph_tpu.cluster.tiering import TieringMixin
from ceph_tpu.cluster.backend_replicated import ReplicatedBackendMixin
from ceph_tpu.cluster.client_ops import ClientOpsMixin
from ceph_tpu.cluster.pg import (  # noqa: F401  (re-exported: tools/tests)
    MOSDPGQuery,
    MOSDPGQueryReply,
    PGMETA,
    PGState,
    PGLogMixin,
    _coll,
)
from ceph_tpu.cluster.recovery import RecoveryMixin
from ceph_tpu.cluster.scrub import ScrubMixin

# the daemon-level metadata collection: superblock with the current osdmap
# (reference OSDSuperblock, read at OSD::init, src/osd/OSD.cc:2556)
METACOLL = "meta"


class OSDDaemon(PGLogMixin, ClientOpsMixin, ReplicatedBackendMixin,
                ECBackendMixin, RecoveryMixin, ScrubMixin, TieringMixin,
                Dispatcher):
    def __init__(self, osd_id: int, mon_addr,
                 config: Optional[Config] = None,
                 store: Optional[ObjectStore] = None):
        self.osd_id = osd_id
        # per-daemon config copy: injectargs on one daemon must never
        # leak into another (each reference daemon owns its md_config_t)
        self.config = Config(**config.show()) if config else Config()
        # the default store advertises (and round 16: ENFORCES) the
        # configured capacity — the memstore_device_bytes analog the
        # cluster-full protection and the disk-fill scenarios size
        self.store = store or MemStore(self.config.memstore_device_bytes)
        self.messenger = Messenger(
            EntityName("osd", osd_id),
            secret=self.config.auth_secret(),
            auth=self.config.cephx_context(f"osd.{osd_id}"),
            config=self.config)
        self.messenger.add_dispatcher(self)
        # chaos seams (ceph_tpu/chaos/): per-daemon skewable clock (our
        # heartbeat/failure timings read THIS, so a scenario can skew one
        # daemon's view of time) + config-driven disk injector on the
        # store; both stay provable no-ops at default config
        from ceph_tpu.chaos.clock import ChaosClock
        from ceph_tpu.chaos.disk import DiskInjector

        self.clock = ChaosClock.from_config(self.config)
        self.store.chaos = DiskInjector.from_config(
            self.config, f"osd.{osd_id}")
        self.config.add_observer(self._chaos_disk_observer)
        # reference ceph_osd.cc:511-525 policy binding: clients are lossy
        # (replies are connection-scoped; the client re-requests) with a
        # byte throttle so a fast client backpressures instead of burying
        # the daemon; osd/mon peers stay lossless (session replay)
        from ceph_tpu.cluster.messenger import Policy, Throttle

        self.messenger.set_policy("client", Policy(
            lossy=True,
            throttle=Throttle(self.config.osd_client_message_size_cap)))
        self.messenger.set_policy("osd", Policy(lossy=False))
        self.messenger.set_policy("mon", Policy(lossy=False))
        # monmap failover (shared MonClient hunting, cluster/monclient.py)
        from ceph_tpu.cluster.monclient import MonTargeter

        from ceph_tpu.chaos.rng import stream as _chaos_stream

        self.monc = MonTargeter(
            self.messenger, mon_addr,
            subscribe_since=lambda: self.osdmap.epoch if self.osdmap else 0,
            rng=_chaos_stream(self.config.chaos_seed,
                              f"monc:osd.{osd_id}")
            if self.config.chaos_seed else None)
        self.osdmap: Optional[OSDMap] = None
        self.pgs: Dict[PGid, PGState] = {}
        # per-daemon counter registry: own counters + the process-wide
        # device-kernel counters, all served by one 'perf dump'
        from ceph_tpu.utils import KERNELS, PerfCountersCollection

        self.perfcoll = PerfCountersCollection()
        self.perf = self.perfcoll.create(f"osd.{osd_id}")
        self.perfcoll.register(KERNELS)
        self._declare_perf_schema()
        from ceph_tpu.cluster.optracker import OpTracker

        self.tracker = OpTracker(
            history_size=self.config.osd_op_history_size,
            slow_size=self.config.osd_op_history_slow_op_size,
            slow_threshold=self.config.osd_op_complaint_time,
            clock=self.clock)
        # graft-trace seams (ceph_tpu/trace/): per-daemon span tracer +
        # event-loop profiler, both provable no-ops at default config
        from ceph_tpu.trace import LoopProfiler, Tracer

        self.tracer = Tracer(f"osd.{osd_id}",
                             enabled=bool(self.config.trace_enabled),
                             keep=self.config.trace_keep)
        self.loopmon = LoopProfiler(
            self.perf, self.config.loop_profile_interval,
            prefix="osd_loop")
        # graft-blackbox flight ring (NULL_FLIGHT when disabled):
        # stamped on this daemon's possibly-skewed chaos clock
        from ceph_tpu.trace import FlightRecorder

        self.flight = FlightRecorder.from_config(
            f"osd.{osd_id}", self.config, clock=self.clock)
        # live depth of the ordered dispatch queues (ShardedOpWQ-depth
        # analog) — maintained by client_ops, exported as a perf gauge
        self._queued_depth = 0
        # admission budgets in use (client_ops._admit_op): ops + payload
        # bytes concurrently queued/executing against osd_op_throttle_*
        self._admit_ops = 0
        self._admit_bytes = 0
        # recent EC sub-read gather latencies (seconds): the quantile
        # the hedge delay for degraded k-of-n reads is derived from
        from collections import deque as _deque

        self._subread_lats = _deque(maxlen=64)
        # ONE shared jitter stream for internal-op pushback backoff:
        # concurrent internal ops interleave draws from it, so their
        # retries desynchronize (per-call streams with one name would
        # retry in lockstep); seeded for chaos replay, else None
        self._internal_backoff_rng = _chaos_stream(
            self.config.chaos_seed, f"internal:osd.{osd_id}") \
            if self.config.chaos_seed else None
        # last slow-op count surfaced to the cluster log (warn on rise,
        # log clearance on drain — the mon health check itself keys off
        # the beacon stream)
        self._slow_warned = 0
        self.asok = self._build_admin_socket()
        self._codecs: Dict[int, object] = {}
        self._pending: Dict[Tuple, Tuple[asyncio.Future, List]] = {}
        self._tid = 0
        # waiters for this OSD's own internal client ops (copy-from, tier
        # promote/flush): reqid -> future resolved by MOSDOpReply
        self._internal_inflight: Dict[Tuple, asyncio.Future] = {}
        self._internal_tid = 0
        # background tasks: a SELF-DISCARDING set (the messenger._track
        # pattern) — per-op and per-map-change spawns must not
        # accumulate one dead Task each for the daemon's life (the bug
        # class the task-spawn graftlint rule polices)
        self._tasks: Set[asyncio.Task] = set()
        # incomplete-recovery retry state (recovery.py
        # _queue_recovery_retry): per-PG capped backoff + the armed
        # retry task, so failed pulls/pushes re-run without needing
        # another map change to trigger peering
        self._recovery_backoffs: Dict[PGid, object] = {}
        self._recovery_retry_tasks: Dict[PGid, asyncio.Task] = {}
        # control plane at scale (round 14): per-pool resolved-placement
        # snapshots diffed across epochs (osdmap.placement_delta), the
        # pending-peering queue those diffs feed, ONE collapsing drain
        # task, a per-OSD concurrency throttle on simultaneous peering
        # rounds, and the seeded stream big waves stagger from
        self._placement_cache: Dict[int, object] = {}
        self._peering_pending: Set[PGid] = set()
        self._peering_task: Optional[asyncio.Task] = None
        # primary PGs owing a peering/recovery round (round 21): added
        # when an epoch queues them to re-peer, cleared when a round
        # completes clean (or the PG leaves this OSD).  The beacon
        # reports the count — the mon's PG_RECOVERING feed that gates
        # the balancer's next round and the reshaper's wait-clean.
        self._unclean_pgs: Set[PGid] = set()
        # a COUNTED throttle, not a mutual-exclusion lock: DepLock has
        # no semaphore mode, and ordering is safe by construction — the
        # semaphore is only ever acquired BEFORE (never while holding)
        # a PG lock (recovery._recover_pg)
        self._peering_sem = asyncio.Semaphore(  # graftlint: ignore[asyncio-blocking]
            max(1, self.config.osd_peering_max_concurrent))
        self._peering_rng = _chaos_stream(
            self.config.chaos_seed, f"peering:osd.{osd_id}") \
            if self.config.chaos_seed else None
        self._hb_last: Dict[int, float] = {}
        self._reported: Set[int] = set()
        # dmClock op scheduling (reference mClockClientQueue plugged into
        # ShardedOpWQ): enabled by osd_op_queue=mclock; ops enqueue per
        # client and a drain task serves them by reservation/weight/limit
        self._opq = None
        self._opq_event = asyncio.Event()
        self._opq_running: Set[asyncio.Task] = set()
        # default (non-mclock) dispatch: per-(connection, PG) FIFO
        # queues drained off the messenger read loop — the reference
        # orders a client session's ops per PG (ShardedOpWQ pg queues)
        self._ordered_q: Dict[Tuple[int, PGid], object] = {}
        self._ordered_active: Set[Tuple[int, PGid]] = set()
        self._opq_default = None
        if self.config.osd_op_queue == "mclock":
            from ceph_tpu.cluster.dmclock import DmClockQueue, QoSSpec

            self._opq_default = QoSSpec(
                reservation=self.config.osd_mclock_default_reservation,
                weight=self.config.osd_mclock_default_weight,
                limit=self.config.osd_mclock_default_limit)
            if self.config.osd_op_shards == 0:
                # legacy global queue; with shards on, each shard owns
                # its own DmClockQueue (mClockClientQueue-per-shard)
                self._opq = DmClockQueue()
        # sharded dispatch (round 11, ShardedOpWQ analog): PG-affine
        # shards with tick-bounded drain; 0 = the legacy path above
        self._shardedq = None
        if self.config.osd_op_shards > 0:
            from ceph_tpu.cluster.sharded_wq import ShardedOpWQ

            self._shardedq = ShardedOpWQ(self,
                                         self.config.osd_op_shards)
        # per-tick stripe-batch coalescer + per-peer sub-write frame
        # batcher (cluster/batcher.py): EC writes ride both when
        # osd_batch_tick_ops > 0
        from ceph_tpu.cluster.batcher import (ClientReplyBatcher,
                                              EncodeBatcher,
                                              ReadBatcher,
                                              SubWriteBatcher)

        self._ec_batcher = EncodeBatcher(self)
        self._sub_batcher = SubWriteBatcher(self)
        # read-side coalescer (round 16): per-tick decode / recovery
        # reencode / shard-crc verification batches — the decode twin
        self._read_batcher = ReadBatcher(self)
        # client-edge reply coalescer (round 18): acks for ops that
        # arrived inside an MOSDOpBatch leave as MOSDOpReplyBatch ticks;
        # per-conn wrapper identity must be STABLE — the ordered-FIFO
        # keys are (id(conn), pgid) — so batch conns are cached here
        self._reply_batcher = ClientReplyBatcher(self)
        self._batch_conns: Dict[int, object] = {}
        # (pgid, oid) pairs with an in-flight async read-repair, so a
        # storm of reads against one corrupt object arms ONE rebuild
        self._read_repairs_inflight: Set[Tuple] = set()
        # boot instance nonce: lets the mon fence a fast rebounce even if
        # the new daemon lands on the identical address
        import itertools as _it
        import secrets as _secrets

        self.boot_instance = _secrets.randbits(63)
        # watch/notify state: (pgid, oid) -> {(watcher, cookie): conn}
        # (reference Watch/Notify on PrimaryLogPG)
        self._watchers: Dict[Tuple, Dict[Tuple[str, int], Connection]] = {}
        self._notifies: Dict[int, Tuple[asyncio.Future, Set[str]]] = {}
        self._notify_id = 0
        # removed snaps already trimmed per PG (purged_snaps analog;
        # in-memory — a restart re-runs one idempotent trim pass)
        self._purged_snaps: Dict[Tuple, set] = {}
        # chaos crash points (round 12): remaining traversals of the
        # armed point before it fires; the launcher (vstart Cluster)
        # installs _chaos_crash_cb so a self-crash keeps the cluster's
        # revive bookkeeping coherent
        self._crash_skip = self.config.chaos_crash_point_skip
        self._crash_fired = False
        self._chaos_crash_cb = None
        self.config.add_observer(self._chaos_crash_observer)
        self._stopped = False

    def _chaos_crash_observer(self, name: str, value) -> None:
        if name == "chaos_crash_point_skip":
            self._crash_skip = int(value)
        elif name == "chaos_crash_point":
            self._crash_fired = False

    def _chaos_point(self, name: str) -> None:
        """Named crash seam (round 12): when the armed chaos_crash_point
        matches, power-cut this daemon AT THIS INSTANT — _stopped flips
        before anything else runs, the actual store-crash/teardown is
        handed to the launcher's callback, and ChaosCrash (a
        CancelledError) unwinds the current path exactly like a task
        dying mid-await.  One falsy test when unarmed (no-op contract).
        """
        cp = self.config.chaos_crash_point
        if not cp or cp != name or self._stopped or self._crash_fired:
            return
        if self._crash_skip > 0:
            self._crash_skip -= 1
            return
        from ceph_tpu.chaos import ChaosCrash
        from ceph_tpu.chaos.counters import CHAOS

        self._crash_fired = True
        self._stopped = True
        CHAOS.inc("crash_points_fired")
        if self.flight:
            self.flight.record("crash_point", point=name)
        if hasattr(self.store, "crash"):
            # freeze the disk AT the instant: nothing the unwinding
            # coroutines do past this point may persist (a real power
            # cut doesn't run except-handlers against the platter)
            self.store.crash()
        cb = self._chaos_crash_cb
        if cb is not None:
            # the callback task is OWNED BY THE LAUNCHER (it outlives
            # this daemon's stop(); tracking it here would cancel the
            # crash mid-flight)
            cb(name)
        raise ChaosCrash(f"chaos crash point {name!r} fired")

    # ------------------------------------------------------------ lifecycle

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> Addr:
        self.store.mount()
        since = self._load_superblock()
        addr = await self.messenger.bind(host, port)
        # boot must surface unreachable monitors, not run unregistered
        await self._mon_send(M.MOSDBoot(osd_id=self.osd_id, addr=addr,
                                        instance=self.boot_instance),
                             raise_on_fail=True)
        await self._mon_send(
            M.MMonSubscribe(what="osdmap", addr=addr, since=since))
        loop = asyncio.get_event_loop()
        self._track(loop.create_task(self._heartbeat_loop()))
        self._track(loop.create_task(self._scrub_loop()))
        self._track(loop.create_task(self._tier_agent_loop()))
        if self._opq is not None:
            self._track(loop.create_task(self._opq_drain()))
        if self._shardedq is not None:
            self._shardedq.start()
        if self.loopmon.enabled:
            self._track(loop.create_task(self.loopmon.sample()))
        if self._peering_pending:
            # superblock resume queued our primary PGs before the loop
            # tasks existed; if the subscribed map matches the persisted
            # one no _post_map_update ever fires changed=True, and the
            # boot-time queue (plus its unclean-beacon claim) would sit
            # forever — the restarted primary owes these PGs a round
            self._kick_peering()
        return addr

    def _track(self, task: asyncio.Task) -> asyncio.Task:
        """Register a background task; it discards itself on completion
        and stop() cancels whatever is still live."""
        from ceph_tpu.utils.tasks import track_task

        return track_task(self._tasks, task)

    def _load_superblock(self) -> int:
        """Resume from the persisted osdmap + PG logs (reference
        read_superblock + load_pgs, OSD.cc:2556,2572).  Returns the epoch
        to subscribe from (0 = never booted)."""
        blob = self.store.getattr(METACOLL, "superblock", "osdmap")
        if blob is None:
            return 0
        self.osdmap = pickle.loads(blob)
        self.perf.set("osd_map_epoch", self.osdmap.epoch)
        self._advance_pgs()  # reloads per-PG logs from their pgmeta objects
        return self.osdmap.epoch

    def _save_superblock(self) -> None:
        self.store.queue_transaction(
            Transaction()
            .create_collection(METACOLL)
            .setattr(METACOLL, "superblock", "osdmap",
                     pickle.dumps(self.osdmap)))

    async def stop(self, crash: bool = False, torn_tail: bool = False,
                   lose_frames: int = 0) -> None:
        """Clean shutdown, or (``crash=True``) a power-cut stop: the
        store skips its clean-shutdown checkpoint — FileStore/BlueStore
        may tear or lose the journal tail; a MemStore's contents are
        simply what a dead host's RAM is."""
        self._stopped = True
        # deregister config observers: the per-daemon config OUTLIVES
        # this incarnation (restart/revive reuse it), and stale
        # observers would pin every dead daemon and mutate its state
        # on later injectargs
        self.config.remove_observer(self._chaos_disk_observer)
        self.config.remove_observer(self._chaos_crash_observer)
        for t in list(self._tasks) + list(self._opq_running):
            t.cancel()
        if self._opq_running:
            # teardown drain of already-cancelled op tasks; their
            # results are void by definition
            await asyncio.gather(*self._opq_running,  # graftlint: ignore[swallowed-async-error]
                                 return_exceptions=True)
        await self.messenger.shutdown()
        if crash:
            if hasattr(self.store, "crash"):
                self.store.crash(torn_tail=torn_tail,
                                 lose_frames=lose_frames)
        else:
            self.store.umount()
        # deregister our counters (the shared KERNELS registry stays)
        self.perfcoll.remove(self.perf.name)

    def _chaos_disk_observer(self, name: str, value) -> None:
        if name.startswith("chaos_disk") or name == "chaos_seed":
            from ceph_tpu.chaos.disk import DiskInjector

            self.store.chaos = DiskInjector.from_config(
                self.config, f"osd.{self.osd_id}")

    def _next_reqid(self) -> Tuple[str, int]:
        self._tid += 1
        return (f"osd.{self.osd_id}", self._tid)

    @property
    def _mclock_dispatch(self) -> bool:
        """Is client-op dispatch QoS-queued (global legacy queue or
        per-shard mclock)?  Governs the internal-op loopback choice:
        under FIFO-ordered dispatch a self-targeted nested op must run
        direct (same-(conn,PG) group serialization would deadlock);
        under mclock each dequeue is a free task, so self-messaging is
        safe and required."""
        return self._opq is not None or (
            self._shardedq is not None and self._shardedq.use_mclock)

    @property
    def mon_addr(self) -> Addr:
        return self.monc.current

    async def _mon_send(self, msg, raise_on_fail: bool = False) -> bool:
        return await self.monc.send(msg, raise_on_fail=raise_on_fail)

    async def internal_op(self, pool_id: int, oid: str, ops,
                          snapid=None, snapc=None,
                          timeout: Optional[float] = None,
                          reqid_override: Optional[Tuple] = None):
        """This OSD acting as a rados client (the reference OSD's own
        Objecter, used by copy-from and cache tiering): target the
        object's primary in ``pool_id`` and run an op vector.  Returns
        the terminal MOSDOpReply."""
        from ceph_tpu.ops.jenkins import str_hash_rjenkins
        from ceph_tpu.osdmap.osdmap import ceph_stable_mod

        if timeout is None:
            timeout = self.config.osd_client_op_timeout + 2.0
        deadline = asyncio.get_event_loop().time() + timeout
        # background class: when the target pushes back THROTTLED under
        # admission pressure (or evicts us for a client op), retry under
        # capped jittered backoff — yielding, not hammering.  The rng
        # is the daemon-wide seeded stream (chaos replay) shared by all
        # internal ops, so concurrent retries interleave draws instead
        # of sleeping identical sequences in lockstep.
        from ceph_tpu.utils.backoff import ExpBackoff

        pushback = ExpBackoff(base=0.05, cap=1.0,
                              rng=self._internal_backoff_rng)
        wall_deadline = time.time() + timeout
        while True:
            m = self.osdmap
            pool = m.pools.get(pool_id)
            if pool is None:
                raise IOError(f"pool {pool_id} gone")
            seed = ceph_stable_mod(str_hash_rjenkins(oid.encode()),
                                   pool.pg_num, pool.pg_num_mask)
            pgid = PGid(pool_id, seed)
            _, _, _, primary = m.pg_to_up_acting_osds(pgid)
            addr = m.osd_addrs.get(primary) if primary >= 0 else None
            if addr is None:
                if asyncio.get_event_loop().time() > deadline:
                    raise IOError(f"no primary for {pool_id}:{oid}")
                await asyncio.sleep(0.1)
                continue
            if reqid_override is not None:
                reqid = reqid_override
            else:
                self._internal_tid += 1
                # nonce'd per incarnation like client reqids: a restarted
                # OSD's counter resets, and a stale reqid colliding with
                # the target's dup detection would silently skip the op
                reqid = (f"osd.{self.osd_id}.int#{self.boot_instance}",
                         self._internal_tid)
            msg = M.MOSDOp(reqid=reqid, pgid=pgid, oid=oid, ops=ops,
                           epoch=m.epoch, snapc=snapc, snapid=snapid,
                           deadline=wall_deadline)
            if primary == self.osd_id and not self._mclock_dispatch:
                # self-targeted: dispatch DIRECTLY instead of messaging
                # ourselves — a nested internal op would share the outer
                # op's self-connection, whose read loop is blocked in the
                # outer dispatch (same-conn serialization deadlock when
                # e.g. the base and cache primaries coincide).  Under
                # mclock (queued dispatch) the read loop never blocks, so
                # normal self-messaging is both safe and required (the
                # loopback would return before the queued op runs).
                replies: List = []

                class _LoopConn:
                    peer = self.messenger.name
                    peer_caps = None

                    async def send(self, reply):
                        replies.append(reply)

                msg.src = self.messenger.name
                # dispatch inline (NOT via _handle_client_op, which
                # detaches execution as a task and would return before
                # any reply lands in `replies`): the loopback caller is
                # an ordinary task, never the messenger read loop, so
                # executing here cannot head-of-line block a connection
                await self._serve_queued_op(_LoopConn(), msg)
                reply = next((r for r in reversed(replies)
                              if isinstance(r, M.MOSDOpReply)), None)
                if reply is None:
                    raise IOError(f"internal loopback op on {oid}: "
                                  "no reply")
                if reply.result == -11:
                    if asyncio.get_event_loop().time() > deadline:
                        raise IOError(
                            f"internal op to {pool_id}:{oid} kept "
                            "misdirecting past the deadline")
                    await asyncio.sleep(0.1)
                    continue
                return reply
            fut = asyncio.get_event_loop().create_future()
            self._internal_inflight[reqid] = fut
            try:
                await self.messenger.send_message(msg, tuple(addr))
                reply = await asyncio.wait_for(
                    fut, timeout=max(0.1, deadline -
                                     asyncio.get_event_loop().time()))
                if reply.result == -11:  # misdirected: map moved, retry
                    if asyncio.get_event_loop().time() > deadline:
                        raise IOError(
                            f"internal op to {pool_id}:{oid} kept "
                            "misdirecting past the deadline")
                    await asyncio.sleep(0.1)
                    continue
                if getattr(reply, "throttled", False):
                    # admission pushback / QoS eviction: back off and
                    # retry until our own deadline
                    if asyncio.get_event_loop().time() > deadline:
                        raise IOError(
                            f"internal op to {pool_id}:{oid} throttled "
                            "past the deadline")
                    await asyncio.sleep(pushback.next())
                    continue
                return reply
            except asyncio.TimeoutError:
                raise IOError(f"internal op to {pool_id}:{oid} timed out")
            finally:
                self._internal_inflight.pop(reqid, None)

    def clog(self, prio: str, text: str) -> None:
        """Fire-and-forget cluster-log event to the mon (reference clog /
        MLog; the mon's log service Paxos-replicates it)."""
        import time as _time

        entry = (f"osd.{self.osd_id}", _time.time(), prio, text)

        async def _send():
            try:
                await self._mon_send(M.MLog(entries=(entry,)))
            except Exception:
                # fire-and-forget by contract, but observable: a clog
                # line lost to transport is counted, never silent
                self.perf.inc("osd_clog_send_errors")

        try:
            self._track(asyncio.get_event_loop().create_task(_send()))
        except RuntimeError:
            pass  # no running loop (teardown)


    # ------------------------------------------------------------- dispatch

    async def ms_dispatch(self, conn: Connection, msg) -> bool:
        if self._stopped:
            # a stopped (or chaos-crashed) daemon serves nothing: its
            # store is frozen, so handling a frame here could neither
            # apply nor ack — exactly a dead process on the wire
            return True
        try:
            return await self._dispatch(conn, msg)
        except Exception as e:
            # store-capacity ENOSPC on a CLIENT op surfaces as the
            # real -28 (the backstop beneath the mon's full flag), not
            # a bare EIO.  On sub-op paths (replica/shard applies) the
            # exception propagates like any replica failure — no reply,
            # the primary stays un-acked and peering owns the divergent
            # entry — so only the delivered client reject counts as one
            enospc = isinstance(msg, M.MOSDOp) and \
                isinstance(e, OSError) and getattr(e, "errno", 0) == 28
            self.perf.inc("osd_full_rejects" if enospc
                          else "osd_dispatch_errors")
            if isinstance(msg, M.MOSDOp):
                await conn.send(M.MOSDOpReply(
                    reqid=msg.reqid, result=-28 if enospc else -5,
                    data=repr(e)))
                return True
            raise

    async def _dispatch(self, conn: Connection, msg) -> bool:
        if isinstance(msg, M.MOSDMapMsg):
            await self._handle_map(msg)
            return True
        if isinstance(msg, M.MOSDOpReply):
            # reply to one of OUR internal client ops (copy-from /
            # tier traffic): resolve the waiter
            fut = self._internal_inflight.pop(tuple(msg.reqid), None)
            if fut is not None and not fut.done():
                fut.set_result(msg)
            return True
        if isinstance(msg, M.MOSDIncMapMsg):
            await self._handle_inc_map(msg)
            return True
        if isinstance(msg, M.MOSDOp):
            await self._handle_client_op(conn, msg)
            return True
        if isinstance(msg, M.MOSDOpBatch):
            await self._handle_client_op_batch(conn, msg)
            return True
        if isinstance(msg, M.MOSDRepOp):
            if self._sub_op_expired(msg):
                # parent op's client deadline passed: the primary's
                # waiter is (or will be) gone — applying + replying is
                # dead work.  No reply: the primary times out -110 and
                # the op stays un-acked, so durability is never claimed
                # for a stripe some member shed.
                return True
            # replica-side span: joins the primary's op tree via the
            # sub-op trace header (absent/None when untraced)
            tr = getattr(msg, "trace", None)
            span = self.tracer.start(
                "rep_op", trace_id=tr.get("id"),
                parent_id=tr.get("span")) if tr else None
            try:
                txn = Transaction.decode(msg.txn_blob)
                self.store.queue_transaction(txn)
                st = self.pgs.get(msg.pgid)
                if st is not None and msg.entry is not None:
                    self._log_mutation(st, msg.entry.op, msg.entry.oid,
                                       msg.entry.version, entry=msg.entry)
                self.perf.inc("osd_rep_ops")
                await self._reply_osd(conn, msg, M.MOSDRepOpReply(
                    reqid=msg.reqid, result=0))
            finally:
                # the failed/retried replica legs are exactly the spans
                # the assembled tree must not lose
                if span is not None:
                    span.finish()
            return True
        if isinstance(msg, M.MOSDRepOpReply) or \
                isinstance(msg, M.MOSDECSubOpWriteReply):
            self._ack(msg.reqid, msg.result, msg)
            return True
        if isinstance(msg, M.MOSDECSubOpWrite):
            await self._handle_ec_write(conn, msg)
            return True
        if isinstance(msg, M.MOSDECSubOpWriteBatch):
            await self._handle_ec_write_batch(conn, msg)
            return True
        if isinstance(msg, M.MOSDECSubOpWriteBatchReply):
            # scatter the batched acks to each op's waiter; the shim
            # carries src+shard so the per-responder ack dedup holds
            from types import SimpleNamespace

            for reqid, result, shard in msg.results:
                self._ack(reqid, result,
                          SimpleNamespace(src=msg.src, shard=shard))
            return True
        if isinstance(msg, M.MOSDECSubOpRead):
            await self._handle_ec_read(conn, msg)
            return True
        if isinstance(msg, M.MOSDECSubOpReadReply):
            self._ack(msg.reqid, msg.result, msg)
            return True
        if isinstance(msg, M.MOSDScrub):
            await self._reply_osd(conn, msg, M.MOSDScrubMap(
                reqid=msg.reqid, pgid=msg.pgid,
                objects=self._build_scrub_map(msg.pgid)))
            return True
        if isinstance(msg, M.MOSDScrubMap):
            self._ack(msg.reqid, 0, msg)
            return True
        if isinstance(msg, M.MOSDPGPush):
            self._handle_push(msg)
            await self._reply_osd(conn, msg, M.MOSDPGPushReply(
                pgid=msg.pgid, oid=msg.oid, result=0))
            return True
        if isinstance(msg, M.MOSDPGPushReply):
            return True
        if isinstance(msg, MOSDPGQuery):
            objects = {
                oid: self.store.get_version(_coll(msg.pgid), oid)
                for oid in self._list_pg_objects(msg.pgid)
            }
            st = self.pgs.get(msg.pgid)
            await self._reply_osd(conn, msg, MOSDPGQueryReply(
                pgid=msg.pgid, objects=objects,
                info=st.info() if st else None,
                log=st.log if st else None))
            return True
        if isinstance(msg, MOSDPGQueryReply):
            self._ack(("pgq", str(msg.pgid), msg.src.num), 0, msg)
            return True
        if isinstance(msg, M.MCommand):
            await self._handle_admin_command(conn, msg)
            return True
        if isinstance(msg, M.MPing):
            if msg.reply:
                if msg.src is not None:
                    self._hb_last[msg.src.num] = self.clock.monotonic()
            else:
                await conn.send(M.MPing(stamp=msg.stamp, reply=True))
            return True
        return False

    def _scrub_stats(self) -> Tuple[int, int]:
        """(unrepaired inconsistent objects, PGs holding any) across
        this OSD's primary PGs — the beacon feed for the mon's
        PG_INCONSISTENT / OSD_SCRUB_ERRORS health checks (raised while
        nonzero, cleared by the next clean beacon, like SLOW_OPS)."""
        objs = pgs = 0
        for st in self.pgs.values():
            if st.primary == self.osd_id and st.inconsistent:
                pgs += 1
                objs += len(st.inconsistent)
        return (objs, pgs)

    def _sub_op_expired(self, msg) -> bool:
        """Dead-work shedding on the replica/shard side: a sub-op whose
        inherited client deadline passed is dropped at dispatch (counted;
        None deadline — recovery traffic — always executes).  Reads the
        daemon's skewable clock, so chaos clock-skew scenarios exercise
        the cross-daemon wall-clock protocol this design rides on."""
        dl = getattr(msg, "deadline", None)
        if dl is None or self.clock.time() <= dl:
            return False
        self.perf.inc("osd_sub_ops_shed_expired")
        return True

    def _ack_wait_timeout(self) -> float:
        """Sub-op ack wait budget: the usual op timeout, clamped to the
        current client op's remaining deadline — replicas SHED expired
        sub-ops without replying, so waiting past the deadline would
        pin the primary (and its ordered FIFO) on work nobody awaits."""
        from ceph_tpu.cluster.pg import CURRENT_OP_DEADLINE

        t = self.config.osd_client_op_timeout
        dl = CURRENT_OP_DEADLINE.get()
        if dl is not None:
            t = min(t, max(0.05, dl - self.clock.time()))
        return t

    async def _yield_under_pressure(self) -> None:
        """Background work (recovery rounds, scrub passes) yields while
        client admission pressure is high — the QoS demotion the
        reference gets from mclock op classes.  No-op with budgets off."""
        budget = self.config.osd_op_throttle_ops
        if not budget:
            return
        yielded = False
        for _ in range(100):
            if self._stopped or \
                    self._admit_ops < max(1, (3 * budget) // 4):
                break
            if not yielded:
                yielded = True
                self.perf.inc("osd_recovery_yields")
            await asyncio.sleep(0.05)

    def _declare_perf_schema(self) -> None:
        """Typed schemas + histograms for the op path (reference
        OSD::create_logger, src/osd/osd_perf_counters.cc)."""
        from ceph_tpu.utils import perf as perfmod

        self.perf.add_u64("osd_client_ops", prio=perfmod.PRIO_CRITICAL,
                          desc="client ops served")
        self.perf.add_u64("osd_rep_ops", desc="replica sub-ops applied")
        self.perf.add_u64("osd_ec_sub_writes",
                          desc="EC shard sub-writes applied")
        self.perf.add_u64("osd_ec_sub_reads",
                          desc="EC shard sub-reads served")
        self.perf.add_time("osd_op_lat", prio=perfmod.PRIO_CRITICAL,
                           desc="client op latency (arrival to reply)")
        # microsecond-bucketed latency + byte-bucketed payload size
        # (reference perf histogram axes on osd_op_*_latency)
        self.perf.add_histogram(
            "osd_op_lat_hist", scale=1e6, unit=perfmod.UNIT_SECONDS,
            prio=perfmod.PRIO_INTERESTING,
            desc="client op latency, log2 microsecond buckets")
        self.perf.add_histogram(
            "osd_op_in_bytes_hist", unit=perfmod.UNIT_BYTES,
            prio=perfmod.PRIO_INTERESTING,
            desc="mutation payload size, log2 byte buckets")
        self.perf.add_u64(
            "osd_dispatch_queue_depth", prio=perfmod.PRIO_INTERESTING,
            desc="client ops waiting in the ordered dispatch queues")
        # overload/degradation telemetry (round 10): admission budgets,
        # deadline shedding, QoS conformance, hedged EC reads — all ride
        # the existing perf/Prometheus export
        self.perf.add_u64("osd_throttle_rejects",
                          prio=perfmod.PRIO_INTERESTING,
                          desc="client ops pushed back THROTTLED at "
                               "admission (budget full)")
        self.perf.add_u64("osd_ops_shed_expired",
                          prio=perfmod.PRIO_INTERESTING,
                          desc="client ops dropped at dequeue past "
                               "their deadline (dead work)")
        self.perf.add_u64("osd_sub_ops_shed_expired",
                          desc="replica/shard sub-ops dropped past the "
                               "inherited parent deadline")
        self.perf.add_u64("osd_qos_preempted",
                          desc="queued background-class ops evicted to "
                               "admit client ops under pressure")
        self.perf.add_u64("osd_qos_served_reservation",
                          desc="dmclock dequeues served by reservation "
                               "tag (conformance)")
        self.perf.add_u64("osd_qos_served_spare",
                          desc="dmclock dequeues served from spare "
                               "capacity by weight tag")
        self.perf.add_u64("osd_qos_evicted",
                          desc="queued requests shed by dmclock "
                               "eviction (raw queue stat, round 13: "
                               "mirrored to the perf/Prometheus path "
                               "so the graft-load SLO judge sees it "
                               "on the scrape)")
        self.perf.add_u64("osd_admit_ops_in_use",
                          desc="admission op budget currently in use")
        self.perf.add_u64("osd_admit_bytes_in_use",
                          unit=perfmod.UNIT_BYTES,
                          desc="admission byte budget currently in use")
        self.perf.add_u64("osd_ec_hedged_reads",
                          desc="EC gathers that hedged straggler "
                               "sub-reads after the quantile delay")
        self.perf.add_u64("osd_ec_hedge_promotions",
                          desc="EC gathers that promoted a spare shard "
                               "after a failed sub-read send")
        self.perf.add_u64("osd_ec_fastk_reads",
                          desc="EC reads that resolved from the first "
                               "k clean shards")
        self.perf.add_u64("osd_recovery_yields",
                          desc="background recovery/scrub rounds "
                               "delayed under client admission pressure")
        # batched data plane (round 11): coalesced dispatch telemetry —
        # coalesced_ops / ticks is the realized batch factor
        self.perf.add_u64("osd_batch_ticks",
                          prio=perfmod.PRIO_INTERESTING,
                          desc="coalesced EC encode ticks dispatched")
        self.perf.add_u64("osd_batch_coalesced_ops",
                          prio=perfmod.PRIO_INTERESTING,
                          desc="EC writes encoded through coalesced "
                               "ticks (ops/ticks = batch factor)")
        self.perf.add_u64("osd_subwrite_batches",
                          desc="multi-item sub-write frames sent "
                               "(per peer per tick)")
        self.perf.add_u64("osd_subwrite_batched_items",
                          desc="shard sub-writes that rode a "
                               "multi-item frame")
        # client-edge batching (round 18): MOSDOpBatch ingest +
        # MOSDOpReplyBatch egress — items/frames is the realized client
        # batch factor, the edge twin of osd_batch_coalesced_ops
        self.perf.add_u64("osd_client_batch_frames",
                          prio=perfmod.PRIO_INTERESTING,
                          desc="MOSDOpBatch frames received from "
                               "client tick coalescers")
        self.perf.add_u64("osd_client_batch_items",
                          prio=perfmod.PRIO_INTERESTING,
                          desc="client ops that arrived inside an "
                               "MOSDOpBatch frame (items/frames = "
                               "client batch factor)")
        self.perf.add_u64("osd_client_batch_item_errors",
                          desc="batch items that failed dispatch and "
                               "were answered per item (-5/-28); their "
                               "tick-mates were unaffected")
        self.perf.add_u64("osd_client_batch_reply_frames",
                          desc="MOSDOpReplyBatch frames sent (one per "
                               "reply tick per client conn)")
        self.perf.add_u64("osd_client_batch_reply_items",
                          desc="client acks that rode a batched reply "
                               "frame")
        self.perf.add_u64("osd_client_batch_reply_drops",
                          desc="batched reply items lost to a dead "
                               "client conn (clients resend on "
                               "timeout)")
        # crash-safe batched plane (round 12): frontier recovery +
        # batched-ack dedup telemetry
        self.perf.add_u64("osd_frontier_rebuilt",
                          desc="open commit-frontier entries "
                               "reconstructed from the pg log at boot "
                               "(resolved by peering roll-forward or "
                               "rewind)")
        self.perf.add_u64("osd_dup_acks_ignored",
                          desc="duplicate sub-op acks absorbed by the "
                               "per-responder dedup (session replay, "
                               "chaos dup/batch-ack faults)")
        self.perf.add_u64("osd_rmw_pipelined",
                          desc="EC RMW writes committed through the "
                               "pipelined frontier path (PG lock held "
                               "only for the commit section)")
        self.perf.add_u64("osd_rep_pipelined",
                          desc="replicated-pool mutations committed "
                               "through the pipelined frontier path")
        self.perf.add_u64("osd_ec_undersized_blocks",
                          desc="EC writes/roll-forwards refused because "
                               "the live acting set was below the "
                               "pool's min_size floor (acked-but-"
                               "unreconstructable guard)")
        # control plane at scale (round 14): vectorized epoch deltas +
        # peering storm control, all on the perf/Prometheus path so the
        # graft-load SLO judge can gate on them from the mgr scrape
        self.perf.add_u64("osd_map_epochs_applied",
                          prio=perfmod.PRIO_INTERESTING,
                          desc="osdmap epochs applied (incremental and "
                               "full) — the churn keep-up signal")
        self.perf.add_u64("osd_map_affected_pgs",
                          desc="PGs the vectorized epoch delta selected "
                               "(placement actually moved this epoch)")
        self.perf.add_u64("osd_pgs_repeered",
                          prio=perfmod.PRIO_INTERESTING,
                          desc="primary PGs queued for peering by map "
                               "advances (per-epoch re-peer fan-out)")
        self.perf.add_u64("osd_map_skip_to_full",
                          desc="incremental chains abandoned for a "
                               "full-map request (chain longer than "
                               "osd_map_max_inc_chain under churn)")
        self.perf.add_u64("osd_peering_rounds",
                          desc="peering rounds started")
        self.perf.add_u64("osd_peering_throttled",
                          desc="peering rounds that waited on the "
                               "per-OSD concurrency throttle "
                               "(osd_peering_max_concurrent)")
        # verified reads + self-healing + cluster-full (round 16): all
        # on the perf/Prometheus path so the graft-load SLO judge can
        # gate on their presence from the mgr scrape
        self.perf.add_u64("osd_read_batch_ticks",
                          prio=perfmod.PRIO_INTERESTING,
                          desc="coalesced read-side ticks dispatched "
                               "(decode / recovery reencode / crc "
                               "verification batches)")
        self.perf.add_u64("osd_read_batch_coalesced",
                          desc="requests that rode a coalesced "
                               "read-side tick")
        self.perf.add_u64("osd_read_shard_crc_errors",
                          prio=perfmod.PRIO_INTERESTING,
                          desc="shard crc mismatches caught by "
                               "verify-on-read before the bytes could "
                               "feed a decode")
        self.perf.add_u64("osd_read_shard_errors",
                          desc="shard media errors (EIO) surfaced to a "
                               "read gather")
        self.perf.add_u64("osd_read_repairs",
                          prio=perfmod.PRIO_INTERESTING,
                          desc="objects rebuilt in place by automatic "
                               "read-repair (crc/EIO/stale shard "
                               "detected during a gather)")
        self.perf.add_u64("osd_read_repair_errors",
                          desc="read-repair attempts that failed "
                               "(object stays inconsistent; scrub "
                               "retries)")
        self.perf.add_u64("osd_scrub_errors_repaired",
                          prio=perfmod.PRIO_INTERESTING,
                          desc="scrub-detected inconsistencies "
                               "repaired (crc rot + stale "
                               "generations)")
        self.perf.add_u64("osd_scrubs_scheduled",
                          desc="background scrubs started by the "
                               "seeded per-PG jittered scheduler")
        self.perf.add_u64("osd_full_rejects",
                          prio=perfmod.PRIO_INTERESTING,
                          desc="client writes rejected ENOSPC while "
                               "the OSDMap carried the full flag "
                               "(deletes stay admitted)")
        self.perf.add_u64("osd_backfill_blocked_full",
                          desc="backfill data movement deferred while "
                               "the map carried the backfillfull flag")
        self.perf.add_histogram(
            "osd_peering_lat_hist", scale=1e6, unit=perfmod.UNIT_SECONDS,
            prio=perfmod.PRIO_INTERESTING,
            desc="peering round duration, log2 microsecond buckets")

    def _build_admin_socket(self):
        """Register this daemon's command table (reference OSD::asok_
        command registration, src/osd/OSD.cc admin_socket hooks)."""
        from ceph_tpu.utils import AdminSocket

        asok = AdminSocket()
        asok.register_common(self.perfcoll, self.config,
                             flight=self.flight)

        def _inject(cmd):
            args = cmd.get("args", {})
            self.config.injectargs(args)
            self.perf.inc("osd_injectargs")
            if self.flight and any(k.startswith("chaos_") for k in args):
                self.flight.record("chaos", args=dict(args))
            # complaint-time/history knobs apply to the live tracker
            self.tracker.slow_threshold = \
                self.config.osd_op_complaint_time
            self.tracker.resize(
                history_size=self.config.osd_op_history_size,
                slow_size=self.config.osd_op_history_slow_op_size)

        asok.register("injectargs", _inject, "runtime config mutation")
        asok.register("dump_ops_in_flight",
                      lambda cmd: self.tracker.dump_ops_in_flight(),
                      "ops currently being served")
        asok.register("dump_historic_ops",
                      lambda cmd: self.tracker.dump_historic_ops(),
                      "recently completed ops with event timelines")
        asok.register("dump_historic_slow_ops",
                      lambda cmd: self.tracker.dump_historic_slow_ops(),
                      "slowest completed ops past the complaint time")

        def _attribution(cmd):
            from ceph_tpu.trace.attribution import aggregate_tracker

            a = {**cmd, **cmd.get("args", {})}
            return aggregate_tracker(
                self.tracker, match=a.get("match"),
                measured_wall_s=a.get("measured_wall_s"))

        asok.register("dump_op_attribution", _attribution,
                      "per-stage wall-time breakdown over completed ops "
                      "(args: match=<desc substring>, measured_wall_s)")

        def _trace_dump(cmd):
            a = {**cmd, **cmd.get("args", {})}
            tid = a.get("trace_id")
            if tid is not None:
                return self.tracer.dump_trace(tid)
            return self.tracer.dump_recent(int(a.get("n", 20)))

        asok.register("trace dump", _trace_dump,
                      "completed graft-trace spans (args: trace_id | n)")

        def _dmclock(cmd):
            if self._opq is not None:
                return {"enabled": True, **self._opq.dump()}
            if self._shardedq is not None and self._shardedq.use_mclock:
                return {"enabled": True, **self._shardedq.dump()}
            return {"enabled": False}

        asok.register("dump_dmclock", _dmclock,
                      "dmclock conformance counters + per-client queue "
                      "depths (QoS shedding telemetry)")

        async def _scrub(cmd):
            reports = {}
            for pgid, st in list(self.pgs.items()):
                if st.primary == self.osd_id:
                    reports[str(pgid)] = await self.scrub_pg(st)
            return reports

        asok.register("scrub", _scrub, "scrub every primary PG")

        def _list_inconsistent(cmd):
            # reference 'rados list-inconsistent-obj' analog: objects a
            # scrub or verifying read flagged and repair has not healed
            a = {**cmd, **cmd.get("args", {})}
            want = a.get("pgid")
            out = {}
            for pgid, st in list(self.pgs.items()):
                if st.primary != self.osd_id:
                    continue
                if want is not None and str(pgid) != str(want):
                    continue
                if st.inconsistent or want is not None:
                    out[str(pgid)] = sorted(st.inconsistent)
            return out

        asok.register("list-inconsistent", _list_inconsistent,
                      "unrepaired inconsistent objects per primary PG "
                      "(args: pgid)")

        async def _repair(cmd):
            # 'ceph pg repair' analog: a scrub pass repairs as it goes
            a = {**cmd, **cmd.get("args", {})}
            want = a.get("pgid")
            reports = {}
            for pgid, st in list(self.pgs.items()):
                if st.primary != self.osd_id:
                    continue
                if want is not None and str(pgid) != str(want):
                    continue
                reports[str(pgid)] = await self.scrub_pg(st)
            return reports

        asok.register("repair", _repair,
                      "scrub-and-repair primary PGs (args: pgid)")
        return asok

    async def _handle_admin_command(self, conn: Connection,
                                    msg: M.MCommand) -> None:
        """Admin-socket surface (reference AdminSocket commands: perf
        dump, dump_historic_ops, config show, injectargs, scrub),
        routed through the per-daemon command table."""
        result, data = await self.asok.dispatch(msg.cmd)
        if msg.tid or msg.cmd.get("prefix") != "injectargs":
            try:
                await conn.send(M.MCommandReply(
                    tid=msg.tid, result=result, data=data))
            except (ConnectionError, OSError):
                pass

    # -------------------------------------------------------------- helpers

    async def _compute(self, fn, *args):
        """Run codec compute (encode/decode, possibly a first-call jit
        compile) off the event loop.  Blocking the loop here starves
        heartbeat replies and triggers false failure reports — the reference
        isolates heartbeats on dedicated messengers for the same reason
        (src/ceph_osd.cc:459-486 creates 4 hb messengers)."""
        return await asyncio.get_event_loop().run_in_executor(
            None, lambda: fn(*args))

    def _ack(self, key, result, payload=None) -> None:
        entry = self._pending.get(tuple(key) if isinstance(key, tuple) else key)
        if entry is None:
            return
        fut, acc = entry
        src = getattr(payload, "src", None)
        if src is not None:
            # lossless-session replay and chaos net dup can deliver the
            # same reply twice: one responder contributes ONE ack, or a
            # duplicated sub-write ack would satisfy the durability
            # threshold in place of a shard that never committed
            sk = (src.type, src.num, getattr(payload, "shard", None))
            seen = getattr(fut, "ackers", None)
            if seen is None:
                seen = set()
                fut.ackers = seen  # type: ignore[attr-defined]
            if sk in seen:
                # counted so batch-chaos runs can PROVE the dedup path
                # absorbed their injected duplicate acks
                self.perf.inc("osd_dup_acks_ignored")
                return
            seen.add(sk)
        acc.append((result, payload))
        if fut.done():
            return
        # early-resolve hook (degraded EC reads): a waiter may install
        # ``check(acc) -> bool`` to resolve as soon as the accumulated
        # replies SUFFICE (e.g. k same-generation shards), without
        # waiting for every contacted responder
        chk = getattr(fut, "check", None)
        if chk is not None and chk(acc):
            fut.set_result(acc)
            return
        if len(acc) >= fut.needed:  # type: ignore[attr-defined]
            fut.set_result(acc)

    def _make_waiter(self, key, needed: int) -> asyncio.Future:
        fut = asyncio.get_event_loop().create_future()
        fut.needed = needed  # type: ignore[attr-defined]
        self._pending[key] = (fut, [])
        return fut

    def _waiter_dec(self, key) -> None:
        """A planned responder became unreachable: lower the threshold AND
        re-check completion — acks that already arrived must be able to
        satisfy the waiter, or a durably-committed op reports failure."""
        entry = self._pending.get(key)
        if entry is None:
            return
        fut, acc = entry
        fut.needed -= 1  # type: ignore[attr-defined]
        if len(acc) >= fut.needed and not fut.done():  # type: ignore[attr-defined]
            fut.set_result(acc)

    async def _send_osd(self, osd: int, msg) -> None:
        addr = self.osdmap.osd_addrs.get(osd)
        if addr is None:
            raise ConnectionError(f"no address for osd.{osd}")
        await self.messenger.send_message(msg, addr)

    async def _reply_osd(self, conn: Connection, msg, reply) -> None:
        """Ack an osd peer over the LOSSLESS session instead of the raw
        accepted connection: a sub-op ack lost to a connection reset
        must be replayed, or the primary stalls its full op timeout on a
        write that IS durable everywhere (the reference's osd-osd policy
        is lossless in both directions for the same reason; surfaced by
        chaos net injection).  Falls back to the raw conn when the peer
        isn't in our map yet."""
        src = msg.src
        if src is not None and src.type == "osd" and \
                self.osdmap is not None:
            addr = self.osdmap.osd_addrs.get(src.num)
            if addr is not None:
                try:
                    await self.messenger.send_message(reply, tuple(addr))
                    return
                except (ConnectionError, OSError, RuntimeError):
                    pass
        await conn.send(reply)

    # ------------------------------------------------------------ map flow

    async def _handle_inc_map(self, msg: M.MOSDIncMapMsg) -> None:
        """Apply a delta chain (reference handle_osd_map incremental path).
        On an epoch gap, re-subscribe from our epoch to resync; a chain
        past osd_map_max_inc_chain skips to a full-map request instead
        of unpickling an unbounded churn burst on the dispatch loop."""
        m = self.osdmap
        if m is None or msg.prev_epoch != m.epoch:
            if m is not None and msg.epoch <= m.epoch:
                return  # stale or duplicate
            await self._mon_send(
                M.MMonSubscribe(what="osdmap", addr=self.messenger.my_addr,
                                since=m.epoch if m else 0))
            return
        if len(msg.inc_blobs) > self.config.osd_map_max_inc_chain:
            self.perf.inc("osd_map_skip_to_full")
            await self._mon_send(
                M.MMonSubscribe(what="osdmap",
                                addr=self.messenger.my_addr, since=0))
            return
        for blob in msg.inc_blobs:
            m.apply_incremental(pickle.loads(blob))
        if msg.inc_blobs:
            self.perf.inc("osd_map_epochs_applied", len(msg.inc_blobs))
        self.perf.set("osd_map_epoch", m.epoch)
        if self.flight:
            self.flight.record("map", epoch=m.epoch,
                               incs=len(msg.inc_blobs))
        await self._post_map_update()

    async def _handle_map(self, msg: M.MOSDMapMsg) -> None:
        newmap: OSDMap = pickle.loads(msg.osdmap_blob)
        old = self.osdmap
        if old is not None and newmap.epoch < old.epoch:
            return  # stale full map
        self.osdmap = newmap
        self.perf.inc("osd_map_epochs_applied",
                      max(1, newmap.epoch - old.epoch) if old is not None
                      else 1)
        self.perf.set("osd_map_epoch", newmap.epoch)
        if self.flight:
            self.flight.record("map", epoch=newmap.epoch, full=True)
        await self._post_map_update()

    async def _post_map_update(self) -> None:
        newmap = self.osdmap
        self._save_superblock()
        if not self._stopped and self.osd_id < newmap.max_osd and \
                not newmap.osd_up[self.osd_id]:
            # the map says we are down but we are alive: re-boot (reference
            # OSD::start_boot after _committed_osd_maps notices the same)
            self.perf.inc("osd_re_boots")
            await self._mon_send(M.MOSDBoot(osd_id=self.osd_id,
                                            addr=self.messenger.my_addr,
                                            instance=self.boot_instance))
        changed = self._advance_pgs()
        if changed and not self._stopped:
            if self.flight:
                self.flight.record("peering", epoch=newmap.epoch)
            self._kick_peering()
        if not self._stopped and any(
                set(newmap.pools[st.pgid.pool].removed_snaps)
                - self._purged_snaps.get(st.pgid, set())
                for st in self.pgs.values()
                if st.pgid.pool in newmap.pools
                and newmap.pools[st.pgid.pool].removed_snaps):
            self._track(asyncio.get_event_loop().create_task(
                self._snap_trim_all()))

    async def _snap_trim_all(self) -> None:
        """Snap trimming (reference PrimaryLogPG::SnapTrimmer): for every
        primary PG whose pool has removed snaps, drop them from object
        snapsets and delete fully-trimmed clone objects.  Idempotent —
        re-running over an already-trimmed snapset is a no-op — and
        _purged_snaps (the reference purged_snaps analog, in-memory) keeps
        later map epochs from rescanning stores for long-gone snaps."""
        from ceph_tpu.cluster import snaps as snapmod

        purged_now: Dict[object, set] = {}
        for st in list(self.pgs.values()):
            if self._stopped or st.primary != self.osd_id:
                continue
            pool = self.osdmap.pools.get(st.pgid.pool)
            if pool is None or not pool.removed_snaps:
                continue
            removed = set(pool.removed_snaps)
            if removed <= self._purged_snaps.get(st.pgid, set()):
                continue
            purged_now.setdefault(st.pgid, set()).update(removed)
            coll = _coll(st.pgid)
            for name in self.store.list_objects(coll):
                if not name.endswith(snapmod._SNAPDIR):
                    continue
                async with st.lock:
                    ops = snapmod.trim_ops(self.store, coll, name, removed)
                    if not ops:
                        continue
                    txn = Transaction()
                    txn.ops.extend(ops)
                    version = self._next_version(st)
                    await self._replicate_txn(
                        st, txn, "trim", snapmod.head_of(name), version)
                    self.perf.inc("osd_snaps_trimmed")
        if not self._stopped:
            for pgid, snaps in purged_now.items():
                self._purged_snaps.setdefault(pgid, set()).update(snaps)

    def _advance_pgs(self) -> bool:
        """Recompute PG membership and queue peering for the PGs an
        epoch actually moved; returns True when peering has work.

        Round 14: with osd_map_vectorized_delta (default) each pool's
        resolved placement is snapshotted after every advance and
        DIFFED against the previous one (osdmap.placement_delta) — one
        batched dispatch plus whole-pool array compares per epoch, zero
        per-PG Python for unaffected PGs, and only primaries whose
        up/acting moved re-peer.  With it off, every PG rescans and any
        change re-peers every primary PG — the per-PG-scan bit-exactness
        anchor (the pre-round-14 behavior).  PG log/last_update are
        preserved across map changes (and reloaded from the pgmeta
        object when the collection already exists on store — the
        load_pgs resume path, reference OSD.cc:2572)."""
        from ceph_tpu.osdmap.osdmap import placement_delta, \
            placement_snapshot

        m = self.osdmap
        use_vec = bool(self.config.osd_map_vectorized_delta)
        if not use_vec:
            # a stale cache from a past vectorized phase must not feed
            # diffs after the option is toggled back on
            self._placement_cache.clear()
        changed = False
        to_peer: Set[PGid] = set()
        batch_min = self.config.osd_map_batch_min_pgs
        # pg_num growth: split local PGs whose persisted split watermark
        # trails the pool's pg_num, BEFORE recomputing membership, so
        # child PGStates load the split-out meta/objects (reference
        # PG::split_colls on map advance).  The watermark rides the
        # PGMETA object, so an OSD that was down across the bump splits
        # on resume.  Skipped per pool when the cached snapshot proves
        # pg_num did not move.
        for pool_id, pool in m.pools.items():
            if pool.is_erasure():
                continue
            cached = self._placement_cache.get(pool_id)
            if cached is not None and cached.pg_num == pool.pg_num:
                continue
            for pgid, st in list(self.pgs.items()):
                if pgid.pool == pool_id and self._maybe_split(pool, st):
                    changed = True
        for pool_id, pool in m.pools.items():
            old_snap = self._placement_cache.get(pool_id)
            snap = placement_snapshot(m, pool_id, batch_min)
            if use_vec:
                self._placement_cache[pool_id] = snap
            seeds = None
            if old_snap is not None:
                seeds = placement_delta(old_snap, snap)
                if seeds is not None:
                    self.perf.inc("osd_map_affected_pgs", len(seeds))
            it = range(pool.pg_num) if seeds is None else sorted(seeds)
            for seed in it:
                pgid = PGid(pool_id, seed)
                up, upp, acting, actp = snap.resolve(seed)
                up, acting = list(up), list(acting)
                mine = self.osd_id in [o for o in acting
                                       if o != CRUSH_ITEM_NONE]
                old = self.pgs.get(pgid)
                if mine:
                    if old is None:
                        changed = True
                        self.store.queue_transaction(
                            Transaction().create_collection(_coll(pgid)))
                        st = PGState(pgid, up, acting, actp)
                        # resumed parent collections split BEFORE their
                        # children (lower seeds iterate first) load meta
                        if not pool.is_erasure():
                            self._maybe_split(pool, st)
                        st.last_update, st.log = self._load_pg_meta(pgid)
                        st.last_complete = self._load_last_complete(pgid)
                        # round 12: logged entries above the persisted
                        # watermark are OPEN frontier entries — their
                        # acks died with the previous process life, so
                        # last_complete must not bless them until
                        # peering rules on each (roll forward / rewind)
                        self._frontier_rebuild(st)
                        self.pgs[pgid] = st
                        if racecheck.TRACKER:  # graft-race: registry
                            # entry REPLACED — in-flight ack waits
                            # holding the old PGState are now stale
                            racecheck.TRACKER.note_write(
                                ("pgs", self.osd_id, str(pgid)),
                                "registry")
                        if actp == self.osd_id:
                            to_peer.add(pgid)
                    else:
                        # up-only changes re-peer too (round 21): a
                        # drain with a minted pg_temp leaves acting
                        # untouched while up moves to the incoming set —
                        # the primary must notice, backfill the up
                        # members, and request the temp clear, and
                        # nothing but this diff tells it to.
                        if old.acting != acting or old.up != up or (
                                old.primary != actp
                                and actp == self.osd_id):
                            changed = True
                            if actp == self.osd_id:
                                to_peer.add(pgid)
                        old.up, old.acting, old.primary = up, acting, actp
                elif old is not None:
                    del self.pgs[pgid]
                    self._unclean_pgs.discard(pgid)
                    changed = True
                    if racecheck.TRACKER:  # graft-race: the PG left
                        # this OSD — snapshots of its state went stale
                        racecheck.TRACKER.note_write(
                            ("pgs", self.osd_id, str(pgid)), "registry")
        # pools deleted from the map: drop their PGs AND their data
        # (reference: pool deletion queues PG removal + collection nuke).
        # Sweep by STORE collection, not just live PGState — collections
        # from past intervals must die too.
        for pgid in [p for p in self.pgs if p.pool not in m.pools]:
            del self.pgs[pgid]
            self._unclean_pgs.discard(pgid)
            changed = True
        for pool_id in [p for p in self._placement_cache
                        if p not in m.pools]:
            del self._placement_cache[pool_id]
        for coll in self.store.list_collections():
            if not coll.startswith("pg_"):
                continue
            try:
                pool_id = int(coll.split("_")[1])
            except (IndexError, ValueError):
                continue
            if pool_id not in m.pools:
                self.store.queue_transaction(
                    Transaction().remove_collection(coll))
                self.perf.inc("osd_pgs_removed")
        # round 12: a crash-restarted primary whose acting set came back
        # IDENTICAL still owes peering a round — its reconstructed open
        # frontier entries resolve only by verified presence/rewind, and
        # nothing else would ever trigger it
        for st in self.pgs.values():
            if st.frontier_recovering and st.primary == self.osd_id:
                to_peer.add(st.pgid)
        if not use_vec and (changed or to_peer):
            # anchor mode: any change re-peers every primary PG (the
            # pre-round-14 stampede, kept for bisection)
            to_peer.update(pgid for pgid, st in self.pgs.items()
                           if st.primary == self.osd_id)
        if to_peer:
            self.perf.inc("osd_pgs_repeered", len(to_peer))
            self._peering_pending.update(to_peer)
            self._unclean_pgs.update(to_peer)
        return bool(to_peer)

    # ------------------------------------------------------------ heartbeat

    async def _heartbeat_loop(self) -> None:
        while not self._stopped:
            await asyncio.sleep(self.config.osd_heartbeat_interval)
            m = self.osdmap
            if m is None:
                continue
            # the chaos-skewable per-daemon clock: a skewed OSD judges
            # peer heartbeat staleness from ITS OWN view of time
            now = self.clock.monotonic()
            # beacon to the mon (reference MOSDBeacon): lets the mon mark
            # us down even when no peer reporters survive; never let a
            # transport hiccup kill the heartbeat task.  The beacon also
            # carries blocked-op telemetry: the mon raises/clears the
            # SLOW_OPS health warning from this stream, so clearance on
            # drain needs no extra message.
            slow_n, slow_oldest = self.tracker.slow_in_flight()
            if slow_n and slow_n != self._slow_warned:
                self.clog("WRN", f"{slow_n} slow ops, oldest age "
                                 f"{slow_oldest:.2f}s "
                                 f"(complaint time "
                                 f"{self.tracker.slow_threshold}s)")
            elif not slow_n and self._slow_warned:
                self.clog("INF", "slow ops cleared")
            self._slow_warned = slow_n
            if self.flight:
                # queue/admission/slow-op sample each beacon window, a
                # LOOP_LAG spike event when the window crossed the
                # warning bound, and scrub detections when any fired
                self.flight.record(
                    "queue", depth=self._queued_depth,
                    admit_ops=self._admit_ops,
                    admit_bytes=self._admit_bytes, slow=slow_n)
                lag = self.loopmon.lag_report()
                if lag is not None and \
                        lag[1] >= self.config.loop_lag_warn > 0:
                    self.flight.record("loop_lag",
                                       window_max=round(lag[1], 6))
                bad_objs, bad_pgs = self._scrub_stats()
                if bad_objs:
                    self.flight.record("scrub", inconsistent=bad_objs,
                                       pgs=bad_pgs)
            try:
                # only PGs we still PRIMARY count as unclean — a PG
                # that moved away (or whose primaryship did) is the new
                # primary's to report; keeping it here pins the mon's
                # PG_RECOVERING check on an OSD that will never run the
                # recovery that clears it
                self._unclean_pgs = {
                    p for p in self._unclean_pgs
                    if p in self.pgs
                    and self.pgs[p].primary == self.osd_id}
                await self._mon_send(M.MOSDAlive(
                    osd_id=self.osd_id, statfs=self.store.statfs(),
                    slow_ops=(slow_n, slow_oldest),
                    loop_lag=self.loopmon.lag_report(),
                    scrub_stats=self._scrub_stats(),
                    unclean_pgs=len(self._unclean_pgs),
                    map_epoch=m.epoch))
                # the beacon delivered this window's max: start the next
                # window, so a drained stall clears LOOP_LAG like a
                # drained op queue clears SLOW_OPS
                self.loopmon.reset_window()
            except Exception:
                # the heartbeat loop must survive any transport hiccup,
                # but a dropped beacon is counted, never silent
                self.perf.inc("osd_beacon_send_errors")
            # perf-counter stream to the active mgr (MgrClient::send_report)
            mgr_addr = getattr(m, "mgr_addr", None)
            if mgr_addr:
                try:
                    counters = dict(
                        self.perf.dump()[f"osd.{self.osd_id}"])
                    # load observation for graft-balance: statfs + this
                    # OSD's per-pool PRIMARY object counts ride the
                    # report (primaries only, so summing across daemons
                    # counts each object once — the autoscaler's and
                    # balancer's byte/object feed)
                    total_b, used_b = self.store.statfs()
                    counters["osd_stat_bytes_total"] = total_b
                    counters["osd_stat_bytes_used"] = used_b
                    for pgid, st in self.pgs.items():
                        if st.primary != self.osd_id:
                            continue
                        key = f"osd_pool_{pgid.pool}_objects"
                        n = sum(1 for o in self.store.list_objects(
                            _coll(pgid)) if o != PGMETA)
                        counters[key] = counters.get(key, 0) + n
                    await self.messenger.send_message(M.MMgrReport(
                        daemon=f"osd.{self.osd_id}",
                        counters=counters, stamp=now), tuple(mgr_addr))
                except (ConnectionError, OSError, RuntimeError):
                    pass
            for osd, addr in list(m.osd_addrs.items()):
                if osd == self.osd_id or not m.osd_up[osd]:
                    continue
                try:
                    await self.messenger.send_message(
                        M.MPing(stamp=now), addr)
                except (ConnectionError, OSError):
                    pass
                last = self._hb_last.get(osd)
                if last is not None and \
                        now - last > self.config.osd_heartbeat_grace and \
                        osd not in self._reported:
                    self._reported.add(osd)
                    if await self._mon_send(M.MOSDFailure(
                            failed_osd=osd, reporter=self.osd_id)):
                        self.perf.inc("osd_failure_reports")
                elif last is None:
                    self._hb_last[osd] = now
            # once the monitor marks a reported peer down, forget it so a
            # future reboot is tracked afresh
            for osd in list(self._reported):
                if not m.osd_up[osd]:
                    self._reported.discard(osd)
                    self._hb_last.pop(osd, None)
