"""Client op dispatch: QoS queue drain, dup detection, op execution
(reference PrimaryLogPG::do_op / do_osd_ops dispatch seam).

Split out of osd.py: everything between "a client message arrived" and
"a backend mutation/read runs" — targeting checks, the dmClock queue,
reqid duplicate detection (pg_log dups analog), and the op interpreter
for data/xattr/omap/exec/watch/notify verbs."""

from __future__ import annotations

import asyncio
import time
from collections import deque
from typing import List, Set

from ceph_tpu.cluster import messages as M
from ceph_tpu.cluster.messenger import Connection
from ceph_tpu.cluster.pg import PGMETA, PGState, _coll
from ceph_tpu.cluster.store import Transaction


class _BatchConn:
    """Reply router for ops that arrived inside an MOSDOpBatch (round
    18): their MOSDOpReply acks coalesce through the OSD's
    ClientReplyBatcher into MOSDOpReplyBatch ticks; every other send
    (watch/notify pushes, map frames) forwards to the raw connection
    untouched.  Only batch-arrived ops get batched replies — a plain
    MOSDOp frame keeps its plain reply, which is what keeps
    objecter_batch_tick_ops=0 a bit-exact legacy anchor."""

    def __init__(self, osd, raw):
        self._osd = osd
        self._raw = raw

    def __getattr__(self, name):
        return getattr(self._raw, name)

    async def send(self, reply):
        if isinstance(reply, M.MOSDOpReply):
            self._osd._reply_batcher.send(self._raw, reply)
        else:
            await self._raw.send(reply)


class ClientOpsMixin:

    # ----------------------------------------------- admission control
    #
    # Layered admission ahead of dispatch (reference: the osd op/byte
    # throttles feeding ShardedOpWQ): an op beyond the configured
    # budgets is pushed back THROTTLED (-EBUSY) instead of queueing
    # unboundedly — the explicit signal the objecter's AIMD congestion
    # window runs against.  Budgets of 0 (default) admit everything.

    @staticmethod
    def _qos_entity(reqid0) -> str:
        """QoS identity = the STABLE entity name: reqids carry a
        per-incarnation nonce after '#' (dup-cache uniqueness), but
        dmClock shares/limits/budgets attach to the entity."""
        return str(reqid0).split("#", 1)[0]

    @classmethod
    def _qos_background(cls, name) -> bool:
        """osd-internal client traffic (tier agent flush/promote,
        copy-from pulls) is the background class: under admission
        pressure it is shed first, yielding to real clients."""
        return cls._qos_entity(name).startswith("osd.")

    @staticmethod
    def _op_cost_bytes(msg: M.MOSDOp) -> int:
        return sum(len(args.get("data", b"")) for _op, args in msg.ops)

    @staticmethod
    def _is_control_op(msg: M.MOSDOp) -> bool:
        """Pure-control vectors (notify_ack: resolves an existing
        waiter, zero payload) are exempt from admission AND from every
        shed point: dropping one blocks its waiter for a full timeout —
        more dead work than serving the one-line ack.  The single
        definition all three exemption sites share."""
        return all(o[0] == "notify_ack" for o in msg.ops)

    def _claim_throttle(self, msg) -> None:
        """Dispatch-byte ownership: the messenger's per-frame byte
        throttle (osd_client_message_size_cap) stays held until the op
        is SERVED, not just enqueued — the cap bounds bytes in dispatch
        like the reference's message throttle (held until the Message
        is destroyed), and a blocked sender resumes exactly when the
        queue drains.  Claimed only for ADMITTED ops: a rejected op is
        never served, so its budget must return via the read loop."""
        if getattr(msg, "_throttle", None) is not None:
            msg._throttle_held = True

    def _admit_op(self, msg: M.MOSDOp) -> bool:
        cap_ops = self.config.osd_op_throttle_ops
        cap_bytes = self.config.osd_op_throttle_bytes
        if not cap_ops and not cap_bytes:
            # admission disabled (default): provable no-op — no
            # accounting, no gauges, nothing for release to undo
            self._claim_throttle(msg)
            return True
        cost = self._op_cost_bytes(msg)
        if cap_ops and self._admit_ops + 1 > cap_ops:
            return False
        # a single op larger than the whole byte budget must not wedge:
        # it is admitted alone (the Throttle.acquire clamp, upstream)
        if cap_bytes and self._admit_bytes + cost > cap_bytes and \
                self._admit_bytes > 0:
            return False
        msg._admitted = cost
        self._admit_ops += 1
        self._admit_bytes += cost
        self.perf.set("osd_admit_ops_in_use", self._admit_ops)
        self.perf.set("osd_admit_bytes_in_use", self._admit_bytes)
        self._claim_throttle(msg)
        return True

    def _admit_release_accounting(self, msg):
        """Synchronous half of the release: return the budget NOW (no
        suspension point, so a caller can re-admit atomically) and hand
        back the messenger-throttle claim to release asynchronously.
        Returns (throttle, bytes) or None.  Budget accounting exists
        only when admission is configured (_admitted set); the throttle
        claim is independent (made for every admitted op)."""
        cost = getattr(msg, "_admitted", None)
        if cost is not None:
            msg._admitted = None
            self._admit_ops = max(0, self._admit_ops - 1)
            self._admit_bytes = max(0, self._admit_bytes - cost)
            self.perf.set("osd_admit_ops_in_use", self._admit_ops)
            self.perf.set("osd_admit_bytes_in_use", self._admit_bytes)
        thr = getattr(msg, "_throttle", None)
        if thr is not None and getattr(msg, "_throttle_held", False):
            msg._throttle_held = False
            return (thr, msg._throttle_bytes)
        return None

    async def _admit_release(self, msg) -> None:
        claim = self._admit_release_accounting(msg)
        if claim is not None:
            await claim[0].release(claim[1])

    def _would_admit_after_evicting(self, msg, victim) -> bool:
        """Would shedding ``victim`` actually admit ``msg``?  Dropping
        background work that doesn't buy admission (e.g. the byte
        budget is the constraint and the victim is tiny) would pay the
        eviction for nothing."""
        cap_ops = self.config.osd_op_throttle_ops
        cap_bytes = self.config.osd_op_throttle_bytes
        cost = self._op_cost_bytes(msg)
        v_cost = getattr(victim, "_admitted", None) or 0
        if cap_ops and self._admit_ops > cap_ops:  # -1 victim +1 msg
            return False
        bytes_after = max(0, self._admit_bytes - v_cost)
        if cap_bytes and bytes_after + cost > cap_bytes and \
                bytes_after > 0:
            return False
        return True

    async def _admit_or_pushback(self, conn, msg, m) -> bool:
        """Admission decision for one arriving client op.  On pressure,
        mclock's tags decide WHAT yields: a client-class arrival may
        evict a queued background-class op (QoS-enforced shedding);
        everything else gets the explicit THROTTLED pushback."""
        if self._is_control_op(msg):
            return True  # control acks bypass admission (see helper)
        if self._admit_op(msg):
            return True
        evq = self._qos_evict_source()
        if evq is not None and \
                not self._qos_background(msg.reqid[0]):
            victim = evq.peek_evict(self._qos_background)
            evicted = evq.evict(self._qos_background) \
                if victim is not None and \
                self._would_admit_after_evicting(msg, victim[1]) else None
            if evicted is not None:
                e_conn, e_msg, _stamp = evicted
                self._queued_depth = max(0, self._queued_depth - 1)
                self.perf.set("osd_dispatch_queue_depth",
                              self._queued_depth)
                # return the victim's budget and take it for THIS op
                # with no await in between: a suspension here would let
                # a concurrent arrival steal the freed slot, wasting
                # the eviction AND pushing this op back
                claim = self._admit_release_accounting(e_msg)
                admitted = self._admit_op(msg)
                self.perf.inc("osd_qos_preempted")
                # the raw dmclock eviction stat rides the perf path
                # (round 13): scrape-visible, not just dump_dmclock
                self.perf.set("osd_qos_evicted", evq.evicted_total())
                if claim is not None:
                    await claim[0].release(claim[1])
                try:
                    # prompt pushback: the background submitter backs
                    # off instead of burning its full op timeout
                    await e_conn.send(M.MOSDOpReply(
                        reqid=e_msg.reqid, result=M.THROTTLED,
                        throttled=True, epoch=m.epoch))
                except (ConnectionError, OSError, RuntimeError):
                    pass
                if admitted:
                    return True
        self.perf.inc("osd_throttle_rejects")
        await conn.send(M.MOSDOpReply(
            reqid=msg.reqid, result=M.THROTTLED, throttled=True,
            epoch=m.epoch))
        return False

    def _qos_default_for(self, qos_client: str):
        """First-sight QoS spec for a client class: the configured
        default, or the background override for osd-internal traffic
        (no reservation, a fraction of spare capacity, first in line
        for eviction)."""
        from ceph_tpu.cluster.dmclock import QoSSpec

        if self._qos_background(qos_client):
            return QoSSpec(
                reservation=0.0,
                weight=self.config.osd_mclock_background_weight,
                limit=self.config.osd_mclock_background_limit)
        return self._opq_default

    def _qos_evict_source(self):
        """The queue QoS-enforced shedding evicts from under admission
        pressure: the legacy global mclock queue, or the sharded queues
        (each shard owns a DmClockQueue).  None without mclock."""
        if self._opq is not None:
            return self._opq
        if self._shardedq is not None and self._shardedq.use_mclock:
            return self._shardedq
        return None

    def _shed_if_expired(self, msg: M.MOSDOp) -> bool:
        """Dead-work shedding at dequeue: an op past its client-stamped
        deadline has nobody awaiting the reply — burning device time on
        it only delays live ops.  Counted and kept in the historic ring
        so attribution shows where the shed op's wall time went.  Reads
        the skewable daemon clock (chaos clock-skew reaches it); pure
        control acks are exempt, mirroring their admission bypass."""
        dl = getattr(msg, "deadline", None)
        if dl is None or self.clock.time() <= dl:
            return False
        if self._is_control_op(msg):
            return False
        self.perf.inc("osd_ops_shed_expired")
        top = self.tracker.create(
            f"osd_op({msg.reqid[0]}:{msg.reqid[1]} {msg.oid} "
            f"SHED expired)", trace=getattr(msg, "trace", None))
        top.mark("shed_expired")
        top.finish()
        return True

    # -------------------------------------------------------- client ops

    async def _resolve_client_op(self, conn: Connection, msg: M.MOSDOp):
        """Map/pool/PG/primary checks for a client op; replies and
        returns None when the op cannot be served here."""
        m = self.osdmap
        if m is None:
            await conn.send(M.MOSDOpReply(reqid=msg.reqid, result=-11))
            return None
        pool = m.pools.get(msg.pgid.pool)
        if pool is None:
            await conn.send(M.MOSDOpReply(reqid=msg.reqid, result=-2))
            return None
        st = self.pgs.get(msg.pgid)
        if st is None or st.primary != self.osd_id:
            # not primary (anymore): tell client to refresh its map
            await conn.send(M.MOSDOpReply(
                reqid=msg.reqid, result=-11, epoch=m.epoch))
            self.perf.inc("osd_misdirected_ops")
            return None
        return m, pool, st

    async def _handle_client_op(self, conn: Connection, msg: M.MOSDOp) -> None:
        resolved = await self._resolve_client_op(conn, msg)
        if resolved is None:
            return
        m, pool, st = resolved
        # admission ahead of dispatch: budgets, QoS-aware eviction, or
        # explicit pushback — the end of unbounded queueing
        if not await self._admit_or_pushback(conn, msg, m):
            return
        if self._shardedq is not None:
            # sharded dispatch (round 11): the shard owns queueing,
            # shedding, and the dispatch tick; PG-affine hashing keeps
            # per-object ordering inside one shard
            qos_client = None
            default = None
            if self._shardedq.use_mclock:
                qos_client = self._qos_entity(msg.reqid[0])
                default = self._qos_default_for(qos_client)
            self._shardedq.enqueue(conn, msg, qos_client, default)
            return
        if self._opq is not None:
            qos_client = self._qos_entity(msg.reqid[0])
            default = self._qos_default_for(qos_client)
            self._opq.ensure_client(qos_client, default)
            # queue ONLY (conn, msg, stamp): map/pool/PG/primary state is
            # re-resolved at dequeue time, and ops that outlived the
            # client's attempt window are dropped (the client has already
            # resent; executing the stale copy would double-apply)
            self._opq.enqueue(qos_client,
                              (conn, msg, time.monotonic()))
            self.perf.inc("osd_ops_queued_mclock")
            self._queued_depth += 1
            self.perf.set("osd_dispatch_queue_depth", self._queued_depth)
            self._opq_event.set()
            return
        # detach execution from the messenger read loop (the reference
        # never executes ops on the msgr thread — ShardedOpWQ): a
        # mutation that waits on sub-op acks would otherwise block THIS
        # connection's dispatch, and when the op's client is another OSD
        # (tier agent internal_op) the sub-op ack can ride the very
        # connection the inline dispatch is blocking — a head-of-line
        # deadlock that only the op timeout unwinds (surfaced by
        # graft-chaos work: _reply_osd routes sub-op acks over the
        # lossless session, i.e. the peer's outgoing client connection).
        # Detached but NOT unordered: ops from one client connection to
        # one PG execute in arrival order (a pipelined A-then-B must
        # apply as A then B), so each (conn, pg) gets a FIFO drained by
        # its own task; different PGs still run in parallel.
        key = (id(conn), msg.pgid)
        q = self._ordered_q.get(key)
        if q is None:
            q = self._ordered_q[key] = deque()
        q.append((conn, msg))
        self._queued_depth += 1
        self.perf.set("osd_dispatch_queue_depth", self._queued_depth)
        if key not in self._ordered_active:
            self._spawn_drainer(key, q)

    def _batch_conn(self, conn):
        """The STABLE reply-routing wrapper for one client connection:
        ordered-FIFO and dup-cache keys use (id(conn), pgid), so every
        batch item from one connection must see the SAME wrapper object
        across frames (a fresh wrapper per frame would fork per-PG
        ordering).  Keyed by id() with an identity re-check, so a
        recycled id after a reconnect can never serve a stale wrap."""
        key = id(conn)
        wrapped = self._batch_conns.get(key)
        if wrapped is None or wrapped._raw is not conn:
            wrapped = self._batch_conns[key] = _BatchConn(self, conn)
        return wrapped

    async def _handle_client_op_batch(self, conn, batch) -> None:
        """Unpack one client tick's MOSDOpBatch: every item is a
        complete MOSDOp, resolved/admitted/queued individually through
        the very seam per-op frames use — the sharded WQ receives the
        whole tick in ONE dispatch, so the EncodeBatcher's next tick
        sees it pre-coalesced instead of dribbling in op-by-op.  Faults
        stay per item (the SubWriteBatcher rule): a failing item
        answers -5/-28 alone and its tick-mates proceed; a THROTTLED or
        shed-expired item simply never joins the reply tick, leaving
        only ITS client un-acked."""
        self.perf.inc("osd_client_batch_frames")
        self.perf.inc("osd_client_batch_items", len(batch.items))
        # the messenger's recv hop stamped the FRAME, not the items:
        # restamp each traced item here so its timeline's wire stage
        # closes at unpack, exactly where a per-op frame's recv lands
        now = time.time()
        arrival = f"msgr:{self.messenger.name}:recv"
        for msg in batch.items:
            tr = getattr(msg, "trace", None)
            if tr is not None:
                tr.setdefault("events", []).append((arrival, now))
        bconn = self._batch_conn(conn)
        for msg in batch.items:
            try:
                await self._handle_client_op(bconn, msg)
            except asyncio.CancelledError:
                raise
            except Exception as e:
                # ms_dispatch's error contract, applied per ITEM: the
                # failing op's client gets a prompt error, everyone
                # else's dispatch continues
                enospc = isinstance(e, OSError) and \
                    getattr(e, "errno", 0) == 28
                if enospc:
                    self.perf.inc("osd_full_rejects")
                else:
                    self.perf.inc("osd_dispatch_errors")
                    self.perf.inc("osd_client_batch_item_errors")
                try:
                    await bconn.send(M.MOSDOpReply(
                        reqid=msg.reqid, result=-28 if enospc else -5,
                        data=repr(e)))
                except (ConnectionError, OSError, RuntimeError):
                    pass

    def _spawn_drainer(self, key, q) -> None:
        """Mark the FIFO active and start its drain task, tracked in
        _opq_running so stop() can cancel it.  The loop profiler (when
        on) wraps it: spawn count + create->first-run queued delay +
        wall time land in the osd_loop_task_* counters."""
        self._ordered_active.add(key)
        t = asyncio.get_event_loop().create_task(
            self.loopmon.wrap(self._drain_ordered(key, q)))
        self._opq_running.add(t)
        t.add_done_callback(self._opq_running.discard)

    async def _drain_ordered(self, key, q) -> None:
        """Serve one (connection, PG) FIFO to empty, in order.  The
        empty-check/cleanup below runs with no await in between, so an
        enqueue can never race the drainer's exit (single event loop)."""
        try:
            while q:
                conn, msg = q.popleft()
                self._queued_depth = max(0, self._queued_depth - 1)
                self.perf.set("osd_dispatch_queue_depth",
                              self._queued_depth)
                await self._serve_admitted(conn, msg)
        finally:
            self._ordered_active.discard(key)
            if q and not self._stopped:
                # the drainer died mid-queue (cancellation): respawn so
                # the queued ops are not stranded
                self._spawn_drainer(key, q)
            elif self._ordered_q.get(key) is q:
                del self._ordered_q[key]

    async def _opq_drain(self) -> None:
        """Serve the dmClock queue (the ShardedOpWQ dequeue loop): QoS
        decides WHEN an op starts; execution runs as its own task so one
        slow write never head-of-line blocks other clients/PGs."""
        while not self._stopped:
            item = self._opq.dequeue()
            if item is None:
                # dead-work purge BEFORE pacing: an op already past its
                # deadline must not wait for its L-tag — shed it now so
                # its admission budget frees for live work (skewable
                # clock, like every shed decision on this daemon)
                now = self.clock.time()
                expired = self._opq.purge(
                    lambda it: getattr(it[1], "deadline", None)
                    is not None and now > it[1].deadline
                    and not self._is_control_op(it[1]))
                for e_conn, e_msg, _stamp in expired:
                    self._queued_depth = max(0, self._queued_depth - 1)
                    self.perf.set("osd_dispatch_queue_depth",
                                  self._queued_depth)
                    self._shed_if_expired(e_msg)
                    await self._admit_release(e_msg)
                wait = self._opq.next_eligible_in()
                if wait is not None:
                    # throttled: sleep until the earliest L-tag matures
                    await asyncio.sleep(min(max(wait, 0.002), 0.25))
                else:
                    self._opq_event.clear()
                    try:
                        await asyncio.wait_for(self._opq_event.wait(), 5.0)
                    except asyncio.TimeoutError:
                        pass
                continue
            conn, msg, stamp = item
            self._queued_depth = max(0, self._queued_depth - 1)
            self.perf.set("osd_dispatch_queue_depth", self._queued_depth)
            # dmclock conformance ride the perf/Prometheus path: which
            # share of dequeues was reservation-driven vs spare capacity
            self.perf.set("osd_qos_served_reservation",
                          self._opq.stats["served_reservation"])
            self.perf.set("osd_qos_served_spare",
                          self._opq.stats["served_spare"])
            self.perf.set("osd_qos_evicted",
                          self._opq.stats["evicted"])
            if time.monotonic() - stamp > self.config.osd_client_op_timeout:
                # the client abandoned this attempt and resent: executing
                # the stale copy would double-apply the op
                self.perf.inc("osd_ops_dropped_stale")
                await self._admit_release(msg)
                continue
            t = asyncio.get_event_loop().create_task(
                self.loopmon.wrap(self._serve_admitted(conn, msg)))
            self._opq_running.add(t)
            t.add_done_callback(self._opq_running.discard)

    async def _serve_admitted(self, conn, msg) -> None:
        """Serve one admitted op, returning its admission budget (and
        the messenger byte-throttle claim) however it exits — incl. the
        deadline shed, which runs HERE, at dequeue, so expired ops never
        reach the backend."""
        try:
            if not self._shed_if_expired(msg):
                await self._serve_queued_op(conn, msg)
        finally:
            await self._admit_release(msg)

    async def _serve_queued_op(self, conn, msg) -> None:
        try:
            resolved = await self._resolve_client_op(conn, msg)
            if resolved is None:
                return
            m, pool, st = resolved
            await self._dispatch_client_op(conn, msg, m, pool, st)
        except Exception as e:
            # mirror ms_dispatch's error contract: the client gets a
            # prompt error instead of a timeout.  A store-level ENOSPC
            # (the capacity backstop beneath the mon's full flag, which
            # can lag a beacon interval behind a fast filler) surfaces
            # as the REAL -28, so the client sees "cluster full" either
            # way, never a generic EIO.
            if isinstance(e, OSError) and getattr(e, "errno", 0) == 28:
                self.perf.inc("osd_full_rejects")
                result = -28
            else:
                self.perf.inc("osd_dispatch_errors")
                result = -5
            try:
                await conn.send(M.MOSDOpReply(
                    reqid=msg.reqid, result=result, data=repr(e)))
            except (ConnectionError, OSError, RuntimeError):
                pass

    def set_qos(self, client: str, reservation: float = 0.0,
                weight: float = 1.0, limit: float = 0.0) -> None:
        """Live per-client QoS update (mclock profile analog)."""
        from ceph_tpu.cluster.dmclock import QoSSpec

        spec = QoSSpec(reservation=reservation, weight=weight,
                       limit=limit)
        if self._opq is not None:
            self._opq.set_client(client, spec)
        if self._shardedq is not None and self._shardedq.use_mclock:
            self._shardedq.set_client(client, spec)

    # ops whose effects are not idempotent under at-least-once delivery;
    # a resend must return the cached original reply (reference pg_log
    # dup detection, PGLog dups / osd_pg_log_dups_tracked)
    _MUTATING_OPS = M.MUTATING_OPS
    # mutations still admitted while the cluster carries the FULL flag:
    # they can only free space, and refusing them would wedge a full
    # cluster forever (the reference admits deletes under
    # CEPH_OSDMAP_FULL for exactly this reason)
    _FULL_ADMITTED_OPS = frozenset({"delete", "rmxattr", "omap_rmkeys"})

    def _full_rejects(self, msg: M.MOSDOp) -> bool:
        """Should this op vector be refused ENOSPC under the map's full
        flag?  Only vectors that could GROW data; reads and the
        space-freeing verbs always pass (round 16 cluster-full
        protection — the flag is the mon's commitment, enforced here at
        every primary from its own map copy)."""
        m = self.osdmap
        if m is None or "full" not in getattr(m, "flags", set()):
            return False
        return any(o[0] in self._MUTATING_OPS
                   and o[0] not in self._FULL_ADMITTED_OPS
                   for o in msg.ops)
    _REQID_DUPS_TRACKED = 3000
    # ops that gate the rest of their vector (CEPH_OSD_OP_CMPXATTR etc.)
    _GUARD_OPS = frozenset({"cmpxattr"})

    def _compound_write_guard(self, pool, st: PGState, oid: str):
        """Object-lock guard for compound EC mutations that commit
        UNDER st.lock (copy_from, rollback): with pipelined writes on,
        an in-flight RMW reads-merges under only the object lock — a
        compound data commit slipping inside that window would be
        overwritten by the RMW's merged full stripe (lost update).
        Acquired BEFORE st.lock (the pg.objlock -> pg.lock order).
        Replicated pools / pipeline-off need no guard (their commits
        and RMW reads share st.lock already)."""
        if pool.is_erasure() and self.config.osd_pipeline_writes > 0:
            return self._obj_write_lock(st, oid)
        import contextlib

        return contextlib.nullcontext()

    async def _dispatch_client_op(self, conn, msg, m, pool, st) -> None:
        caps = getattr(conn, "peer_caps", None)
        if caps is not None:
            # cephx session: enforce OSD caps at dispatch (OSDCap analog)
            from ceph_tpu.cluster import auth as authmod

            need = "rw" if any(o[0] in self._MUTATING_OPS
                               for o in msg.ops) else "r"
            if not authmod.allows(caps, "osd", need):
                self.perf.inc("osd_eperm")
                await conn.send(M.MOSDOpReply(
                    reqid=msg.reqid, result=-1, epoch=m.epoch))
                return
        self.perf.inc("osd_client_ops")
        # absorb the client-side trace header so this op's historic dump
        # shows the objecter/messenger timeline ahead of OSD events
        top = self.tracker.create(
            f"osd_op({msg.reqid[0]}:{msg.reqid[1]} {msg.oid} "
            f"{[o[0] for o in msg.ops]})",
            trace=getattr(msg, "trace", None))
        top.mark("dispatched")
        in_bytes = sum(len(args.get("data", b""))
                       for opname, args in msg.ops
                       if opname in self._MUTATING_OPS)
        if in_bytes:
            self.perf.hinc("osd_op_in_bytes_hist", in_bytes)
        from ceph_tpu.cluster.optracker import CURRENT_OP
        from ceph_tpu.cluster.pg import CURRENT_OP_DEADLINE

        # graft-trace: this daemon's dispatch span parents under the
        # client's root via the header's span id; entering it installs
        # CURRENT_SPAN so sub-op fan-out parents under it in turn
        # (NULL_SPAN when tracing is off — no allocation, no retention)
        tr = getattr(msg, "trace", None) or {}
        token = CURRENT_OP.set(top)
        # sub-writes/sub-reads fanned out under this op inherit its
        # client deadline, so replicas can shed the dead legs too
        dl_token = CURRENT_OP_DEADLINE.set(getattr(msg, "deadline", None))
        try:
            with self.tracer.start("osd_op", trace_id=tr.get("id"),
                                   parent_id=tr.get("span")) as ospan:
                ospan.annotate(oid=msg.oid, pg=str(msg.pgid))
                if any(o[0] in self._MUTATING_OPS for o in msg.ops):
                    await self._execute_mutation_dedup(conn, msg, m, pool,
                                                      st, top)
                else:
                    await self._execute_client_ops(conn, msg, m, pool, st,
                                                   top)
        finally:
            CURRENT_OP_DEADLINE.reset(dl_token)
            CURRENT_OP.reset(token)
            top.finish()
            if top.duration is not None:
                self.perf.tinc("osd_op_lat", top.duration)
                self.perf.hinc("osd_op_lat_hist", top.duration)
                if self.flight:
                    self.flight.op_sample(
                        top.desc, top.duration,
                        slow=0 < self.tracker.slow_threshold
                        <= top.duration)

    async def _execute_mutation_dedup(self, conn, msg, m, pool, st, top):
        reqid = tuple(msg.reqid)
        cached = st.reqid_replies.get(reqid)
        if cached is None and reqid in st.reqid_inflight:
            # dup racing its first instance: wait for it, then answer
            # from its replies
            await asyncio.shield(st.reqid_inflight[reqid])
            cached = st.reqid_replies.get(reqid)
        if cached is not None:
            self.perf.inc("osd_dup_ops")
            top.mark("dup_reply_from_cache")
            for reply in cached:
                await conn.send(reply)
            return
        # the in-memory cache is primary-local; the pg log is not.  A
        # resend that survived a primary change finds its reqid in the
        # replicated log entries (reference pg_log_entry_t::reqid dups)
        # and must NOT re-execute — reply success (the recorded effect is
        # applied; per-op out data is not reconstructible from the log).
        # Durability gate: only entries at-or-below the commit watermark
        # may dup-ack — a logged-but-un-acked entry (sub-writes lost
        # around a bounce) can still rewind during peering, and
        # dup-acking it would bless a write that then vanishes (surfaced
        # by graft-chaos mid-write restarts).  Above the watermark we
        # WAIT for peering's verdict rather than guess: if the entry
        # survives and the watermark catches up (roll-forward) it is
        # durable — dup-ack; if peering rewound it the effects are
        # undone — re-execute; if neither resolves in time, -11 sends
        # the client back for a map refresh + retry (re-executing
        # blindly would double-apply non-idempotent ops like append).
        logged = st.log.reqid_version(reqid)
        if logged is not None and logged > st.last_complete:
            loop = asyncio.get_event_loop()
            # wait only HALF the client's own attempt window: the -11
            # retry hint must reach a waiter that hasn't already timed
            # out and resent, or every unresolved resend burns a full
            # timeout before learning anything
            deadline = loop.time() + self.config.osd_client_op_timeout / 2
            while (loop.time() < deadline
                   and st.log.reqid_version(reqid) is not None
                   and st.last_complete < logged):
                await asyncio.sleep(0.05)
            logged = st.log.reqid_version(reqid)
            if logged is not None and logged > st.last_complete:
                top.mark("dup_unresolved_retry")
                await conn.send(M.MOSDOpReply(
                    reqid=msg.reqid, result=-11, epoch=m.epoch))
                return
        if logged is not None and logged <= st.last_complete:
            self.perf.inc("osd_dup_ops_from_log")
            top.mark("dup_refused_from_log")
            await conn.send(M.MOSDOpReply(
                reqid=msg.reqid, result=0, epoch=m.epoch))
            return
        # cluster-full reject AFTER the dup resolution above: a resend
        # of an already-committed mutation must get its original ack
        # even while the map carries the full flag — ENOSPC-ing a
        # durably-applied write would be exactly the acked-then-lost
        # confusion the full protection exists to prevent.  A genuinely
        # NEW growing write still rejects promptly (never a timeout).
        if self._full_rejects(msg):
            self.perf.inc("osd_full_rejects")
            top.mark("full_reject")
            await conn.send(M.MOSDOpReply(
                reqid=msg.reqid, result=-28, epoch=m.epoch))
            return
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        st.reqid_inflight[reqid] = fut

        sent: List = []

        class _RecordingConn:
            """Forwards sends while capturing replies for the dup cache."""

            def __init__(self, inner):
                self._inner = inner

            def __getattr__(self, name):
                return getattr(self._inner, name)

            async def send(self, reply):
                sent.append(reply)
                await self._inner.send(reply)

        from ceph_tpu.cluster.pg import CURRENT_CLIENT_REQID

        token = CURRENT_CLIENT_REQID.set(reqid)
        try:
            await self._execute_client_ops(
                _RecordingConn(conn), msg, m, pool, st, top)
            st.reqid_replies[reqid] = sent
            while len(st.reqid_replies) > self._REQID_DUPS_TRACKED:
                st.reqid_replies.popitem(last=False)
            if pool.is_tier() and sent and \
                    getattr(sent[-1], "result", -1) == 0:
                await self._tier_mark_dirty_after_write(pool, st, msg)
        finally:
            CURRENT_CLIENT_REQID.reset(token)
            st.reqid_inflight.pop(reqid, None)
            if not fut.done():
                fut.set_result(None)

    def _resolve_snap_read(self, pool, st, oid: str):
        """Map (oid, msg.snapid) -> the store object serving the read
        (reference find_object_context): the head, a clone, or ENOENT."""
        from ceph_tpu.cluster import snaps as snapmod

        coll = _coll(st.pgid)
        ss = snapmod.load_snapset(self.store, coll, oid)
        head_exists = self.store.stat(coll, oid) is not None
        return ss, coll, head_exists

    def _snap_read_oid(self, pool, st, oid: str, snapid) -> str:
        from ceph_tpu.cluster import snaps as snapmod

        if snapid is None:
            return oid
        if snapid in pool.removed_snaps:
            # a trimmed snap no longer exists; resolving it against the
            # shrunk SnapSet would silently serve head data
            raise FileNotFoundError(f"{oid}@{snapid}: snap removed")
        ss, coll, head_exists = self._resolve_snap_read(pool, st, oid)
        kind, cid = ss.resolve_read(snapid, head_exists)
        if kind == "head":
            return oid
        if kind == "clone":
            return snapmod.clone_oid(oid, cid)
        raise FileNotFoundError(f"{oid}@{snapid}")

    async def _execute_client_ops(self, conn, msg, m, pool, st, top):
        """Run the op vector like the reference do_osd_ops loop
        (`while (!bp.end() && !result)`, PrimaryLogPG.cc): stop at the
        FIRST failing op — a cmpxattr mismatch really gates the writes
        behind it — and send ONE terminal MOSDOpReply for the whole
        vector (ADVICE r4 medium: per-op replies produced multiple
        replies for one reqid)."""
        if any(o[0] == "notify" for o in msg.ops):
            if len(msg.ops) != 1:
                await conn.send(M.MOSDOpReply(
                    reqid=msg.reqid, result=-22, epoch=m.epoch))
                return
            # off the connection's dispatch loop: a notifier that also
            # watches the object acks over this same connection, which
            # must keep reading while the notify gathers acks
            args = msg.ops[0][1]

            async def _notify_bg(reqid=msg.reqid, oid=msg.oid,
                                 a=args, epoch=m.epoch):
                ackers = await self._op_notify(st, oid, a)
                try:
                    await conn.send(M.MOSDOpReply(
                        reqid=reqid, result=0, data=ackers,
                        epoch=epoch))
                except (ConnectionError, OSError):
                    pass

            self._track(
                asyncio.get_event_loop().create_task(_notify_bg()))
            return
        # cache-pool admission (promote / proxy / forward /
        # delete-through).  Runs INSIDE the dedup wrapper so a resent
        # mutation answers from the reqid cache before it can forward or
        # delete-through a second time.
        if pool.is_tier() and await self._tier_intercept(
                conn, msg, m, pool, st):
            return
        # two-phase, approximating the reference's discard-txn-on-error
        # atomicity: GUARD ops run first (in their vector order), the rest
        # of the vector runs second in order — so a mutation can never
        # land ahead of a failing guard, while read/write ordering within
        # the vector is preserved.  (librados vectors are read-ops OR
        # write-ops, never mixed, so guards-first matches the patterns the
        # reference APIs generate.)  Mutations still apply sequentially: a
        # failure mid-way leaves earlier mutations of the same vector
        # applied, reported via the terminal result.
        result = 0
        outs: List = [None] * len(msg.ops)
        phases = (
            [(i, o) for i, o in enumerate(msg.ops)
             if o[0] in self._GUARD_OPS],
            [(i, o) for i, o in enumerate(msg.ops)
             if o[0] not in self._GUARD_OPS],
        )
        for phase in phases:
            for i, (opname, args) in phase:
                r, data = await self._do_one_op(conn, msg, m, pool, st,
                                                opname, args)
                outs[i] = data
                if r < 0:
                    result = r
                    break
            if result < 0:
                break
        data = outs[0] if len(msg.ops) == 1 else outs
        reply = M.MOSDOpReply(
            reqid=msg.reqid, result=result, data=data, epoch=m.epoch)
        tr = getattr(msg, "trace", None)
        if tr is not None:
            # reply-leg trace (round 11): the messengers stamp the
            # send/recv hops and the objecter closes with its wakeup —
            # the previously-untraced tail of wall_coverage
            reply.trace = {"id": tr.get("id"), "events": []}
        await conn.send(reply)

    async def _do_one_op(self, conn, msg, m, pool, st, opname, args):
        """One op of the vector -> (result, out_data).

        Round 12: the hot mutation verbs (write_full, write, zero,
        append, truncate, delete, create) commit through ONE pipelined
        frontier path for both pool kinds — prepare under the object
        write lock (EC read-merge-encode) or the PG lock (replicated
        txn build), ordered commit section under the PG lock, ack wait
        with everything released.  ``osd_pipeline_writes=0`` restores
        the round-10 full-PG-lock serial commits as the bit-exactness
        anchor.  Compound read-modify verbs (copy_from, rollback, exec,
        xattr/omap) keep the serial shape — they still register with
        the same commit frontier via _replicate_txn."""
        pipe = self.config.osd_pipeline_writes > 0
        if opname == "write_full":
            if pool.is_erasure():
                if pipe:
                    # encode outside the PG lock, ordered commit under
                    # it, ack wait after release — the PG admits the
                    # next write while this one's shards commit
                    r = await self._ec_write_pipelined(
                        pool, st, msg.oid, args["data"], None,
                        snapc=msg.snapc)
                else:
                    async with st.lock:
                        r = await self._ec_write(
                            pool, st, msg.oid, args["data"], None,
                            snapc=msg.snapc)
                return r, None
            if pipe:
                r = await self._rep_mutate_pipelined(
                    st, msg.oid,
                    lambda version: self._txn_write_full(
                        st, msg.oid, args["data"], msg.snapc, version))
                return r, None
            async with st.lock:
                r = await self._op_write_full(
                    pool, st, msg.oid, args["data"], snapc=msg.snapc)
            return r, None
        if opname in ("write", "zero"):
            data = args["data"] if opname == "write" \
                else b"\0" * args["length"]
            offset = args["offset"]
            if pipe:
                if pool.is_erasure():
                    r = await self._ec_write_pipelined(
                        pool, st, msg.oid, data, offset,
                        snapc=msg.snapc)
                else:
                    r = await self._rep_mutate_pipelined(
                        st, msg.oid,
                        lambda version: self._txn_write(
                            st, msg.oid, offset, data, msg.snapc,
                            version))
                return r, None
            async with st.lock:
                r = await self._op_write(pool, st, msg.oid,
                                         offset, data,
                                         snapc=msg.snapc)
            return r, None
        if opname == "read":
            try:
                oid = self._snap_read_oid(pool, st, msg.oid, msg.snapid)
                data = await self._op_read(
                    pool, st, oid,
                    args.get("offset", 0), args.get("length"))
                return 0, data
            except FileNotFoundError:
                return -2, None
        if opname == "delete":
            if pipe:
                r = await self._op_delete_pipelined(pool, st, msg.oid,
                                                    snapc=msg.snapc)
                return r, None
            async with st.lock:
                r = await self._op_delete(pool, st, msg.oid,
                                          snapc=msg.snapc)
            return r, None
        if opname == "append":
            # CEPH_OSD_OP_APPEND: a write at the CURRENT size — atomic
            # under the object write lock (pipelined; concurrent
            # appends serialize per object, do_osd_ops:4917 case) or
            # the PG lock (serial fallback)
            if pipe and pool.is_erasure():
                async with self._obj_write_lock(st, msg.oid):
                    size = self._head_size(pool, st, msg.oid)
                    token = await self._ec_start_objlocked(
                        pool, st, msg.oid, args["data"], size,
                        msg.snapc)
                r = await self._ec_commit_finish(st, token)
                return r, size
            if pipe:
                sizebox = []

                def _build(version):
                    sizebox.append(
                        self._head_size(pool, st, msg.oid))
                    return self._txn_write(st, msg.oid, sizebox[0],
                                           args["data"], msg.snapc,
                                           version)

                r = await self._rep_mutate_pipelined(st, msg.oid,
                                                     _build)
                return r, sizebox[0] if sizebox else 0
            async with st.lock:
                size = self._head_size(pool, st, msg.oid)
                r = await self._op_write(pool, st, msg.oid,
                                         size, args["data"],
                                         snapc=msg.snapc)
            return r, size
        if opname == "truncate":
            if pipe and pool.is_erasure():
                r = await self._ec_truncate_pipelined(
                    pool, st, msg.oid, args["size"], snapc=msg.snapc)
                return r, None
            if pipe:
                r = await self._rep_mutate_pipelined(
                    st, msg.oid,
                    lambda version: self._txn_truncate(
                        st, msg.oid, args["size"], msg.snapc,
                        version))
                return r, None
            async with st.lock:
                r = await self._op_truncate(pool, st, msg.oid,
                                            args["size"],
                                            snapc=msg.snapc)
            return r, None
        if opname == "create":
            # exclusive create (CEPH_OSD_OP_CREATE + EXCL flag): the
            # exists-check must be atomic with the commit start, so the
            # pipelined shape holds the object lock (EC) / PG lock
            # (replicated) across both
            if pipe and pool.is_erasure():
                async with self._obj_write_lock(st, msg.oid):
                    if self._head_size(pool, st, msg.oid,
                                       missing=None) is not None:
                        return -17, None  # EEXIST
                    token = await self._ec_start_objlocked(
                        pool, st, msg.oid, b"", None, msg.snapc)
                r = await self._ec_commit_finish(st, token)
                return r, None
            if pipe:
                async with st.lock:
                    if self._head_size(pool, st, msg.oid,
                                       missing=None) is not None:
                        return -17, None  # EEXIST
                    version = self._next_version(st)
                    txn = self._txn_write_full(st, msg.oid, b"",
                                               msg.snapc, version)
                    token = await self._replicate_txn_start(
                        st, txn, "modify", msg.oid, version)
                r = await self._replicate_txn_finish(st, token)
                return r, None
            async with st.lock:
                if self._head_size(pool, st, msg.oid, missing=None) \
                        is not None:
                    return -17, None  # EEXIST
                r = await self._op_write_full(
                    pool, st, msg.oid, b"", snapc=msg.snapc)
            return r, None
        if opname == "cmpxattr":
            # CEPH_OSD_OP_CMPXATTR (eq): gate for compound client
            # ops; mismatch -> -ECANCELED like the reference
            cur = self.store.getattr(_coll(st.pgid), msg.oid,
                                     "_" + args["name"])
            return (0 if cur == args["value"] else -125), None
        if opname == "stat":
            try:
                oid = self._snap_read_oid(pool, st, msg.oid, msg.snapid)
            except FileNotFoundError:
                oid = None
            size = None
            if oid is not None:
                size = self.store.stat(_coll(st.pgid), oid)
                if pool.is_erasure():
                    xs = self.store.getattr(_coll(st.pgid), oid, "size")
                    size = int(xs) if xs else \
                        (None if size is None else size)
            return (0 if size is not None else -2), size
        if opname == "list":
            from ceph_tpu.cluster import snaps as snapmod

            names = [o for o in self._list_pg_objects(st.pgid)
                     if not snapmod.is_snap_key(o)]
            return 0, names
        if opname in ("getxattr", "getxattrs", "omap_get"):
            # snap-aware like "read": resolve the serving clone first
            try:
                moid = self._snap_read_oid(pool, st, msg.oid, msg.snapid)
            except FileNotFoundError:
                return -2, None
            return self._op_read_meta(st, moid, opname, args)
        if opname in ("setxattr", "rmxattr", "omap_set", "omap_rmkeys"):
            async with st.lock:
                r = await self._op_write_meta(st, msg.oid, opname, args,
                                              snapc=msg.snapc, pool=pool)
            return r, None
        if opname == "exec":
            async with st.lock:
                return await self._op_exec(st, msg.oid, args,
                                           snapc=msg.snapc, pool=pool)
        if opname == "watch":
            self._watchers.setdefault((st.pgid, msg.oid), {})[
                (str(msg.src), args["cookie"])] = conn
            self.perf.inc("osd_watches")
            return 0, None
        if opname == "unwatch":
            self._watchers.get((st.pgid, msg.oid), {}).pop(
                (str(msg.src), args["cookie"]), None)
            return 0, None
        if opname == "copy_from":
            # CEPH_OSD_OP_COPY_FROM (reference PrimaryLogPG.cc:3113
            # do_osd_ops COPY_FROM -> start_copy): the DESTINATION
            # primary pulls the source object — data, user xattrs, omap —
            # through its own internal client (works cross-pool and
            # across pool types) and REPLACES the destination wholesale
            src_pool = args.get("src_pool", st.pgid.pool)
            src_oid = args["src_oid"]
            src_snapid = args.get("src_snapid")
            reply = await self.internal_op(
                src_pool, src_oid,
                [("read", {}), ("getxattrs", {}), ("omap_get", {})],
                snapid=src_snapid)
            if reply.result < 0:
                return reply.result, None
            data, xattrs, omap = reply.data
            async with self._compound_write_guard(pool, st, msg.oid):
                async with st.lock:
                    r = await self._op_write_full(pool, st, msg.oid,
                                                  data,
                                                  snapc=msg.snapc)
                    if r < 0:
                        return r, None
                    r = await self._replace_meta(st, msg.oid,
                                                 xattrs or {},
                                                 omap or {})
            return (r, None) if r < 0 else (0, len(data))
        if opname == "rollback":
            # CEPH_OSD_OP_ROLLBACK (reference PrimaryLogPG::_rollback_to):
            # make the head IDENTICAL to the object's state at ``snapid``
            # — the restore runs through the normal write path, so the
            # CURRENT head still COWs into its own clone first
            snapid = args["snapid"]
            try:
                src = self._snap_read_oid(pool, st, msg.oid, snapid)
            except FileNotFoundError:
                return -2, None
            if src == msg.oid:
                return 0, None  # head already carries the snap state
            data = await self._op_read(pool, st, src, 0, None)
            coll = _coll(st.pgid)
            xattrs = {k[1:]: v for k, v in
                      self.store.get_xattrs(coll, src).items()
                      if k.startswith("_")}
            omap = self.store.omap_get(coll, src)
            async with self._compound_write_guard(pool, st, msg.oid):
                async with st.lock:
                    r = await self._op_write_full(pool, st, msg.oid,
                                                  data,
                                                  snapc=msg.snapc)
                    if r < 0:
                        return r, None
                    r = await self._replace_meta(st, msg.oid, xattrs,
                                                 omap)
            return (r, None) if r < 0 else (0, None)
        if opname == "notify_ack":
            entry = self._notifies.get(args["notify_id"])
            if entry is not None:
                fut, acked = entry
                acked.add(str(msg.src))
                if not fut.done() and len(acked) >= fut.needed:  # type: ignore[attr-defined]
                    fut.set_result(None)
            return 0, None
        return -95, None

    # ------------------------------------------------- xattr/omap/exec ops
    #
    # User xattrs are stored with a "_" prefix, exactly like the reference
    # object store's user-attr namespace, so they never collide with the
    # internal shard/size/hinfo attrs.

    async def _replace_meta(self, st: PGState, oid: str,
                            xattrs: Dict, omap: Dict) -> int:
        """Make the object's user xattrs and omap IDENTICAL to the given
        sets (copy-from/rollback are wholesale replacements, never
        merges): stale head keys absent from the source are removed."""
        coll = _coll(st.pgid)
        cur_x = {k[1:] for k in self.store.get_xattrs(coll, oid)
                 if k.startswith("_")}
        for name in cur_x - set(xattrs):
            r = await self._op_write_meta(st, oid, "rmxattr",
                                          {"name": name})
            if r < 0:
                return r
        for name, value in xattrs.items():
            r = await self._op_write_meta(st, oid, "setxattr",
                                          {"name": name, "value": value})
            if r < 0:
                return r
        stale = set(self.store.omap_get(coll, oid)) - set(omap)
        if stale:
            r = await self._op_write_meta(st, oid, "omap_rmkeys",
                                          {"keys": sorted(stale)})
            if r < 0:
                return r
        if omap:
            r = await self._op_write_meta(st, oid, "omap_set",
                                          {"kv": omap})
            if r < 0:
                return r
        return 0

    def _op_read_meta(self, st: PGState, oid: str, opname: str, args):
        coll = _coll(st.pgid)
        if self.store.stat(coll, oid) is None:
            return -2, None
        if opname == "getxattr":
            v = self.store.getattr(coll, oid, "_" + args["name"])
            return (0, v) if v is not None else (-61, None)  # ENODATA
        if opname == "getxattrs":
            return 0, {k[1:]: v for k, v in
                       self.store.get_xattrs(coll, oid).items()
                       if k.startswith("_")}
        if opname == "omap_get":
            return 0, self.store.omap_get(coll, oid)
        return -95, None

    async def _op_write_meta(self, st: PGState, oid: str, opname: str,
                             args, snapc=None, pool=None) -> int:
        """Metadata mutations ride the same logged+replicated transaction
        path as data writes (reference do_osd_ops xattr/omap cases write
        into the op's transaction, PrimaryLogPG.cc:4917).  ``snapc``
        clone-on-writes the object first like data mutations do — omap
        and xattr state snapshot with the object (the CephFS dirfrag
        snapshots ride this)."""
        coll = _coll(st.pgid)
        txn = Transaction()
        if snapc is not None:
            txn.ops.extend(self._cow_pre_ops(
                st, oid, snapc,
                erasure=bool(pool is not None and pool.is_erasure())))
        txn.touch(coll, oid)
        if opname == "setxattr":
            txn.setattr(coll, oid, "_" + args["name"], args["value"])
        elif opname == "rmxattr":
            txn.rmattr(coll, oid, "_" + args["name"])
        elif opname == "omap_set":
            txn.omap_set(coll, oid, args["kv"])
        elif opname == "omap_rmkeys":
            txn.omap_rmkeys(coll, oid, list(args["keys"]))
        version = self._next_version(st)
        txn.set_version(coll, oid, version[1])
        return await self._replicate_txn(st, txn, "modify", oid, version)

    async def _op_exec(self, st: PGState, oid: str, args, snapc=None,
                       pool=None):
        """Object-class execution (reference do_osd_ops CEPH_OSD_OP_CALL):
        the method's reads hit the store, its writes collect into a txn
        that commits + replicates atomically with the op.  ``snapc``
        clone-on-writes first, so cls-mutated state (dirfrags, bucket
        indexes) snapshots like plain data."""
        from ceph_tpu.cluster.objclass import (
            ClassRegistry, ClsError, MethodContext,
        )

        coll = _coll(st.pgid)
        txn = Transaction()
        if snapc is not None:
            txn.ops.extend(self._cow_pre_ops(
                st, oid, snapc,
                erasure=bool(pool is not None and pool.is_erasure())))
        txn.touch(coll, oid)
        base_ops = len(txn.ops)
        ctx = MethodContext(self.store, coll, oid, txn)
        try:
            out = ClassRegistry.instance().call(
                args["cls"], args["method"], ctx, args.get("indata", b""))
        except ClsError as e:
            return e.errno, str(e)
        self.perf.inc("osd_cls_calls")
        if len(txn.ops) > base_ops:  # method added mutations to commit
            version = self._next_version(st)
            txn.set_version(coll, oid, version[1])
            r = await self._replicate_txn(st, txn, "modify", oid, version)
            if r != 0:
                return r, None
        return 0, out

    async def _op_notify(self, st: PGState, oid: str, args):
        """Fan a notify out to every watcher and gather acks within the
        timeout (reference PrimaryLogPG::do_osd_op_effects + Notify)."""
        watchers = self._watchers.get((st.pgid, oid), {})
        live = {k: c for k, c in watchers.items() if not c.closed}
        self._watchers[(st.pgid, oid)] = live
        if not live:
            return []
        self._notify_id += 1
        nid = self._notify_id
        fut = asyncio.get_event_loop().create_future()
        fut.needed = len(live)  # type: ignore[attr-defined]
        acked: Set[str] = set()
        self._notifies[nid] = (fut, acked)
        for (watcher, cookie), conn in live.items():
            try:
                await conn.send(M.MWatchNotify(
                    pool=st.pgid.pool, oid=oid, notify_id=nid,
                    cookie=cookie, payload=args.get("payload", b"")))
            except (ConnectionError, OSError, RuntimeError):
                fut.needed -= 1  # type: ignore[attr-defined]
                if len(acked) >= fut.needed and not fut.done():  # type: ignore[attr-defined]
                    fut.set_result(None)
        try:
            if not fut.done() and fut.needed > 0:  # type: ignore[attr-defined]
                await asyncio.wait_for(
                    fut, timeout=args.get("timeout",
                                          self.config.osd_client_op_timeout))
        except asyncio.TimeoutError:
            pass
        finally:
            self._notifies.pop(nid, None)
        self.perf.inc("osd_notifies")
        return sorted(acked)
