"""ECBackend: striped shard writes/reads, RMW, decode recovery
(reference src/osd/ECBackend.cc:921,986,1141 via the PGBackend seam).
Encode/decode of the touched stripe range is one batched TPU dispatch."""

from __future__ import annotations

import asyncio
import pickle
from typing import Dict, List, Optional, Set, Tuple

from ceph_tpu.cluster import messages as M
from ceph_tpu.cluster.messenger import Connection
from ceph_tpu.cluster.pglog import LogEntry
from ceph_tpu.crush.types import CRUSH_ITEM_NONE
from ceph_tpu.cluster.pg import PGRB, PGState, _coll
from ceph_tpu.cluster.store import Transaction
from ceph_tpu.ec import planar_store
from ceph_tpu.ops import crc32c as crcmod
from ceph_tpu.osdmap.osdmap import PGid, PGPool


class ECUndersized(Exception):
    """The live acting set is below the pool's EC write floor
    (min_size, never below k): admitting the write would create a
    generation with fewer than k unique shards — acked-but-
    unreconstructable by construction, and a subsequent roll-forward
    would wedge the PG on a generation nothing can ever decode
    (surfaced by graft-chaos batch-kill-midtick: a primary alone in a
    bounced acting set committed a 1-of-3-shard write).  Mapped to -11
    so the client refreshes its map and retries once the set heals."""


class ECSizeMismatch(Exception):
    """The chosen decode group's object size disagrees with the size the
    caller assumed from its LOCAL shard attrs — the local shard is a
    stale generation (e.g. a primary whose recovery pull never finished).
    Carries the group's size so the caller can recompute the stripe
    range and retry against the authoritative generation; mixing group
    bytes with the local length would serve torn reads (surfaced by
    graft-chaos: g2 bytes truncated to g1's length)."""

    def __init__(self, size: int):
        super().__init__(f"decode group size {size}")
        self.size = size


def choose_decode_group(got: Dict[int, Tuple[bytes, int, int]],
                        need_k: int, committed,
                        committed_before=None) -> Tuple[
                            Dict[int, bytes], int, int, Set[int]]:
    """Choose the shard group that decodes consistently: newest version
    first, but versions ABOVE the commit watermark are skipped when an
    older viable group exists — an un-acked write may still be rolled
    back by peering, and serving bytes that later vanish would break
    read-your-ack (the reference compares object_info versions in
    handle_sub_read_reply and serves committed state).

    Pure function (round 16) so the mixed-generation corruption-matrix
    tests drive it without a cluster: ``got`` maps shard -> (bytes,
    version, size), ``committed(v)`` answers "is generation v at/below
    the commit watermark (or a resolved frontier entry)".  Returns
    ``(shards, size, version, stale_shards)`` — ``stale_shards`` are
    members whose shard belongs to an OLDER generation than a COMMITTED
    chosen one: they missed an acked write (crash/rewind/interrupted
    recovery) and are read-repair candidates.  ``committed_before``
    (default: ``committed``) is the STRICTER predicate staleness is
    judged by — the caller passes its start-of-gather watermark
    snapshot, so a generation that commits WHILE the gather is in
    flight never flags members whose replies merely predate their own
    apply (a healthy write/read race, not damage).  Raises IOError when an
    acked newer generation lacks k same-version shards: serving an
    older group would be a silent stale read (ADVICE r4), so the read
    fails and recovery repairs the object instead."""
    shards: Dict[int, bytes] = {}
    size = 0
    version = 0
    stale: Set[int] = set()
    versions = sorted({ver for _, ver, _ in got.values()}, reverse=True)
    viable = []
    for v in versions:
        group = {s: d for s, (d, ver, _) in got.items() if ver == v}
        if len(group) >= min(need_k, len(got)):
            viable.append((v, group))
    chosen = None
    for v, group in viable:
        if committed(v):
            chosen = (v, group)
            break
    if chosen is None and viable:
        chosen = viable[0]  # only un-acked state exists (new object)
    acked_newest = max((v for v in versions if committed(v)),
                       default=None)
    if (acked_newest is not None and chosen is not None
            and chosen[0] < acked_newest):
        have = sum(1 for _, ver, _ in got.values()
                   if ver == acked_newest)
        raise IOError(
            f"acked version {acked_newest} has only {have} "
            f"of {need_k} shards; refusing stale read")
    if chosen is not None:
        version, shards = chosen[0], chosen[1]
        size = max(sz for _, ver, sz in got.values() if ver == version)
        if (committed_before or committed)(version):
            # a shard BELOW a generation committed BEFORE the gather
            # began can only exist if its member missed an acked write
            # (EC commits require every shard); in-flight newer writes
            # sit above it, and a generation that committed mid-gather
            # is excluded by the stricter predicate
            stale = {s for s, (_d, ver, _sz) in got.items()
                     if ver < version}
    return shards, size, version, stale


class ECBackendMixin:

    def _codec(self, pool: PGPool):
        codec = self._codecs.get(pool.pool_id)
        if codec is None:
            from ceph_tpu.ec import factory

            profile = pool.ec_profile or {
                "plugin": "jerasure", "technique": "reed_sol_van",
                "k": "2", "m": "1"}
            codec = factory(profile)
            if self.config.osd_ec_mesh == "on":
                # route the pool's batch encode/decode over the device
                # mesh (parallel/engine.py) — the multi-chip data plane
                from ceph_tpu.parallel.engine import wrap_codec_for_mesh

                codec = wrap_codec_for_mesh(
                    codec, self.config.osd_ec_mesh_devices)
            self._codecs[pool.pool_id] = codec
        return codec

    def _sinfo(self, pool: PGPool, codec) -> "StripeInfo":
        """Stripe layout for a pool (ECUtil::stripe_info_t analog)."""
        from ceph_tpu.ec.stripe import StripeInfo

        unit = int((pool.ec_profile or {}).get(
            "stripe_unit", self.config.osd_ec_stripe_unit))
        return StripeInfo(codec.get_data_chunk_count(), unit)

    def _planar_mode(self, codec, sinfo) -> bool:
        """Bit-planar AT-REST gate (round 19): config on AND the codec/
        stripe geometry supports conversion-free plane-domain compute
        (w=8 matrix codec, unit % 8 == 0).  Unsupported geometries
        quietly stay byte-at-rest — the gate never changes what bytes a
        client sees, only how shards are laid out."""
        if not self.config.osd_ec_planar_at_rest:
            return False
        from ceph_tpu.ec import stripe as stripemod

        return stripemod.planar_at_rest_ok(codec, sinfo.chunk_size)

    # ----------------------------------------------------------- EC backend
    #
    # Objects are striped (ECUtil::stripe_info_t math, ceph_tpu.ec.stripe):
    # shard s holds stripe-chunk s of every stripe, concatenated.  Encode /
    # decode of the whole touched stripe range happens in one batched TPU
    # dispatch; partial writes are read-modify-write over stripe bounds
    # (reference ECBackend::start_rmw, ECBackend.cc:1785-1886).
    #
    # Round-6 layout contract: between those host boundaries the stripe
    # batch lives in the bit-planar device layout (ec/planar.py) — the
    # encode/decode/RMW-delta hops are planar GF(2) matmuls and a batch is
    # converted (transposed) at most once per direction per client op.
    # Byte layout appears only where bytes must: the store transaction and
    # the sub-write wire format.

    async def _ec_write_pipelined(self, pool: PGPool, st: PGState,
                                  oid: str, data: bytes,
                                  offset: Optional[int],
                                  snapc=None) -> int:
        """Pipelined EC mutation — full rewrite (offset None) AND RMW
        (round 12 unified): prepare (read-merge for RMW, coalesced
        encode) under the per-OBJECT write lock, take the PG lock only
        for the ordered commit section (version assignment, log append,
        local apply, sub-write sends), and await the fan-out acks with
        both RELEASED — the reference's in-flight RepGather pipeline,
        where the PG admits the next write while this one's shards are
        still committing.  The object lock is what the full PG lock
        used to provide for RMW: no other write to the SAME object can
        commit inside the read-merge window (lost-update exclusion,
        ECBackend::start_rmw wait queue), while the rest of the PG
        proceeds.  The commit frontier (pg.py _frontier_*) keeps the
        watermark honest under out-of-order ack arrival."""
        async with self._obj_write_lock(st, oid):
            token = await self._ec_start_objlocked(
                pool, st, oid, data, offset, snapc)
        return await self._ec_commit_finish(st, token)

    async def _ec_start_objlocked(self, pool: PGPool, st: PGState,
                                  oid: str, data: bytes,
                                  offset: Optional[int], snapc):
        """Prepare + commit-start half of a pipelined EC write; the
        caller holds the object write lock and awaits
        ``_ec_commit_finish`` on the returned token OUTSIDE it (an int
        token is an already-final result, e.g. -11 undersized)."""
        codec = self._codec(pool)
        sinfo = self._sinfo(pool, codec)
        if not self._ec_acting_writeable(pool, codec, st):
            return -11  # retry after the map heals; no encode burned
        shards, crcs, new_size, chunk_off, layout = \
            await self._ec_prepare_write(
                pool, st, oid, data, offset, codec, sinfo)
        if offset is not None:
            self.perf.inc("osd_rmw_pipelined")
        try:
            async with st.lock:
                return await self._ec_commit_start(
                    pool, st, oid, new_size, shards, crcs, snapc,
                    codec, sinfo, chunk_off=chunk_off, layout=layout)
        except ECUndersized:
            return -11

    def _ec_acting_writeable(self, pool: PGPool, codec, st: PGState
                             ) -> bool:
        """EC write admission floor (reference: a PG below min_size is
        not active and ops wait): at least min_size live members —
        never below k — or every 'committed' stripe would be missing
        shards it can never reconstruct."""
        live = sum(1 for o in st.acting if o != CRUSH_ITEM_NONE)
        k = codec.get_data_chunk_count()
        need = min(codec.get_chunk_count(), max(k, pool.min_size))
        if live >= need:
            return True
        self.perf.inc("osd_ec_undersized_blocks")
        return False

    async def _ec_truncate_pipelined(self, pool: PGPool, st: PGState,
                                     oid: str, size: int,
                                     snapc=None) -> int:
        """Pipelined EC truncate (round 12): read the surviving prefix
        and re-encode it as a full rewrite, all under the OBJECT write
        lock (the read-then-rewrite window must exclude other writes to
        this object — the full PG lock's old job), committing through
        the same frontier path as every other pipelined write."""
        async with self._obj_write_lock(st, oid):
            cur = self._head_size(pool, st, oid)
            if size == cur:
                return 0
            if size < cur:
                head = await self._op_read(pool, st, oid, 0, size)
                head = head.ljust(size, b"\0")
            else:
                head = (await self._op_read(pool, st, oid, 0, cur)
                        ).ljust(size, b"\0")
            token = await self._ec_start_objlocked(
                pool, st, oid, head, None, snapc)
        return await self._ec_commit_finish(st, token)

    async def _ec_write(self, pool: PGPool, st: PGState, oid: str,
                        data: bytes, offset: Optional[int],
                        snapc=None) -> int:
        """Serial (full-PG-lock) EC write incl. the RMW sequence — the
        ``osd_pipeline_writes=0`` fallback and the path for compound
        read-modify callers that hold st.lock across multiple ops
        (copy_from, rollback, EC truncate's read-then-rewrite).
        Callers hold the PG-wide st.lock across the whole op, so
        overlapping RMWs can never interleave.  The hot path uses
        ``_ec_write_pipelined`` instead, which narrows the locks to the
        ordered commit section."""
        codec = self._codec(pool)
        sinfo = self._sinfo(pool, codec)
        if not self._ec_acting_writeable(pool, codec, st):
            return -11
        shards, crcs, new_size, chunk_off, layout = \
            await self._ec_prepare_write(
                pool, st, oid, data, offset, codec, sinfo)
        try:
            token = await self._ec_commit_start(
                pool, st, oid, new_size, shards, crcs, snapc, codec,
                sinfo, chunk_off=chunk_off, layout=layout)
        except ECUndersized:
            return -11
        return await self._ec_commit_finish(st, token)

    async def _ec_prepare_write(self, pool: PGPool, st: PGState,
                                oid: str, data: bytes,
                                offset: Optional[int], codec, sinfo):
        """The pure-compute half of an EC write: RMW read-merge (when
        offset is given) + coalesced encode.  Returns ``(shards, crcs,
        new_size, chunk_off, layout)``.  Shared verbatim by the serial
        and pipelined paths so the two stay bit-identical by
        construction (the tier-1 exactness gate compares their stored
        bytes).  In planar mode the RMW read-half books the sanctioned
        egress (inside the read coalescer) and the re-encode books the
        sanctioned ingest — the merge itself is logical bytes, which
        is the CLIENT's layout, not a shard layout conversion."""
        from ceph_tpu.ec import stripe as stripemod

        coll = _coll(st.pgid)
        if offset is None:
            # write_full: replace the object — a full-shard rewrite, so
            # the coalesced tick also batch-computes the shard crcs
            shards, crcs, layout = await self._encode_for_write(
                codec, sinfo, data, want_crc=True)
            return shards, crcs, len(data), 0, layout
        sa = self.store.getattr(coll, oid, "size")
        if sa is None:
            # no local shard (lost, or never held): the committed
            # size must come from the acting set — merging against
            # an assumed-empty object would truncate committed bytes
            _, old_size, _, _ = await self._gather_shards(
                pool, st, oid, codec.get_data_chunk_count(), 0, 0)
        else:
            old_size = int(sa)
        off0, len0 = sinfo.offset_len_to_stripe_bounds(offset, len(data))
        chunk_off = sinfo.aligned_logical_offset_to_chunk_offset(off0)
        old_bytes = b""
        for _attempt in range(2):
            old_in_range = max(0, min(old_size - off0, len0))
            if not old_in_range:
                break
            try:
                old_bytes = await self._ec_read_stripes(
                    pool, st, oid, chunk_off, old_in_range,
                    expected_size=old_size)
                break
            except ECSizeMismatch as e:
                if _attempt:
                    # still unstable (write racing recovery): fail
                    # the op rather than merge against absent bytes
                    raise IOError(
                        f"{oid}: object size unstable under RMW")
                # stale local size attr: redo the RMW against the
                # decode group's (committed) size
                old_size, old_bytes = e.size, b""
        merged = stripemod.merge_range(
            old_bytes, old_in_range, offset - off0, data)
        new_size = max(old_size, offset + len(data))
        # RMW touches a sub-range: the replica-side mid-shard crc
        # merge stays local, so no batch crc here
        shards, crcs, layout = await self._encode_for_write(
            codec, sinfo, merged, want_crc=False)
        return shards, crcs, new_size, chunk_off, layout

    async def _ec_commit_start(self, pool: PGPool, st: PGState, oid: str,
                               new_size: int, shards, crcs, snapc,
                               codec, sinfo, chunk_off: int = 0,
                               layout: Optional[str] = None):
        """Ordered commit section of an EC write (runs under st.lock):
        version assignment + frontier registration, local shard apply,
        log append, and the sub-write fan-out SENDS — everything whose
        PG-wide order must match the version order.  Returns the token
        ``_ec_commit_finish`` resolves outside the lock.

        ``layout`` == "planar8" means ``shards[i]`` is an (8, cols)
        AT-REST plane matrix: tobytes() serializes it row-major — the
        same bytes that land in the store and ride the wire, so the
        commit path is conversion-free end to end (round 19)."""
        from ceph_tpu.cluster.optracker import mark_current

        # re-checked UNDER the lock: the acting set can shrink during
        # the prepare awaits, and a commit into an undersized set is
        # the unreconstructable-write bug whatever the prepare-time
        # check saw
        if not self._ec_acting_writeable(pool, codec, st):
            raise ECUndersized(f"{st.pgid}: acting {st.acting}")
        eversion = self._next_version(st)
        version = eversion[1]
        self._frontier_open(st, eversion)
        self._chaos_point("frontier_open")
        shard_size = sinfo.shard_size(new_size)
        hinfo = {"size": new_size, "version": version}

        def hinfo_for(shard: int) -> Dict:
            # full rewrites carry the batch-computed shard crc so no
            # member (local or replica) re-checksums on its event loop
            if crcs is None:
                return hinfo
            return {**hinfo, "crc": crcs[shard]}

        try:
            # clone-on-write (make_writeable): the pre-ops clone each
            # member's SHARD object in place — no snapshot data crosses
            # the wire — and persist the updated SnapSet; they ride the
            # sub-write so clone + write are atomic per shard
            pre_ops = self._cow_pre_ops(st, oid, snapc, erasure=True)
            n = codec.get_chunk_count()
            reqid = self._next_reqid()
            peers = []
            my_shard = None
            for shard in range(n):
                osd = st.acting[shard] if shard < len(st.acting) \
                    else CRUSH_ITEM_NONE
                if osd == self.osd_id:
                    my_shard = shard
                elif osd != CRUSH_ITEM_NONE:
                    peers.append((osd, shard))
            if my_shard is not None:
                self._apply_shard(st.pgid, oid, my_shard,
                                  shards[my_shard].tobytes(), chunk_off,
                                  shard_size, hinfo_for(my_shard),
                                  pre_ops=pre_ops, layout=layout)
                mark_current("store:journal_queued")
            entry = self._log_mutation(st, "modify", oid, eversion)
            self._chaos_point("commit_pre_fanout")
            fut = None
            send_failures = 0
            if peers:
                fut = self._make_waiter(reqid, len(peers))
                # span propagation: each shard sub-write carries the
                # current span id so the replica's apply span joins
                # this op's tree
                subctx = self.tracer.context()
                # sub-writes inherit the client op's deadline (None for
                # recovery traffic): a replica sheds the dead legs
                from ceph_tpu.cluster.pg import CURRENT_OP_DEADLINE

                sub_deadline = CURRENT_OP_DEADLINE.get()
                subs = []
                for osd, shard in peers:
                    sub = M.MOSDECSubOpWrite(
                        reqid=reqid, pgid=st.pgid, oid=oid, shard=shard,
                        data=shards[shard].tobytes(),
                        chunk_off=chunk_off,
                        shard_size=shard_size, hinfo=hinfo_for(shard),
                        entry=entry,
                        pre_ops=pre_ops,
                        epoch=self.osdmap.epoch,
                        deadline=sub_deadline,
                        layout=layout)
                    if subctx is not None:
                        sub.trace = dict(subctx)
                    subs.append((osd, sub))
                if self.config.osd_batch_tick_ops > 0:
                    # batched fan-out (round 11): same-tick sub-writes
                    # for one peer share a frame; a failed send still
                    # surfaces per sub-write, so the every-shard-durable
                    # rule holds
                    results = await asyncio.gather(
                        *(self._sub_batcher.send(o, s) for o, s in subs),
                        return_exceptions=True)
                    for res in results:
                        if isinstance(res, asyncio.CancelledError):
                            # daemon stop / chaos crash mid-fan-out:
                            # propagate — counting cancellation as a
                            # peer send failure would swallow the
                            # teardown (the swallowed-async-error bug
                            # class graftlint now polices)
                            raise res
                        if isinstance(res, BaseException):
                            send_failures += 1
                            self._waiter_dec(reqid)
                else:
                    for osd, sub in subs:
                        try:
                            await self._send_osd(osd, sub)
                        except (ConnectionError, OSError, RuntimeError):
                            send_failures += 1
                            self._waiter_dec(reqid)
                mark_current("ec_sub_write_sent")
        except BaseException:
            # frontier hygiene: a registered-but-unresolved entry would
            # wedge the PG's commit watermark forever
            self._frontier_done(st, eversion, ok=False)
            raise
        return (reqid, eversion, fut, send_failures, entry)

    async def _ec_commit_finish(self, st: PGState, token) -> int:
        """Ack-wait half of an EC write — runs with the PG lock
        RELEASED on the pipelined path, so the next same-PG write
        overlaps this one's shard commits.  Resolves the commit
        frontier however it exits."""
        from ceph_tpu.cluster.optracker import mark_current

        if isinstance(token, int):
            return token  # already-final result (e.g. -11 undersized)
        reqid, eversion, fut, send_failures, entry = token
        try:
            if fut is not None:
                try:
                    if not fut.done():
                        await asyncio.wait_for(
                            fut, timeout=self._ack_wait_timeout())
                    mark_current("sub_write_acked")
                except asyncio.TimeoutError:
                    self._frontier_done(st, eversion, ok=False)
                    return -110
                finally:
                    self._pending.pop(reqid, None)
                if send_failures:
                    # a shard sub-write never left this host: unlike the
                    # replicated path (full copies, reachable set
                    # suffices) every EC shard is unique, so the stripe
                    # is NOT k+m durable and must not ack — the
                    # reference blocks EC writes until EVERY acting
                    # shard commits.  Stay un-acked (-110): the
                    # divergent entry rewinds during peering and the
                    # client retries against the post-peering acting
                    # set.  (Surfaced by graft-chaos: a just-restarted
                    # primary with dead peer sessions could ack a
                    # 1-shard stripe.)
                    self._frontier_done(st, eversion, ok=False)
                    return -110
        except BaseException:
            self._frontier_done(st, eversion, ok=False)
            raise
        if not self._entry_still_logged(st, entry):
            # a concurrent peering round REWOUND this entry (or
            # replaced the log) while our acks were in flight: whatever
            # the shards said, the entry is no longer part of the PG's
            # history — stay un-acked so the client retries (and
            # dup-resolves) against the post-peering state.  Checked by
            # entry IDENTITY: head/version comparisons are foolable
            # once post-rewind writes re-advance (or re-mint) versions.
            self._frontier_done(st, eversion, ok=False)
            return -110
        # every shard acked: this version can never roll back now
        self._chaos_point("frontier_pre_done")
        self._frontier_done(st, eversion, ok=True)
        mark_current("commit")
        return 0

    async def _encode_for_write(self, codec, sinfo, data: bytes,
                                want_crc: bool):
        """Encode one op's stripe range -> (shards, crcs-or-None,
        layout).

        With ``osd_batch_tick_ops`` > 0 the encode rides the per-tick
        coalescer (cluster/batcher.py): every same-profile write in the
        tick shares ONE planar conversion + fused dispatch + crc32c
        batch, and the op's timeline gets the round-11 attribution
        stages — ``batch_wait`` (parked awaiting its tick) and
        ``batch_encode`` (its amortized share of the coalesced
        dispatch).  At 0 this is exactly the round-10 per-op dispatch.

        Round 19 (planar at rest): when the gate is on, the tick runs
        ``encode_planes_multi`` and the returned shards are (n, 8,
        cols) AT-REST plane matrices with plane-major crcs —
        layout == "planar8" tells the commit path to land and ship
        them as planes (store txn write_planar, wire layout field)."""
        from ceph_tpu.cluster.optracker import CURRENT_OP, mark_current

        planar = self._planar_mode(codec, sinfo)
        layout = planar_store.LAYOUT_PLANAR if planar else None
        if self.config.osd_batch_tick_ops > 0:
            mark_current("batch_parked")
            shards, crcs, (t0, t1, batch_n) = \
                await self._ec_batcher.encode(codec, sinfo, data,
                                              want_crc, planar=planar)
            op = CURRENT_OP.get()
            if op is not None:
                # amortized attribution: this op's share of the tick's
                # encode wall; the rest of the window books as parked
                # time (both stamps stay monotone: t1 - share >= t0)
                share = (t1 - t0) / max(batch_n, 1)
                op.mark_at("batch_tick", t1 - share)
                op.mark_at("batch_encoded", t1)
            if planar:
                # the tick's client-bytes -> planes hop was this op's
                # one sanctioned ingest conversion — stamp it so
                # `bench.py --attribute` books it as planar_convert
                mark_current("planar_ingest")
            return shards, crcs, layout
        mark_current("ec_encode")
        # round 16: even the per-op anchor dispatches through the
        # sanctioned coalescer module (batcher.encode_once) — zero
        # device entry points on cluster/ op paths outside that seam
        shards = await self._ec_batcher.encode_once(codec, sinfo, data,
                                                    planar=planar)
        mark_current("planar_ingest" if planar else "ec_encoded")
        return shards, None, layout

    def _apply_shard(self, pgid: PGid, oid: str, shard: int, data: bytes,
                     chunk_off: int, shard_size: int, hinfo: Dict,
                     pre_ops: Optional[List[Tuple]] = None,
                     layout: Optional[str] = None) -> None:
        """Apply a shard sub-range write with its crc in ONE atomic
        transaction (ECUtil::HashInfo analog, reference ECUtil.h:105-163:
        the crc is CUMULATIVE for appends/full rewrites — no whole-shard
        re-read on the hot path — and data+crc can never disagree).

        ``layout`` == "planar8" routes to the planar-at-rest twin: the
        payload is a plane window, not shard bytes (round 19)."""
        if layout == planar_store.LAYOUT_PLANAR:
            self._apply_shard_planar(pgid, oid, shard, data, chunk_off,
                                     shard_size, hinfo, pre_ops)
            return
        coll = _coll(pgid)
        old_size = self.store.stat(coll, oid)
        if chunk_off == 0 and len(data) >= shard_size:
            # full-shard rewrite: use the tick's batch-computed crc when
            # the primary shipped one (hinfo["crc"], round 11) — no
            # per-shard host pass on the event loop; else one pass here
            crc = hinfo.get("crc")
            if crc is None:
                crc = crcmod.crc32c(0xFFFFFFFF, data[:shard_size])
        elif old_size is not None and chunk_off == old_size and \
                shard_size == chunk_off + len(data):
            # append: combine the stored cumulative crc with the new
            # bytes' crc (GF(2) zero-extension, reference HashInfo append)
            stored = self.store.getattr(coll, oid, "hinfo_crc")
            if stored is not None:
                crc = crcmod.crc32c_combine(
                    int(stored), crcmod.crc32c(0, data), len(data))
            else:
                crc = crcmod.crc32c(0xFFFFFFFF,
                                    self.store.read(coll, oid) + data)
        else:
            # true mid-shard RMW: recompute over the merged bytes
            old = bytearray(self.store.read(coll, oid)) \
                if old_size is not None else bytearray()
            if len(old) < shard_size:
                old.extend(b"\0" * (shard_size - len(old)))
            old[chunk_off:chunk_off + len(data)] = data
            crc = crcmod.crc32c(0xFFFFFFFF, bytes(old[:shard_size]))
        txn = Transaction()
        if pre_ops:
            # snapshot pre-ops (shard-local COW clone + snapset) must land
            # in the same transaction, BEFORE the new bytes
            txn.ops.extend(tuple(op) for op in pre_ops)
        # rollback record (ecbackend.rst:10-27): the exact pre-write state
        # of the touched shard range, so peering can REWIND this entry if
        # the write never completes cluster-wide; pruned at commit
        existed = old_size is not None
        rec = {
            "oid": oid, "existed": existed, "chunk_off": chunk_off,
            "old_range": (bytes(self.store.read(coll, oid, chunk_off,
                                                len(data)))
                          if existed else b""),
            "old_total": old_size or 0,
            "old_attrs": {k: self.store.getattr(coll, oid, k)
                          for k in ("shard", "size", "hinfo_crc")},
            "old_version": self.store.get_version(coll, oid),
        }
        txn.omap_set(coll, PGRB,
                     {self._rb_key(hinfo["version"]): pickle.dumps(rec)})
        txn.write(coll, oid, chunk_off, data) \
           .truncate(coll, oid, shard_size) \
           .setattr(coll, oid, "shard", str(shard).encode()) \
           .setattr(coll, oid, "size", str(hinfo["size"]).encode()) \
           .setattr(coll, oid, "hinfo_crc", str(crc).encode()) \
           .set_version(coll, oid, hinfo["version"])
        self.store.queue_transaction(txn)

    def _apply_shard_planar(self, pgid: PGid, oid: str, shard: int,
                            data: bytes, chunk_off: int, shard_size: int,
                            hinfo: Dict,
                            pre_ops: Optional[List[Tuple]] = None) -> None:
        """Planar-at-rest twin of ``_apply_shard`` (round 19): ``data``
        is an (8, cols) plane window serialized row-major — the SAME
        bytes the encode produced and the wire carried — and it lands
        via the store's ``write_planar`` op without ever materializing
        the byte view.  The cumulative hinfo crc stays bit-identical to
        the byte anchor because crc32c over plane-major rows uses the
        column-spread identity (ops/crc32c.crc32c_planar_rows), so
        verify-on-read and scrub agree across mixed-layout members."""
        coll = _coll(pgid)
        Q = planar_store.QUANTUM
        if chunk_off % Q or len(data) % Q:
            raise ValueError(f"{oid}: unaligned planar sub-write "
                             f"(off={chunk_off}, len={len(data)})")
        old_size = self.store.stat(coll, oid)
        old_layout = self.store.object_layout(coll, oid)
        cols = shard_size // Q
        col_off = chunk_off // Q
        window = planar_store.blob_to_planes(data)
        if col_off + window.shape[1] > cols:
            # window overshoots the final shard (byte path: write then
            # truncate) — clip COLUMNS, not blob bytes: the serialized
            # form is row-major so a byte-level cut would shear rows
            window = window[:, :cols - col_off]
            data = planar_store.planes_to_blob(window)
        if chunk_off == 0 and window.shape[1] >= cols:
            # full-shard rewrite: the tick's batch-computed plane-major
            # crc when the primary shipped one; else one host pass here
            crc = hinfo.get("crc")
            if crc is None:
                crc = crcmod.crc32c_planar_rows(window)[0]
        elif old_size is not None and chunk_off == old_size and \
                shard_size == chunk_off + len(data) and \
                self.store.getattr(coll, oid, "hinfo_crc") is not None:
            # append: combine the stored cumulative crc with the delta
            # window's crc (GF(2) zero-extension) — no whole-shard pass,
            # and the delta crc comes straight off the planes
            stored = int(self.store.getattr(coll, oid, "hinfo_crc"))
            crc = crcmod.crc32c_combine(
                stored, crcmod.crc32c_planar_rows(window, seed=0)[0],
                len(data))
        else:
            # true mid-shard RMW (or no stored crc): splice the window
            # into the old plane matrix and crc the merge — plane-major
            # throughout, zero byte-view materializations
            old = None
            if old_size is not None:
                if old_layout == planar_store.LAYOUT_PLANAR:
                    old = planar_store.blob_to_planes(
                        self.store.read_planar(coll, oid))
                else:
                    # byte-at-rest pre-state meeting a planar write: the
                    # one legal relayout hop — the STORE books it when
                    # the write_planar op lands, so seam=None here
                    raw = bytes(self.store.read(coll, oid))
                    if len(raw) % Q:
                        raw += b"\0" * (Q - len(raw) % Q)
                    old = planar_store.shard_to_planes(raw, seam=None)
            merged = planar_store.splice_columns(old, col_off, window,
                                                 cols)
            crc = crcmod.crc32c_planar_rows(merged)[0]
        txn = Transaction()
        if pre_ops:
            txn.ops.extend(tuple(op) for op in pre_ops)
        # rollback record: planar pre-state is captured WHOLE-OBJECT as
        # the raw stored blob (plane-major for planar members, logical
        # bytes for a byte-at-rest pre-state) so the peering rewind can
        # restore it without any layout conversion — rec["layout"]
        # tells pg.rewind_divergent_log which restore op to emit
        existed = old_size is not None
        if existed and old_layout == planar_store.LAYOUT_PLANAR:
            old_range = self.store.read_planar(coll, oid)
        elif existed:
            old_range = bytes(self.store.read(coll, oid))
        else:
            old_range = b""
        rec = {
            "oid": oid, "existed": existed, "chunk_off": 0,
            "old_range": old_range,
            "old_total": old_size or 0,
            "layout": old_layout,
            "old_attrs": {k: self.store.getattr(coll, oid, k)
                          for k in ("shard", "size", "hinfo_crc")},
            "old_version": self.store.get_version(coll, oid),
        }
        txn.omap_set(coll, PGRB,
                     {self._rb_key(hinfo["version"]): pickle.dumps(rec)})
        # ONE op covers the byte path's write+truncate pair: total_cols
        # pins the final shard extent, so no separate truncate
        txn.write_planar(coll, oid, col_off, data, cols) \
           .setattr(coll, oid, "shard", str(shard).encode()) \
           .setattr(coll, oid, "size", str(hinfo["size"]).encode()) \
           .setattr(coll, oid, "hinfo_crc", str(crc).encode()) \
           .set_version(coll, oid, hinfo["version"])
        self.store.queue_transaction(txn)

    def _apply_ec_sub_write(self, msg: M.MOSDECSubOpWrite) -> None:
        """Apply one shard sub-write (store txn + log) — the shared
        core of the single-frame and batched handlers."""
        # replica-side span: joins the primary's op tree via the sub-op
        # trace header (NULL_SPAN when untraced/disabled)
        tr = getattr(msg, "trace", None)
        span = self.tracer.start(
            "ec_sub_write", trace_id=tr.get("id"),
            parent_id=tr.get("span")) if tr else None
        try:
            shard_size = msg.shard_size if msg.shard_size is not None \
                else msg.chunk_off + len(msg.data)
            self._apply_shard(msg.pgid, msg.oid, msg.shard, msg.data,
                              msg.chunk_off, shard_size, msg.hinfo,
                              pre_ops=msg.pre_ops,
                              layout=getattr(msg, "layout", None))
            st = self.pgs.get(msg.pgid)
            if st is not None and msg.entry is not None:
                self._log_mutation(st, msg.entry.op, msg.entry.oid,
                                   msg.entry.version, entry=msg.entry)
            self.perf.inc("osd_ec_sub_writes")
        finally:
            if span is not None:
                span.annotate(shard=msg.shard, oid=msg.oid)
                span.finish()

    async def _handle_ec_write(self, conn: Connection,
                               msg: M.MOSDECSubOpWrite) -> None:
        if self._sub_op_expired(msg):
            # dead work: the parent op's client deadline passed — no
            # apply, no reply (the primary times out and stays un-acked,
            # so a shed shard can never count toward durability)
            return
        self._apply_ec_sub_write(msg)
        await self._reply_osd(conn, msg, M.MOSDECSubOpWriteReply(
            reqid=msg.reqid, result=0))

    async def _handle_ec_write_batch(self, conn: Connection,
                                     msg: M.MOSDECSubOpWriteBatch) -> None:
        """A peer's tick batch: apply every item in list order, ack them
        in ONE reply.  Expired items are silently absent from the
        results — the shed contract of the unbatched path."""
        results = []
        for item in msg.items:
            if results:
                # crash seam: peer dies MID-TICK — some of the frame's
                # items applied (and will ack via nothing), the rest
                # never land; the primaries' acks all die with us
                self._chaos_point("batch_apply_mid")
            if self._sub_op_expired(item):
                continue
            try:
                self._apply_ec_sub_write(item)
            except Exception:
                # per-item fault isolation: one item's failure (e.g. a
                # chaos store injection) must not abort the rest of the
                # frame or their acks — the failed item simply never
                # acks, so ITS primary alone stays un-acked (the
                # unbatched path's one-op blast radius)
                self.perf.inc("osd_dispatch_errors")
                continue
            results.append((item.reqid, 0, item.shard))
        await self._reply_osd(conn, msg, M.MOSDECSubOpWriteBatchReply(
            results=results))

    async def _handle_ec_read(self, conn: Connection,
                              msg: M.MOSDECSubOpRead) -> None:
        if self._sub_op_expired(msg):
            return  # nobody awaits: shed instead of burning device time
        coll = _coll(msg.pgid)
        # round 19: a planar-at-rest shard is read, verified, sliced and
        # SHIPPED as its plane matrix — zero layout conversions on this
        # holder (whole-object pulls, shard == -1, stay on bytes: they
        # come from the replicated pull path, which stores bytes)
        planar = (msg.shard != -1 and
                  self.store.object_layout(coll, msg.oid)
                  == planar_store.LAYOUT_PLANAR)
        try:
            full = (self.store.read_planar(coll, msg.oid) if planar
                    else self.store.read(coll, msg.oid))
        except FileNotFoundError:
            await self._reply_osd(conn, msg, M.MOSDECSubOpReadReply(
                reqid=msg.reqid, result=-2, shard=msg.shard))
            return
        except IOError:
            # media EIO: DISTINCT from absent (-2) — the gatherer
            # queues this shard for in-place read-repair
            self.perf.inc("osd_read_shard_errors")
            await self._reply_osd(conn, msg, M.MOSDECSubOpReadReply(
                reqid=msg.reqid, result=-5, shard=msg.shard))
            return
        stored_crc = self.store.getattr(coll, msg.oid, "hinfo_crc")
        # verify-on-read (round 16, default on): the shard crc checks
        # against the stored hinfo before any byte leaves this holder
        # (ecbackend.rst:86-99); concurrent sub-reads on this daemon
        # share one crc32c batch through the read coalescer — planar
        # shards verify over plane-major rows via the spread identity,
        # bit-identical to the byte anchor's cumulative crc
        if stored_crc is not None and self.config.osd_ec_verify_reads:
            [ok] = await self._read_batcher.verify([full],
                                                   [int(stored_crc)],
                                                   planar=planar)
            if not ok:
                self.perf.inc("osd_read_shard_crc_errors")
                await self._reply_osd(conn, msg, M.MOSDECSubOpReadReply(
                    reqid=msg.reqid, result=-5, shard=msg.shard))
                return
        out_layout = None
        if planar:
            Q = planar_store.QUANTUM
            if msg.off % Q == 0 and (msg.length is None
                                     or msg.length % Q == 0):
                # sub-range by COLUMN slice of the plane matrix — every
                # chunk-aligned gather lands here (unit % 8 == 0 gates
                # planar mode, so chunk offsets are always 8-aligned)
                planes = planar_store.blob_to_planes(full)
                hi = (msg.off + msg.length) // Q \
                    if msg.length is not None else None
                data = planar_store.planes_to_blob(
                    planes[:, msg.off // Q: hi])
                out_layout = planar_store.LAYOUT_PLANAR
            else:
                # unaligned range: correctness-only byte fallback (books
                # the unseamed counter; never hit by aligned gathers)
                full = self.store.read(coll, msg.oid)
                data = full[msg.off: msg.off + msg.length] \
                    if msg.length is not None else full[msg.off:]
        else:
            data = full[msg.off: msg.off + msg.length] \
                if msg.length is not None else full[msg.off:]
        shard_attr = self.store.getattr(coll, msg.oid, "shard")
        shard = int(shard_attr) if shard_attr else msg.shard
        size = self.store.getattr(coll, msg.oid, "size")
        hinfo = {"size": int(size) if size else 0,
                 # version on EVERY reply: the gatherer groups shards
                 # by generation before decoding (stale-member guard)
                 "version": self.store.get_version(coll, msg.oid)}
        if msg.shard == -1:
            # whole-object fetch (pull recovery): carry xattrs so the
            # puller stores a faithful copy
            hinfo["xattrs"] = dict(self.store.get_xattrs(
                coll, msg.oid))
        await self._reply_osd(conn, msg, M.MOSDECSubOpReadReply(
            reqid=msg.reqid, result=0, shard=shard, data=data,
            hinfo=hinfo, layout=out_layout))
        self.perf.inc("osd_ec_sub_reads")

    def _hedge_delay(self) -> float:
        """Straggler-hedge delay for degraded k-of-n reads: the p90 of
        recent sub-read gather latencies x2, floored by config and
        capped well under the op timeout — a slow shard holder costs
        one quantile, not a full timeout."""
        floor = self.config.osd_ec_hedge_delay_floor
        lats = sorted(self._subread_lats)
        if not lats:
            return floor * 4
        q = lats[min(len(lats) - 1, (9 * len(lats)) // 10)]
        return min(max(2.0 * q, floor),
                   self.config.osd_client_op_timeout / 4.0)

    async def _subread_round(self, st: PGState, oid: str, targets,
                             off: int, length: Optional[int],
                             spare=None, check=None) -> List:
        """One shard sub-read fan-out: contact ``targets``, promoting a
        ``spare`` shard holder immediately when a send fails outright
        (dead peer), and hedging the remaining spares after the
        quantile-derived delay (slow peer).  ``check(acc)`` resolves the
        waiter early — typically "k same-generation shards arrived".
        Returns the (result, reply) accumulator."""
        from ceph_tpu.cluster.optracker import mark_current
        from ceph_tpu.cluster.pg import CURRENT_OP_DEADLINE

        spare = list(spare or [])
        reqid = self._next_reqid()
        fut = self._make_waiter(reqid, len(targets))
        if check is not None:
            fut.check = check  # type: ignore[attr-defined]
        sub_deadline = CURRENT_OP_DEADLINE.get()

        async def _send_one(shard: int, osd: int) -> bool:
            try:
                await self._send_osd(osd, M.MOSDECSubOpRead(
                    reqid=reqid, pgid=st.pgid, oid=oid, shard=shard,
                    off=off, length=length, deadline=sub_deadline))
                return True
            except (ConnectionError, OSError, RuntimeError):
                return False

        pending = list(targets)
        while pending:
            shard, osd = pending.pop(0)
            if await _send_one(shard, osd):
                continue
            if spare:
                # dead shard holder: promote a spare NOW instead of
                # shrinking the gather below k
                pending.append(spare.pop(0))
                self.perf.inc("osd_ec_hedge_promotions")
            else:
                self._waiter_dec(reqid)
        mark_current("ec_sub_read_sent")
        hedge_task = None
        if spare and not fut.done():
            delay = self._hedge_delay()

            async def _hedge():
                await asyncio.sleep(delay)
                if fut.done() or self._stopped:
                    return
                # a straggler is late past the quantile: widen the
                # gather so a slow holder degrades latency, not
                # availability
                self.perf.inc("osd_ec_hedged_reads")
                mark_current("ec_hedge_sent")
                for shard, osd in spare:
                    fut.needed += 1  # type: ignore[attr-defined]
                    if not await _send_one(shard, osd):
                        self._waiter_dec(reqid)

            hedge_task = self._track(
                asyncio.get_event_loop().create_task(_hedge()))
        t0 = asyncio.get_event_loop().time()
        try:
            if fut.done():
                acc = fut.result()
            else:
                acc = await asyncio.wait_for(
                    fut, timeout=self._ack_wait_timeout())
            mark_current("sub_read_acked")
            self._subread_lats.append(
                asyncio.get_event_loop().time() - t0)
        except asyncio.TimeoutError:
            acc = self._pending[reqid][1]
        finally:
            self._pending.pop(reqid, None)
            if hedge_task is not None:
                hedge_task.cancel()
        return acc

    async def _gather_shards(
        self, pool: PGPool, st: PGState, oid: str, need_k: int,
        off: int = 0, length: Optional[int] = None,
        exclude_shards: Optional[Set[int]] = None,
        fast_k: bool = False,
    ) -> Tuple[Dict[int, bytes], int, int, Dict[int, Optional[str]]]:
        """Collect >= k shard (ranges) from the acting set (own shard
        free).  ``exclude_shards``: shard ids known corrupt — they must
        never be decode sources (scrub repair would otherwise reconstruct
        FROM the corruption and bless it).  ``fast_k``: degraded-mode
        client reads — contact only the first k shard holders, resolve
        on the first k clean same-generation shards, and hedge/promote
        stragglers instead of gathering the full group.

        Round 19: the 4th return maps each CHOSEN shard id to the
        layout its payload arrived in (``"planar8"`` plane matrices
        from planar-at-rest holders, None for byte ranges) — payload
        lengths are identical either way, so the generation grouping
        and size checks below are layout-blind.

        Round 16 (verified reads): the LOCAL shard's crc checks against
        its stored hinfo before it may feed a decode (riding the read
        coalescer's per-tick crc batch; peers verify their own shards
        in _handle_ec_read), and any shard that fails crc, returns EIO,
        or proves generation-stale queues an ASYNCHRONOUS in-place
        read-repair — never on the client's critical path."""
        exclude_shards = exclude_shards or set()
        coll = _coll(st.pgid)
        # shard id -> why it needs repair ("crc" | "eio" | "stale")
        repair: Dict[int, str] = {}
        # (shard -> (bytes, version, size, layout)): versions gate which
        # shards may decode together — a stale rejoined member's shard
        # from an older generation mixed with current shards would
        # decode to garbage (the reference compares per-shard
        # object_info versions when gathering,
        # ECBackend::handle_sub_read_reply)
        got: Dict[int, Tuple[bytes, int, int, Optional[str]]] = {}
        my = self.store.stat(coll, oid)
        if my is not None:
            shard_attr = self.store.getattr(coll, oid, "shard")
            local_shard = int(shard_attr) if shard_attr is not None \
                else None
            Q = planar_store.QUANTUM
            # planar-at-rest local shard with an aligned range: read
            # the plane blob, verify plane-major, slice COLUMNS — the
            # byte view is never materialized (round 19)
            lp = (self.store.object_layout(coll, oid)
                  == planar_store.LAYOUT_PLANAR and off % Q == 0
                  and (length is None or length % Q == 0))
            data = full = None
            try:
                if lp:
                    full = self.store.read_planar(coll, oid)
                elif self.config.osd_ec_verify_reads:
                    # the cumulative crc covers the WHOLE shard: read
                    # it all, verify, then slice the requested range
                    full = self.store.read(coll, oid)
                else:
                    data = self.store.read(coll, oid, off, length)
            except IOError:
                # local-shard media error (chaos disk EIO): our own
                # shard is absent from the gather — decode from peers,
                # mirroring the peer-side path — and queues repair
                # (counted like the peer-side detection, so EIOs that
                # only ever hit primaries still move the counter)
                self.perf.inc("osd_read_shard_errors")
                if local_shard is not None:
                    repair[local_shard] = "eio"
            if full is not None:
                stored = self.store.getattr(coll, oid, "hinfo_crc")
                ok = True
                if stored is not None and \
                        self.config.osd_ec_verify_reads:
                    [ok] = await self._read_batcher.verify(
                        [full], [int(stored)], planar=lp)
                if ok:
                    if lp:
                        planes = planar_store.blob_to_planes(full)
                        hi = (off + length) // Q \
                            if length is not None else None
                        data = planar_store.planes_to_blob(
                            planes[:, off // Q: hi])
                    else:
                        data = full[off:] if length is None \
                            else full[off: off + length]
                else:
                    self.perf.inc("osd_read_shard_crc_errors")
                    if local_shard is not None:
                        repair[local_shard] = "crc"
            if data is not None and local_shard is not None and \
                    local_shard not in exclude_shards and \
                    local_shard not in repair:
                sa = self.store.getattr(coll, oid, "size")
                got[local_shard] = (
                    data,
                    self.store.get_version(coll, oid),
                    int(sa) if sa else 0,
                    planar_store.LAYOUT_PLANAR if lp else None)
        committed_seq = st.last_complete[1]

        def _committed(v: int) -> bool:
            # at/below the watermark, OR a resolved frontier entry the
            # contiguous-prefix sweep hasn't reached (round 12: fully
            # acked writes stay readable while an earlier open entry —
            # e.g. a crash-restart reconstruction awaiting peering —
            # holds last_complete back; read-your-ack must not regress)
            return v <= committed_seq or st.frontier_acked(v)

        peers = [(shard, osd) for shard, osd in enumerate(st.acting)
                 if osd not in (self.osd_id, CRUSH_ITEM_NONE)
                 and shard not in got and shard not in exclude_shards]
        if peers and len(got) < need_k:
            want = need_k - len(got)
            fast = (fast_k and bool(self.config.osd_ec_hedge_reads)
                    and len(peers) > want)
            if fast:
                # the object's newest logged generation: when the pg
                # log still covers the object, early-resolve ONLY on
                # exactly that generation — k shards of an OLDER
                # committed generation (just-revived members not yet
                # recovered) must never outvote an unseen newer one.
                # Objects past the log window have had no recent
                # writes, so no newer generation can exist to miss
                # (kill victims boot empty and reply ENOENT, they
                # don't serve stale bytes).
                logged_ver = next(
                    (e.version[1] for e in reversed(st.log.entries)
                     if e.oid == oid), None)

                def _viable(acc, _local=dict(got), _c=_committed,
                            _k=need_k, _lv=logged_ver):
                    """k same-generation shards at/below the commit
                    watermark — pinned to the logged generation when
                    the log knows it."""
                    byver: Dict[int, set] = {}
                    for s, (_d, v, _sz, _ly) in _local.items():
                        byver.setdefault(v, set()).add(s)
                    for result, reply in acc:
                        if result == 0 and reply is not None:
                            byver.setdefault(
                                reply.hinfo.get("version", 0),
                                set()).add(reply.shard)
                    if _lv is not None and _c(_lv):
                        ss = byver.get(_lv)
                        return ss is not None and len(ss) >= _k
                    return any(_c(v) and len(ss) >= _k
                               for v, ss in byver.items())

                acc = await self._subread_round(
                    st, oid, peers[:want], off, length,
                    spare=peers[want:], check=_viable)
                if _viable(acc):
                    self.perf.inc("osd_ec_fastk_reads")
                else:
                    # fast path came up short (mixed generations, dead
                    # holders, un-acked head): widen to every shard not
                    # yet heard from — correctness never rests on the
                    # fast path
                    heard = {r.shard for res, r in acc
                             if res == 0 and r is not None}
                    rest = [(s, o) for s, o in peers if s not in heard]
                    if rest:
                        acc = acc + await self._subread_round(
                            st, oid, rest, off, length)
            else:
                acc = await self._subread_round(st, oid, peers, off,
                                                length)
            for result, reply in acc:
                if result == 0 and reply is not None:
                    got[reply.shard] = (
                        reply.data,
                        reply.hinfo.get("version", 0),
                        reply.hinfo.get("size", 0),
                        getattr(reply, "layout", None))
                elif result == -5 and reply is not None and \
                        reply.shard >= 0:
                    # the holder found its shard corrupt (crc) or
                    # unreadable (EIO): absent from the decode, queued
                    # for in-place repair
                    repair.setdefault(reply.shard, "crc")
        try:
            # staleness judged against the START-of-gather watermark
            # snapshot: a write committing mid-gather must not flag
            # members whose replies simply predate their own apply
            # (choose_decode_group stays the layout-blind 3-tuple pure
            # function the corruption-matrix tests drive directly)
            shards, size, version, stale = choose_decode_group(
                {s: (d, v, sz) for s, (d, v, sz, _ly) in got.items()},
                need_k, _committed,
                committed_before=lambda v: v <= committed_seq)
        except IOError as e:
            raise IOError(f"{oid}: {e}") from None
        for s in stale:
            repair.setdefault(s, "stale")
        if repair:
            self._queue_read_repair(pool, st, oid, repair)
        layouts = {s: got[s][3] for s in shards}
        return shards, size, version, layouts

    def _queue_read_repair(self, pool: PGPool, st: PGState, oid: str,
                           bad: Dict[int, str]) -> None:
        """Arm ONE asynchronous in-place repair for shards a gather
        found bad (crc mismatch, media EIO, generation-stale): the
        object is reconstructed from the surviving shards — the bad
        ones excluded as decode sources — and rewritten on the affected
        members, OFF the client's critical path (the read that detected
        the corruption already decoded from survivors and returned).
        The PG rides the inconsistent -> clean health flow: the object
        joins ``st.inconsistent`` (beacon-fed PG_INCONSISTENT /
        OSD_SCRUB_ERRORS warnings) until the repair lands."""
        if not self.config.osd_read_repair or self._stopped or \
                st.primary != self.osd_id:
            return
        key = (st.pgid, oid)
        if key in self._read_repairs_inflight:
            return
        self._read_repairs_inflight.add(key)
        st.inconsistent.add(oid)
        targets = sorted({st.acting[s] for s in bad
                          if s < len(st.acting)
                          and st.acting[s] != CRUSH_ITEM_NONE})
        reasons = dict(bad)

        async def _repair() -> None:
            try:
                # the object write lock excludes concurrent writes to
                # THIS object while the rebuild is being stamped (the
                # scrub path holds st.lock for the same reason); other
                # objects of the PG proceed
                async with self._obj_write_lock(st, oid):
                    ok = await self._recover_ec_object(
                        pool, st, oid, targets=targets,
                        exclude_sources=set(reasons))
                if ok:
                    self.perf.inc("osd_read_repairs")
                    st.inconsistent.discard(oid)
                    self.clog(
                        "WRN",
                        f"pg {st.pgid} read-repair: {oid} shards "
                        f"{reasons} rebuilt on osds {targets}")
                # not ok: the object stays inconsistent — the scheduled
                # scrub (or the next detecting read) retries the repair
            except asyncio.CancelledError:
                raise
            except Exception:
                self.perf.inc("osd_read_repair_errors")
            finally:
                self._read_repairs_inflight.discard(key)

        self._track(asyncio.get_event_loop().create_task(_repair()))

    async def _ec_read_stripes(self, pool: PGPool, st: PGState, oid: str,
                               chunk_off: int, logical_len: int,
                               expected_size: Optional[int] = None) -> bytes:
        """Read a stripe-aligned logical range: gather the touched chunk
        range from >= k shards and decode it as a mini-object.  When the
        caller computed the range from a size it assumed (its local size
        attr), pass ``expected_size``: a disagreeing decode group raises
        ECSizeMismatch BEFORE the under/over-fetch can fail or truncate,
        so the caller re-ranges against the group's size."""
        import numpy as np

        from ceph_tpu.cluster.optracker import mark_current

        codec = self._codec(pool)
        sinfo = self._sinfo(pool, codec)
        k = codec.get_data_chunk_count()
        nstripes = sinfo.object_stripes(logical_len)
        chunk_len = nstripes * sinfo.chunk_size
        # degraded-mode client read: first k clean shards decode, a
        # slow/dead holder is hedged/promoted instead of awaited
        shards, gsize, _, layouts = await self._gather_shards(
            pool, st, oid, k, off=chunk_off, length=chunk_len,
            fast_k=True)
        if expected_size is not None and shards and gsize != expected_size:
            raise ECSizeMismatch(gsize)
        planar = self._planar_mode(codec, sinfo)
        avail = {}
        for s, d in shards.items():
            if len(d) != chunk_len:
                continue
            shard_planar = layouts.get(s) == planar_store.LAYOUT_PLANAR
            if planar:
                # steady state: the holder shipped planes and the
                # decode consumes planes — blob_to_planes is a reshape,
                # not a conversion.  A byte reply (mixed-generation
                # member still byte-at-rest) takes the one legal
                # relayout hop on the gather edge.
                avail[s] = planar_store.blob_to_planes(d) \
                    if shard_planar \
                    else planar_store.shard_to_planes(d, seam="relayout")
            else:
                if shard_planar:
                    # byte-mode decode of a still-planar holder's reply
                    # (gate just flipped off): normalize — legal, never
                    # on the pinned steady-state path
                    d = planar_store.planes_to_shard(
                        planar_store.blob_to_planes(d), seam="relayout")
                avail[s] = np.frombuffer(d, dtype=np.uint8)
        if len(avail) < k:
            raise IOError(
                f"only {len(avail)} of {k} shard ranges for {oid}")
        # round 16: the decode rides the read coalescer — a tick's read
        # gathers share one layout conversion + one fused decode batch
        # (round 19 planar: NO layout conversion — the fused kernel
        # consumes the at-rest planes as-shipped)
        out = await self._read_batcher.decode(
            codec, sinfo, avail, logical_len, planar=planar)
        if planar:
            # the assemble's planes -> logical-bytes hop was this op's
            # one sanctioned egress conversion — stamp it so
            # `bench.py --attribute` books it as planar_convert
            mark_current("planar_egress")
        return out

    async def _ec_read(self, pool: PGPool, st: PGState, oid: str,
                       offset: int = 0, length: Optional[int] = None) -> bytes:
        """objects_read_async analog: min shards + batched TPU decode
        (ECBackend.cc:2111,1588,2262)."""
        coll = _coll(st.pgid)
        sa = self.store.getattr(coll, oid, "size")
        if sa is None:
            # primary lost its shard (or never had one): probe peers
            codec = self._codec(pool)
            shards, size, _, _ = await self._gather_shards(
                pool, st, oid, codec.get_data_chunk_count(), 0, 0)
            if not shards and size == 0:
                raise FileNotFoundError(oid)
        else:
            size = int(sa)
        codec = self._codec(pool)
        sinfo = self._sinfo(pool, codec)
        # the object length is a property of the GENERATION being read:
        # when the decode group disagrees with our local size attr (our
        # own shard is stale), re-range against the group's size instead
        # of truncating/overstretching its bytes to the local length
        for attempt in range(2):
            want = max(0, size - offset) if length is None else length
            if want == 0 or offset >= size:
                return b""
            want = min(want, size - offset)
            off0, len0 = sinfo.offset_len_to_stripe_bounds(offset, want)
            len0 = min(len0, max(0, size - off0))
            chunk_off = sinfo.aligned_logical_offset_to_chunk_offset(off0)
            try:
                out = await self._ec_read_stripes(
                    pool, st, oid, chunk_off, len0, expected_size=size)
            except ECSizeMismatch as e:
                if attempt:
                    raise IOError(f"{oid}: object size unstable "
                                  "(write or recovery in flight)")
                size = e.size
                continue
            return out[offset - off0: offset - off0 + want]
        raise IOError(f"{oid}: unreadable")  # unreachable

    async def _recover_ec_object(self, pool: PGPool, st: PGState, oid: str,
                                 targets: Optional[List[int]] = None,
                                 entry: Optional[LogEntry] = None,
                                 exclude_sources: Optional[Set[int]] = None,
                                 ) -> bool:
        """Reconstruct shards for the target members (batched TPU decode +
        encode, ECBackend::run_recovery_op analog).  targets=None rebuilds
        every acting member's shard; exclude_sources keeps known-corrupt
        shard ids out of the decode.  Returns False when the object is
        currently unrecoverable (fewer than k shard sources)."""
        import numpy as np

        codec = self._codec(pool)
        sinfo = self._sinfo(pool, codec)
        k = codec.get_data_chunk_count()
        shards, size, group_version, layouts = await self._gather_shards(
            pool, st, oid, k, exclude_shards=exclude_sources)
        shard_len = sinfo.shard_size(size)
        planar = self._planar_mode(codec, sinfo)
        avail = {}
        for s, d in shards.items():
            if len(d) != shard_len:
                continue
            shard_planar = layouts.get(s) == planar_store.LAYOUT_PLANAR
            if planar:
                # steady state: sources shipped planes, the rebuild
                # decodes AND re-encodes in the plane domain, and the
                # pushed shards land as planes — conversion-free end to
                # end; byte replies (mixed members) relayout once here
                avail[s] = planar_store.blob_to_planes(d) \
                    if shard_planar \
                    else planar_store.shard_to_planes(d, seam="relayout")
            else:
                if shard_planar:
                    d = planar_store.planes_to_shard(
                        planar_store.blob_to_planes(d), seam="relayout")
                avail[s] = np.frombuffer(d, dtype=np.uint8)
        if len(avail) < k:
            self.perf.inc("osd_unrecoverable")
            return False
        # decode + re-encode in ONE round trip through the read
        # coalescer (round 16): concurrent recovery rebuilds of a tick
        # share a layout conversion + fused decode/encode batch; on CPU
        # jax backends the rebuild runs the table-driven host GF engine
        # like the coalesced write path (engine-per-backend)
        chunks = await self._read_batcher.reencode(
            codec, sinfo, avail, size, planar=planar)
        out_layout = planar_store.LAYOUT_PLANAR if planar else None
        # stamp the rebuilt shards with the DECODE GROUP's version, not
        # our local one: a primary whose own shard is newer (or staler)
        # than the group it decoded from would otherwise relabel old
        # bytes as new, and a later read could mix generations that
        # claim the same version (surfaced by graft-chaos as torn reads)
        version = max(group_version, 1)
        hinfo = {"size": size, "version": version}
        ok = True
        for shard, osd in enumerate(st.acting):
            if osd == CRUSH_ITEM_NONE:
                continue
            if targets is not None and osd not in targets:
                continue
            blob = chunks[shard].tobytes()
            if osd == self.osd_id:
                self._apply_shard(st.pgid, oid, shard, blob, 0,
                                  shard_len, hinfo, layout=out_layout)
            else:
                try:
                    await self._send_osd(osd, M.MOSDECSubOpWrite(
                        reqid=self._next_reqid(), pgid=st.pgid, oid=oid,
                        shard=shard, data=blob, chunk_off=0,
                        shard_size=shard_len, hinfo=hinfo, entry=entry,
                        epoch=self.osdmap.epoch, layout=out_layout))
                    self.perf.inc("osd_pushes_sent")
                except ConnectionError:
                    # target unreachable: the rebuild did NOT land there —
                    # report incompleteness so the recovery round retries
                    ok = False
        return ok
