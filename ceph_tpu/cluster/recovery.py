"""Peering-driven recovery + backfill (reference PG::start_peering_
interval -> PrimaryLogPG::start_recovery_ops seam): authoritative-log
selection, delta recovery, whole-PG backfill."""

from __future__ import annotations

import asyncio
import pickle
from typing import Dict

from ceph_tpu.analysis import racecheck
from ceph_tpu.cluster import messages as M
from ceph_tpu.cluster import pglog
from ceph_tpu.cluster.pglog import PGInfo, PGLog
from ceph_tpu.crush.types import CRUSH_ITEM_NONE
from ceph_tpu.cluster.pg import MOSDPGQuery, MOSDPGQueryReply, PGState, _coll
from ceph_tpu.cluster.store import Transaction
from ceph_tpu.osdmap.osdmap import PGid, PGPool


class RecoveryMixin:

    # ------------------------------------------------------------- recovery

    def _kick_peering(self) -> None:
        """Start (or let run) the single peering drain task: concurrent
        map changes collapse into the live pass instead of stacking one
        _recover_all per epoch — under a churn burst the pending set
        absorbs every epoch's re-peer fan-out (round 14 storm control)."""
        t = self._peering_task
        if t is not None and not t.done():
            return  # the running pass re-checks the pending set
        self._peering_task = self._track(
            asyncio.get_event_loop().create_task(self._recover_all()))

    async def _recover_all(self) -> None:
        """Drain the pending-peering queue in bounded waves: each PG's
        round runs as its own task behind the per-OSD concurrency
        throttle (_recover_pg's semaphore); waves larger than
        osd_peering_stagger_after desynchronize their starts with
        capped seeded jitter so hundreds of simultaneously-bouncing
        OSDs do not stampede each other with peer queries."""
        await asyncio.sleep(self.config.osd_recovery_delay_start)
        while not self._stopped:
            # snapshot-and-clear is atomic (no await between): a map
            # change landing mid-wave re-adds to the live set and the
            # next while pass picks it up
            pending = sorted(self._peering_pending)
            self._peering_pending.difference_update(pending)
            if not pending:
                return
            stagger_after = self.config.osd_peering_stagger_after
            stagger = bool(stagger_after) and len(pending) > stagger_after
            from ceph_tpu.utils.tasks import track_task

            waves: set = set()
            for pgid in pending:
                st = self.pgs.get(pgid)
                if st is None or st.primary != self.osd_id:
                    # no longer ours to recover: the new primary's
                    # beacon carries the unclean claim now
                    self._unclean_pgs.discard(pgid)
                    continue
                track_task(waves, asyncio.get_event_loop().create_task(
                    self._peer_one(st, stagger)))
            if waves:
                # _peer_one contains its own error accounting; the
                # gather only orders the wave against the next pass
                await asyncio.gather(*list(waves))

    async def _peer_one(self, st: PGState, stagger: bool) -> None:
        try:
            if stagger:
                cap = self.config.osd_peering_stagger_max
                if cap > 0:
                    import random as _random

                    r = self._peering_rng.random() \
                        if self._peering_rng is not None \
                        else _random.random()
                    await asyncio.sleep(r * cap)
            # background class yields to client admission pressure
            # (mclock demotion analog): recovery pulls wait for the op
            # budget to drain below 3/4
            await self._yield_under_pressure()
            await self._recover_pg(st)
        except asyncio.CancelledError:
            raise
        except Exception:
            # count AND surface: a silently-failing recovery loop
            # means a pool that never re-protects itself
            self.perf.inc("osd_recovery_errors")
            import logging
            logging.getLogger("ceph_tpu.osd").exception(
                "osd.%d: recovery of pg %s failed", self.osd_id, st.pgid)

    async def _query_pg(self, osd: int, pgid: PGid):
        """GetInfo/GetLog exchange with one member (reference peering
        Query/Notify, PG.h RecoveryMachine GetInfo)."""
        key = ("pgq", str(pgid), osd)
        fut = self._make_waiter(key, 1)
        try:
            await self._send_osd(osd, MOSDPGQuery(pgid=pgid))
            acc = await asyncio.wait_for(fut, timeout=2.0)
            return acc[0][1]
        except (asyncio.TimeoutError, ConnectionError):
            return None
        finally:
            self._pending.pop(key, None)

    async def _recover_pg(self, st: PGState) -> None:
        """Primary-driven peering + recovery (flattened RecoveryMachine,
        reference src/osd/PG.h:1994-2498):

        1. GetInfo: collect (last_update, log) from every acting member.
        2. GetLog: the max last_update owns the authoritative log; if that
           is not us, bring ourselves up first (delta when our
           last_update is inside the auth log window, backfill otherwise).
        3. Active/Recovering: push ONLY the log delta to each stale
           member; full-inventory backfill when a member is behind the
           log tail.

        Runs under the PG lock: peering mutates st.log/st.last_update, and
        a client write interleaving with log adoption could regress
        last_update and reuse an eversion (the reference blocks ops during
        peering for the same reason).

        An INCOMPLETE round (unreachable member, failed pull/push) arms a
        capped-backoff retry (_queue_recovery_retry): peering re-runs on
        map changes, but a pull that fails AFTER the last map change of an
        outage would otherwise never retry — the primary stays stale
        forever, serving old-generation state (surfaced by graft-chaos as
        persistent torn EC reads).

        Rounds run behind the per-OSD concurrency throttle
        (osd_peering_max_concurrent, round 14): a mass bounce produces a
        bounded wave of simultaneous rounds, and every entry path — map
        advance, incomplete-round retry, frontier reconstruction —
        shares the one gate.  Round duration rides the
        osd_peering_lat_hist histogram on the perf/Prometheus path."""
        sem = self._peering_sem
        if sem.locked():
            self.perf.inc("osd_peering_throttled")
        async with sem:
            self.perf.inc("osd_peering_rounds")
            t0 = self.clock.monotonic()
            try:
                async with st.lock:
                    complete = await self._recover_pg_locked(st)
            except asyncio.CancelledError:
                raise
            except Exception:
                # a round that RAISES must still re-arm (round 12): infos
                # racing in-flight commits can be transiently inconsistent,
                # and a wedged retry chain leaves reconstructed frontier
                # entries unresolved forever
                self.perf.inc("osd_recovery_errors")
                import logging

                logging.getLogger("ceph_tpu.osd").exception(
                    "osd.%d: peering round for pg %s errored",
                    self.osd_id, st.pgid)
                complete = False
            finally:
                self.perf.hinc("osd_peering_lat_hist",
                               self.clock.monotonic() - t0)
        if complete:
            self._recovery_backoffs.pop(st.pgid, None)
            self._unclean_pgs.discard(st.pgid)
        else:
            self._queue_recovery_retry(st)
            self._unclean_pgs.add(st.pgid)

    async def _recover_pg_locked(self, st: PGState) -> bool:
        m = self.osdmap
        pool = m.pools[st.pgid.pool]
        members = [o for o in st.acting
                   if o not in (self.osd_id, CRUSH_ITEM_NONE)]
        infos: Dict[int, PGInfo] = {self.osd_id: st.info()}
        if racecheck.TRACKER:  # graft-race: round-start self-info
            # snapshot — the roll-forward floor must NOT rest on it
            # after the member awaits below (the PR-11 bug class)
            racecheck.TRACKER.note_read(
                ("pg", self.osd_id, str(st.pgid)), "self_info")
        logs: Dict[int, PGLog] = {self.osd_id: st.log}
        inventories: Dict[int, Dict[str, int]] = {}
        complete = True
        for osd in members:
            reply = await self._query_pg(osd, st.pgid)
            if reply is None:
                complete = False  # unreachable member: retry later
                continue
            infos[osd] = reply.info or PGInfo()
            logs[osd] = reply.log or PGLog()
            inventories[osd] = reply.objects or {}

        auth = pglog.choose_authoritative(
            infos, require_rollback=pool.is_erasure())
        auth_head = infos[auth].last_update
        if auth_head < st.last_complete:
            # STALE ROUND (round 12): in-flight ack waits advanced our
            # watermark while we were collecting infos — rewinding (or
            # syncing) toward a head below it would roll back ACKED
            # writes.  Drop this round; the retry collects fresh infos.
            return False
        if pool.is_erasure() and st.last_update > auth_head:
            # we hold entries the authoritative log rolls back: an
            # un-acked partial-stripe write that not every shard applied
            # (reference PGLog::rewind_divergent_log, PGLog.cc:287 +
            # ecbackend.rst rollback).  Undo from our rollback journal.
            need = self.rewind_divergent_log(st, auth_head)
            for oid in need:  # record lost: re-pull the auth copy
                complete &= await self._recover_ec_object(
                    pool, st, oid, targets=[self.osd_id])
        if auth != self.osd_id and \
                infos[auth].last_update > st.last_update:
            complete &= await self._sync_self_from(
                pool, st, auth, logs[auth], inventories.get(auth, {}))

        # backfillfull gate (round 16): with the map flag set, FULL-
        # INVENTORY backfill is deferred — bulk-copying a whole PG into
        # stores past the backfillfull ratio would drive them straight
        # to FULL.  The round stays incomplete, so the capped-backoff
        # retry re-runs it after the flag clears.  Log-DELTA recovery
        # still proceeds (reference semantics: backfillfull gates
        # backfill, not recovery — the delta pushes mostly overwrite
        # existing shards, and blocking them would pin reduced
        # redundancy on every bounce while merely nearfull-ish).
        backfill_gated = "backfillfull" in getattr(m, "flags", set())
        for osd in members:
            if osd not in infos:
                continue
            peer_lu = infos[osd].last_update
            if pool.is_erasure() and peer_lu > st.last_update and \
                    st.last_update >= auth_head:
                # divergent member: instruct it to rewind to our head
                # (it holds a superset of our log, so after the rewind
                # it is exactly current — nothing to push).  Guarded on
                # US holding the authoritative head: a stale primary
                # that failed to self-sync must never roll healthy
                # replicas back to its own stale state
                try:
                    await self._send_osd(osd, M.MOSDPGPush(
                        pgid=st.pgid, op="rewind",
                        data=pickle.dumps(st.last_update)))
                except ConnectionError:
                    complete = False
                continue
            if peer_lu >= st.last_update:
                continue
            to_sync = st.log.objects_to_sync(peer_lu)
            if to_sync is None:
                if backfill_gated:
                    self.perf.inc("osd_backfill_blocked_full")
                    complete = False
                    continue
                complete &= await self._backfill_member(
                    pool, st, osd, inventories.get(osd, {}))
            else:
                # replay in VERSION order so the member's log advances
                # monotonically (out-of-order pushes would hit the
                # duplicate guard and leave silent log holes)
                for oid, entry in sorted(to_sync.items(),
                                         key=lambda kv: kv[1].version):
                    complete &= await self._push_object(
                        pool, st, osd, oid, entry)

        # roll-forward (reference PG::activate: last_complete =
        # last_update once missing is empty): every acting member
        # REPORTED last_update >= V, so every entry up to V exists on
        # every shard and can never rewind — advance the watermark.
        # Without this, a write whose sub-writes all landed but whose
        # ack was lost (bounce mid-commit) leaves last_complete behind
        # forever: no rewind fires (nothing is divergent) and no later
        # ack arrives (surfaced by graft-chaos as a stuck-incomplete PG)
        # the sync/push phase above may have advanced OUR OWN log past
        # the info snapshotted at round start (_sync_self_from pulls,
        # racing pipelined commits): the floor must rest on the CURRENT
        # self state, or a stale self-info pins the watermark below
        # entries every member verifiably holds — the round then ends
        # complete=True with last_complete wedged behind last_update
        # and nothing ever re-arms it (round 14: the re-peer-all
        # stampede that used to paper over this is gone by design)
        infos[self.osd_id] = st.info()
        if racecheck.TRACKER:  # graft-race: the PR-11 fix — the
            # re-read revalidates the round-start snapshot; reverting
            # it re-convicts under the race smoke
            racecheck.TRACKER.note_read(
                ("pg", self.osd_id, str(st.pgid)), "self_info")
        live = [o for o in st.acting if o != CRUSH_ITEM_NONE]
        # EC undersized guard (round 12): with fewer than min_size live
        # members, "every member holds it" is vacuous — rolling the
        # watermark forward over entries only a sub-k shard subset
        # holds commits a generation nothing can ever decode (the same
        # bug class _ec_acting_writeable blocks at admission)
        undersized = pool.is_erasure() and not self._ec_acting_writeable(
            pool, self._codec(pool), st)
        if all(o in infos for o in live) and not undersized:
            floor = min(i.last_update for i in infos.values())
            if complete and floor < st.last_update and members:
                # this round PUSHED the delta above the floor: re-query
                # the members' heads before rolling the watermark over
                # the pushed entries — roll-forward must rest on a
                # REPORT that every member holds them, never on a send
                # having been queued (round 12: reconstructed frontier
                # entries resolve only by verified presence)
                for osd in members:
                    reply = await self._query_pg(osd, st.pgid)
                    if reply is None:
                        complete = False
                        infos.pop(osd, None)
                        continue
                    infos[osd] = reply.info or PGInfo()
                # the re-query AWAITED: acting can have changed while
                # the replies trickled in, and a member that joined
                # mid-round has no info row — re-read it so the
                # every-live-member-reported gate judges the membership
                # the roll-forward will actually cover (graft-race:
                # stale-snapshot-across-await on the round-start `live`)
                live = [o for o in st.acting if o != CRUSH_ITEM_NONE]
                if all(o in infos for o in live):
                    floor = min(i.last_update for i in infos.values())
            floor = min(floor, st.last_update)
            # routed through the frontier (round 12): entries at/below
            # the verified floor resolve — including crash-restart
            # reconstructions (_frontier_rebuild) whose acks died with
            # the previous process life
            if floor > st.last_complete or st.pipeline_pending:
                self._frontier_learn(st, floor)
        if st.frontier_recovering:
            # open boot entries above what this round could verify:
            # the PG is not crash-consistent yet — retry (the members
            # behind them are still syncing, or unreachable)
            complete = False
        # pg_temp handoff (round 21): this PG runs on a mon-minted temp
        # acting set (the pre-reshape donors) while its REAL owners are
        # the up-members outside acting.  Backfill them current, then
        # ask the mon to clear the temp entry — the clear commits a new
        # epoch that re-peers the PG onto its up set.  Returning
        # incomplete keeps the capped-backoff retry armed until that
        # map lands (a lost clear message just re-sends; the backfill
        # pushes are idempotent via version guards).
        if complete and st.pgid in m.pg_temp:
            handoff = [o for o in st.up
                       if o != CRUSH_ITEM_NONE and o not in st.acting]
            for osd in handoff:
                if backfill_gated:
                    self.perf.inc("osd_backfill_blocked_full")
                    complete = False
                    break
                reply = await self._query_pg(osd, st.pgid)
                if reply is None:
                    complete = False
                    continue
                complete &= await self._backfill_member(
                    pool, st, osd, reply.objects or {})
            if complete:
                await self._mon_send(M.MOSDPGTemp(
                    pgid=st.pgid, osds=(), epoch=m.epoch,
                    osd_id=self.osd_id))
                self.perf.inc("osd_pg_temp_clear_requested")
                complete = False
        self.perf.inc("osd_pg_recoveries")
        return complete

    def _queue_recovery_retry(self, st: PGState) -> None:
        """Arm ONE delayed re-peering attempt for this PG (capped
        exponential backoff, seeded jitter when the chaos seed is set, so
        scenario retry timing replays).  Collapses with in-flight
        retries; the backoff resets when a round completes."""
        if self._stopped or st.primary != self.osd_id:
            return
        if st.pgid in self._recovery_retry_tasks:
            return
        bo = self._recovery_backoffs.get(st.pgid)
        if bo is None:
            from ceph_tpu.chaos.rng import stream
            from ceph_tpu.utils.backoff import ExpBackoff

            rng = stream(self.config.chaos_seed,
                         f"recovery:osd.{self.osd_id}:{st.pgid}") \
                if self.config.chaos_seed else None
            bo = ExpBackoff(base=0.25, cap=3.0, rng=rng)
            self._recovery_backoffs[st.pgid] = bo
        delay = bo.next()
        self.perf.inc("osd_recovery_retries")

        async def _retry() -> None:
            try:
                await asyncio.sleep(delay)
                self._recovery_retry_tasks.pop(st.pgid, None)
                if not self._stopped and st.primary == self.osd_id and \
                        self.pgs.get(st.pgid) is st:
                    await self._recover_pg(st)
            except asyncio.CancelledError:
                raise
            except Exception:
                self.perf.inc("osd_recovery_errors")

        task = asyncio.get_event_loop().create_task(_retry())
        self._recovery_retry_tasks[st.pgid] = task
        # track in the self-discarding set (not _tasks: a long-lived OSD
        # would keep one dead Task per retry for its lifetime)
        self._opq_running.add(task)
        task.add_done_callback(self._opq_running.discard)

    async def _sync_self_from(self, pool: PGPool, st: PGState, auth: int,
                              auth_log: PGLog,
                              auth_inventory: Dict[str, int]) -> bool:
        """Bring the primary up to the authoritative member's state.
        Returns False when a pull failed (the auth log was NOT adopted
        and the caller must retry)."""
        coll = _coll(st.pgid)
        to_sync = auth_log.objects_to_sync(st.last_update)
        if to_sync is None:
            # behind the log window: full backfill from auth's inventory
            mine = {oid: self.store.get_version(coll, oid)
                    for oid in self._list_pg_objects(st.pgid)}
            to_pull = [oid for oid, ver in auth_inventory.items()
                       if mine.get(oid, -1) < ver]
            # objects we hold that the authoritative member does not =
            # deletes we missed (possibly trimmed past the log tail);
            # without this, a rejoining primary resurrects deleted objects
            for oid in mine:
                if oid not in auth_inventory:
                    self.store.queue_transaction(
                        Transaction().remove(coll, oid))
        else:
            to_pull = []
            for oid, entry in to_sync.items():
                if entry.op == "delete":
                    self.store.queue_transaction(
                        Transaction().remove(coll, oid))
                else:
                    to_pull.append(oid)
        from ceph_tpu.cluster import snaps as snapmod

        ok = True
        for oid in to_pull:
            if pool.is_erasure() and not oid.endswith(snapmod._SNAPDIR):
                ok &= await self._recover_ec_object(
                    pool, st, oid, targets=[self.osd_id])
            else:
                # snapdir metadata objects pull as plain copies even on
                # EC pools (identical on every member)
                ok &= await self._pull_rep_object(st, auth, oid)
            if not snapmod.is_snap_key(oid):
                # a delta-synced head may imply clone/snapset changes that
                # have no log entries of their own (COW writes, trims);
                # a FAILED snap pull must block adoption of the
                # authoritative log exactly like a failed head pull
                ok &= await self._pull_snap_state(pool, st, auth, oid)
        if not ok:
            # a pull failed (auth unreachable mid-recovery): do NOT claim
            # the authoritative version — stay stale so the retry/next
            # peering round re-pulls instead of serving stale bytes as new
            self.perf.inc("osd_recovery_incomplete")
            return False
        # adopt the authoritative log
        st.log = PGLog(tail=auth_log.tail,
                       entries=list(auth_log.entries),
                       max_entries=auth_log.max_entries)
        st.last_update = auth_log.head if auth_log.entries else \
            max(st.last_update, auth_log.tail)
        self._save_pg_meta(st)
        return True

    async def _pull_snap_state(self, pool: PGPool, st: PGState, auth: int,
                               head: str) -> bool:
        """Pull one head's snapshot state from the authoritative member:
        its snapdir SnapSet, any clone objects we lack, and prune clones
        the set no longer lists (missed trims).  Returns False on a pull
        FAILURE (auth unreachable) — the caller must then refuse to adopt
        the authoritative log; "auth has no snap state" is success."""
        from ceph_tpu.cluster import snaps as snapmod

        coll = _coll(st.pgid)
        sd = snapmod.snapdir_oid(head)
        status = await self._pull_rep_object_st(st, auth, sd)
        if status == "enoent":
            return True  # no snap state upstream (the common case)
        if status != "ok":
            return False
        blob = self.store.getattr(coll, sd, "ss")
        if blob is None:
            return True
        ss = snapmod.SnapSet.decode(blob)
        ok = True
        for c in ss.clones:
            cname = snapmod.clone_oid(head, c)
            if self.store.stat(coll, cname) is not None:
                continue
            if pool.is_erasure():
                ok &= await self._recover_ec_object(pool, st, cname,
                                                    targets=[self.osd_id])
            else:
                ok &= await self._pull_rep_object(st, auth, cname)
        txn = Transaction()
        txn.ops.extend(snapmod.prune_clone_ops(self.store, coll, head, ss))
        if txn.ops:
            self.store.queue_transaction(txn)
        return ok

    async def _backfill_member(self, pool: PGPool, st: PGState, osd: int,
                               inventory: Dict[str, int]) -> bool:
        """Full-inventory resync for a member behind the log tail
        (reference Backfilling state).  Returns False when any push
        failed (the member is still stale; the caller must retry)."""
        from ceph_tpu.cluster import snaps as snapmod

        ok = True
        for oid in self._list_pg_objects(st.pgid):
            ver = self.store.get_version(_coll(st.pgid), oid)
            if inventory.get(oid, -1) >= ver:
                continue
            # snapdir objects are pure metadata (identical on every
            # member, EC pools included): push data+xattrs directly;
            # everything else on an EC pool (clones included) is a real
            # EC object whose member shard gets reconstructed
            if pool.is_erasure() and not oid.endswith(snapmod._SNAPDIR):
                ok &= await self._recover_ec_object(pool, st, oid,
                                                    targets=[osd])
            else:
                data = self.store.read(_coll(st.pgid), oid)
                try:
                    await self._send_osd(osd, M.MOSDPGPush(
                        pgid=st.pgid, oid=oid, data=data,
                        xattrs=self.store.get_xattrs(_coll(st.pgid), oid),
                        version=ver))
                    self.perf.inc("osd_pushes_sent")
                except ConnectionError:
                    ok = False
        # stale objects the member has but we (authoritative) don't
        mine = set(self._list_pg_objects(st.pgid))
        for oid in inventory:
            if oid not in mine:
                try:
                    await self._send_osd(osd, M.MOSDPGPush(
                        pgid=st.pgid, oid=oid, op="delete",
                        version=st.last_update[1]))
                    self.perf.inc("osd_pushes_sent")
                except ConnectionError:
                    ok = False
        # hand the member our log state so the next peering round sees it
        # as current instead of re-backfilling — only when every push
        # landed: a log_sync over missed pushes would mark a still-stale
        # member current and silently skip the missing objects
        if ok:
            blob = pickle.dumps((st.last_update, st.log))
            try:
                await self._send_osd(osd, M.MOSDPGPush(
                    pgid=st.pgid, op="log_sync", data=blob))
            except ConnectionError:
                ok = False
        return ok
