"""Striper: file/image byte ranges -> object extents.

Behavioral mirror of reference Striper::file_to_extents
(src/osdc/Striper.h:31-54, Striper.cc) over file_layout_t
(src/include/fs_types.h:84): a file is cut into PERIODS of
stripe_count * object_size bytes; within a period, stripe_unit blocks
round-robin across the period's stripe_count objects.  This is the
layout premise RBD images and CephFS files share.

TPU-angle: the extent math is pure host arithmetic; the payload I/O it
drives lands on the OSD batched encode/decode paths.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Tuple


@dataclass(frozen=True)
class FileLayout:
    """file_layout_t analog."""

    stripe_unit: int = 1 << 22
    stripe_count: int = 1
    object_size: int = 1 << 22

    def validate(self) -> None:
        if self.stripe_unit <= 0 or self.stripe_count <= 0 \
                or self.object_size <= 0:
            raise ValueError("layout fields must be positive")
        if self.object_size % self.stripe_unit:
            raise ValueError("object_size must be a multiple of stripe_unit")


@dataclass
class ObjectExtent:
    """One contiguous byte range inside one object (reference
    ObjectExtent): buffer_extents maps it back into the logical buffer."""

    oid: str
    objectno: int
    offset: int
    length: int
    buffer_extents: List[Tuple[int, int]] = field(default_factory=list)


def file_to_extents(object_format: str, layout: FileLayout,
                    offset: int, length: int) -> List[ObjectExtent]:
    """Map a logical (offset, length) range to object extents
    (reference Striper::file_to_extents).  ``object_format`` is the
    object-name pattern taking the object number (e.g.
    "rbd_data.{image}.%016x")."""
    layout.validate()
    su = layout.stripe_unit
    sc = layout.stripe_count
    os_ = layout.object_size
    su_per_object = os_ // su

    lookup: Dict[int, ObjectExtent] = {}
    order: List[int] = []
    pos = offset
    left = length
    while left > 0:
        blockno = pos // su
        stripeno = blockno // sc
        stripepos = blockno % sc
        objectsetno = stripeno // su_per_object
        objectno = objectsetno * sc + stripepos
        block_start = (stripeno % su_per_object) * su
        block_off = pos % su
        x_offset = block_start + block_off
        x_len = min(left, su - block_off)

        ex = lookup.get(objectno)
        if ex is None:
            ex = ObjectExtent(oid=object_format % objectno,
                              objectno=objectno,
                              offset=x_offset, length=x_len)
            lookup[objectno] = ex
            order.append(objectno)
        else:
            # a linear logical range touches each object in increasing,
            # adjacent in-object offsets, so fragments always coalesce
            assert ex.offset + ex.length == x_offset, (ex, x_offset)
            ex.length += x_len
        ex.buffer_extents.append((pos - offset, x_len))
        pos += x_len
        left -= x_len
    return [lookup[k] for k in order]


class StripedReader:
    """Assemble a logical buffer from per-object reads."""

    @staticmethod
    def assemble(extents: List[ObjectExtent],
                 object_data: Dict[str, bytes], length: int,
                 relative: bool = False) -> bytes:
        """``relative=True``: blobs are already extent-relative (start at
        ex.offset), avoiding object-sized zero padding on the hot path."""
        out = bytearray(length)
        for ex in extents:
            blob = object_data.get(ex.oid, b"")
            # the object may be short/absent (sparse): zero-fill
            src = blob[: ex.length] if relative else \
                blob[ex.offset: ex.offset + ex.length]
            src = src + b"\0" * (ex.length - len(src)) \
                if len(src) < ex.length else src
            off_in_ex = 0
            for buf_off, ln in ex.buffer_extents:
                out[buf_off: buf_off + ln] = src[off_in_ex: off_in_ex + ln]
                off_in_ex += ln
        return bytes(out)

    @staticmethod
    def scatter(extents: List[ObjectExtent],
                data: bytes) -> Dict[str, List[Tuple[int, bytes]]]:
        """Split a logical write buffer into per-object (offset, bytes)."""
        out: Dict[str, List[Tuple[int, bytes]]] = {}
        for ex in extents:
            off_in_ex = 0
            chunks = []
            for buf_off, ln in ex.buffer_extents:
                chunks.append(data[buf_off: buf_off + ln])
                off_in_ex += ln
            out.setdefault(ex.oid, []).append(
                (ex.offset, b"".join(chunks)))
        return out
