"""PG log: the per-PG ordered mutation record enabling delta resync.

Behavioral mirror of the reference's pg_log_t / PGLog machinery
(src/osd/osd_types.h pg_log_entry_t; src/osd/PG.h:1994-2498 peering
statechart GetInfo/GetLog/GetMissing; doc/dev/osd_internals/pg.rst): every
mutation appends an (eversion, op, oid) entry to a bounded log; on map
change the primary elects the authoritative log (max last_update across
the acting set), and stale members resynchronize by LOG DELTA when their
last_update lies inside the auth log window — pushing only the objects
named by the missing entries — falling back to full-inventory BACKFILL
when they have fallen behind the log tail.

eversion = (epoch, seq): the map epoch when the op was performed plus a
per-PG monotonically increasing sequence (reference eversion_t).  seq
never resets, so versions totally order all mutations of a PG.

TPU-angle: none — this is pure control-plane state; the data it moves is
reconstructed by the batched device decode/encode paths in the OSD.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

Eversion = Tuple[int, int]
ZERO: Eversion = (0, 0)


@dataclass
class LogEntry:
    """pg_log_entry_t analog."""

    op: str                       # "modify" | "delete"
    oid: str
    version: Eversion
    prior_version: Eversion = ZERO
    # primary's last_complete at append time: replicas learn the commit
    # watermark from the entry stream and prune their rollback journal
    # up to it (reference min_last_complete_ondisk piggybacking)
    committed: Eversion = ZERO
    # originating client reqid (reference pg_log_entry_t::reqid): entries
    # replicate to peers, so a NEW primary can refuse to re-execute a
    # resent non-idempotent op whose effect its log already records —
    # the in-memory reqid_replies cache is primary-local and dies with it
    client_reqid: Optional[Tuple] = None


@dataclass
class PGLog:
    """Bounded ordered entry list covering versions (tail, head]."""

    tail: Eversion = ZERO
    entries: List[LogEntry] = field(default_factory=list)
    max_entries: int = 500

    @property
    def head(self) -> Eversion:
        return self.entries[-1].version if self.entries else self.tail

    def append(self, entry: LogEntry) -> None:
        assert entry.version > self.head, (entry.version, self.head)
        self.entries.append(entry)
        rq = getattr(entry, "client_reqid", None)
        if rq is not None and getattr(self, "_reqids", None) is not None:
            ent = self._reqids.get(rq)
            if ent is None:
                self._reqids[rq] = [1, entry.version]
            else:
                ent[0] += 1
                ent[1] = entry.version  # append is monotonic: newest

    def trim(self) -> List[LogEntry]:
        """Drop oldest entries beyond max_entries, advancing the tail;
        returns the dropped entries (reference PGLog::trim to
        osd_min/max_pg_log_entries)."""
        excess = len(self.entries) - self.max_entries
        if excess <= 0:
            return []
        dropped = self.entries[:excess]
        self.tail = self.entries[excess - 1].version
        del self.entries[:excess]
        idx = getattr(self, "_reqids", None)
        if idx is not None:
            # trim drops the OLDEST entries, so a reqid's newest logged
            # version survives in the index until its count hits zero
            for e in dropped:
                rq = getattr(e, "client_reqid", None)
                if rq is not None and rq in idx:
                    idx[rq][0] -= 1
                    if idx[rq][0] <= 0:
                        del idx[rq]
        return dropped

    def has_reqid(self, reqid) -> bool:
        """O(1) dup lookup over the entries' client reqids (reference
        pg_log dup index).  The index builds lazily so wholesale log
        replacements (peering adoption, store load, log push — all of
        which construct a NEW PGLog) can never serve a stale view."""
        idx = getattr(self, "_reqids", None)
        if idx is None:
            idx = self._reqids = {}
            for e in self.entries:
                rq = getattr(e, "client_reqid", None)
                if rq is not None:
                    ent = idx.get(rq)
                    if ent is None:
                        idx[rq] = [1, e.version]
                    else:
                        ent[0] += 1
                        ent[1] = e.version
        ent = idx.get(reqid)
        return ent is not None and ent[0] > 0

    def reqid_version(self, reqid) -> Optional[Eversion]:
        """Newest logged version carrying this client reqid, or None —
        O(1) off the reqid index (dup-resolution polls this in a loop).
        Callers gate dup-acks on it: an entry ABOVE the commit watermark
        may still rewind during peering, so replying success from it
        would ack a write that can subsequently vanish."""
        if not self.has_reqid(reqid):
            return None
        return self._reqids[reqid][1]

    def since(self, v: Eversion) -> Optional[List[LogEntry]]:
        """Entries strictly newer than v, or None when v is before the
        tail (out of the log window -> caller must backfill)."""
        if v < self.tail:
            return None
        return [e for e in self.entries if e.version > v]

    def objects_to_sync(self, v: Eversion) -> Optional[Dict[str, LogEntry]]:
        """Collapse the delta since v to one final LogEntry per object
        (the last write wins; a trailing delete means remove)."""
        delta = self.since(v)
        if delta is None:
            return None
        out: Dict[str, LogEntry] = {}
        for e in delta:
            out[e.oid] = e
        return out


@dataclass
class PGInfo:
    """pg_info_t analog: what peers exchange during peering."""

    last_update: Eversion = ZERO
    log_tail: Eversion = ZERO
    last_complete: Eversion = ZERO


def choose_authoritative(infos: Dict[int, PGInfo],
                         require_rollback: bool = False) -> int:
    """Authoritative-log election (reference find_best_info).

    Replicated pools: max last_update wins (a write present anywhere may
    have been acked; full-object pushes make roll-FORWARD cheap).

    EC pools (``require_rollback``, the reference's pg_pool_t flag): the
    MIN last_update among members at-or-above the global commit
    watermark wins, so an un-acked partial-stripe write — applied on
    some shards only, unreconstructable if fewer than k have it — is
    ROLLED BACK rather than blessed.  Members below the watermark are
    stale rejoiners, excluded so acked writes can never be rolled back
    (the reference excludes them via last_epoch_started)."""
    if not require_rollback:
        return min(infos,
                   key=lambda o: (tuple(-x for x in infos[o].last_update), o))
    committed = max(i.last_complete for i in infos.values())
    candidates = {o: i for o, i in infos.items()
                  if i.last_update >= committed}
    if not candidates:
        # infos raced in-flight commits (a member's watermark moved
        # after another snapshotted): no member's log covers the
        # claimed watermark IN THIS SNAPSHOT.  Fall back to the whole
        # set rather than crash the peering round — the per-member
        # rewind guards refuse unsafe targets and the caller's
        # stale-round check + retry re-elect from fresh infos.
        candidates = dict(infos)
    return min(candidates,
               key=lambda o: (candidates[o].last_update, o))
