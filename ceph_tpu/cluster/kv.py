"""KeyValueDB: the ordered key-value abstraction under the monitor/store.

Behavioral mirror of reference src/kv/ (KeyValueDB.h): prefixed keyspace,
atomic transactions (set/rmkey/rmkeys_by_prefix), ordered iteration —
with MemDB (src/kv/MemDB.cc analog) and a store-backed implementation
persisting through an ObjectStore collection (the MonitorDBStore.h
pattern: mon state as a kv database over the storage layer).
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Tuple


class KVTransaction:
    def __init__(self):
        self.ops: List[Tuple] = []

    def set(self, prefix: str, key: str, value: bytes) -> "KVTransaction":
        self.ops.append(("set", prefix, key, bytes(value)))
        return self

    def rmkey(self, prefix: str, key: str) -> "KVTransaction":
        self.ops.append(("rmkey", prefix, key))
        return self

    def rmkeys_by_prefix(self, prefix: str) -> "KVTransaction":
        self.ops.append(("rmprefix", prefix))
        return self


class KeyValueDB:
    def submit_transaction(self, txn: KVTransaction) -> None:
        raise NotImplementedError

    def get(self, prefix: str, key: str) -> Optional[bytes]:
        raise NotImplementedError

    def iterate(self, prefix: str) -> Iterator[Tuple[str, bytes]]:
        raise NotImplementedError


class MemDB(KeyValueDB):
    def __init__(self):
        self._data: Dict[str, Dict[str, bytes]] = {}

    def submit_transaction(self, txn: KVTransaction) -> None:
        for op in txn.ops:
            if op[0] == "set":
                _, p, k, v = op
                self._data.setdefault(p, {})[k] = v
            elif op[0] == "rmkey":
                _, p, k = op
                self._data.get(p, {}).pop(k, None)
            elif op[0] == "rmprefix":
                self._data.pop(op[1], None)

    def get(self, prefix: str, key: str) -> Optional[bytes]:
        return self._data.get(prefix, {}).get(key)

    def iterate(self, prefix: str) -> Iterator[Tuple[str, bytes]]:
        yield from sorted(self._data.get(prefix, {}).items())


class StoreDB(KeyValueDB):
    """KV over an ObjectStore collection: one object per prefix, keys in
    its omap (the MonitorDBStore-over-storage pattern).  Inherits the
    store's durability (journaled FileStore -> durable kv)."""

    COLL = "kvdb"

    def __init__(self, store):
        from ceph_tpu.cluster.store import Transaction

        self.store = store
        self._Transaction = Transaction
        store.queue_transaction(
            Transaction().create_collection(self.COLL))

    def submit_transaction(self, txn: KVTransaction) -> None:
        t = self._Transaction()
        for op in txn.ops:
            if op[0] == "set":
                _, p, k, v = op
                t.touch(self.COLL, p).omap_set(self.COLL, p, {k: v})
            elif op[0] == "rmkey":
                _, p, k = op
                t.omap_rmkeys(self.COLL, p, [k])
            elif op[0] == "rmprefix":
                t.remove(self.COLL, op[1])
        self.store.queue_transaction(t)

    def get(self, prefix: str, key: str) -> Optional[bytes]:
        return self.store.omap_get(self.COLL, prefix).get(key)

    def iterate(self, prefix: str) -> Iterator[Tuple[str, bytes]]:
        yield from sorted(self.store.omap_get(self.COLL, prefix).items())
