"""Objecter + librados-style client surface.

Mirrors the reference client op engine (src/osdc/Objecter.cc): ops are
targeted client-side — object name -> ps (ceph_str_hash_rjenkins) ->
PG -> acting primary against the cached OSDMap (_calc_target,
Objecter.cc:2749) — sent as MOSDOp, and resent with a refreshed map on
misdirect or connection failure (:1272-1329 resend semantics).  The
RadosClient/IoCtx pair mirrors librados (src/librados/IoCtxImpl.cc).
"""

from __future__ import annotations

import asyncio
import pickle
from typing import Any, Dict, List, Optional, Set, Tuple

from ceph_tpu.cluster import messages as M
from ceph_tpu.cluster.messenger import (
    Addr,
    Connection,
    Dispatcher,
    EntityName,
    Messenger,
)
from ceph_tpu.ops.jenkins import str_hash_rjenkins
from ceph_tpu.osdmap.osdmap import OSDMap, PGid, ceph_stable_mod
from ceph_tpu.utils import Config
from ceph_tpu.utils.backoff import AIMDWindow, ExpBackoff
from ceph_tpu.utils.tasks import track_task


class Objecter(Dispatcher):
    def __init__(self, name: str, mon_addr,
                 config: Optional[Config] = None):
        import secrets as _secrets

        # reqid identity carries a per-incarnation nonce (reference
        # osd_reqid_t: client gid + incarnation): a restarted client
        # reusing a name must never collide with the OSDs' reqid dup
        # cache from its previous life — tids restart at 1
        self.client_name = f"{name}#{_secrets.token_hex(4)}"
        self.display_name = name
        # per-client config copy (daemons copy theirs the same way):
        # chaos injectargs against one client must not leak into the
        # cluster-wide template config
        self.config = Config(**config.show()) if config else Config()
        self.messenger = Messenger(
            EntityName("client", abs(hash(name)) % 10000),
            secret=self.config.auth_secret(),
            auth=self.config.cephx_context(f"client.{name}"),
            config=self.config)
        self.messenger.add_dispatcher(self)
        # graft-trace: the client mints the root span of every op's
        # cross-daemon tree (NULL_SPAN factory when trace_enabled=0)
        from ceph_tpu.trace import Tracer

        self.tracer = Tracer(f"client.{name}",
                             enabled=bool(self.config.trace_enabled),
                             keep=self.config.trace_keep)
        from ceph_tpu.cluster.monclient import MonTargeter

        self.monc = MonTargeter(
            self.messenger, mon_addr,
            subscribe_since=lambda: self.osdmap.epoch if self.osdmap else 0)
        self.osdmap: Optional[OSDMap] = None
        self._map_event = asyncio.Event()
        self._tid = 0
        self._trace_seq = 0
        self._inflight: Dict[Tuple[str, int], asyncio.Future] = {}
        self._mon_tid = 0
        self._mon_inflight: Dict[int, asyncio.Future] = {}
        self._cmd_inflight: Dict[int, asyncio.Future] = {}
        self._mds_inflight: Dict[int, asyncio.Future] = {}
        # linger ops (watches) re-registered on every map change
        # (reference Objecter::linger_register, Objecter.cc:778)
        self._cookie = 0
        self._watches: Dict[Tuple[int, str, int], object] = {}
        self._relinger_task = None
        # client-side flow control against OSD admission throttles: an
        # AIMD congestion window on inflight ops, driven by explicit
        # THROTTLED (-EBUSY) pushback — the primary flow-control signal,
        # replacing blind wait_for timeouts.  Wide open until the first
        # pushback, so with throttles off (default) it never constrains.
        self._primary_cache: Tuple[Optional[int], Dict] = (None, {})
        # reply-leg tail timelines (round 11): the OSD's terminal reply
        # carries a trace whose hop stamps + our completion stamp cover
        # the previously-untraced reply flight + client wakeup; bench
        # --attribute merges these so wall_coverage holds on short ops
        from collections import deque as _deque

        self._op_tails: "_deque" = _deque(maxlen=4096)
        self.cwnd = AIMDWindow(self.config.objecter_inflight_max)
        self._cwnd_inflight = 0
        self._cwnd_event = asyncio.Event()
        self._pushback_backoff = ExpBackoff(
            base=0.02, cap=1.0, rng=self._backoff_rng("pushback"))
        self._ops_acked = 0
        # graft-blackbox flight ring (NULL_FLIGHT when disabled):
        # clients have no ChaosClock — wall time, zero recorded skew
        from ceph_tpu.trace import FlightRecorder

        self.flight = FlightRecorder.from_config(
            f"client.{self.display_name}", self.config)
        # client-edge op coalescer (round 18): the objecter twin of the
        # OSD's SubWriteBatcher.  Built unconditionally — the gate is
        # consulted PER SEND (objecter_batch_tick_ops, injectargs-able),
        # so 0 keeps the legacy one-frame-per-op anchor byte-for-byte.
        from ceph_tpu.cluster.batcher import OpBatcher

        self._tasks: Set[asyncio.Task] = set()
        self._stopped = False
        self._op_batcher = OpBatcher(self)
        self._batch_ticks = 0
        self._batch_tick_ops = 0
        self._batch_reply_frames = 0
        self._batch_reply_items = 0

    def _track(self, task: asyncio.Task) -> None:
        track_task(self._tasks, task)

    # -- client telemetry on the mgr Prometheus path (round 13) ------------

    def flow_counters(self) -> Dict[str, int]:
        """Client-side flow-control telemetry: the AIMD congestion
        window state the graft-load SLO judge grades ("converged, not
        collapsed") — exported through the mgr so it rides the SAME
        Prometheus scrape as the daemon counters."""
        return {
            "client_cwnd": self.cwnd.limit,
            "client_cwnd_pushbacks": self.cwnd.pushbacks,
            "client_inflight_ops": self._cwnd_inflight,
            "client_ops_acked": self._ops_acked,
            "client_batch_ticks": self._batch_ticks,
            "client_batch_ops": self._batch_tick_ops,
            "client_batch_reply_frames": self._batch_reply_frames,
            "client_batch_reply_items": self._batch_reply_items,
        }

    async def mgr_report(self) -> bool:
        """Push this client's counters to the active mgr (the client
        half of MgrClient::send_report; daemons stream theirs from the
        heartbeat loop).  Clients have no beacon loop, so consumers —
        the load driver's telemetry loop, tests — call this at their
        own cadence.  False when no mgr is published in the map."""
        import time as _time

        m = self.osdmap
        addr = getattr(m, "mgr_addr", None) if m is not None else None
        if not addr:
            return False
        try:
            await self.messenger.send_message(M.MMgrReport(
                daemon=f"client.{self.display_name}",
                counters=self.flow_counters(),
                stamp=_time.monotonic()), tuple(addr))
            if self.flight:
                self.flight.record("cwnd", **self.flow_counters())
            return True
        except (ConnectionError, OSError, RuntimeError):
            return False

    def _backoff_rng(self, tag: str):
        """Seeded jitter stream when the client carries a chaos seed
        (deterministic scenario replay — the messenger/monclient
        contract); fresh entropy otherwise.  Keyed by the STABLE display
        name: the reqid nonce must not perturb replay."""
        if self.config.chaos_seed:
            from ceph_tpu.chaos.rng import stream

            return stream(self.config.chaos_seed,
                          f"objecter:{self.display_name}:{tag}")
        return None

    @property
    def mon_addr(self) -> Addr:
        return self.monc.current

    def _hunt(self) -> None:
        self.monc.hunt()

    async def _mon_send(self, msg) -> None:
        await self.monc.send(msg, raise_on_fail=True)

    async def start(self) -> None:
        addr = await self.messenger.bind()
        auth_ctx = self.messenger.auth
        if auth_ctx is not None and auth_ctx.master is None:
            # cephx client: bootstrap a ticket from the mon before any
            # session traffic (reference MonClient authenticate())
            await self.messenger.cephx_bootstrap(self.monc.current)
        await self._mon_send(M.MMonSubscribe(what="osdmap", addr=addr))
        await asyncio.wait_for(self._map_event.wait(), timeout=10)

    async def stop(self) -> None:
        self._stopped = True
        for t in list(self._tasks):
            t.cancel()
        if self._tasks:
            # teardown barrier: cancelled batcher ticks fail their
            # parked ops via the batcher's own finally (ConnectionError)
            await asyncio.gather(*self._tasks, return_exceptions=True)  # graftlint: ignore[swallowed-async-error]
        await self.messenger.shutdown()

    async def ms_handle_reset(self, conn: Connection) -> None:
        """A connection died: our watches ride accepted server-side conns
        that a transparent session reconnect does NOT restore — re-register
        them (reference: watch reconnect on session reset)."""
        self._schedule_relinger()

    async def ms_dispatch(self, conn: Connection, msg) -> bool:
        if isinstance(msg, M.MOSDMapMsg):
            newmap = pickle.loads(msg.osdmap_blob)
            if self.osdmap is None or newmap.epoch >= self.osdmap.epoch:
                self.osdmap = newmap
                self._schedule_relinger()
            self._map_event.set()
            return True
        if isinstance(msg, M.MWatchNotify):
            await self._handle_watch_notify(msg)
            return True
        if isinstance(msg, M.MOSDIncMapMsg):
            m = self.osdmap
            if m is not None and msg.prev_epoch == m.epoch:
                for blob in msg.inc_blobs:
                    m.apply_incremental(pickle.loads(blob))
                if msg.inc_blobs:
                    self._schedule_relinger()
                self._map_event.set()
            elif m is not None and msg.epoch <= m.epoch:
                self._map_event.set()  # already current
            else:
                # gap: resync from our epoch
                await self._mon_send(
                    M.MMonSubscribe(what="osdmap",
                                    addr=self.messenger.my_addr,
                                    since=m.epoch if m else 0))
            return True
        if isinstance(msg, M.MOSDOpReplyBatch):
            # scatter a reply tick per item: each MOSDOpReply inside
            # resolves only ITS op's future — a reqid the OSD shed
            # (expired deadline) is simply absent, so its future stays
            # pending and the op's own timeout/resend covers it.  The
            # SubWriteBatcher per-item rule, applied at the client edge;
            # per-item `throttled` flags reach _op_submit_attempts
            # unchanged, so AIMD pushback/ack stays per-op (one
            # throttled item never collapses its tick-mates' window).
            self._batch_reply_frames += 1
            self._batch_reply_items += len(msg.items)
            for item in msg.items:
                fut = self._inflight.pop(tuple(item.reqid), None)
                if fut and not fut.done():
                    fut.set_result(item)
            return True
        if isinstance(msg, M.MOSDOpReply):
            fut = self._inflight.pop(tuple(msg.reqid), None)
            if fut and not fut.done():
                fut.set_result(msg)
            return True
        if isinstance(msg, M.MMonCommandReply):
            fut = self._mon_inflight.pop(msg.tid, None)
            if fut and not fut.done():
                fut.set_result(msg)
            return True
        if isinstance(msg, M.MCommandReply):
            fut = self._cmd_inflight.pop(msg.tid, None)
            if fut and not fut.done():
                fut.set_result(msg)
            return True
        tname = type(msg).__name__
        if tname == "MClientReply":   # MDS replies (cluster/mds.py)
            fut = self._mds_inflight.pop(msg.tid, None)
            if fut and not fut.done():
                fut.set_result(msg)
            return True
        return False

    # -- targeting (reference _calc_target) --------------------------------

    def object_pgid(self, pool_id: int, oid: str) -> PGid:
        pool = self.osdmap.pools[pool_id]
        ps = str_hash_rjenkins(oid.encode())
        seed = ceph_stable_mod(ps, pool.pg_num, pool.pg_num_mask)
        return PGid(pool_id, seed)

    def _target_osd(self, pgid: PGid) -> int:
        # per-epoch primary cache: the scalar CRUSH walk per op was a
        # measurable slice of the t16 hot path; any map change bumps the
        # epoch and drops the whole cache (pg_temp/primary_temp ride
        # epochs too, so staleness is impossible by construction)
        m = self.osdmap
        epoch, cache = self._primary_cache
        if epoch != m.epoch:
            cache = {}
            self._primary_cache = (m.epoch, cache)
        primary = cache.get(pgid)
        if primary is None:
            _, _, _, primary = m.pg_to_up_acting_osds(pgid)
            cache[pgid] = primary
        return primary

    def _record_reply_tail(self, reply) -> None:
        """Keep the reply's hop timeline + our wakeup stamp (no-op for
        untraced replies)."""
        tr = getattr(reply, "trace", None)
        if tr is None:
            return
        import time as _time

        # header events are (name, wall_ts); attribution timelines are
        # (time, name) pairs
        evs = [(ts, name) for name, ts in tr.get("events", ())]
        evs.append((_time.time(), "objecter:complete"))
        self._op_tails.append(evs)

    def drain_op_tails(self):
        """Return and clear the recorded reply tails (bench --attribute
        drains once after warm-up, once after the timing window)."""
        out = [list(e) for e in self._op_tails]
        self._op_tails.clear()
        return out

    async def _refresh_map(self) -> None:
        # A subscribe that lands in a DYING mon's socket gets no push
        # back — the send itself "succeeds" into a half-dead session.
        # One silent window must not fail the caller (a pool_create
        # racing a leader failover saw exactly this): hunt to the next
        # mon and re-subscribe before giving up.
        for attempt in range(3):
            self._map_event.clear()
            await self._mon_send(
                M.MMonSubscribe(what="osdmap",
                                addr=self.messenger.my_addr,
                                since=self.osdmap.epoch
                                if self.osdmap else 0))
            try:
                await asyncio.wait_for(self._map_event.wait(), timeout=4)
                return
            except asyncio.TimeoutError:
                self._hunt()
                if attempt == 2:
                    raise

    # -- op submission with resend-on-map-change ---------------------------

    # write verbs for overlay targeting (shared with the OSD's dedup set)
    _WRITE_OPS = M.MUTATING_OPS

    def _overlay_pool(self, pool_id: int, ops) -> int:
        """Cache-tier overlay redirect (reference Objecter::_calc_target,
        src/osdc/Objecter.cc: target_oloc.pool = read_tier/write_tier):
        ops against a base pool with an overlay go to the cache pool."""
        pool = self.osdmap.pools.get(pool_id)
        if pool is None:
            return pool_id
        writes = any(o[0] in self._WRITE_OPS for o in ops)
        if writes and pool.has_write_tier():
            return pool.write_tier
        if not writes and pool.has_read_tier():
            return pool.read_tier
        return pool_id

    async def op_submit(self, pool_id: int, oid: str,
                        ops: List[Tuple[str, Dict[str, Any]]],
                        timeout: Optional[float] = None,
                        pgid=None, snapc=None,
                        snapid=None) -> M.MOSDOpReply:
        if timeout is None:
            timeout = self.config.rados_osd_op_timeout
        deadline = asyncio.get_event_loop().time() + timeout
        explicit_pgid = pgid
        # op-lifecycle trace header: one id for the op across resends;
        # the events ride the MOSDOp into the OSD's TrackedOp so
        # dump_historic_ops shows the client-side timeline too
        import time as _time

        self._trace_seq += 1
        trace_id = f"{self.client_name}:op{self._trace_seq}"
        trace_events = [("objecter:submit", _time.time())]
        # wall-clock deadline rides the message header: OSDs and their
        # sub-ops shed this op at dequeue once it passes (nobody awaits)
        wall_deadline = _time.time() + timeout
        # congestion-window gate BEFORE targeting: inflight ops beyond
        # the AIMD window wait here, and an op whose deadline passes
        # while waiting is shed client-side (never sent at all)
        waited = await self._cwnd_acquire(deadline, oid)
        if waited:
            trace_events.append(("objecter:throttle_wait", _time.time()))
        try:
            # root span of the op's cross-daemon tree: lives for the
            # whole submit incl. resends, so its duration IS the
            # client-observed wall time stage attribution is judged by
            with self.tracer.start("op_submit", trace_id=trace_id) as root:
                root.annotate(oid=oid, ops=[o[0] for o in ops])
                return await self._op_submit_attempts(
                    pool_id, oid, ops, deadline, wall_deadline,
                    explicit_pgid, trace_id, trace_events, root,
                    snapc, snapid)
        finally:
            self._cwnd_release()

    async def _cwnd_acquire(self, deadline: float, oid: str) -> bool:
        waited = False
        loop = asyncio.get_event_loop()
        while self._cwnd_inflight >= self.cwnd.limit:
            waited = True
            remaining = deadline - loop.time()
            if remaining <= 0:
                # client-side dead-work shed: the op expired before it
                # ever left this host — don't add it to the pile
                raise TimeoutError(
                    f"op on {oid} expired waiting for congestion window")
            self._cwnd_event.clear()
            try:
                await asyncio.wait_for(self._cwnd_event.wait(),
                                       timeout=remaining)
            except asyncio.TimeoutError:
                pass
        self._cwnd_inflight += 1
        return waited

    def _cwnd_release(self) -> None:
        self._cwnd_inflight = max(0, self._cwnd_inflight - 1)
        self._cwnd_event.set()

    async def _send_op(self, msg: M.MOSDOp, addr: Tuple) -> None:
        """Route one op frame out: through the per-(session, OSD) tick
        coalescer when client batching is on, else the legacy per-op
        frame.  Gated per SEND so objecter_batch_tick_ops=0 is a live
        anchor (injectargs mid-run flips the path for the next op)."""
        if self.config.objecter_batch_tick_ops > 0:
            await self._op_batcher.send(addr, msg)
        else:
            await self.messenger.send_message(msg, addr)

    async def _op_submit_attempts(self, pool_id, oid, ops, deadline,
                                  wall_deadline, explicit_pgid, trace_id,
                                  trace_events, root, snapc, snapid):
        import time as _time

        loop = asyncio.get_event_loop()
        # capped full-jitter backoff between retargeting attempts (was a
        # blind doubling sleep); a separate stream paces throttle
        # pushback retries so congestion retries and map-refresh retries
        # never share an attempt counter
        retarget_backoff = ExpBackoff(base=0.05, cap=1.0,
                                      rng=self._backoff_rng("retarget"))
        while True:
            # re-resolve the overlay every attempt: a tier/overlay change
            # mid-retry must re-target (the redirect is map state)
            target_pool = self._overlay_pool(pool_id, ops)
            pgid = explicit_pgid if explicit_pgid is not None \
                else self.object_pgid(target_pool, oid)
            primary = self._target_osd(pgid)
            addr = self.osdmap.osd_addrs.get(primary) if primary >= 0 else None
            if addr is not None:
                self._tid += 1
                reqid = (self.client_name, self._tid)
                fut = loop.create_future()
                self._inflight[reqid] = fut
                msg = M.MOSDOp(reqid=reqid, pgid=pgid, oid=oid, ops=ops,
                               epoch=self.osdmap.epoch,
                               snapc=snapc, snapid=snapid,
                               deadline=wall_deadline)
                msg.trace = {"id": trace_id,
                             "events": trace_events +
                             [("objecter:send", _time.time())]}
                if root.span_id is not None:
                    # span propagation: the OSD's dispatch span parents
                    # under this client root
                    msg.trace["span"] = root.span_id
                try:
                    await self._send_op(msg, tuple(addr))
                    # outwait the OSD's own replica-ack timeout (abandoning
                    # in parallel just queues a duplicate op behind the PG
                    # lock), but never past the op deadline — an ack past
                    # the deadline must not reach the caller as success
                    attempt = min(self.config.osd_client_op_timeout + 2.0,
                                  max(0.05, deadline - loop.time()))
                    reply = await asyncio.wait_for(fut, timeout=attempt)
                    if getattr(reply, "throttled", False):
                        # explicit admission pushback: shrink the window
                        # (multiplicative decrease), pause a jittered
                        # beat, resend — WITHOUT a map refresh (the
                        # target is right, the daemon is full)
                        self.cwnd.on_pushback()
                        if self.flight:
                            self.flight.record(
                                "cwnd", event="pushback",
                                limit=self.cwnd.limit)
                        if loop.time() > deadline:
                            raise TimeoutError(
                                f"op on {oid} throttled past deadline")
                        await asyncio.sleep(self._pushback_backoff.next())
                        continue
                    if reply.result != -11:  # not misdirected
                        self.cwnd.on_ack()
                        self._ops_acked += 1
                        self._pushback_backoff.reset()
                        self._record_reply_tail(reply)
                        return reply
                except (ConnectionError, OSError, asyncio.TimeoutError):
                    self._inflight.pop(reqid, None)
            if loop.time() > deadline:
                raise TimeoutError(f"op on {oid} timed out")
            await asyncio.sleep(retarget_backoff.next())
            try:
                await self._refresh_map()
            except asyncio.TimeoutError:
                pass

    # -- watch/notify (linger ops) -----------------------------------------

    def _schedule_relinger(self) -> None:
        """Re-register every watch after a map change: the PG's primary
        may have moved (reference linger resend on map change)."""
        if not self._watches:
            return
        if self._relinger_task is None or self._relinger_task.done():
            self._relinger_task = asyncio.get_event_loop().create_task(
                self._relinger())

    async def _relinger(self) -> None:
        for (pool_id, oid, cookie) in list(self._watches):
            try:
                await self.op_submit(pool_id, oid,
                                     [("watch", {"cookie": cookie})],
                                     timeout=10.0)
            except (IOError, OSError, TimeoutError):
                pass  # rewatch is best-effort; next reset retries

    async def _handle_watch_notify(self, msg: M.MWatchNotify) -> None:
        cb = self._watches.get((msg.pool, msg.oid, msg.cookie))
        if cb is not None:
            try:
                res = cb(msg.payload)
                if asyncio.iscoroutine(res):
                    await res
            except Exception:
                pass
        # ack one-way: this runs INSIDE our read loop, so a waiting
        # op_submit could never see its reply (self-deadlock until timeout)
        try:
            pgid = self.object_pgid(msg.pool, msg.oid)
            primary = self._target_osd(pgid)
            addr = self.osdmap.osd_addrs.get(primary)
            if addr is not None:
                self._tid += 1
                await self.messenger.send_message(
                    M.MOSDOp(reqid=(self.client_name, self._tid),
                             pgid=pgid, oid=msg.oid,
                             ops=[("notify_ack",
                                   {"notify_id": msg.notify_id})],
                             epoch=self.osdmap.epoch), tuple(addr))
        except (ConnectionError, OSError, RuntimeError, KeyError):
            pass  # unacked notify: the notifier's timeout covers it

    async def watch(self, pool_id: int, oid: str, callback) -> int:
        self._cookie += 1
        cookie = self._cookie
        self._watches[(pool_id, oid, cookie)] = callback
        reply = await self.op_submit(pool_id, oid,
                                     [("watch", {"cookie": cookie})])
        if reply.result != 0:
            del self._watches[(pool_id, oid, cookie)]
            raise IOError(f"watch({oid}) -> {reply.result}")
        return cookie

    async def unwatch(self, pool_id: int, oid: str, cookie: int) -> None:
        self._watches.pop((pool_id, oid, cookie), None)
        await self.op_submit(pool_id, oid, [("unwatch", {"cookie": cookie})])

    async def daemon_command(self, addr, cmd: Dict[str, Any],
                             timeout: float = 10.0):
        """Admin command straight to a daemon ('ceph tell' / admin-socket
        analog): osd perf dump, dump_historic_ops, mgr status, ..."""
        self._mon_tid += 1
        tid = self._mon_tid
        fut = asyncio.get_event_loop().create_future()
        self._cmd_inflight[tid] = fut
        try:
            await self.messenger.send_message(
                M.MCommand(cmd=cmd, tid=tid), tuple(addr))
            reply = await asyncio.wait_for(fut, timeout=timeout)
        finally:
            self._cmd_inflight.pop(tid, None)
        if reply.result != 0:
            raise RuntimeError(f"daemon command failed: {reply.data}")
        return reply.data

    async def mon_command(self, cmd: Dict[str, Any], timeout: float = 10.0):
        """Command with failover: retries against the other monitors when
        the current one dies or has no leader (commands are idempotent at
        the mon: pool create returns the existing pool on a retry)."""
        deadline = asyncio.get_event_loop().time() + timeout * 3
        last_err = None
        # capped jittered backoff between retries: a mon that answers -11
        # INSTANTLY (leaderless quorum) must not be hammered at loop
        # speed — fixed sleeps made every leaderless client resonate
        backoff = ExpBackoff(base=0.05, cap=1.0,
                             rng=self._backoff_rng("mon_command"))
        while asyncio.get_event_loop().time() < deadline:
            self._mon_tid += 1
            tid = self._mon_tid
            fut = asyncio.get_event_loop().create_future()
            self._mon_inflight[tid] = fut
            try:
                await self._mon_send(M.MMonCommand(cmd=cmd, tid=tid))
                reply = await asyncio.wait_for(fut, timeout=timeout)
            except (asyncio.TimeoutError, ConnectionError, OSError) as e:
                self._mon_inflight.pop(tid, None)
                last_err = e
                self._hunt()
                await asyncio.sleep(backoff.next())
                continue
            if reply.result == -11:   # no leader yet: retry
                last_err = RuntimeError(str(reply.data))
                await asyncio.sleep(backoff.next())
                continue
            if reply.result != 0:
                raise RuntimeError(f"mon command failed: {reply.data}")
            return reply.data
        raise TimeoutError(f"mon command never succeeded: {last_err}")


class IoCtx:
    """Pool I/O context (librados IoCtx analog).

    Snapshot surface (librados snap API): pool snaps attach their
    SnapContext to writes automatically (from the osdmap's pg_pool_t);
    ``set_snap_context`` installs an explicit selfmanaged context (RBD's
    mode); ``set_snap_read``/per-call ``snapid`` select the snap reads
    observe (reference rados_ioctx_snap_set_read)."""

    def __init__(self, objecter: Objecter, pool_id: int):
        self.objecter = objecter
        self.pool_id = pool_id
        self._snapc: Optional[Tuple[int, Tuple[int, ...]]] = None
        self._snap_read: Optional[int] = None

    # -- snapshot controls -------------------------------------------------

    def set_snap_context(self, seq: int, snaps) -> None:
        """Selfmanaged SnapContext for subsequent writes (descending)."""
        self._snapc = (seq, tuple(snaps))

    def set_snap_read(self, snapid: Optional[int]) -> None:
        """Snap observed by subsequent reads (None = HEAD)."""
        self._snap_read = snapid

    def _write_snapc(self):
        if self._snapc is not None:
            return self._snapc
        pool = self.objecter.osdmap.pools.get(self.pool_id) \
            if self.objecter.osdmap else None
        if pool is not None and pool.snaps:
            return pool.snap_context()
        return None

    async def snap_create(self, name: str) -> int:
        """Pool snapshot (reference rados_ioctx_snap_create)."""
        sid = await self.objecter.mon_command({
            "prefix": "osd pool mksnap", "pool": self.pool_id, "snap": name})
        await self.objecter._refresh_map()
        return sid

    async def snap_remove(self, name: str) -> int:
        sid = await self.objecter.mon_command({
            "prefix": "osd pool rmsnap", "pool": self.pool_id, "snap": name})
        await self.objecter._refresh_map()
        return sid

    def snap_list(self) -> Dict[int, str]:
        pool = self.objecter.osdmap.pools[self.pool_id]
        return dict(pool.snaps)

    def snap_lookup(self, name: str) -> int:
        for sid, n in self.snap_list().items():
            if n == name:
                return sid
        raise FileNotFoundError(name)

    async def selfmanaged_snap_create(self) -> int:
        """Allocate a snap id the CLIENT manages (reference
        rados_ioctx_selfmanaged_snap_create — RBD's snapshot mode)."""
        sid = await self.objecter.mon_command({
            "prefix": "osd pool selfmanaged_snap_create",
            "pool": self.pool_id})
        await self.objecter._refresh_map()
        return sid

    async def selfmanaged_snap_remove(self, snapid: int) -> None:
        await self.objecter.mon_command({
            "prefix": "osd pool selfmanaged_snap_remove",
            "pool": self.pool_id, "snapid": snapid})
        await self.objecter._refresh_map()

    # -- data ops ----------------------------------------------------------

    @staticmethod
    def _raise_write_error(verb: str, oid: str, reply) -> None:
        """Map a mutation's failed result to the exception the caller
        can act on: -28 becomes a REAL OSError(ENOSPC) — the cluster is
        full (round 16), not broken, and the remedy is deleting data,
        not retrying or refreshing maps."""
        if reply.result == -28:
            raise OSError(
                28, f"{verb}({oid}): cluster full (ENOSPC); deletes "
                    f"still admitted")
        raise IOError(f"{verb}({oid}) -> {reply.result}: {reply.data}")

    async def write_full(self, oid: str, data: bytes,
                         timeout: float = None) -> None:
        reply = await self.objecter.op_submit(
            self.pool_id, oid, [("write_full", {"data": data})],
            timeout=timeout, snapc=self._write_snapc())
        if reply.result != 0:
            self._raise_write_error("write_full", oid, reply)

    async def write(self, oid: str, data: bytes, offset: int = 0,
                    timeout: float = None) -> None:
        """Partial write at an offset — the EC read-modify-write path
        (reference IoCtxImpl::write -> ECBackend::start_rmw)."""
        reply = await self.objecter.op_submit(
            self.pool_id, oid, [("write", {"offset": offset, "data": data})],
            timeout=timeout, snapc=self._write_snapc())
        if reply.result != 0:
            self._raise_write_error("write", oid, reply)

    async def read(self, oid: str, offset: int = 0,
                   length: int = None, timeout: float = None,
                   snapid: int = None) -> bytes:
        args = {}
        if offset:
            args["offset"] = offset
        if length is not None:
            args["length"] = length
        reply = await self.objecter.op_submit(
            self.pool_id, oid, [("read", args)], timeout=timeout,
            snapid=snapid if snapid is not None else self._snap_read)
        if reply.result == -2:
            raise FileNotFoundError(oid)
        if reply.result != 0:
            raise IOError(f"read({oid}) -> {reply.result}: {reply.data}")
        return reply.data

    async def remove(self, oid: str, timeout: float = None) -> None:
        reply = await self.objecter.op_submit(self.pool_id, oid,
                                              [("delete", {})],
                                              timeout=timeout,
                                              snapc=self._write_snapc())
        if reply.result == -2:
            # -ENOENT maps like read/stat: callers that tolerate a
            # missing object catch FileNotFoundError, not a generic
            # IOError (rbd.remove's journal cleanup relies on this)
            raise FileNotFoundError(oid)
        if reply.result != 0:
            raise IOError(f"remove({oid}) -> {reply.result}")

    async def append(self, oid: str, data: bytes,
                     timeout: float = None) -> int:
        """Atomic append; returns the offset the data landed at
        (reference rados_append)."""
        reply = await self.objecter.op_submit(
            self.pool_id, oid, [("append", {"data": bytes(data)})],
            timeout=timeout, snapc=self._write_snapc())
        if reply.result != 0:
            self._raise_write_error("append", oid, reply)
        return reply.data

    async def truncate(self, oid: str, size: int) -> None:
        reply = await self.objecter.op_submit(
            self.pool_id, oid, [("truncate", {"size": size})],
            snapc=self._write_snapc())
        if reply.result != 0:
            raise IOError(f"truncate({oid}) -> {reply.result}")

    async def zero(self, oid: str, offset: int, length: int) -> None:
        reply = await self.objecter.op_submit(
            self.pool_id, oid,
            [("zero", {"offset": offset, "length": length})],
            snapc=self._write_snapc())
        if reply.result != 0:
            raise IOError(f"zero({oid}) -> {reply.result}")

    async def copy_from(self, dst_oid: str, src_oid: str,
                        src_pool: Optional[int] = None,
                        src_snapid: Optional[int] = None) -> int:
        """Server-side object copy (reference rados_copy /
        CEPH_OSD_OP_COPY_FROM): the destination primary pulls data,
        user xattrs, and omap from the source — cross-pool and across
        pool types — without routing bytes through this client.
        Returns the copied byte count."""
        args = {"src_oid": src_oid}
        if src_pool is not None:
            args["src_pool"] = src_pool
        if src_snapid is not None:
            args["src_snapid"] = src_snapid
        reply = await self.objecter.op_submit(
            self.pool_id, dst_oid, [("copy_from", args)],
            snapc=self._write_snapc())
        if reply.result != 0:
            raise IOError(f"copy_from({dst_oid} <- {src_oid}) -> "
                          f"{reply.result}")
        return reply.data

    async def rollback(self, oid: str, snapid: int) -> None:
        """Roll the head back to its state at ``snapid`` (reference
        rados_ioctx_snap_rollback -> _rollback_to); the current head
        still COWs into its own clone first."""
        reply = await self.objecter.op_submit(
            self.pool_id, oid, [("rollback", {"snapid": snapid})],
            snapc=self._write_snapc())
        if reply.result != 0:
            raise IOError(f"rollback({oid}@{snapid}) -> {reply.result}")

    async def create(self, oid: str, exclusive: bool = True) -> None:
        """Exclusive object create (rados_write_op create + EXCL)."""
        reply = await self.objecter.op_submit(
            self.pool_id, oid, [("create", {})],
            snapc=self._write_snapc())
        if reply.result == -17:
            raise FileExistsError(oid)
        if reply.result != 0:
            raise IOError(f"create({oid}) -> {reply.result}")

    async def cmpxattr(self, oid: str, name: str, value: bytes) -> bool:
        """Equality xattr guard; False on mismatch (-ECANCELED)."""
        reply = await self.objecter.op_submit(
            self.pool_id, oid,
            [("cmpxattr", {"name": name, "value": bytes(value)})])
        if reply.result == -125:
            return False
        if reply.result != 0:
            raise IOError(f"cmpxattr({oid}) -> {reply.result}")
        return True

    async def stat(self, oid: str, snapid: int = None,
                   timeout: float = None) -> int:
        reply = await self.objecter.op_submit(
            self.pool_id, oid, [("stat", {})], timeout=timeout,
            snapid=snapid if snapid is not None else self._snap_read)
        if reply.result != 0:
            raise FileNotFoundError(oid)
        return reply.data

    async def list_objects(self) -> List[str]:
        """Pool-wide object listing: one list op per PG against its
        primary (librados NObjectIterator analog)."""
        from ceph_tpu.osdmap.osdmap import PGid

        pool = self.objecter.osdmap.pools[self.pool_id]
        replies = await asyncio.gather(*[
            self.objecter.op_submit(self.pool_id, "", [("list", {})],
                                    pgid=PGid(self.pool_id, seed))
            for seed in range(pool.pg_num)])
        names: List[str] = []
        for reply in replies:
            names.extend(reply.data or [])
        return sorted(names)

    # -- xattrs (librados rados_getxattr/setxattr family) -------------------

    async def getxattr(self, oid: str, name: str,
                       snapid: Optional[int] = None) -> bytes:
        reply = await self.objecter.op_submit(
            self.pool_id, oid, [("getxattr", {"name": name})],
            snapid=snapid if snapid is not None else self._snap_read)
        if reply.result == -61:
            raise KeyError(name)
        if reply.result != 0:
            raise IOError(f"getxattr({oid}, {name}) -> {reply.result}")
        return reply.data

    async def setxattr(self, oid: str, name: str, value: bytes) -> None:
        reply = await self.objecter.op_submit(
            self.pool_id, oid, [("setxattr", {"name": name,
                                              "value": bytes(value)})],
            snapc=self._write_snapc())
        if reply.result != 0:
            raise IOError(f"setxattr({oid}, {name}) -> {reply.result}")

    async def rmxattr(self, oid: str, name: str) -> None:
        reply = await self.objecter.op_submit(
            self.pool_id, oid, [("rmxattr", {"name": name})],
            snapc=self._write_snapc())
        if reply.result != 0:
            raise IOError(f"rmxattr({oid}, {name}) -> {reply.result}")

    async def getxattrs(self, oid: str) -> Dict[str, bytes]:
        reply = await self.objecter.op_submit(
            self.pool_id, oid, [("getxattrs", {})])
        if reply.result != 0:
            raise IOError(f"getxattrs({oid}) -> {reply.result}")
        return reply.data

    # -- omap ---------------------------------------------------------------

    async def omap_set(self, oid: str, kv: Dict[str, bytes],
                       timeout: float = None) -> None:
        reply = await self.objecter.op_submit(
            self.pool_id, oid, [("omap_set", {"kv": dict(kv)})],
            timeout=timeout, snapc=self._write_snapc())
        if reply.result != 0:
            raise IOError(f"omap_set({oid}) -> {reply.result}")

    async def omap_get(self, oid: str,
                       snapid: Optional[int] = None,
                       timeout: float = None) -> Dict[str, bytes]:
        reply = await self.objecter.op_submit(
            self.pool_id, oid, [("omap_get", {})], timeout=timeout,
            snapid=snapid if snapid is not None else self._snap_read)
        if reply.result != 0:
            raise IOError(f"omap_get({oid}) -> {reply.result}")
        return reply.data

    async def omap_rmkeys(self, oid: str, keys,
                          timeout: float = None) -> None:
        reply = await self.objecter.op_submit(
            self.pool_id, oid, [("omap_rmkeys", {"keys": list(keys)})],
            timeout=timeout, snapc=self._write_snapc())
        if reply.result != 0:
            raise IOError(f"omap_rmkeys({oid}) -> {reply.result}")

    # -- object classes (rados_exec) ----------------------------------------

    async def execute(self, oid: str, cls: str, method: str,
                      indata: bytes = b"",
                      timeout: float = None) -> bytes:
        reply = await self.objecter.op_submit(
            self.pool_id, oid, [("exec", {"cls": cls, "method": method,
                                          "indata": bytes(indata)})],
            timeout=timeout, snapc=self._write_snapc())
        if reply.result != 0:
            raise IOError(
                f"exec({oid}, {cls}.{method}) -> {reply.result}: "
                f"{reply.data}")
        return reply.data

    # -- watch/notify -------------------------------------------------------

    async def watch(self, oid: str, callback) -> int:
        """Register a watch; callback(payload) fires on every notify
        (re-registered across map changes — a linger op)."""
        return await self.objecter.watch(self.pool_id, oid, callback)

    async def unwatch(self, oid: str, cookie: int) -> None:
        await self.objecter.unwatch(self.pool_id, oid, cookie)

    async def notify(self, oid: str, payload: bytes = b"",
                     timeout: float = 5.0):
        """Notify all watchers; returns the list of ackers."""
        reply = await self.objecter.op_submit(
            self.pool_id, oid, [("notify", {"payload": bytes(payload),
                                            "timeout": timeout})])
        if reply.result != 0:
            raise IOError(f"notify({oid}) -> {reply.result}")
        return reply.data


class RadosClient:
    """librados rados_t analog: connect, pools, ioctx."""

    def __init__(self, mon_addr: Addr, name: str = "admin",
                 config: Optional[Config] = None):
        self.objecter = Objecter(name, mon_addr, config)

    async def connect(self) -> None:
        await self.objecter.start()

    async def shutdown(self) -> None:
        await self.objecter.stop()

    async def pool_create(self, name: str, pool_type: str = "replicated",
                          pg_num: int = 16, size: int = 3,
                          ec_profile: Optional[Dict[str, str]] = None) -> int:
        pool_id = await self.objecter.mon_command({
            "prefix": "osd pool create", "pool": name,
            "pool_type": pool_type, "pg_num": pg_num, "size": size,
            "ec_profile": ec_profile})
        await self.objecter._refresh_map()
        return pool_id

    async def status(self):
        return await self.objecter.mon_command({"prefix": "status"})

    async def tier_add(self, base: str, cache: str) -> None:
        """'osd tier add <base> <cache>' (reference OSDMonitor)."""
        await self.objecter.mon_command({
            "prefix": "osd tier add", "pool": base, "tierpool": cache})
        await self.objecter._refresh_map()

    async def tier_remove(self, base: str, cache: str) -> None:
        await self.objecter.mon_command({
            "prefix": "osd tier remove", "pool": base, "tierpool": cache})
        await self.objecter._refresh_map()

    async def tier_cache_mode(self, cache: str, mode: str) -> None:
        """'osd tier cache-mode <cache> writeback|readproxy|forward|none'."""
        await self.objecter.mon_command({
            "prefix": "osd tier cache-mode", "pool": cache, "mode": mode})
        await self.objecter._refresh_map()

    async def tier_set_overlay(self, base: str, cache: str) -> None:
        await self.objecter.mon_command({
            "prefix": "osd tier set-overlay", "pool": base,
            "overlaypool": cache})
        await self.objecter._refresh_map()

    async def tier_remove_overlay(self, base: str) -> None:
        await self.objecter.mon_command({
            "prefix": "osd tier remove-overlay", "pool": base})
        await self.objecter._refresh_map()

    async def pool_delete(self, name: str, sure: bool = False) -> None:
        """Irreversible; mirrors the reference's name-twice + sure gate."""
        await self.objecter.mon_command({
            "prefix": "osd pool delete", "pool": name, "pool2": name,
            "sure": sure})
        await self.objecter._refresh_map()

    async def pool_rename(self, src: str, dst: str) -> None:
        await self.objecter.mon_command({
            "prefix": "osd pool rename", "srcpool": src, "destpool": dst})
        await self.objecter._refresh_map()

    async def pool_set(self, name: str, var: str, val) -> None:
        await self.objecter.mon_command({
            "prefix": "osd pool set", "pool": name, "var": var,
            "val": val})
        await self.objecter._refresh_map()

    def pool_list(self):
        m = self.objecter.osdmap
        return {p.name or pid: pid for pid, p in m.pools.items()}

    def ioctx(self, pool_id: int) -> IoCtx:
        return IoCtx(self.objecter, pool_id)
