"""Self-discarding background-task registry helper.

THE sanctioned spawn pattern the graftlint ``task-spawn`` rule
enforces for cluster daemons: a spawned task joins a set and discards
itself on completion, so per-op/per-event spawns never accumulate dead
Tasks for the daemon's life, while ``stop()`` can still cancel
whatever is live.  One implementation — messenger, OSD, and MDS all
delegate their ``_track`` here, so a change to the pattern (e.g.
surfacing a swallowed task exception) happens in exactly one place.
"""

from __future__ import annotations

import asyncio
from typing import Set


def track_task(registry: Set[asyncio.Task],
               task: asyncio.Task) -> asyncio.Task:
    registry.add(task)
    task.add_done_callback(registry.discard)
    return task
