"""Typed option schema + runtime-mutable config.

Mirrors the shape of the reference's md_config_t / Option machinery
(src/common/options.cc ~1,338 entries; src/common/config.cc): each option
has a type, default, and optional bounds; values can be set from kwargs,
dicts, or at runtime ("injectargs"), and observers are notified on change
(md_config_obs_t semantics).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional


@dataclass(frozen=True)
class Option:
    name: str
    type: type
    default: Any
    desc: str = ""
    min: Optional[float] = None
    max: Optional[float] = None


OPTIONS: List[Option] = [
    # messenger
    Option("ms_type", str, "async", "messenger transport"),
    Option("ms_bind_host", str, "127.0.0.1"),
    Option("ms_connect_timeout", float, 5.0),
    # osd
    Option("osd_heartbeat_interval", float, 0.5, "peer ping period (s)"),
    Option("osd_heartbeat_grace", float, 2.0, "grace before failure report"),
    Option("osd_pool_default_size", int, 3, min=1, max=16),
    Option("osd_pool_default_min_size", int, 2, min=1),
    Option("osd_pool_default_pg_num", int, 32, min=1),
    Option("osd_recovery_delay_start", float, 0.0),
    Option("osd_client_op_timeout", float, 10.0),
    Option("osd_tier_agent_interval", float, 1.0,
           "cache-tier agent flush/evict period (s)"),
    Option("osd_client_message_size_cap", int, 500 * 1024 * 1024,
           "byte budget concurrently in dispatch from clients "
           "(reference osd_client_message_size_cap throttle)"),
    Option("rados_osd_op_timeout", float, 30.0,
           "client-side total op budget incl. resends"),
    # overload / graceful degradation (round 10): layered admission
    # control ahead of dispatch (reference osd_op_throttle feeding
    # ShardedOpWQ) + client congestion window + deadline shedding +
    # degraded EC reads.  Zero budgets = unlimited (provable no-op).
    Option("osd_op_throttle_ops", int, 0,
           "admission budget: client ops concurrently queued+executing; "
           "beyond it the op is pushed back -EBUSY (0 = unlimited)",
           min=0),
    Option("osd_op_throttle_bytes", int, 0,
           "admission budget: mutation payload bytes concurrently "
           "queued+executing (0 = unlimited)", min=0),
    Option("objecter_inflight_max", int, 256,
           "client congestion-window ceiling (AIMD shrinks from here on "
           "throttle pushback, recovers additively on acks)", min=1),
    Option("osd_ec_hedge_reads", int, 1,
           "EC reads gather only the first k clean shards and hedge "
           "stragglers after a quantile-derived delay (0 = full gather)",
           min=0, max=1),
    Option("osd_ec_hedge_delay_floor", float, 0.05,
           "minimum hedge delay before contacting spare EC shards (s)",
           min=0),
    Option("osd_mclock_background_weight", float, 0.25,
           "dmClock weight for background (osd-internal) op classes; "
           "under admission pressure these are shed first"),
    Option("osd_mclock_background_limit", float, 0.0,
           "ops/s cap for the background class (0 = unlimited, like "
           "every dmclock limit)"),
    Option("osd_map_cache_size", int, 50),
    Option("osd_map_batch_min_pgs", int, 256,
           "pools with at least this many PGs use batched placement"),
    # control plane at scale (round 14): vectorized epoch deltas,
    # bounded delta chains, and peering storm control.  The vectorized
    # path defaults ON; 0 restores the per-PG rescan + full re-peer —
    # the bit-exactness/bisection anchor.
    Option("osd_map_vectorized_delta", int, 1,
           "compute per-epoch affected-PG sets by diffing whole-pool "
           "batched placements (osdmap.placement_delta) so epoch "
           "application peers only PGs whose up/acting moved.  0 = "
           "per-PG rescan and full re-peer on any change (the anchor)",
           min=0, max=1),
    Option("osd_map_max_inc_chain", int, 64,
           "longest incremental chain an OSD applies from one map "
           "message; beyond it the daemon requests a full map instead "
           "of unpickling the chain on the dispatch loop", min=1),
    Option("osd_peering_max_concurrent", int, 4,
           "simultaneous peering rounds per OSD (reservation-style "
           "throttle: a mass bounce produces a bounded wave, not a "
           "stampede)", min=1),
    Option("osd_peering_stagger_after", int, 8,
           "peering waves larger than this stagger their round starts "
           "with capped seeded jitter so hundreds of OSDs bouncing at "
           "once desynchronize their peer queries (0 = never stagger)",
           min=0),
    Option("osd_peering_stagger_max", float, 0.25,
           "cap on the per-round seeded stagger delay (s)", min=0),
    Option("osd_scrub_interval", float, 0.0,
           "background deep-scrub period per primary PG (0 disables); "
           "round 16: the scheduler is per-PG and seeded-jittered so "
           "a daemon's PGs never scrub in lockstep"),
    Option("osd_scrub_jitter", float, 0.5,
           "fraction of osd_scrub_interval used as the per-PG seeded "
           "jitter band (first scrub spreads across it; later scrubs "
           "wobble +/- half of it)", min=0, max=1),
    # verified reads + read-repair (round 16): every EC shard's crc is
    # checked by its holder before the bytes may feed a decode, and a
    # shard that fails crc / returns EIO / proves generation-stale is
    # rebuilt in place asynchronously.  Both default ON; 0 restores the
    # round-15 opportunistic-verify / fail-the-read behavior (the
    # verify-on-read A/B lever BENCH_NOTES round 16 uses).
    Option("osd_ec_verify_reads", int, 1,
           "verify every EC shard crc at read time (local shard "
           "batched through the read coalescer's crc tick, peers in "
           "their sub-read handlers).  0 = serve unverified bytes",
           min=0, max=1),
    Option("osd_read_repair", int, 1,
           "automatically rebuild shards a read gather found bad "
           "(crc/EIO/stale) from the surviving shards, off the client "
           "path.  0 = detect only", min=0, max=1),
    Option("osd_op_queue", str, "fifo",
           "client op scheduling: fifo | mclock (dmClock QoS)"),
    # sharded dispatch + per-tick stripe-batch coalescing (round 11):
    # the ShardedOpWQ analog.  Zero defaults preserve the round-10
    # per-op dispatch/encode path exactly — the bisection anchor; vstart
    # _fast_config (tests + bench) turns both on.
    Option("osd_op_shards", int, 0,
           "client-op dispatch shards (PG-affine hashing; each shard "
           "drains on a bounded dispatch tick and owns its own "
           "mclock/FIFO queue + shedding).  0 = the per-(conn,PG) "
           "FIFO / global-mclock legacy path", min=0),
    Option("osd_batch_tick_ops", int, 0,
           "max EC stripe-batch encodes coalesced into ONE device "
           "dispatch per tick (one to_planar, one fused encode, one "
           "crc32c batch).  0 = per-op encode (legacy)", min=0),
    Option("osd_batch_tick_window", float, 0.0,
           "extra accumulation window (s) after a tick's first encode "
           "request; 0 = pure group-commit self-clocking (a lone op "
           "never waits)", min=0),
    # client-edge op coalescing (round 18): the objecter twin of the
    # OSD tick batchers.  Ops targeting the same OSD park in a
    # per-(session, OSD) coalescer and ship as ONE MOSDOpBatch frame
    # per tick; replies coalesce back as ONE MOSDOpReplyBatch per reply
    # tick.  Per-item semantics are preserved end to end: a THROTTLED
    # or shed item un-acks only itself and AIMD pushback/ack accounting
    # stays per item.  0 = one MOSDOp frame + one reply per op — the
    # legacy bit-exactness / same-host A/B anchor; vstart _fast_config
    # turns it on.
    Option("objecter_batch_tick_ops", int, 0,
           "max client ops coalesced into ONE MOSDOpBatch frame per "
           "(session, OSD) tick; a 1-op tick ships the plain legacy "
           "MOSDOp frame.  0 = per-op frames (the anchor)", min=0),
    Option("objecter_batch_tick_window", float, 0.0,
           "extra accumulation window (s) after a client tick's first "
           "parked op; 0 = pure group-commit self-clocking (a lone op "
           "never waits)", min=0),
    # unified pipelined commit frontier (round 12): EC RMW and
    # replicated-pool mutations commit through the same split
    # commit-start (under the PG lock) / ack-wait (lock released)
    # path as round-11 pipelined EC full writes, all registered with
    # the PG's commit frontier.  0 = the round-10 full-PG-lock commit
    # for EVERY mutation — the serial bit-exactness anchor.
    Option("osd_pipeline_writes", int, 1,
           "pipeline mutation commits: hold the PG lock only for the "
           "ordered commit section, await fan-out acks with it "
           "released (EC full/RMW + replicated unified).  0 = legacy "
           "full-lock serial commits (bisection anchor)",
           min=0, max=1),
    Option("osd_op_complaint_time", float, 30.0,
           "ops blocked this long raise 'slow ops' warnings "
           "(reference osd_op_complaint_time; 0 disables)", min=0),
    Option("osd_op_history_size", int, 20,
           "completed ops kept for dump_historic_ops", min=0),
    Option("osd_op_history_slow_op_size", int, 20,
           "slowest completed ops kept for dump_historic_slow_ops",
           min=0),
    Option("osd_mclock_default_reservation", float, 0.0),
    Option("osd_mclock_default_weight", float, 1.0),
    Option("osd_mclock_default_limit", float, 0.0),
    # graft-trace (ceph_tpu/trace/): span tracing + event-loop profiling.
    # All-off defaults keep both provable no-ops (the chaos-injector
    # contract): Tracer.start returns the NULL_SPAN singleton and the
    # LoopProfiler declares/samples nothing.
    Option("trace_enabled", int, 0,
           "graft-trace span tracing (0 = off: provable no-op)",
           min=0, max=1),
    Option("trace_keep", int, 256,
           "completed traces retained per daemon tracer", min=1),
    Option("loop_profile_interval", float, 0.0,
           "event-loop lag sampler period (s); 0 disables", min=0),
    Option("loop_lag_warn", float, 0.5,
           "sampled loop lag at/above this raises the LOOP_LAG health "
           "warning (needs the sampler on)", min=0),
    # graft-blackbox (ceph_tpu/trace/flight.py + postmortem.py): the
    # per-daemon flight-recorder ring and triggered postmortem bundles.
    # Default-off keeps the provable-no-op contract: every daemon's
    # recorder is the shared NULL_FLIGHT singleton and the trigger path
    # in vstart/load/chaos is one falsy test.
    Option("blackbox_enabled", int, 0,
           "per-daemon flight recorder + triggered postmortem bundles "
           "(0 = off: provable no-op, the graft-trace contract)",
           min=0, max=1),
    Option("blackbox_ring", int, 512,
           "flight-recorder ring capacity per daemon (hard memory "
           "bound; overflow drops oldest and counts)", min=1),
    Option("blackbox_sample", int, 8,
           "record every Nth completed op in the flight ring (slow "
           "ops always recorded)", min=1),
    Option("blackbox_dir", str, "",
           "directory for triggered POSTMORTEM_*.json bundles; empty "
           "keeps bundles in-memory only (cluster.postmortems)"),
    Option("mon_health_history", int, 128,
           "health-transition records kept in the mon's bounded "
           "history ring (served by 'health history')", min=1),
    # graft-balance (ceph_tpu/balance/): the elastic-cluster policy
    # subsystem — device-batched upmap balancer, pg_num autoscaler and
    # grow/drain reshape ops, all mgr-hosted.  Default-off keeps the
    # provable-no-op contract: no loops start, no mon commands are
    # issued, and the mgr_balancer_*/mgr_autoscale_* counter families
    # stay declared-but-zero on the Prometheus scrape.
    Option("mgr_balancer_enabled", int, 0,
           "mgr upmap balancer loop (0 = off: provable no-op, counters "
           "declared but zero)", min=0, max=1),
    Option("mgr_balancer_vectorized", int, 1,
           "1 = device-batched candidate scorer (balance/scorer.py); "
           "0 = the greedy scalar anchor (osdmap/balancer.py) — the "
           "bisection anchor for the bit-exactness gate", min=0, max=1),
    Option("mgr_balancer_interval", float, 5.0,
           "seconds between balancer optimization rounds", min=0.05),
    Option("mgr_balancer_max_moves", int, 16,
           "pg_upmap_items moves committed per round (caps per-round "
           "backfill churn, reference upmap_max_optimizations)", min=1),
    Option("mgr_balancer_max_deviation_ratio", float, 0.05,
           "per-OSD fill deviation ratio the balancer tolerates before "
           "moving PGs (calc_pg_upmaps threshold)", min=0),
    Option("mgr_balancer_primary_weight", float, 0.0,
           "secondary objective weight on primary-count balance "
           "(0 keeps the objective identical to the scalar anchor's "
           "fill-variance energy)", min=0),
    Option("mgr_balancer_move_cost", float, 0.0,
           "projected-move-bytes penalty per candidate (0 = pure "
           "balance objective)", min=0),
    Option("mgr_balancer_require_clean", int, 1,
           "pause optimization while PG_DEGRADED/OSD_DOWN health "
           "checks fire (backfill pressure throttle)", min=0, max=1),
    Option("mgr_autoscale_enabled", int, 0,
           "mgr pg_num autoscaler loop (0 = off: provable no-op)",
           min=0, max=1),
    Option("mgr_autoscale_interval", float, 5.0,
           "seconds between autoscaler rounds", min=0.05),
    Option("mgr_autoscale_objects_per_pg", int, 64,
           "grow a pool's pg_num once its PGs average this many "
           "objects (load-derived target)", min=1),
    Option("mgr_autoscale_pgs_per_osd", int, 100,
           "cluster PG budget: pool pg_num*size summed must stay under "
           "this per in-OSD (mon_max_pg_per_osd analog)", min=1),
    # graft-race (ceph_tpu/analysis/racecheck.py + utils/schedfuzz.py):
    # the seeded schedule-perturbation sanitizer.  Default-off keeps the
    # provable-no-op contract: the module-global probe target stays the
    # falsy NULL_RACE singleton and every cluster probe site is one
    # truthiness test (pinned by tests/test_racecheck.py).
    Option("race_check_enabled", int, 0,
           "arm the cross-task write-after-read tracker at the cluster "
           "probe seams (0 = off: provable no-op; 1 = vstart boot arms "
           "the process-global tracker, served by 'race report'; race "
           "runs install their own tracker + the SchedFuzzLoop shim)",
           min=0, max=1),
    Option("race_check_seed", int, 0,
           "seed for the schedule-perturbation rng stream and the "
           "tracker it reports under (chaos-rng derived: replays "
           "bit-identically)", min=0),
    # mon
    Option("mon_osd_down_out_interval", float, 30.0,
           "auto-out after down this long"),
    # cluster-full protection (round 16, reference mon_osd_*_ratio):
    # the mon judges per-OSD utilization from beacon statfs and commits
    # nearfull/backfillfull/full flags into the OSDMap; full pools
    # reject client writes with ENOSPC (deletes still admitted so the
    # cluster can dig itself out), backfillfull gates backfill data
    # movement, and the flags clear as space frees.
    Option("mon_osd_nearfull_ratio", float, 0.85,
           "per-OSD used/total at/above this raises OSD_NEARFULL and "
           "sets the map's nearfull flag", min=0, max=1),
    Option("mon_osd_backfillfull_ratio", float, 0.90,
           "at/above this, backfill data movement is refused "
           "(OSD_BACKFILLFULL + the map's backfillfull flag)",
           min=0, max=1),
    Option("mon_osd_full_ratio", float, 0.95,
           "at/above this the cluster is FULL: client writes are "
           "rejected with ENOSPC until space frees (OSD_FULL, "
           "HEALTH_ERR, the map's full flag)", min=0, max=1),
    Option("mon_osd_min_down_reporters", int, 1),
    Option("mon_osd_failure_coalesce", float, 0.05,
           "window (s) to aggregate concurrent failure reports into "
           "ONE map epoch — N simultaneous markdowns coalesce into one "
           "incremental instead of N Paxos rounds (0 = commit each "
           "markdown immediately, the pre-round-14 behavior)", min=0),
    Option("mon_osd_map_max_incs", int, 32,
           "longest incremental chain the mon sends one subscriber; "
           "beyond it the mon skips to a full map (cheaper than a long "
           "per-epoch pickle chain on both ends)", min=1),
    Option("mon_osd_beacon_grace", float, 6.0,
           "mark an osd down when its beacons go stale this long "
           "(reference osd_beacon_report_interval + mon grace)"),
    Option("mon_tick_interval", float, 0.5),
    Option("mon_election_timeout", float, 0.3,
           "elector victory-check window"),
    Option("mon_paxos_timeout", float, 1.0,
           "collect/accept round timeout"),
    Option("mon_lease_interval", float, 0.25,
           "leader lease extension period"),
    Option("mon_lease_ack_timeout", float, 1.2,
           "peon lease staleness before calling an election"),
    # auth (reference auth_supported / cephx)
    Option("auth_shared_secret", str, "",
           "cluster HMAC signing key; empty = auth none"),
    # "none" | "shared" (static HMAC signing) | "cephx" (mon-issued
    # tickets, per-session keys, caps — cluster/auth.py)
    Option("auth_supported", str, "shared"),
    Option("auth_ticket_ttl", float, 3600.0),
    # client-side: hex per-entity key (provisioned keyring analog);
    # empty + cephx -> derive from auth_shared_secret when present
    Option("auth_entity_key", str, ""),
    # mds (MDSMap-lite + Locker caps-lite)
    Option("mds_lease_ttl", float, 2.0),
    Option("mds_beacon_interval", float, 1.0),
    # ec
    Option("osd_ec_batch_size", int, 64, "stripes per device dispatch"),
    Option("osd_ec_stripe_unit", int, 4096),
    # bit-planar AT-REST shards (round 19): EC shard objects are stored,
    # shipped (sub-writes/sub-reads/recovery push), and verified as
    # packed bit-plane matrices — zero layout conversions on the
    # steady-state write/read/RMW/recovery/scrub paths (pinned by the
    # ec_planar_unseamed counter).  0 = byte-at-rest, the
    # bisection/bit-exactness anchor; requires w=8 matrix codecs and
    # stripe_unit % 8 == 0 (else the OSD quietly stays on bytes).
    Option("osd_ec_planar_at_rest", int, 0, min=0, max=1),
    # route EC pool batch encode/decode through the sharded mesh engine
    # (parallel/engine.py): "on" = use a device mesh, "off" = the
    # single-device codec engines.  ("on" needs >1 jax device; the mesh
    # is the EC data plane the way NCCL fan-out is the reference's.)
    Option("osd_ec_mesh", str, "off"),
    Option("osd_ec_mesh_devices", int, 0),  # 0 = all visible devices
    # store
    Option("memstore_device_bytes", int, 1 << 30),
    Option("bluestore_csum_type", str, "crc32c"),
    # debug
    Option("debug_ms", int, 0, min=0, max=20),
    Option("debug_osd", int, 0, min=0, max=20),
    Option("debug_mon", int, 0, min=0, max=20),
    # chaos (deterministic fault injection, ceph_tpu/chaos/): the
    # injectargs-able analog of the reference's ms_inject_socket_failures
    # / filestore_debug_inject_read_err debug seams.  All-zero defaults
    # keep every injector a provable no-op (messenger.chaos is None,
    # store.chaos is None, clock skew a plain passthrough).
    Option("chaos_seed", int, 0, "root seed for per-injector rng streams"),
    Option("chaos_net_drop", float, 0.0, "frame drop probability",
           min=0, max=1),
    Option("chaos_net_dup", float, 0.0, "frame duplication probability",
           min=0, max=1),
    Option("chaos_net_delay", float, 0.0,
           "max injected frame delay (s)", min=0),
    Option("chaos_net_delay_prob", float, 0.0,
           "frame delay probability", min=0, max=1),
    Option("chaos_net_reorder", float, 0.0,
           "frame reorder (deferral) probability", min=0, max=1),
    Option("chaos_net_reset", float, 0.0,
           "post-send session reset probability", min=0, max=1),
    Option("chaos_net_partition", str, "",
           "comma-separated host:port peers unreachable FROM this "
           "daemon (asymmetric partition side)"),
    Option("chaos_disk_read_err", float, 0.0,
           "injected EIO probability per store read", min=0, max=1),
    Option("chaos_disk_enospc", float, 0.0,
           "injected ENOSPC probability per transaction", min=0, max=1),
    Option("chaos_disk_bitrot", float, 0.0,
           "silent bit-flip probability per committed write txn",
           min=0, max=1),
    Option("chaos_clock_skew", float, 0.0,
           "seconds added to this daemon's time source"),
    # batch-aware fault injection (round 12): per-item faults INSIDE a
    # coalesced tick's frames, and named crash points at the
    # tick/commit seams.  All-zero/empty defaults keep the no-op
    # contract (mutate_batch is never consulted, _chaos_point is one
    # falsy test).
    Option("chaos_net_batch_item_drop", float, 0.0,
           "per-item drop probability INSIDE a MOSDECSubOpWriteBatch "
           "frame (the rest of the frame still delivers — a partial "
           "tick on the wire)", min=0, max=1),
    Option("chaos_net_batch_ack_dup", float, 0.0,
           "per-entry duplication probability in a batched sub-write "
           "ack (exercises per-responder ack dedup)", min=0, max=1),
    Option("chaos_net_batch_ack_reorder", float, 0.0,
           "probability of shuffling a batched ack's result order "
           "(acks must be order-independent)", min=0, max=1),
    Option("chaos_crash_point", str, "",
           "named crash seam: the daemon power-cuts itself the next "
           "time its write path passes this point (tick_mid_encode, "
           "tick_post_encode, commit_pre_fanout, commit_mid_fanout, "
           "frontier_open, frontier_pre_done, batch_apply_mid); "
           "one-shot, '' = off.  Round 15 adds front-door seams: on "
           "an MDS config, mds_journal_mid (journalled but unapplied) "
           "and mds_replay_mid (boot replay cut between events) crash "
           "the rank; on a CLIENT config, rbd_snap_pre_header, "
           "rbd_copyup_mid, rbd_clone_mid, rgw_part_mid, "
           "rgw_complete_mid, and rgw_abort_mid interrupt the library "
           "op (ChaosInterrupt) — the 'application' dies "
           "mid-transaction and a retry models its restart"),
    Option("chaos_crash_point_skip", int, 0,
           "traversals of the armed crash point to let pass before "
           "firing (seed-resolved by scenarios for deterministic "
           "crash timing)", min=0),
]

_BY_NAME = {o.name: o for o in OPTIONS}


class Config:
    def __init__(self, **overrides):
        self._values: Dict[str, Any] = {o.name: o.default for o in OPTIONS}
        self._observers: List[Callable[[str, Any], None]] = []
        for k, v in overrides.items():
            self.set(k, v)

    def get(self, name: str):
        return self._values[name]

    def __getattr__(self, name: str):
        values = object.__getattribute__(self, "_values")
        if name in values:
            return values[name]
        raise AttributeError(name)

    def __setattr__(self, name: str, value) -> None:
        # route option assignment through set(): a shadowing instance
        # attribute would be read back by __getattr__ but silently lost
        # by show()-based per-daemon copies
        if name.startswith("_"):
            object.__setattr__(self, name, value)
        else:
            self.set(name, value)

    def set(self, name: str, value) -> None:
        opt = _BY_NAME.get(name)
        if opt is None:
            raise KeyError(f"unknown option {name}")
        value = opt.type(value)
        if opt.min is not None and value < opt.min:
            raise ValueError(f"{name}={value} below min {opt.min}")
        if opt.max is not None and value > opt.max:
            raise ValueError(f"{name}={value} above max {opt.max}")
        self._values[name] = value
        for obs in self._observers:
            obs(name, value)

    def injectargs(self, args: Dict[str, Any]) -> None:
        """Runtime mutation (reference injectargs admin command)."""
        for k, v in args.items():
            self.set(k, v)

    def add_observer(self, fn: Callable[[str, Any], None]) -> None:
        self._observers.append(fn)

    def remove_observer(self, fn: Callable[[str, Any], None]) -> None:
        """Deregister an observer (daemon teardown).  Configs are
        REUSED across daemon incarnations (vstart restart/revive keep
        the per-daemon config so injected options survive bounces), so
        a stop() that leaves its observers behind pins every dead
        incarnation in memory for the config's lifetime."""
        try:
            self._observers.remove(fn)
        except ValueError:
            pass

    def auth_secret(self):
        """Messenger signing key, or None for auth 'none'."""
        s = self._values.get("auth_shared_secret", "")
        return s.encode() if s else None

    def cephx_context(self, entity: str):
        """CephxContext for a daemon/client messenger when
        auth_supported=cephx, else None (legacy shared/none modes)."""
        if self._values.get("auth_supported") != "cephx":
            return None
        from ceph_tpu.cluster import auth as authmod

        master = self.auth_secret()
        ek = self._values.get("auth_entity_key", "")
        entity_secret = bytes.fromhex(ek) if ek else None
        kind = entity.split(".", 1)[0]
        if kind in ("mon", "osd", "mds", "mgr"):
            return authmod.CephxContext(
                entity, master=master,
                ttl=self._values.get("auth_ticket_ttl", 3600.0))
        # clients never hold the master key — only their entity key
        if entity_secret is None and master is not None:
            entity_secret = authmod.entity_key(master, entity)
        return authmod.CephxContext(
            entity, entity_secret=entity_secret,
            ttl=self._values.get("auth_ticket_ttl", 3600.0))

    def show(self) -> Dict[str, Any]:
        return dict(self._values)
