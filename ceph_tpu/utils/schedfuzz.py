"""graft-race dynamic half, part 1: the schedule-perturbation loop.

asyncio's ready queue is FIFO, so every test run explores ONE
interleaving of the data plane's tasks — the one where whoever called
``call_soon`` first runs first.  Await-atomicity bugs (stale snapshot
across an ack-wait, check-then-act across a fan-out) only fire under
the interleavings FIFO never produces.  ``SchedFuzzLoop`` is a
SelectorEventLoop whose per-tick callback order is a seeded
Fisher-Yates permutation drawn from a chaos-rng stream
(``stream(seed, "schedfuzz")``), plus seeded DEFERRAL of ready
callbacks to the next tick — an injected yield window at every await
boundary, bounded per handle so nothing starves.  Same seed, same
workload => bit-identical permutation stream (``trace_digest``);
different seeds explore different interleavings of the same program.

Two hard safety rules keep the shim honest:

- at least one ready handle always runs per tick (deferring the whole
  queue would park the loop in ``select()`` with runnable work held
  hostage — a deadlock the PROGRAM doesn't have);
- a handle is deferred at most ``max_defer`` consecutive times, then
  it runs unconditionally (bounded starvation, so timeouts measure the
  program, not the shim).

The shim perturbs only ORDER and tick assignment, never drops or
duplicates a callback, so any invariant breach under it is a real
interleaving the unperturbed loop was licensed to produce all along.

``self._ready`` is CPython's private BaseEventLoop queue; the shim
gates on its existence and degrades to a plain (unperturbed) loop with
an empty trace when an implementation doesn't expose it.
"""

from __future__ import annotations

import asyncio
import hashlib
from typing import Callable, List, Optional, Tuple

from ceph_tpu.chaos.rng import stream


class SchedFuzzLoop(asyncio.SelectorEventLoop):
    """A SelectorEventLoop with seeded ready-queue perturbation."""

    def __init__(self, seed: int, defer_prob: float = 0.25,
                 max_defer: int = 4,
                 on_tick: Optional[Callable[[], None]] = None):
        super().__init__()
        self.seed = seed
        self._fuzz_rng = stream(seed, "schedfuzz")
        self._fuzz_defer_prob = float(defer_prob)
        self._fuzz_max_defer = max(0, int(max_defer))
        self._fuzz_on_tick = on_tick
        self._fuzz_tick = 0
        self._fuzz_trace: List[Tuple[int, int, Tuple[int, ...], int]] = []
        self._fuzz_deferred: List = []
        self._fuzz_defer_counts: dict = {}
        # private-API gate: no _ready => plain loop, empty trace
        self._fuzz_active = hasattr(self, "_ready")

    # -- the perturbation ----------------------------------------------------

    def _fuzz_perturb(self) -> None:
        ready = self._ready
        # handles deferred last tick re-enter ahead of this tick's
        # shuffle (they may be deferred again, up to max_defer)
        if self._fuzz_deferred:
            ready.extend(self._fuzz_deferred)
            self._fuzz_deferred.clear()
        if len(ready) <= 1:
            return
        # partition: only TASK steps and wakeups are perturbable —
        # they are the coroutine interleaving points the sanitizer
        # explores.  Loop and transport plumbing (sock-connect
        # completions, reader/writer lifecycle, _sock_write_done) must
        # keep FIFO order among themselves: deferring an fd-lifecycle
        # callback past the fd's reuse breaks asyncio itself, and a
        # crash the PROGRAM can't produce is a false conviction.
        fixed: List = []
        tasky: List = []
        for h in ready:
            cb = getattr(h, "_callback", None)
            owner = getattr(cb, "__self__", None)
            if isinstance(owner, asyncio.Task) \
                    and not getattr(h, "_cancelled", False):
                tasky.append(h)
            else:
                fixed.append(h)
        n = len(tasky)
        if n <= 1:
            return  # nothing to permute: queue left untouched
        self._fuzz_tick += 1
        if self._fuzz_on_tick is not None:
            self._fuzz_on_tick()
        # seeded Fisher-Yates over this tick's task handles
        perm = list(range(n))
        for i in range(n - 1, 0, -1):
            j = self._fuzz_rng.randrange(i + 1)
            perm[i], perm[j] = perm[j], perm[i]
        items = [tasky[k] for k in perm]
        # seeded deferral: push a task step past the tick boundary —
        # the injected yield window.  Never the whole queue, never the
        # same handle more than max_defer times in a row.
        run_now: List = []
        deferred = 0
        for h in items:
            key = id(h)
            over = self._fuzz_defer_counts.get(key, 0)
            if ((fixed or run_now) and over < self._fuzz_max_defer
                    and self._fuzz_rng.random() < self._fuzz_defer_prob):
                self._fuzz_defer_counts[key] = over + 1
                self._fuzz_deferred.append(h)
                deferred += 1
            else:
                self._fuzz_defer_counts.pop(key, None)
                run_now.append(h)
        ready.clear()
        ready.extend(fixed)
        ready.extend(run_now)
        self._fuzz_trace.append((self._fuzz_tick, n, tuple(perm), deferred))

    def _run_once(self):
        if self._fuzz_active:
            self._fuzz_perturb()
        super()._run_once()

    # -- replay evidence -----------------------------------------------------

    def fuzz_trace(self) -> List[Tuple[int, int, Tuple[int, ...], int]]:
        """(tick, ready-set size, permutation, deferred count) per
        perturbed tick — the full decision record."""
        return list(self._fuzz_trace)

    def trace_digest(self) -> str:
        """Compact replay key over the decision record.  Two runs of
        the same seed over the same (IO-free) workload produce the same
        digest bit for bit; cluster scenarios with real sockets compare
        ``Verdict.replay_key()`` instead (select() readiness order is
        the OS's, not ours)."""
        h = hashlib.sha256(repr(self._fuzz_trace).encode())
        return h.hexdigest()


def run_fuzzed(factory, seed: int, defer_prob: float = 0.25,
               max_defer: int = 4,
               on_tick: Optional[Callable[[], None]] = None):
    """Run ``factory()`` (a coroutine factory) to completion on a fresh
    SchedFuzzLoop; returns ``(result, trace_digest)``.  The loop is
    installed as the thread's event loop for the duration (cluster code
    reaches it via ``get_event_loop``) and always restored + closed."""
    loop = SchedFuzzLoop(seed, defer_prob=defer_prob, max_defer=max_defer,
                         on_tick=on_tick)
    try:
        asyncio.set_event_loop(loop)
        result = loop.run_until_complete(factory())
        return result, loop.trace_digest()
    finally:
        asyncio.set_event_loop(None)
        loop.close()
