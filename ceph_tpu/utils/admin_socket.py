"""AdminSocket-style command router (reference src/common/admin_socket.cc).

Every daemon owns one AdminSocket and registers command handlers into it
(AdminSocket::register_command analog); the daemon's MCommand dispatch
becomes one ``dispatch()`` call instead of a per-daemon if/elif ladder,
and the ``ceph daemon <name> <cmd>`` CLI path reaches any daemon through
the same table.

Handlers take the full command dict and return the reply payload; they
may be sync or async (the reference's equivalent seam is AdminSocketHook
::call running on the admin socket thread).  Errors surface as
(-EINVAL, repr(e)) like the daemons' previous inline handling.
"""

from __future__ import annotations

import inspect
from typing import Any, Callable, Dict, Tuple

from ceph_tpu.utils.perf import PerfCounters, PerfCountersCollection


class AdminSocket:
    def __init__(self):
        self._commands: Dict[str, Tuple[Callable, str]] = {}
        self.register("help", lambda cmd: self.commands(),
                      "list registered commands")

    def register(self, prefix: str, handler: Callable[[Dict], Any],
                 desc: str = "") -> None:
        """Bind ``prefix`` -> handler(cmd_dict) -> reply payload."""
        self._commands[prefix] = (handler, desc)

    def commands(self) -> Dict[str, str]:
        return {p: d for p, (_, d) in sorted(self._commands.items())}

    def has(self, prefix: str) -> bool:
        return prefix in self._commands

    async def dispatch(self, cmd: Dict) -> Tuple[int, Any]:
        """Run the handler for cmd['prefix']; returns (result, data)
        with -22/EINVAL for unknown commands or handler errors."""
        entry = self._commands.get(cmd.get("prefix"))
        if entry is None:
            return -22, f"unknown command {cmd.get('prefix')!r} " \
                        f"(try 'help')"
        handler, _ = entry
        try:
            data = handler(cmd)
            if inspect.isawaitable(data):
                data = await data
        except Exception as e:
            return -22, repr(e)
        return 0, data

    # -- the standard per-daemon command set --------------------------------

    def register_common(self, perf, config=None, flight=None) -> None:
        """Register the commands every daemon serves: the perf family
        (reference perf dump / perf schema / perf histogram dump /
        perf reset) and config show/injectargs.  ``perf`` is a
        PerfCounters or a PerfCountersCollection.  ``flight`` (a
        FlightRecorder or NULL_FLIGHT) adds ``blackbox dump`` — the
        per-daemon postmortem snapshot: the flight ring plus the
        high-priority perf slice.  NULL_FLIGHT serves a disabled
        payload, so bundle collection never errors on a daemon that
        has the recorder off."""
        assert isinstance(perf, (PerfCounters, PerfCountersCollection))
        if flight is not None:
            self.register(
                "blackbox dump",
                lambda cmd: {"flight": flight.dump(),
                             "perf_critical": perf.dump_critical()},
                "flight-recorder ring + critical perf counters "
                "(the postmortem bundle's per-daemon slice)")
        self.register("perf dump", lambda cmd: perf.dump(),
                      "dump perf counter values")
        self.register("perf schema", lambda cmd: perf.dump_schema(),
                      "dump perf counter types/units/priorities")
        self.register("perf histogram dump",
                      lambda cmd: perf.dump_histograms(),
                      "dump histogram counters only")
        self.register("perf reset",
                      lambda cmd: perf.reset() or "reset",
                      "zero perf counter values (schemas kept)")
        if config is not None:
            self.register("config show", lambda cmd: config.show(),
                          "dump the daemon's config values")
            self.register(
                "injectargs",
                lambda cmd: config.injectargs(cmd.get("args", {})),
                "runtime config mutation")
        self.register("lockdep dump", _lockdep_dump,
                      "dump the observed runtime lock-ordering graph")
        self.register("graftlint report", _graftlint_report,
                      "last static-analysis summary (lint runs on "
                      "first request)")
        self.register("chaos report",
                      lambda cmd: _chaos_report(config),
                      "injected-fault counters + this daemon's active "
                      "chaos options")
        self.register("race report", lambda cmd: _race_report(),
                      "graft-race tracker state: probe counts, ticks, "
                      "and write-after-read convictions with both "
                      "task stacks (disabled payload when no tracker "
                      "is installed)")


def _chaos_report(config):
    """Process-wide chaos counters + the daemon's chaos_* option view
    (config-driven injectors are fully described by those values)."""
    from ceph_tpu.chaos.counters import chaos_report

    return chaos_report(config)


def _race_report():
    """The process-wide graft-race tracker's report: NULL_RACE serves
    its disabled payload, so the command never errors when the
    sanitizer is off (the blackbox-dump contract)."""
    from ceph_tpu.analysis import racecheck

    return racecheck.TRACKER.report()


def _lockdep_dump(cmd):
    """The live runtime lock graph; feed it to `scripts/graftlint.py
    --runtime-edges` to merge with the static graph."""
    from ceph_tpu.utils.lockdep import LockDep

    return LockDep.instance().dump()


async def _graftlint_report(cmd):
    """The cached graftlint summary; a live cluster's first request (or
    cmd={"refresh": true}) runs the whole-repo lint — pure AST walking,
    but ~seconds of CPU over 150+ files, so it runs in an executor: the
    daemon's event loop must keep serving heartbeats/ops meanwhile
    (stalling it would be exactly the asyncio-blocking bug class this
    subsystem lints for)."""
    import asyncio

    from ceph_tpu import analysis

    loop = asyncio.get_event_loop()
    if cmd.get("refresh"):
        from ceph_tpu.analysis.baseline import default_baseline_path, \
            load_baseline
        from ceph_tpu.utils.lockdep import LockDep

        # a refresh also folds the CURRENT runtime edges into the
        # merged-graph acyclicity check
        baseline = load_baseline(default_baseline_path())
        edges = LockDep.instance().dump()["edges"]
        report = await loop.run_in_executor(
            None, lambda: analysis.run_lint(baseline=baseline,
                                            runtime_edges=edges))
        return report.summary()
    cached = analysis.last_report(run_if_missing=False)
    if cached is not None:
        return cached
    return await loop.run_in_executor(None, analysis.last_report)
