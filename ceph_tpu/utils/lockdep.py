"""lockdep: runtime lock-ordering cycle detection.

Behavioral mirror of reference src/common/lockdep.cc (408 LoC): every
named lock acquisition records "held -> acquiring" ordering edges in a
global graph; an acquisition that would close a cycle raises immediately
with both conflicting chains — turning potential deadlocks into loud
failures at first occurrence.  Wraps asyncio locks (our serialization
primitive) the way the reference wraps Mutex.
"""

from __future__ import annotations

import asyncio
from typing import Dict, List, Optional, Set


class LockCycleError(RuntimeError):
    pass


# op-trace seam (graft-trace): called as hook(lock_name, phase) with
# phase "wait" just before acquisition and "acquired" just after, so
# lock-wait time lands on the current op's event timeline without any
# per-call-site instrumentation.  Installed by ceph_tpu.cluster
# .optracker at import; the default None keeps DepLock standalone.
TRACE_HOOK = None


class LockDep:
    _instance: Optional["LockDep"] = None

    def __init__(self):
        self.edges: Dict[str, Set[str]] = {}   # held -> then-acquired
        self.enabled = True

    @classmethod
    def instance(cls) -> "LockDep":
        if cls._instance is None:
            cls._instance = LockDep()
        return cls._instance

    def _path(self, src: str, dst: str) -> Optional[List[str]]:
        """DFS for an existing ordering path src -> ... -> dst."""
        stack = [(src, [src])]
        seen = set()
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            if node in seen:
                continue
            seen.add(node)
            for nxt in self.edges.get(node, ()):
                stack.append((nxt, path + [nxt]))
        return None

    def will_lock(self, name: str, held: List[str]) -> None:
        if not self.enabled:
            return
        for h in held:
            if h == name:
                continue
            # adding h -> name; a cycle exists if name -> ... -> h already
            back = self._path(name, h)
            if back is not None:
                raise LockCycleError(
                    f"lock ordering cycle: acquiring {name!r} while "
                    f"holding {h!r}, but existing order is "
                    f"{' -> '.join(back)}")
            self.edges.setdefault(h, set()).add(name)

    def reset(self) -> None:
        self.edges.clear()

    def dump(self) -> Dict[str, object]:
        """The observed runtime lock graph, JSON-shaped for the admin
        socket (`lockdep dump`) and for merging into graftlint's static
        graph (scripts/graftlint.py --runtime-edges)."""
        return {
            "edges": {h: sorted(nxt) for h, nxt in sorted(self.edges.items())},
            "locks": sorted(set(self.edges) |
                            {n for nxt in self.edges.values() for n in nxt}),
            "held": {str(k): list(v) for k, v in DepLock._held.items() if v},
            "enabled": self.enabled,
        }


class DepLock:
    """An asyncio.Lock with lockdep tracking (named, per-task held set)."""

    _held: Dict[int, List[str]] = {}

    def __init__(self, name: str):
        self.name = name
        self._lock = asyncio.Lock()

    def _task_key(self) -> int:
        return id(asyncio.current_task())

    async def __aenter__(self):
        key = self._task_key()
        held = DepLock._held.setdefault(key, [])
        LockDep.instance().will_lock(self.name, held)
        hook = TRACE_HOOK
        if hook is not None:
            hook(self.name, "wait")
        await self._lock.acquire()
        if hook is not None:
            hook(self.name, "acquired")
        held.append(self.name)
        return self

    async def __aexit__(self, *exc):
        key = self._task_key()
        held = DepLock._held.get(key, [])
        # pop the MOST RECENT occurrence: releases unwind LIFO, and
        # list.remove would drop the first (outermost) entry, corrupting
        # the per-task stack whenever same-named locks nest
        for i in range(len(held) - 1, -1, -1):
            if held[i] == self.name:
                del held[i]
                break
        if not held:
            DepLock._held.pop(key, None)
        self._lock.release()
        return False
