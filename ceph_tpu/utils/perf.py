"""Perf counters (reference src/common/perf_counters.cc).

Per-daemon registry of named counters: u64 counters, time sums, and
long-running averages (avgcount/sum pairs), dumped as JSON-able dicts — the
"perf dump" admin-socket surface.

Round 6 telemetry extensions mirroring the reference more closely:

- typed schemas (``add_u64``/``add_time``/``add_histogram``): unit
  (none/bytes), priority, and description per counter, served by
  ``perf schema`` exactly like PerfCountersBuilder's type/unit/prio
  metadata (src/common/perf_counters.h PERFCOUNTER_* flags);
- time counters carry last/min/max alongside avgcount/sum (the
  reference's PERFCOUNTER_TIME + LONGRUNAVG pairing);
- ``PerfHistogram``: power-of-2 bucketed histograms for latencies and
  I/O sizes (reference src/common/perf_histogram.h with
  SCALE_LOG2 axis config), served by ``perf histogram dump``;
- ``PerfCountersCollection`` is thread-safe and supports ``remove()``
  so daemons deregister their counters on shutdown (reference
  PerfCountersCollectionImpl holds m_lock for add/remove/dump).

``KERNELS`` is the process-wide device-kernel instrumentation registry:
the dense-compute layers (ops/crc32c, ec/codec, ec/stripe, crush/mapper)
record invocation counts, bytes processed, and padding waste there, and
every daemon folds it into its own ``perf dump``.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

# counter units (reference unit_t, perf_counters.h)
UNIT_NONE = "none"
UNIT_BYTES = "bytes"
UNIT_SECONDS = "seconds"

# counter priorities (reference PRIO_* in perf_counters.h)
PRIO_CRITICAL = 10
PRIO_INTERESTING = 8
PRIO_USEFUL = 5
PRIO_DEBUGONLY = 0


class PerfHistogram:
    """Power-of-2 bucketed histogram (reference perf_histogram.h,
    SCALE_LOG2): bucket i counts values in [2^i, 2^(i+1)) after scaling.

    ``scale`` maps the recorded value into bucket units first — e.g.
    scale=1e6 buckets a seconds-valued latency by microseconds, the
    reference's op-latency axis config.
    """

    def __init__(self, buckets: int = 32, scale: float = 1.0,
                 unit: str = UNIT_NONE, desc: str = ""):
        self.n_buckets = buckets
        self.scale = scale
        self.unit = unit
        self.desc = desc
        self.buckets: List[int] = [0] * buckets
        self.count = 0
        self.sum = 0.0

    def add(self, value: float) -> None:
        v = int(value * self.scale)
        if v < 1:
            idx = 0
        else:
            idx = min(self.n_buckets - 1, v.bit_length() - 1)
        self.buckets[idx] += 1
        self.count += 1
        self.sum += value

    def reset(self) -> None:
        self.buckets = [0] * self.n_buckets
        self.count = 0
        self.sum = 0.0

    def lower_bounds(self) -> List[int]:
        """Bucket i's inclusive lower bound in SCALED units."""
        return [0] + [1 << i for i in range(1, self.n_buckets)]

    def dump(self) -> Dict:
        return {
            "buckets": list(self.buckets),
            "lower_bounds": self.lower_bounds(),
            "scale": self.scale,
            "count": self.count,
            "sum": self.sum,
        }


class PerfCounters:
    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}
        # name -> [count, sum, last, min, max]
        self._avgs: Dict[str, list] = {}
        self._hists: Dict[str, PerfHistogram] = {}
        # name -> {"type", "unit", "priority", "description"}
        self._schema: Dict[str, Dict] = {}

    # -- schema declarations (PerfCountersBuilder analog) -------------------

    def _declare(self, name: str, ctype: str, unit: str, prio: int,
                 desc: str) -> None:
        self._schema[name] = {"type": ctype, "unit": unit,
                              "priority": prio, "description": desc}

    def add_u64(self, name: str, unit: str = UNIT_NONE,
                prio: int = PRIO_USEFUL, desc: str = "") -> None:
        with self._lock:
            self._declare(name, "u64", unit, prio, desc)
            self._counters.setdefault(name, 0)

    def add_time(self, name: str, prio: int = PRIO_USEFUL,
                 desc: str = "") -> None:
        with self._lock:
            self._declare(name, "time_avg", UNIT_SECONDS, prio, desc)
            self._avgs.setdefault(name, [0, 0.0, 0.0, None, None])

    def add_histogram(self, name: str, buckets: int = 32,
                      scale: float = 1.0, unit: str = UNIT_NONE,
                      prio: int = PRIO_USEFUL,
                      desc: str = "") -> PerfHistogram:
        with self._lock:
            self._declare(name, "histogram", unit, prio, desc)
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = PerfHistogram(
                    buckets=buckets, scale=scale, unit=unit, desc=desc)
            return h

    # -- updates -------------------------------------------------------------

    def inc(self, name: str, amount: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + amount

    def set(self, name: str, value: int) -> None:
        with self._lock:
            self._counters[name] = value

    def get(self, name: str) -> int:
        with self._lock:
            return self._counters.get(name, 0)

    def tinc(self, name: str, seconds: float) -> None:
        """Time/average counter (avgcount + sum + last/min/max, like
        PERFCOUNTER_TIME|PERFCOUNTER_LONGRUNAVG)."""
        with self._lock:
            entry = self._avgs.setdefault(name, [0, 0.0, 0.0, None, None])
            entry[0] += 1
            entry[1] += seconds
            entry[2] = seconds
            entry[3] = seconds if entry[3] is None \
                else min(entry[3], seconds)
            entry[4] = seconds if entry[4] is None \
                else max(entry[4], seconds)

    def hinc(self, name: str, value: float) -> None:
        """Histogram insert; auto-declares a default log2 histogram for
        an undeclared name (unschema'd counters stay usable, like inc)."""
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = PerfHistogram()
                self._declare(name, "histogram", UNIT_NONE,
                              PRIO_USEFUL, "")
            h.add(value)

    def time(self, name: str):
        """Context manager timing a block into a tinc counter."""
        perf = self

        class _Timer:
            def __enter__(self):
                self.t0 = time.perf_counter()
                return self

            def __exit__(self, *exc):
                perf.tinc(name, time.perf_counter() - self.t0)
                return False

        return _Timer()

    def reset(self) -> None:
        """Zero every value, keeping schemas (reference 'perf reset')."""
        with self._lock:
            for k in self._counters:
                self._counters[k] = 0
            for entry in self._avgs.values():
                entry[:] = [0, 0.0, 0.0, None, None]
            for h in self._hists.values():
                h.reset()

    # -- dump surfaces -------------------------------------------------------

    def dump(self) -> Dict:
        with self._lock:
            out: Dict = dict(self._counters)
            for k, (count, total, last, mn, mx) in self._avgs.items():
                out[k] = {"avgcount": count, "sum": total, "last": last,
                          "min": mn, "max": mx}
            for k, h in self._hists.items():
                out[k] = h.dump()
            return {self.name: out}

    def dump_histograms(self) -> Dict:
        """Histogram-only view (reference 'perf histogram dump')."""
        with self._lock:
            return {self.name: {k: h.dump()
                                for k, h in self._hists.items()}}

    def dump_critical(self, min_prio: int = PRIO_INTERESTING) -> Dict:
        """High-priority counters only (reference prio_adjust on the
        mgr report path): the postmortem bundle's perf slice — small
        enough to snapshot per daemon at trigger time without dragging
        the full dump (histograms excluded; they're bulk, not triage)."""
        with self._lock:
            out: Dict = {}
            for k, v in self._counters.items():
                meta = self._schema.get(k)
                if meta is None or meta["priority"] >= min_prio:
                    out[k] = v
            for k, (count, total, last, mn, mx) in self._avgs.items():
                meta = self._schema.get(k)
                if meta is None or meta["priority"] >= min_prio:
                    out[k] = {"avgcount": count, "sum": total,
                              "last": last, "min": mn, "max": mx}
            return {self.name: out}

    def dump_schema(self) -> Dict:
        """Counter metadata (reference 'perf schema')."""
        with self._lock:
            schema = dict(self._schema)
            # untyped counters surface with inferred defaults so the
            # schema always covers the dump
            for k in self._counters:
                schema.setdefault(k, {"type": "u64", "unit": UNIT_NONE,
                                      "priority": PRIO_USEFUL,
                                      "description": ""})
            for k in self._avgs:
                schema.setdefault(k, {"type": "time_avg",
                                      "unit": UNIT_SECONDS,
                                      "priority": PRIO_USEFUL,
                                      "description": ""})
            return {self.name: schema}


class PerfCountersCollection:
    """Registry of all PerfCounters in a daemon (perf dump aggregates).

    Thread-safe: create/register/remove/dump serialize on one lock
    (reference PerfCountersCollectionImpl m_lock) — daemons mutate the
    registry from the event loop while device-compute executors read it.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._all: Dict[str, PerfCounters] = {}
        self._shared: set = set()

    def create(self, name: str) -> PerfCounters:
        pc = PerfCounters(name)
        with self._lock:
            self._all[name] = pc
        return pc

    def register(self, pc: PerfCounters,
                 shared: bool = True) -> PerfCounters:
        """Adopt an existing PerfCounters (e.g. the process-wide KERNELS
        registry) into this daemon's dump.  ``shared`` counters are
        excluded from this collection's reset(): one daemon's
        'perf reset' must not wipe telemetry every other daemon in the
        process reads from the same registry."""
        with self._lock:
            self._all[pc.name] = pc
            if shared:
                self._shared.add(pc.name)
            else:
                self._shared.discard(pc.name)
        return pc

    def get(self, name: str) -> Optional[PerfCounters]:
        with self._lock:
            return self._all.get(name)

    def remove(self, name: str) -> None:
        """Deregister on daemon shutdown (reference remove() path)."""
        with self._lock:
            self._all.pop(name, None)
            self._shared.discard(name)

    def _snapshot(self, skip_shared: bool = False):
        with self._lock:
            return [pc for name, pc in self._all.items()
                    if not (skip_shared and name in self._shared)]

    def dump(self) -> Dict:
        out: Dict = {}
        for pc in self._snapshot():
            out.update(pc.dump())
        return out

    def dump_histograms(self) -> Dict:
        out: Dict = {}
        for pc in self._snapshot():
            out.update(pc.dump_histograms())
        return out

    def dump_schema(self) -> Dict:
        out: Dict = {}
        for pc in self._snapshot():
            out.update(pc.dump_schema())
        return out

    def dump_critical(self, min_prio: int = PRIO_INTERESTING) -> Dict:
        out: Dict = {}
        for pc in self._snapshot():
            out.update(pc.dump_critical(min_prio=min_prio))
        return out

    def reset(self) -> None:
        for pc in self._snapshot(skip_shared=True):
            pc.reset()


# Process-wide device-kernel instrumentation (one per process like the
# reference's per-process g_ceph_context counters): the dense-compute
# layers are libraries shared by every daemon in the process, so their
# counters live here and each daemon folds them into its perf dump.
KERNELS = PerfCounters("device_kernels")
