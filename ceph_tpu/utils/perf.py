"""Perf counters (reference src/common/perf_counters.cc).

Per-daemon registry of named counters: u64 counters, time sums, and
long-running averages (avgcount/sum pairs), dumped as JSON-able dicts — the
"perf dump" admin-socket surface.
"""

from __future__ import annotations

import threading
import time
from typing import Dict


class PerfCounters:
    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}
        self._avgs: Dict[str, list] = {}  # name -> [count, sum]

    def inc(self, name: str, amount: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + amount

    def set(self, name: str, value: int) -> None:
        with self._lock:
            self._counters[name] = value

    def get(self, name: str) -> int:
        with self._lock:
            return self._counters.get(name, 0)

    def tinc(self, name: str, seconds: float) -> None:
        """Time/average counter (avgcount + sum, like PERFCOUNTER_TIME)."""
        with self._lock:
            entry = self._avgs.setdefault(name, [0, 0.0])
            entry[0] += 1
            entry[1] += seconds

    def time(self, name: str):
        """Context manager timing a block into a tinc counter."""
        perf = self

        class _Timer:
            def __enter__(self):
                self.t0 = time.perf_counter()
                return self

            def __exit__(self, *exc):
                perf.tinc(name, time.perf_counter() - self.t0)
                return False

        return _Timer()

    def dump(self) -> Dict:
        with self._lock:
            out: Dict = dict(self._counters)
            for k, (count, total) in self._avgs.items():
                out[k] = {"avgcount": count, "sum": total}
            return {self.name: out}


class PerfCountersCollection:
    """Registry of all PerfCounters in a daemon (perf dump aggregates)."""

    def __init__(self):
        self._all: Dict[str, PerfCounters] = {}

    def create(self, name: str) -> PerfCounters:
        pc = PerfCounters(name)
        self._all[name] = pc
        return pc

    def dump(self) -> Dict:
        out: Dict = {}
        for pc in self._all.values():
            out.update(pc.dump())
        return out
