"""Capped exponential backoff with seeded full jitter + AIMD window.

The retry-delay policy for monclient hunting, messenger session
reconnect, mon-command leaderless retries, and objecter resends
(reference: the osdc/Objecter and MonClient backoff knobs; jitter shape
per the classic full-jitter scheme — delay drawn uniformly from
[0, min(cap, base * factor^n)]).  Deterministic when handed a seeded
``random.Random``: chaos scenarios derive one per consumer from the
scenario seed, so retry timing replays with the fault schedule.

``AIMDWindow`` is the client-side congestion window the objecter runs
against OSD admission throttles: multiplicative decrease on an explicit
throttle pushback, additive (1/w per ack) recovery — TCP-Reno-shaped
flow control where the congestion signal is the OSD saying EBUSY
instead of a lost packet.
"""

from __future__ import annotations

import random
from typing import List, Optional


class ExpBackoff:
    def __init__(self, base: float = 0.05, cap: float = 1.0,
                 factor: float = 2.0,
                 rng: Optional[random.Random] = None):
        self.base = base
        self.cap = cap
        self.factor = factor
        self.rng = rng or random.Random()
        self._n = 0

    def next(self) -> float:
        """The next delay: full jitter over the capped exponential
        envelope.  Each call advances the attempt counter."""
        ceiling = min(self.cap, self.base * (self.factor ** self._n))
        self._n += 1
        return self.rng.uniform(0.0, ceiling)

    def reset(self) -> None:
        """Back to attempt 0 (call on success)."""
        self._n = 0

    def schedule(self, n: int) -> List[float]:
        """Preview the next ``n`` delays without consuming real retries
        on a live consumer: runs on a COPY of the rng state."""
        rng = random.Random()
        rng.setstate(self.rng.getstate())
        out = []
        saved = self._n
        for _ in range(n):
            ceiling = min(self.cap, self.base * (self.factor ** saved))
            saved += 1
            out.append(rng.uniform(0.0, ceiling))
        return out


class AIMDWindow:
    """Additive-increase / multiplicative-decrease inflight-op window.

    Starts wide open (``ceiling``): with admission throttles off (the
    default) no pushback ever arrives and the window never constrains
    anything — a provable no-op, like the chaos injectors.  The first
    pushback halves it; each subsequent ack recovers +1/w (one window's
    worth of acks per +1 of window, the Reno congestion-avoidance
    slope)."""

    def __init__(self, ceiling: int):
        self.ceiling = max(1, int(ceiling))
        self.window = float(self.ceiling)
        self.pushbacks = 0

    @property
    def limit(self) -> int:
        return max(1, int(self.window))

    def on_ack(self) -> None:
        self.window = min(float(self.ceiling),
                          self.window + 1.0 / max(self.window, 1.0))

    def on_pushback(self) -> None:
        self.pushbacks += 1
        self.window = max(1.0, self.window / 2.0)
