"""Compressor: the pluggable compression registry.

Behavioral mirror of the reference compressor plugin system
(src/compressor/Compressor.h: Compressor::create(type) with
zlib/snappy/zstd/lz4 plugins loaded like EC plugins) — used by BlueStore
blobs and messenger payloads.  Python's baked-in zlib/lzma/bz2 provide
the codecs; the seam (registry + create + compress/decompress contract)
matches the reference so further codecs slot in.
"""

from __future__ import annotations

import bz2
import lzma
import zlib
from typing import Callable, Dict, Optional, Tuple


class Compressor:
    def __init__(self, name: str,
                 compress: Callable[[bytes], bytes],
                 decompress: Callable[[bytes], bytes]):
        self.name = name
        self._c = compress
        self._d = decompress

    def compress(self, data: bytes) -> bytes:
        return self._c(bytes(data))

    def decompress(self, blob: bytes) -> bytes:
        return self._d(bytes(blob))


_REGISTRY: Dict[str, Compressor] = {}


def register(name: str, compress, decompress) -> None:
    _REGISTRY[name] = Compressor(name, compress, decompress)


def create(name: str) -> Compressor:
    """Compressor::create analog; raises on unknown plugin."""
    try:
        return _REGISTRY[name]
    except KeyError:
        raise ValueError(f"unsupported compressor {name!r} "
                         f"(have {sorted(_REGISTRY)})")


def get_available() -> Tuple[str, ...]:
    return tuple(sorted(_REGISTRY))


register("zlib", lambda d: zlib.compress(d, 6), zlib.decompress)
register("lzma", lzma.compress, lzma.decompress)
register("bz2", bz2.compress, bz2.decompress)
# "snappy" fallback: zlib level 1 (fast path; real snappy is not baked in)
register("snappy", lambda d: zlib.compress(d, 1), zlib.decompress)


def maybe_compress(name: str, data: bytes,
                   required_ratio: float = 0.875) -> Tuple[bool, bytes]:
    """BlueStore-style conditional compression: keep the compressed blob
    only when it beats the required ratio
    (bluestore_compression_required_ratio semantics)."""
    c = create(name)
    blob = c.compress(data)
    if len(blob) <= len(data) * required_ratio:
        return True, blob
    return False, data
