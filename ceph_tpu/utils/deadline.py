"""Client deadline bookkeeping for multi-op front-door transactions.

The librados verbs carry a per-op ``timeout`` end-to-end (PR 7/10:
op_submit caps its attempt budget at the remaining deadline, queues
shed expired work, and the chaos/load "deadline" invariant convicts any
ack arriving past it).  Front-door ops — an RBD striped write, an RGW
multipart complete — fan out into SEVERAL internal RADOS ops; handing
each the full budget would let the transaction ack at N x timeout.

These helpers thread ONE wall deadline through the fan-out: the caller
converts its budget once (``deadline_of``), and every internal op gets
only what remains (``remaining``), which raises TimeoutError the moment
the budget is gone — the op is never submitted, so nothing can ack past
the client's deadline.
"""

from __future__ import annotations

import asyncio
from typing import Optional


def deadline_of(timeout: Optional[float]) -> Optional[float]:
    """Absolute loop-time deadline for a relative budget (None = no
    deadline, the library-default behavior)."""
    if timeout is None:
        return None
    return asyncio.get_event_loop().time() + timeout


def remaining(deadline: Optional[float]) -> Optional[float]:
    """Budget left before ``deadline``; raises TimeoutError when spent
    so an expired transaction stops BEFORE submitting its next op."""
    if deadline is None:
        return None
    left = deadline - asyncio.get_event_loop().time()
    if left <= 0:
        raise TimeoutError("client deadline expired mid-transaction")
    return left
