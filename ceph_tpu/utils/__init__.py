"""Common runtime: typed config schema, perf counters, admin socket."""

from ceph_tpu.utils.admin_socket import AdminSocket  # noqa: F401
from ceph_tpu.utils.backoff import ExpBackoff  # noqa: F401
from ceph_tpu.utils.config import Config, Option  # noqa: F401
from ceph_tpu.utils.lockdep import DepLock, LockCycleError, LockDep  # noqa: F401
from ceph_tpu.utils.perf import (  # noqa: F401
    KERNELS,
    PerfCounters,
    PerfCountersCollection,
    PerfHistogram,
)
