"""Common runtime: typed config schema, perf counters."""

from ceph_tpu.utils.config import Config, Option  # noqa: F401
from ceph_tpu.utils.perf import PerfCounters  # noqa: F401
