"""Vectorized CRUSH mapper: whole-OSDMap placement as one TPU dispatch.

The TPU-native replacement for per-PG scalar crush_do_rule calls (reference
mapper.c:883): every PG is a lane, and the firstn/indep retry loops become
masked fixed-trip loops (SURVEY §3.3's vectorization plan).  Exactness
contract: identical outputs to ScalarMapper (and therefore to the reference
C) for straw2 maps with zero local retries — the reference's 'optimal'
tunables profile.  Straw2 draws use uint32-pair arithmetic with pack-time
Granlund-Montgomery reciprocals (ops/u64pair.py) instead of emulated s64.

Supported: straw2 buckets; TAKE / CHOOSE(LEAF)_FIRSTN / CHOOSE(LEAF)_INDEP /
EMIT / SET_* steps; vary_r / stable / descend_once semantics.  Uniform/list/
tree/straw buckets and nonzero local-retry tunables fall back to the scalar
oracle at the OSDMap layer.
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ceph_tpu.crush.ln import LH_TBL, RH_TBL
from ceph_tpu.crush._ll_table import LL_TBL
from ceph_tpu.crush.types import (
    CRUSH_ITEM_NONE,
    CRUSH_ITEM_UNDEF,
    CrushMap,
    RULE_CHOOSELEAF_FIRSTN,
    RULE_CHOOSELEAF_INDEP,
    RULE_CHOOSE_FIRSTN,
    RULE_CHOOSE_INDEP,
    RULE_EMIT,
    RULE_SET_CHOOSELEAF_STABLE,
    RULE_SET_CHOOSELEAF_TRIES,
    RULE_SET_CHOOSELEAF_VARY_R,
    RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES,
    RULE_SET_CHOOSE_LOCAL_TRIES,
    RULE_SET_CHOOSE_TRIES,
    RULE_TAKE,
)
from ceph_tpu.ops import jenkins, u64pair

U32 = jnp.uint32
I32 = jnp.int32


def _split_u64(vals) -> Tuple[np.ndarray, np.ndarray]:
    v = np.asarray(vals, dtype=np.object_)
    hi = np.array([int(x) >> 32 for x in v], dtype=np.uint32)
    lo = np.array([int(x) & 0xFFFFFFFF for x in v], dtype=np.uint32)
    return hi, lo


class TensorMapper:
    @staticmethod
    def unsupported_reason(cmap: CrushMap):
        """Cheap shape probe: None when this map can run vectorized,
        else the rejection reason — the SAME conditions __init__
        enforces, minus the array/device construction (mon `status`
        answers placement_path with this, not a full build)."""
        t = cmap.tunables
        if t.choose_local_tries or t.choose_local_fallback_tries:
            return "legacy tunables (local retries)"
        ids = sorted(cmap.buckets, reverse=True)
        if ids != [-1 - i for i in range(len(ids))]:
            return "sparse bucket ids"
        for b in cmap.buckets.values():
            if b.alg != "straw2":
                return f"non-straw2 bucket ({b.alg})"
        return None

    def __init__(self, cmap: CrushMap, chunk: int = 1 << 16):
        self.map = cmap
        self.chunk = chunk
        t = cmap.tunables
        if t.choose_local_tries or t.choose_local_fallback_tries:
            raise NotImplementedError(
                "vectorized mapper requires zero local retries (optimal "
                "tunables); use ScalarMapper for legacy profiles")
        ids = sorted(cmap.buckets, reverse=True)
        self.nb = len(ids)
        assert ids == [-1 - i for i in range(self.nb)], "bucket ids must be dense"
        max_sz = max(b.size for b in cmap.buckets.values())
        items = np.zeros((self.nb, max_sz), dtype=np.int32)
        weights = np.zeros((self.nb, max_sz), dtype=np.uint32)
        sizes = np.zeros(self.nb, dtype=np.int32)
        btypes = np.zeros(self.nb, dtype=np.int32)
        recip_hi = np.zeros((self.nb, max_sz), dtype=np.uint32)
        recip_lo = np.zeros((self.nb, max_sz), dtype=np.uint32)
        for bid, b in cmap.buckets.items():
            row = -1 - bid
            if b.alg != "straw2":
                raise NotImplementedError(
                    f"vectorized mapper supports straw2 buckets, not {b.alg}")
            sizes[row] = b.size
            btypes[row] = b.type
            items[row, : b.size] = b.items
            weights[row, : b.size] = b.weights
            for i, w in enumerate(b.weights):
                recip_hi[row, i], recip_lo[row, i] = self._recip_u64(int(w))
        self.items = jnp.asarray(items)
        self.iweights = jnp.asarray(weights)
        self.sizes = jnp.asarray(sizes)
        self.btypes = jnp.asarray(btypes)
        self.recip_hi = jnp.asarray(recip_hi)
        self.recip_lo = jnp.asarray(recip_lo)
        self._items_np = items
        self._iweights_np = weights
        # choose_args override tensors (inactive placeholders; see
        # _activate_choose_args)
        self._ca_active = False
        self._ca_pdim = 1
        self._ca_ids = jnp.zeros((1, 1), dtype=I32)
        self._ca_w = jnp.zeros((1, 1), dtype=U32)
        self._ca_rh = jnp.zeros((1, 1), dtype=U32)
        self._ca_rl = jnp.zeros((1, 1), dtype=U32)
        self._ca_pmax = jnp.zeros(1, dtype=I32)
        self._ca_cache: Dict = {}
        self.max_devices = cmap.max_devices
        self.max_depth = cmap.max_depth()
        rh_hi, rh_lo = _split_u64(RH_TBL)
        lh_hi, lh_lo = _split_u64(LH_TBL)
        ll_hi, ll_lo = _split_u64(LL_TBL)
        self._rh = (jnp.asarray(rh_hi), jnp.asarray(rh_lo))
        self._lh = (jnp.asarray(lh_hi), jnp.asarray(lh_lo))
        self._ll = (jnp.asarray(ll_hi), jnp.asarray(ll_lo))
        self._rh_np = _split_u64(RH_TBL)
        self._lh_np = _split_u64(LH_TBL)
        self._ll_np = _split_u64(LL_TBL)
        # precomputed |ln| table (512 KiB): one gather on the hot path.
        # (A select-tree variant, _ln_neg_tree, is exact and ~14x faster per
        # element but blows up compile time when inlined in the retry loops.
        # A Pallas rewrite was evaluated in round 3 for the sibling gf8
        # matmul and measured ~7x SLOWER than XLA's fusion — see
        # ops/gf8_pallas.py — so the gather path stays; at 239M mappings/s
        # for the 10k-OSD/1M-PG benchmark it is not the bottleneck.)
        from ceph_tpu.crush.ln import crush_ln

        ln_neg = [0x1000000000000 - crush_ln(u) for u in range(0x10000)]
        lnn_hi, lnn_lo = _split_u64(ln_neg)
        self._lnn = (jnp.asarray(lnn_hi), jnp.asarray(lnn_lo))
        self._build_fast_straw2(items, weights, sizes, ln_neg)
        # per-bucket scalar metadata as ONE row-gathered tensor: element
        # gathers (sizes[bno], btypes[bno], ...) scalarize on TPU (~0.5 ms
        # per 64 Ki lanes) while row gathers vectorize (~76 us); packing
        # [size, type, wbase, rep] into one (nb, 4) row costs one row
        # gather where four element gathers used to run
        meta = np.zeros((self.nb, 4), dtype=np.int32)
        meta[:, 0] = sizes
        meta[:, 1] = btypes
        if self._fast:
            meta[:, 2] = (self._wclass_np.astype(np.int64) << 17).astype(
                np.int32)
            meta[:, 3] = np.asarray(self._rep)[self._wclass_np]
        self._meta = jnp.asarray(meta)
        # bound per-dispatch memory: lanes * max_bucket_size * ~32 u32 temps
        self.chunk = max(512, min(chunk, (1 << 24) // max(max_sz, 1)))
        self._compiled: Dict = {}

    # ------------------------------------------------- fast straw2 tables

    _MAX_WEIGHT_CLASSES = 64

    def _build_fast_straw2(self, items, weights, sizes, ln_neg):
        """Precompute the gather-free straw2 path (round 5).

        The honest (on-device-loop) benchmark showed the per-(lane, item)
        gathers from the 64 Ki |ln| table scalarize on TPU and cost ~37 ms
        per straw2 call at 64 Ki lanes — ~100% of rule runtime.  For
        buckets whose item weights are UNIFORM, the winning item can be
        found without evaluating draws at all: draw = div64_s64(ln, w) is
        a non-decreasing function g of u = hash & 0xffff (crush_ln is
        non-decreasing except at the single u = 65535 table anomaly), so
        "first item with draw == max draw" (mapper.c:322-367 keeps the
        first strict maximum) equals "first item whose u lies in the top
        plateau of g".  Host-side, per distinct bucket weight, we build
        the plateau-start table on a doubled domain u' = 2u (u = 65535
        maps to an odd/even representative that is order-isomorphic to
        g(65535), preserving exact tie semantics with the anomaly), and
        the device does: u'max = max(u'), T = P2[u'max], winner = first
        item with u' >= T — ONE lane-sized gather instead of two
        (lane x item)-sized ones.  Bit-exact vs the C semantics by
        construction; golden tests cover it.

        Maps with any non-uniform bucket (e.g. balancer weight_set
        overrides) keep the general |ln|-gather path.
        """
        nb = items.shape[0]
        self._fast = False
        self._wclass_np = None
        # placeholders so _tensor_args stays total on non-fast maps
        self._p2flat = jnp.zeros(1, dtype=I32)
        self._wclass = jnp.zeros(1, dtype=I32)
        self._rep = jnp.zeros(1, dtype=I32)
        # uniform check per bucket (over the first `size` items)
        class_weights = []
        wclass = np.zeros(nb, dtype=np.int32)
        for row in range(nb):
            sz = int(sizes[row])
            ws = weights[row, :sz]
            if sz == 0:
                wclass[row] = 0 if class_weights else -1
                continue
            w0 = int(ws[0])
            if w0 == 0 or not np.all(ws == w0):
                return  # non-uniform bucket: general path for this map
            if w0 not in class_weights:
                class_weights.append(w0)
            wclass[row] = class_weights.index(w0)
        if not class_weights or len(class_weights) > self._MAX_WEIGHT_CLASSES:
            return
        # empty buckets with no class yet: point at class 0 (never drawn)
        wclass[wclass < 0] = 0
        lnn = np.array(ln_neg, dtype=np.uint64)
        # the construction below relies on crush_ln being non-decreasing on
        # [0, 65534] (the single decreasing site is 65534 -> 65535)
        assert np.all(np.diff(lnn[:65535].astype(np.int64)) <= 0)
        p2_all = np.zeros((len(class_weights), 1 << 17), dtype=np.int32)
        rep_all = np.zeros(len(class_weights), dtype=np.int32)
        for ci, w in enumerate(class_weights):
            # g(u) = -draw = ln_neg[u] // w, non-increasing on [0, 65534]
            g = (lnn // np.uint64(w)).astype(np.int64)
            body, g_last = g[:65535], int(g[65535])
            # plateau starts on the monotone body (g non-increasing)
            change = np.empty(65535, dtype=bool)
            change[0] = True
            change[1:] = body[1:] != body[:-1]
            starts = np.maximum.accumulate(
                np.where(change, np.arange(65535), 0))
            p2 = np.zeros(1 << 17, dtype=np.int32)
            p2[0::2][:65535] = 2 * starts
            p2[1::2] = np.arange(1, 1 << 17, 2)  # odd slots: own plateau
            # u = 65535 anomaly: place g_last order-exactly among the body
            # (body is DEscending in u; draws AScend).  Find its plateau.
            asc = body[::-1]  # ascending g
            import bisect

            lo = bisect.bisect_left(asc, g_last)
            hi_i = bisect.bisect_right(asc, g_last)
            if lo != hi_i:
                # ties an existing plateau [a, b] (in u-domain)
                a = 65534 - (hi_i - 1)
                b = 65534 - lo
                rep = 2 * b        # behaves as the plateau's largest u
                p2[rep] = 2 * a    # plateau start covers the anomaly rep
                rep_all[ci] = rep
                p2[2 * 65535] = 2 * a  # if u'max==2*65535 slot ever read
            else:
                # unique value: sits between two plateaus; `lo` entries of
                # the body have g < g_last (draw greater), and they occupy
                # the largest u values, so the first such u-index is:
                a = 65535 - lo
                rep = 2 * a - 1 if a > 0 else -1
                rep_all[ci] = rep
                if rep >= 0:
                    p2[rep] = rep  # its own (singleton) plateau
            p2_all[ci] = p2
        self._fast = True
        self._wclass_np = wclass
        self._p2flat = jnp.asarray(p2_all.reshape(-1))
        self._wclass = jnp.asarray(wclass)
        self._rep = jnp.asarray(rep_all)

    # ------------------------------------------------------- choose_args

    @staticmethod
    def _recip_u64(w: int) -> Tuple[int, int]:
        if w == 1:
            r = 2**64 - 1
        elif w > 1:
            r = 2**64 // w
        else:
            r = 0
        return r >> 32, r & 0xFFFFFFFF

    def _build_ca_tensors(self, cargs) -> Tuple[Dict, int]:
        """Device tensors for a choose_args set (reference crush.h:273-278
        crush_choose_arg: per-bucket weight_set positions + id remaps,
        consumed by bucket_straw2_choose via mapper.c:302-320).

        Layout: ids (nb, S) replace the HASH input (chosen items stay the
        bucket's real items); weights flatten to (nb*P, S) rows indexed by
        bno*P + min(position, pmax[bno]), with precomputed u64 reciprocals
        for the draw division."""
        nb, S = self._items_np.shape
        P = 1
        for a in cargs.values():
            if a.weight_set:
                P = max(P, len(a.weight_set))
        ids = self._items_np.astype(np.int64).copy()
        w = np.repeat(self._iweights_np[:, None, :], P, axis=1).copy()
        pmax = np.zeros(nb, dtype=np.int32)
        for bid, arg in cargs.items():
            row = -1 - bid
            if not (0 <= row < nb):
                continue
            if arg.ids:
                ids[row, :len(arg.ids)] = arg.ids
            if arg.weight_set:
                # positions beyond len(weight_set) are never selected:
                # _straw2 clamps with pmax, so no padding is needed
                for p, ws in enumerate(arg.weight_set):
                    w[row, p, :len(ws)] = ws
                pmax[row] = len(arg.weight_set) - 1
        rh = np.zeros((nb, P, S), dtype=np.uint32)
        rl = np.zeros((nb, P, S), dtype=np.uint32)
        recip_memo: Dict[int, Tuple[int, int]] = {}
        for idx, wv in np.ndenumerate(w):
            wv = int(wv)
            pair = recip_memo.get(wv)
            if pair is None:
                pair = recip_memo[wv] = self._recip_u64(wv)
            rh[idx], rl[idx] = pair
        tensors = {
            "_ca_ids": jnp.asarray(ids.astype(np.int32)),
            "_ca_w": jnp.asarray(w.reshape(nb * P, S).astype(np.uint32)),
            "_ca_rh": jnp.asarray(rh.reshape(nb * P, S)),
            "_ca_rl": jnp.asarray(rl.reshape(nb * P, S)),
            "_ca_pmax": jnp.asarray(pmax),
        }
        return tensors, P

    def _resolve_choose_args(self, choose_args):
        """-> (cache_key, tensors, P) for a name or {bucket_id: ChooseArg}."""
        if isinstance(choose_args, str):
            cargs = self.map.choose_args[choose_args]
            key = choose_args
        else:
            cargs = choose_args
            # content-addressed: a balancer loop passing fresh weights for
            # the same buckets must never hit a stale tensor set
            key = ("dict", tuple(sorted(
                (bid,
                 tuple(a.ids) if a.ids else None,
                 tuple(tuple(ws) for ws in a.weight_set)
                 if a.weight_set else None)
                for bid, a in cargs.items())))
        cached = self._ca_cache.get(key)
        if cached is None:
            cached = self._ca_cache[key] = self._build_ca_tensors(cargs)
            # bound the content-addressed tensor cache (balancer loops
            # mint a fresh weight set per iteration)
            while len(self._ca_cache) > 16:
                self._ca_cache.pop(next(iter(self._ca_cache)))
        return key, cached[0], cached[1]

    # ------------------------------------------------------------------ ln

    @staticmethod
    def _tree_lookup(table: np.ndarray, idx, nbits: int):
        """Constant-select-tree table lookup: TPU gathers scalarize, but a
        log2(N)-deep where-tree over scalar constants fuses into one
        elementwise pass (~14x faster than gather at 16M elements)."""
        n = 1 << nbits
        level = [np.uint32(int(v)) for v in table] + \
                [np.uint32(0)] * (n - len(table))
        bits = [(idx >> b) & 1 for b in range(nbits)]
        for b in range(nbits):
            sel = bits[b] == 1
            level = [jnp.where(sel, level[j + 1], level[j])
                     for j in range(0, len(level), 2)]
        return level[0]

    def _ln_neg_tree(self, u):
        """Gather-free |ln|: arithmetic path with select-tree LUTs."""
        x = (u + 1).astype(U32)
        no_msb = (x & 0x18000) == 0
        bits = (jax.lax.clz((x & 0x1FFFF).astype(U32)).astype(I32) - 16)
        bits = jnp.where(no_msb, bits, 0).astype(U32)
        x = (x << bits).astype(U32)
        iexpon = (15 - bits.astype(I32)).astype(U32)
        k = (x >> 8) - 128
        rh_hi = self._tree_lookup(self._rh_np[0], k, 8)
        rh_lo = self._tree_lookup(self._rh_np[1], k, 8)
        r0 = rh_lo & 0xFFFF
        r1 = rh_lo >> 16
        r2 = rh_hi & 0xFFFF
        r3 = rh_hi >> 16
        p0 = x * r0
        t1 = x * r1 + (p0 >> 16)
        t2 = x * r2 + (t1 >> 16)
        t3 = x * r3 + (t2 >> 16)
        index2 = t3 & 0xFF
        lh = (self._tree_lookup(self._lh_np[0], k, 8),
              self._tree_lookup(self._lh_np[1], k, 8))
        ll = (self._tree_lookup(self._ll_np[0], index2, 8),
              self._tree_lookup(self._ll_np[1], index2, 8))
        s = u64pair.shr(u64pair.add(lh, ll), 4)
        res = u64pair.add((iexpon << 12, jnp.zeros_like(x)), s)
        return u64pair.sub((jnp.full_like(x, 0x10000), jnp.zeros_like(x)), res)

    def _ln_neg(self, u):
        """|ln| = 0x1000000000000 - crush_ln(u), as a uint32 pair.

        Exact mirror of reference mapper.c:248-290 in 32-bit ops.
        """
        x = (u + 1).astype(U32)
        no_msb = (x & 0x18000) == 0
        bits = (jax.lax.clz((x & 0x1FFFF).astype(U32)).astype(I32) - 16)
        bits = jnp.where(no_msb, bits, 0).astype(U32)
        x = (x << bits).astype(U32)
        iexpon = (15 - bits.astype(I32)).astype(U32)
        k = (x >> 8) - 128
        rh_hi = self._rh[0][k]
        rh_lo = self._rh[1][k]
        # xl64 = (x * RH) >> 48 via 16-bit limbs of RH
        r0 = rh_lo & 0xFFFF
        r1 = rh_lo >> 16
        r2 = rh_hi & 0xFFFF
        r3 = rh_hi >> 16
        p0 = x * r0
        t1 = x * r1 + (p0 >> 16)
        t2 = x * r2 + (t1 >> 16)
        t3 = x * r3 + (t2 >> 16)
        index2 = t3 & 0xFF
        s = u64pair.add((self._lh[0][k], self._lh[1][k]),
                        (self._ll[0][index2], self._ll[1][index2]))
        s = u64pair.shr(s, 4)
        res = u64pair.add((iexpon << 12, jnp.zeros_like(x)), s)
        return u64pair.sub((jnp.full_like(x, 0x10000), jnp.zeros_like(x)), res)

    # -------------------------------------------------------------- straw2

    def _straw2(self, bno, x, r, wpos=None):
        """bucket_straw2_choose (mapper.c:322-367) over a lane batch.

        bno (L,), x (L,) uint32, r (L,) int32 -> chosen item (L,) int32.
        ``wpos`` (L,) is the output position selecting the choose_args
        weight_set row (mapper.c:302-320); ignored without choose_args.

        Uniform-weight maps take the gather-free plateau path (see
        _build_fast_straw2); choose_args overrides and non-uniform maps
        evaluate |ln| draws via table gather.
        """
        it = self.items[bno]                      # (L, S)
        meta = self._meta[bno]                    # (L, 4) row gather
        sz = meta[:, 0]
        if self._ca_active:
            # choose_args: alternate ids feed the hash (the chosen item
            # stays the bucket's real item), alternate weights feed the
            # draws
            hash_ids = self._ca_ids[bno]
            if wpos is None:
                wpos = jnp.zeros_like(bno)
            p = jnp.minimum(wpos, self._ca_pmax[bno])
            row = bno * self._ca_pdim + p
            wt = self._ca_w[row]                  # (L, S)
            u = jenkins.hash3(x[:, None], hash_ids.astype(U32),
                              r.astype(U32)[:, None]) & 0xFFFF
            pos = jnp.arange(it.shape[1], dtype=I32)
            invalid = (wt == 0) | (pos[None, :] >= sz[:, None])
            return self._draw_argmin(it, u, wt, self._ca_rh[row],
                                     self._ca_rl[row], invalid)
        u = jenkins.hash3(x[:, None], it.astype(U32), r.astype(U32)[:, None]) & 0xFFFF
        pos = jnp.arange(it.shape[1], dtype=I32)
        if self._fast:
            # uniform weights are nonzero by construction: invalid = padding
            invalid = pos[None, :] >= sz[:, None]
            u2 = jnp.where(u == 65535, meta[:, 3:4], (2 * u).astype(I32))
            u2 = jnp.where(invalid, I32(-1), u2)
            umax = u2.max(axis=1)
            tidx = meta[:, 2] + jnp.clip(umax, 0)
            thresh = self._p2flat[tidx]           # (L,) gather
            win = u2 >= thresh[:, None]
            idx = jnp.argmax(win, axis=1)
            return jnp.take_along_axis(it, idx[:, None], axis=1)[:, 0]
        wt = self.iweights[bno]
        invalid = (wt == 0) | (pos[None, :] >= sz[:, None])
        return self._draw_argmin(it, u, wt, self.recip_hi[bno],
                                 self.recip_lo[bno], invalid)

    def _draw_argmin(self, it, u, wt, rh, rl, invalid):
        """Shared |ln|-draw evaluation + first-occurrence two-level
        argmin (draw > high_draw semantics) over (L, S) lanes."""
        n = (self._lnn[0][u], self._lnn[1][u])
        qh, ql = u64pair.div_by_recip(n, wt, rh, rl)
        qh = jnp.where(invalid, jnp.uint32(0xFFFFFFFF), qh)
        ql = jnp.where(invalid, jnp.uint32(0xFFFFFFFF), ql)
        m1 = qh.min(axis=1, keepdims=True)
        c1 = qh == m1
        ql2 = jnp.where(c1, ql, jnp.uint32(0xFFFFFFFF))
        m2 = ql2.min(axis=1, keepdims=True)
        winner = c1 & (ql2 == m2)
        idx = jnp.argmax(winner, axis=1)
        return jnp.take_along_axis(it, idx[:, None], axis=1)[:, 0]

    # ------------------------------------------------------------- helpers

    def _is_out(self, weights, item, x):
        """is_out (mapper.c:407-421); item (L,) int32 device ids."""
        idx = jnp.clip(item, 0, self.max_devices - 1)
        w = weights[idx]
        over = item >= self.max_devices
        hashed = (jenkins.hash2(x, item.astype(U32)) & 0xFFFF) >= w
        return over | (w == 0) | ((w < 0x10000) & hashed)

    def _descend(self, start, x, r, type_, wpos=None):
        """Descend intervening buckets until an item of type_ (or dead end).

        Returns (item, hit_empty).  Mirrors the retry_bucket descent of
        choose_firstn/indep (same r at every level for straw2 maps).
        """
        cur = start
        hit_empty = jnp.zeros(x.shape, dtype=bool)
        for _ in range(self.max_depth):
            is_b = cur < 0
            bno = jnp.clip(-1 - cur, 0, self.nb - 1)
            meta = self._meta[bno]
            need = is_b & (meta[:, 1] != type_)
            empty = need & (meta[:, 0] == 0)
            hit_empty = hit_empty | empty
            nxt = self._straw2(bno, x, r, wpos)
            cur = jnp.where(need & ~empty, nxt, cur)
        return cur, hit_empty

    def _bad_item(self, cur, type_):
        bno = jnp.clip(-1 - cur, 0, self.nb - 1)
        wrong_bucket = (cur < 0) & (self._meta[bno][:, 1] != type_)
        wrong_dev = (cur >= 0) & ((type_ != 0) | (cur >= self.max_devices))
        return wrong_bucket | wrong_dev

    # -------------------------------------------------------------- firstn

    def _leaf_firstn(self, host, x, inner_rep, sub_r, tries, out2, cnt, act):
        """Recursive chooseleaf descent (single stable rep).

        Mirrors the recursive crush_choose_firstn call at mapper.c:556-573.
        Returns (leaf, ok).
        """
        already = host >= 0  # "we already have a leaf"
        leaf = jnp.where(already, host, CRUSH_ITEM_NONE)
        done = ~act | already
        lftotal = jnp.zeros_like(x, dtype=I32)

        def cond(s):
            leaf, done, lftotal = s
            return jnp.any(~done & (lftotal < tries))

        def body(s):
            leaf, done, lftotal = s
            live = ~done & (lftotal < tries)
            r2 = inner_rep + sub_r + lftotal
            # choose_args position: the recursing slot (scalar passes the
            # outer outpos through to the leaf's bucket_choose)
            cur, hit_empty = self._descend(host, x, r2, 0, cnt)
            bad = self._bad_item(cur, 0) & ~hit_empty
            coll = jnp.any(
                (out2 == cur[:, None])
                & (jnp.arange(out2.shape[1])[None, :] < cnt[:, None]),
                axis=1,
            )
            rej = self._is_out(self._w, cur, x) | hit_empty
            ok = live & ~bad & ~coll & ~rej
            leaf = jnp.where(ok, cur, leaf)
            done = done | ok | (live & bad)  # bad -> inner skip_rep
            lftotal = jnp.where(live & ~ok & ~bad, lftotal + 1, lftotal)
            return leaf, done, lftotal

        leaf, done, _ = jax.lax.while_loop(cond, body, (leaf, done, lftotal))
        ok = act & (already | (leaf != CRUSH_ITEM_NONE))
        return leaf, ok

    def _choose_firstn_vec(self, take, x, numrep, type_, tries, recurse_tries,
                           recurse_to_leaf, vary_r, stable, lane_mask):
        """crush_choose_firstn (mapper.c:443-631), zero local retries."""
        L = x.shape[0]
        out = jnp.full((L, numrep), CRUSH_ITEM_NONE, dtype=I32)
        out2 = jnp.full((L, numrep), CRUSH_ITEM_NONE, dtype=I32)
        cnt = jnp.zeros(L, dtype=I32)
        for rep in range(numrep):
            def cond(s):
                out, out2, cnt, ftotal, done = s
                return jnp.any(~done & (ftotal < tries))

            def body(s, rep=rep):
                out, out2, cnt, ftotal, done = s
                live = ~done & (ftotal < tries)
                r = rep + ftotal
                # choose_args position = the slot being filled (outpos)
                cur, hit_empty = self._descend(take, x, r, type_, cnt)
                bad = live & self._bad_item(cur, type_) & ~hit_empty
                coll = jnp.any(
                    (out == cur[:, None])
                    & (jnp.arange(numrep)[None, :] < cnt[:, None]),
                    axis=1,
                )
                reject = hit_empty
                leaf = cur
                if recurse_to_leaf:
                    sub_r = (r >> (vary_r - 1)) if vary_r else jnp.zeros_like(r)
                    inner_rep = jnp.zeros_like(cnt) if stable else cnt
                    leaf, leaf_ok = self._leaf_firstn(
                        cur, x, inner_rep, sub_r, recurse_tries, out2, cnt,
                        live & ~bad & ~coll & (cur < 0))
                    leaf = jnp.where(cur >= 0, cur, leaf)
                    reject = reject | ((cur < 0) & ~leaf_ok)
                if type_ == 0:
                    reject = reject | self._is_out(self._w, cur, x)
                success = live & ~bad & ~coll & ~reject
                slot = jnp.arange(numrep)[None, :] == cnt[:, None]
                out = jnp.where(slot & success[:, None], cur[:, None], out)
                out2 = jnp.where(slot & success[:, None], leaf[:, None], out2)
                cnt = cnt + success.astype(I32)
                done = done | success | bad
                ftotal = jnp.where(live & ~success & ~bad, ftotal + 1, ftotal)
                return out, out2, cnt, ftotal, done

            ftotal = jnp.zeros(L, dtype=I32)
            done = ~lane_mask
            out, out2, cnt, _, _ = jax.lax.while_loop(
                cond, body, (out, out2, cnt, ftotal, done))
        return (out2 if recurse_to_leaf else out), cnt

    # --------------------------------------------------------------- indep

    def _leaf_indep(self, host, x, rep, numrep, parent_r, tries, act):
        """Recursive chooseleaf for indep (mapper.c:767-786)."""
        already = host >= 0
        leaf = jnp.where(already & act, host, CRUSH_ITEM_UNDEF)
        done = ~act | already

        def cond(s):
            leaf, done, ftotal = s
            return jnp.any(~done & (ftotal < tries))

        def body(s):
            leaf, done, ftotal = s
            live = ~done & (ftotal < tries)
            r = rep + parent_r + numrep * ftotal
            # scalar's indep leaf recursion passes its slot as outpos
            cur, hit_empty = self._descend(
                host, x, r, 0, jnp.full_like(host, rep))
            bad = self._bad_item(cur, 0)
            rej = self._is_out(self._w, cur, x) | hit_empty
            ok = live & ~bad & ~rej
            leaf = jnp.where(ok, cur, leaf)
            leaf = jnp.where(live & bad, CRUSH_ITEM_NONE, leaf)
            done = done | ok | (live & bad)
            ftotal = ftotal + live.astype(I32)
            return leaf, done, ftotal

        leaf, _, _ = jax.lax.while_loop(
            cond, body, (leaf, done, jnp.zeros_like(x, dtype=I32)))
        leaf = jnp.where(leaf == CRUSH_ITEM_UNDEF, CRUSH_ITEM_NONE, leaf)
        return leaf

    def _choose_indep_vec(self, take, x, out_size, numrep, type_, tries,
                          recurse_tries, recurse_to_leaf, lane_mask):
        """crush_choose_indep (mapper.c:638-826), parent_r = 0."""
        L = x.shape[0]
        out = jnp.where(lane_mask[:, None],
                        jnp.full((L, out_size), CRUSH_ITEM_UNDEF, dtype=I32),
                        jnp.full((L, out_size), CRUSH_ITEM_NONE, dtype=I32))
        out2 = out

        def cond(s):
            out, out2, ftotal = s
            return jnp.any((out == CRUSH_ITEM_UNDEF) & (ftotal[:, None] < tries))

        def body(s):
            out, out2, ftotal = s
            lane_live = jnp.any(out == CRUSH_ITEM_UNDEF, axis=1) & (ftotal < tries)
            for rep in range(out_size):
                act = lane_live & (out[:, rep] == CRUSH_ITEM_UNDEF)
                r = rep + numrep * ftotal
                cur, hit_empty = self._descend(take, x, r, type_)
                bad = act & self._bad_item(cur, type_) & ~hit_empty
                coll = jnp.any(out == cur[:, None], axis=1)
                leaf = cur
                leaf_fail = jnp.zeros_like(bad)
                if recurse_to_leaf:
                    leaf = self._leaf_indep(
                        cur, x, rep, numrep, r, recurse_tries,
                        act & ~bad & ~coll & (cur < 0))
                    leaf = jnp.where(cur >= 0, cur, leaf)
                    leaf_fail = (cur < 0) & (leaf == CRUSH_ITEM_NONE)
                rej = jnp.zeros_like(bad)
                if type_ == 0:
                    rej = self._is_out(self._w, cur, x)
                success = act & ~bad & ~coll & ~leaf_fail & ~rej & ~hit_empty
                col = jnp.arange(out_size)[None, :] == rep
                out = jnp.where(col & success[:, None], cur[:, None], out)
                out = jnp.where(col & bad[:, None], CRUSH_ITEM_NONE, out)
                out2 = jnp.where(col & success[:, None], leaf[:, None], out2)
                out2 = jnp.where(col & bad[:, None], CRUSH_ITEM_NONE, out2)
            ftotal = ftotal + lane_live.astype(I32)
            return out, out2, ftotal

        out, out2, _ = jax.lax.while_loop(
            cond, body, (out, out2, jnp.zeros(L, dtype=I32)))
        out = jnp.where(out == CRUSH_ITEM_UNDEF, CRUSH_ITEM_NONE, out)
        out2 = jnp.where(out2 == CRUSH_ITEM_UNDEF, CRUSH_ITEM_NONE, out2)
        return (out2 if recurse_to_leaf else out)

    # ------------------------------------------------------------- rule VM

    # Device-resident map tensors the rule functions need.  They are
    # threaded through jit as ARGUMENTS (run(..., tensors)) with the traced
    # values temporarily bound onto self during tracing — a jit closure
    # over a device-resident array permanently degrades every subsequent
    # dispatch in the process on the axon platform (~150x slowdown).
    _TENSOR_ATTRS = ("items", "iweights", "sizes", "btypes", "recip_hi",
                     "recip_lo", "_rh", "_lh", "_ll", "_lnn",
                     "_p2flat", "_meta",
                     "_ca_ids", "_ca_w", "_ca_rh", "_ca_rl", "_ca_pmax")

    def _tensor_args(self):
        return {a: getattr(self, a) for a in self._TENSOR_ATTRS}

    def _build_rule_fn(self, ruleno: int, result_max: int,
                       ca_active: bool = False, ca_pdim: int = 1):
        m = self.map
        t = m.tunables
        rule = m.rules[ruleno]

        def run(xs, weights, tensors):
            saved = {a: getattr(self, a) for a in self._TENSOR_ATTRS}
            saved_ca = (self._ca_active, self._ca_pdim)
            for a, v in tensors.items():
                setattr(self, a, v)
            # static choose_args mode must bind at TRACE time (jit traces
            # lazily on first call, not at build)
            self._ca_active, self._ca_pdim = ca_active, ca_pdim
            try:
                return self._run_rule(xs, weights, rule, t, result_max)
            finally:
                self._ca_active, self._ca_pdim = saved_ca
                for a, v in saved.items():
                    setattr(self, a, v)

        return jax.jit(run)

    def _run_rule(self, xs, weights, rule, t, result_max: int):
        self._w = weights
        L = xs.shape[0]
        choose_tries = t.choose_total_tries + 1
        choose_leaf_tries = 0
        vary_r = t.chooseleaf_vary_r
        stable = t.chooseleaf_stable
        w_items = jnp.full((L, result_max), CRUSH_ITEM_NONE, dtype=I32)
        wsize = jnp.zeros(L, dtype=I32)
        result = jnp.full((L, result_max), CRUSH_ITEM_NONE, dtype=I32)
        rlen = jnp.zeros(L, dtype=I32)
        for op, arg1, arg2 in rule.steps:
            if op == RULE_TAKE:
                w_items = w_items.at[:, 0].set(arg1)
                wsize = jnp.full(L, 1, dtype=I32)
            elif op == RULE_SET_CHOOSE_TRIES:
                if arg1 > 0:
                    choose_tries = arg1
            elif op == RULE_SET_CHOOSELEAF_TRIES:
                if arg1 > 0:
                    choose_leaf_tries = arg1
            elif op == RULE_SET_CHOOSELEAF_VARY_R:
                if arg1 >= 0:
                    vary_r = arg1
            elif op == RULE_SET_CHOOSELEAF_STABLE:
                if arg1 >= 0:
                    stable = arg1
            elif op in (RULE_SET_CHOOSE_LOCAL_TRIES,
                        RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES):
                if arg1 > 0:
                    raise NotImplementedError("local retries not vectorized")
            elif op in (RULE_CHOOSE_FIRSTN, RULE_CHOOSELEAF_FIRSTN,
                        RULE_CHOOSE_INDEP, RULE_CHOOSELEAF_INDEP):
                firstn = op in (RULE_CHOOSE_FIRSTN, RULE_CHOOSELEAF_FIRSTN)
                recurse = op in (RULE_CHOOSELEAF_FIRSTN, RULE_CHOOSELEAF_INDEP)
                numrep = arg1
                if numrep <= 0:
                    numrep += result_max
                    if numrep <= 0:
                        continue
                o_items = jnp.full((L, result_max), CRUSH_ITEM_NONE, dtype=I32)
                osize = jnp.zeros(L, dtype=I32)
                # Each W entry gets an independent output segment
                # (reference passes o+osize per input bucket).
                for i in range(result_max):
                    mask = (i < wsize) & (w_items[:, i] < 0)
                    take = w_items[:, i]
                    if firstn:
                        if choose_leaf_tries:
                            recurse_tries = choose_leaf_tries
                        elif t.chooseleaf_descend_once:
                            recurse_tries = 1
                        else:
                            recurse_tries = choose_tries
                        vals, cnt = self._choose_firstn_vec(
                            take, xs, numrep, arg2, choose_tries,
                            recurse_tries, recurse, vary_r, stable, mask)
                        ncols = numrep
                        cnt = jnp.where(mask, cnt, 0)
                    else:
                        # out_size depends on osize only when segments
                        # overflow result_max; clamp below on append
                        vals = self._choose_indep_vec(
                            take, xs, numrep, numrep, arg2, choose_tries,
                            choose_leaf_tries if choose_leaf_tries else 1,
                            recurse, mask)
                        ncols = numrep
                        cnt = jnp.where(mask, numrep, 0)
                    for j in range(ncols):
                        valid = (j < cnt) & (osize < result_max)
                        slot = jnp.arange(result_max)[None, :] == osize[:, None]
                        o_items = jnp.where(
                            slot & valid[:, None], vals[:, j][:, None], o_items)
                        osize = osize + valid.astype(I32)
                w_items = o_items
                wsize = osize
            elif op == RULE_EMIT:
                for j in range(result_max):
                    valid = (j < wsize) & (rlen < result_max)
                    slot = jnp.arange(result_max)[None, :] == rlen[:, None]
                    result = jnp.where(
                        slot & valid[:, None], w_items[:, j][:, None], result)
                    rlen = rlen + valid.astype(I32)
                wsize = jnp.zeros(L, dtype=I32)
            else:
                raise NotImplementedError(f"rule op {op}")
        return result, rlen
    def compiled_rule(self, ruleno: int, result_max: int,
                      choose_args=None):
        """Public seam for external dispatch harnesses (e.g. the mesh
        shard-out in parallel/engine.py): the cached compiled rule fn
        ``(xs, weights, tensors) -> (result, lens)`` plus the map tensor
        args, sharing this mapper's compile cache.  ``choose_args``: a
        name registered in map.choose_args or a {bucket_id: ChooseArg}
        dict — compiles a variant whose straw2 draws use the override
        weights/ids (mapper.c:302-320)."""
        if choose_args is None:
            key = (ruleno, result_max)
            if key not in self._compiled:
                self._compiled[key] = self._build_rule_fn(
                    ruleno, result_max)
            return self._compiled[key], self._tensor_args()
        ca_key, ca_tensors, P = self._resolve_choose_args(choose_args)
        # the compiled fn depends only on (rule, result_max, P) — the
        # override tensors are runtime args — so a balancer loop with
        # fresh weights each iteration reuses one compilation
        key = (ruleno, result_max, "ca", P)
        if key not in self._compiled:
            self._compiled[key] = self._build_rule_fn(
                ruleno, result_max, ca_active=True, ca_pdim=P)
        # tensor-args snapshot with the override tensors swapped in
        saved = {a: getattr(self, a) for a in ca_tensors}
        for a, v in ca_tensors.items():
            setattr(self, a, v)
        try:
            return self._compiled[key], self._tensor_args()
        finally:
            for a, v in saved.items():
                setattr(self, a, v)

    def do_rule_batch(self, ruleno: int, xs, result_max: int, weights,
                      choose_args=None):
        """Map a batch of x values; returns (N, result_max) int32 with
        CRUSH_ITEM_NONE padding, plus lengths, matching crush_do_rule."""
        from ceph_tpu.utils.perf import KERNELS

        fn, tensors = self.compiled_rule(ruleno, result_max, choose_args)
        xs = jnp.asarray(xs, dtype=U32)
        weights = jnp.asarray(weights, dtype=U32)
        n = xs.shape[0]
        KERNELS.inc("crush_map_calls")
        KERNELS.inc("crush_map_pgs", int(n))
        outs = []
        lens = []
        for start in range(0, n, self.chunk):
            part = xs[start : start + self.chunk]
            pad = 0
            if part.shape[0] < self.chunk and n > self.chunk:
                pad = self.chunk - part.shape[0]
                part = jnp.pad(part, (0, pad))
                # padded lanes run the full rule VM for discarded output
                KERNELS.inc("crush_map_pad_lanes", pad)
            res, rl = fn(part, weights, tensors)
            if pad:
                res = res[:-pad]
                rl = rl[:-pad]
            outs.append(res)
            lens.append(rl)
        if len(outs) == 1:
            return outs[0], lens[0]
        return jnp.concatenate(outs), jnp.concatenate(lens)
