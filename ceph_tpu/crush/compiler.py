"""CRUSH text-map compiler/decompiler — the operator map language.

Behavioral analog of the reference CrushCompiler
(/root/reference/src/crush/CrushCompiler.cc: decompile_* and the
parse_* grammar): the `crushtool -d`/`-c` round-trippable text format
operators hand-edit —

    tunable choose_total_tries 50
    device 0 osd.0 class ssd
    type 0 osd
    host host0 {
        id -1
        alg straw2
        hash 0
        item osd.0 weight 1.000
    }
    rule replicated_rule {
        ruleset 0
        type replicated
        min_size 1
        max_size 10
        step take default
        step chooseleaf firstn 0 type host
        step emit
    }

Covered subset: tunables, devices (+classes), types, all five bucket
algs, take/choose/chooseleaf (firstn|indep)/emit steps — the constructs
the rest of this framework implements.  choose_args (a binary-era
extension) are not expressible in the classic text format, matching the
reference.
"""

from __future__ import annotations

import re
from typing import Dict, List, Tuple

from ceph_tpu.crush.types import (
    Bucket,
    CrushMap,
    Rule,
    Tunables,
    RULE_CHOOSE_FIRSTN,
    RULE_CHOOSE_INDEP,
    RULE_CHOOSELEAF_FIRSTN,
    RULE_CHOOSELEAF_INDEP,
    RULE_EMIT,
    RULE_TAKE,
)

_TUNABLES = ("choose_local_tries", "choose_local_fallback_tries",
             "choose_total_tries", "chooseleaf_descend_once",
             "chooseleaf_vary_r", "chooseleaf_stable")

_STEP_OPS = {
    (RULE_TAKE): "take",
    (RULE_CHOOSE_FIRSTN): "choose firstn",
    (RULE_CHOOSE_INDEP): "choose indep",
    (RULE_CHOOSELEAF_FIRSTN): "chooseleaf firstn",
    (RULE_CHOOSELEAF_INDEP): "chooseleaf indep",
    (RULE_EMIT): "emit",
}


def decompile(cmap: CrushMap) -> str:
    """CrushMap -> operator text (CrushCompiler::decompile)."""
    out: List[str] = ["# begin crush map"]
    t = cmap.tunables
    for name in _TUNABLES:
        out.append(f"tunable {name} {getattr(t, name)}")
    out.append("")
    out.append("# devices")
    for dev in range(cmap.max_devices):
        cls = cmap.device_class.get(dev)
        suffix = f" class {cls}" if cls else ""
        out.append(f"device {dev} osd.{dev}{suffix}")
    out.append("")
    out.append("# types")
    for tid in sorted(cmap.type_names):
        out.append(f"type {tid} {cmap.type_names[tid]}")
    out.append("")
    out.append("# buckets")
    # children before parents (the reference emits leaves upward)
    emitted = set()

    def emit_bucket(bid: int) -> None:
        if bid in emitted:
            return
        b = cmap.buckets[bid]
        for item in b.items:
            if item < 0:
                emit_bucket(item)
        emitted.add(bid)
        name = cmap.item_names.get(bid, f"bucket{-bid}")
        tname = cmap.type_names.get(b.type, str(b.type))
        out.append(f"{tname} {name} {{")
        out.append(f"\tid {bid}")
        out.append(f"\talg {b.alg}")
        out.append(f"\thash {b.hash}\t# rjenkins1")
        for item, w in zip(b.items, b.weights):
            iname = (f"osd.{item}" if item >= 0
                     else cmap.item_names.get(item, f"bucket{-item}"))
            # 5 decimals: 1/0x10000 granularity round-trips exactly
            # (3 would silently perturb reweighted values -> placements)
            out.append(f"\titem {iname} weight {w / 0x10000:.5f}")
        out.append("}")
    for bid in sorted(cmap.buckets, reverse=True):
        emit_bucket(bid)
    out.append("")
    out.append("# rules")
    for ruleno, rule in enumerate(cmap.rules):
        out.append(f"rule rule{ruleno} {{")
        out.append(f"\truleset {ruleno}")
        out.append("\ttype replicated" if rule.type == 1
                   else "\ttype erasure")
        out.append(f"\tmin_size {rule.min_size}")
        out.append(f"\tmax_size {rule.max_size}")
        for op, arg1, arg2 in rule.steps:
            if op == RULE_TAKE:
                name = cmap.item_names.get(arg1, f"bucket{-arg1}")
                out.append(f"\tstep take {name}")
            elif op == RULE_EMIT:
                out.append("\tstep emit")
            elif op in (RULE_CHOOSE_FIRSTN, RULE_CHOOSE_INDEP,
                        RULE_CHOOSELEAF_FIRSTN, RULE_CHOOSELEAF_INDEP):
                tname = cmap.type_names.get(arg2, str(arg2))
                out.append(f"\tstep {_STEP_OPS[op]} {arg1} type {tname}")
            else:
                raise ValueError(f"undecompilable step op {op}")
        out.append("}")
    out.append("")
    out.append("# end crush map")
    return "\n".join(out) + "\n"


def compile_text(text: str) -> CrushMap:
    """Operator text -> CrushMap (CrushCompiler::compile grammar)."""
    # strip comments, blank lines
    lines: List[str] = []
    for raw in text.splitlines():
        line = raw.split("#", 1)[0].strip()
        if line:
            lines.append(line)

    cmap = CrushMap(Tunables())
    cmap.type_names = {}
    name_to_id: Dict[str, int] = {}
    type_by_name: Dict[str, int] = {}
    pending_buckets: List[Tuple[str, str, List[str]]] = []
    i = 0
    while i < len(lines):
        line = lines[i]
        if line.startswith("tunable "):
            _, name, val = line.split()
            if name not in _TUNABLES:
                raise ValueError(f"unknown tunable {name!r}")
            setattr(cmap.tunables, name, int(val))
            i += 1
        elif line.startswith("device "):
            parts = line.split()
            dev = int(parts[1])
            cmap.max_devices = max(cmap.max_devices, dev + 1)
            if len(parts) >= 5 and parts[3] == "class":
                cmap.device_class[dev] = parts[4]
            i += 1
        elif line.startswith("type "):
            _, tid, tname = line.split()
            cmap.type_names[int(tid)] = tname
            type_by_name[tname] = int(tid)
            i += 1
        elif line.startswith("rule ") and line.endswith("{"):
            body, i = _block(lines, i, line)
            _parse_rule(cmap, body, name_to_id)
        else:
            m = re.match(r"^(\S+)\s+(\S+)\s*\{$", line)
            if m is None:
                raise ValueError(f"cannot parse line: {line!r}")
            tname, bname = m.group(1), m.group(2)
            body, i = _block(lines, i, line)
            _parse_bucket(cmap, tname, bname, body, name_to_id,
                          type_by_name)
    return cmap


def _block(lines: List[str], i: int, opener: str) -> Tuple[List[str], int]:
    """Collect the body of a { } block; a hand-edited map missing its
    closing brace must fail as a parse error, not an IndexError."""
    body: List[str] = []
    i += 1
    while i < len(lines) and lines[i] != "}":
        body.append(lines[i])
        i += 1
    if i >= len(lines):
        raise ValueError(f"unterminated block: {opener!r} has no '}}'")
    return body, i + 1


def _parse_bucket(cmap, tname, bname, body, name_to_id, type_by_name):
    if tname not in type_by_name:
        raise ValueError(f"bucket {bname!r} has unknown type {tname!r}")
    bid = None
    alg = "straw2"
    hashv = 0
    items: List[int] = []
    weights: List[int] = []
    for line in body:
        parts = line.split()
        if parts[0] == "id":
            bid = int(parts[1])
        elif parts[0] == "alg":
            if parts[1] not in ("uniform", "list", "tree", "straw",
                                "straw2"):
                raise ValueError(f"unknown bucket alg {parts[1]!r}")
            alg = parts[1]
        elif parts[0] == "hash":
            hashv = int(parts[1])
        elif parts[0] == "item":
            iname = parts[1]
            w = 0x10000
            if "weight" in parts:
                w = int(round(float(parts[parts.index("weight") + 1])
                              * 0x10000))
            if iname.startswith("osd."):
                item = int(iname[4:])
                cmap.max_devices = max(cmap.max_devices, item + 1)
            elif iname in name_to_id:
                item = name_to_id[iname]
            else:
                raise ValueError(
                    f"bucket {bname!r} references undefined item {iname!r}")
            items.append(item)
            weights.append(w)
        else:
            raise ValueError(f"bad bucket line: {line!r}")
    b = Bucket(id=bid if bid is not None else 0,
               type=type_by_name[tname], alg=alg, hash=hashv,
               items=items, weights=weights)
    got = cmap.add_bucket(b, name=bname)
    name_to_id[bname] = got


def _parse_rule(cmap, body, name_to_id):
    rtype = 1
    min_size, max_size = 1, 10
    steps: List[Tuple[int, int, int]] = []
    for line in body:
        parts = line.split()
        if parts[0] in ("ruleset", "id"):
            pass  # rule number = position, as crushtool renumbers
        elif parts[0] == "type":
            rtype = 1 if parts[1] == "replicated" else 3
        elif parts[0] == "min_size":
            min_size = int(parts[1])
        elif parts[0] == "max_size":
            max_size = int(parts[1])
        elif parts[0] == "step":
            if parts[1] == "take":
                if parts[2] not in name_to_id:
                    raise ValueError(f"take of undefined {parts[2]!r}")
                steps.append((RULE_TAKE, name_to_id[parts[2]], 0))
            elif parts[1] == "emit":
                steps.append((RULE_EMIT, 0, 0))
            elif parts[1] in ("choose", "chooseleaf"):
                mode = parts[2]          # firstn | indep
                n = int(parts[3])
                tname = parts[5]         # "type" at parts[4]
                by_name = {v: k for k, v in cmap.type_names.items()}
                if tname not in by_name:
                    raise ValueError(
                        f"step references undeclared type {tname!r}")
                tid = by_name[tname]
                op = {
                    ("choose", "firstn"): RULE_CHOOSE_FIRSTN,
                    ("choose", "indep"): RULE_CHOOSE_INDEP,
                    ("chooseleaf", "firstn"): RULE_CHOOSELEAF_FIRSTN,
                    ("chooseleaf", "indep"): RULE_CHOOSELEAF_INDEP,
                }[(parts[1], mode)]
                steps.append((op, n, tid))
            else:
                raise ValueError(f"bad step: {line!r}")
        else:
            raise ValueError(f"bad rule line: {line!r}")
    cmap.add_rule(Rule(steps=steps, type=rtype, min_size=min_size,
                       max_size=max_size))
