"""CRUSH placement: map structures, straw2, scalar oracle, vmapped mapper.

Behavioral mirror of reference src/crush/ (mapper.c, hash.c, builder.c,
crush.h): deterministic hierarchical placement with straw2 buckets,
firstn/indep selection, tunable retry semantics — rebuilt so a whole
OSDMap's PG->OSD mapping evaluates as one batched TPU dispatch.
"""

from ceph_tpu.crush.types import (  # noqa: F401
    Bucket,
    CrushMap,
    Rule,
    Tunables,
    CRUSH_ITEM_NONE,
    CRUSH_ITEM_UNDEF,
)
from ceph_tpu.crush.scalar import ScalarMapper  # noqa: F401


def bench_map(n_osds: int = 10_000, n_pgs: int = 1_000_000, iters: int = 3):
    """Whole-map placement throughput (mappings/s) for bench.py."""
    import time

    import jax
    import numpy as np

    from ceph_tpu.crush.mapper import TensorMapper
    from ceph_tpu.crush.types import build_three_level

    # 10k OSDs as deployed: root -> 40 racks -> 16 hosts -> 16 osds
    n_racks = max(1, n_osds // 256)
    cmap, rule = build_three_level(
        n_racks=n_racks, hosts_per_rack=16, osds_per_host=16, numrep=3
    )
    mapper = TensorMapper(cmap)
    xs = np.arange(n_pgs, dtype=np.uint32)
    weights = np.full(cmap.max_devices, 0x10000, dtype=np.uint32)
    out = mapper.do_rule_batch(rule, xs, result_max=3, weights=weights)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = mapper.do_rule_batch(rule, xs, result_max=3, weights=weights)
        jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / iters
    return n_pgs / dt
