"""CrushTester: batch placement verification with distribution stats.

Behavioral analog of the reference's crushtool --test machinery
(CrushTester::test, src/crush/CrushTester.cc:472; crushtool.cc:1024):
map a range of x values through a rule and report per-device placement
counts, utilization vs weight expectation, bad (short) mappings, and
first-choice distribution — the tool operators use to validate a map
before deploying it.

TPU-first: when the map is straw2-only with optimal tunables the whole
batch runs through the vectorized TensorMapper (one device dispatch per
chunk); other maps fall back to the scalar oracle.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from ceph_tpu.crush.scalar import ScalarMapper
from ceph_tpu.crush.types import CRUSH_ITEM_NONE, CrushMap


@dataclass
class TestReport:
    n_inputs: int
    result_max: int
    total_placements: int
    bad_mappings: List[int] = field(default_factory=list)
    device_counts: Dict[int, int] = field(default_factory=dict)
    first_counts: Dict[int, int] = field(default_factory=dict)
    expected_share: Dict[int, float] = field(default_factory=dict)
    max_deviation: float = 0.0

    def summary(self) -> str:
        """crushtool --test --show-utilization-style text."""
        lines = [f"tested {self.n_inputs} inputs, numrep {self.result_max}: "
                 f"{self.total_placements} placements, "
                 f"{len(self.bad_mappings)} bad mappings"]
        for dev in sorted(self.device_counts):
            exp = self.expected_share.get(dev, 0.0) * self.total_placements
            got = self.device_counts[dev]
            lines.append(
                f"  device {dev}:\t{got}\texpected {exp:.0f}")
        lines.append(f"  max deviation from weight share: "
                     f"{self.max_deviation:.3f}")
        return "\n".join(lines)


class CrushTester:
    def __init__(self, cmap: CrushMap):
        self.map = cmap

    def _weights_under(self, root: int) -> Dict[int, int]:
        out: Dict[int, int] = {}

        def walk(bid: int, w: int):
            b = self.map.buckets[bid]
            total = b.weight or 1
            for item, iw in zip(b.items, b.weights):
                share = w * iw // total
                if item >= 0:
                    out[item] = out.get(item, 0) + share
                else:
                    walk(item, share)

        walk(root, 1 << 32)
        return out

    def test(self, ruleno: int, result_max: int,
             min_x: int = 0, max_x: int = 1023,
             weights: Optional[List[int]] = None,
             choose_args=None) -> TestReport:
        m = self.map
        if weights is None:
            weights = [0x10000] * m.max_devices
        xs = range(min_x, max_x + 1)
        results: List[List[int]] = []
        # TensorMapper raises NotImplementedError for maps it cannot
        # vectorize (non-straw2 buckets, local retries); since round 5 it
        # vectorizes choose_args too
        use_tensor = True
        try:
            from ceph_tpu.crush.mapper import TensorMapper

            tm = TensorMapper(m)
            out, lens = tm.do_rule_batch(
                ruleno, np.arange(min_x, max_x + 1, dtype=np.uint32),
                result_max=result_max,
                weights=np.asarray(weights, dtype=np.uint32),
                choose_args=choose_args)
            out = np.asarray(out)
            lens = np.asarray(lens)
            results = [
                [int(v) for v in out[i, :int(lens[i])]]
                for i in range(out.shape[0])]
        except (NotImplementedError, AssertionError):
            use_tensor = False
        if not use_tensor:
            sm = ScalarMapper(m)
            results = [sm.do_rule(ruleno, x, result_max, weights,
                                  choose_args=choose_args) for x in xs]

        report = TestReport(n_inputs=len(results), result_max=result_max,
                            total_placements=0)
        for x, res in zip(xs, results):
            live = [d for d in res if d != CRUSH_ITEM_NONE]
            if len(live) < result_max:
                report.bad_mappings.append(x)
            for j, d in enumerate(live):
                report.device_counts[d] = report.device_counts.get(d, 0) + 1
                if j == 0:
                    report.first_counts[d] = \
                        report.first_counts.get(d, 0) + 1
            report.total_placements += len(live)

        # expected share from the rule's TAKE root subtree weights,
        # modulated by the reweight vector (crushtool --show-utilization)
        take = next((s[1] for s in m.rules[ruleno].steps if s[0] == 1), None)
        if take is not None and take in m.buckets:
            shares = self._weights_under(take)
            for d in list(shares):
                if d < len(weights):
                    shares[d] = shares[d] * weights[d] // 0x10000
            total = sum(shares.values()) or 1
            report.expected_share = {d: s / total
                                     for d, s in shares.items()}
            if report.total_placements:
                for d, exp in report.expected_share.items():
                    got = report.device_counts.get(d, 0) / \
                        report.total_placements
                    report.max_deviation = max(
                        report.max_deviation, abs(got - exp))
        return report
