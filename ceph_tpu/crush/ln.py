"""crush_ln: fixed-point 2^44*log2(x+1) (reference mapper.c:248-290).

The RH/LH halves of the LUT follow exact closed forms (verified entry-by-
entry against the reference table):

    RH[k] = ceil(2^48 * 128 / (128 + k))      k = 0..128
    LH[k] = floor(2^48 * log2(1 + k/128))     k = 0..127

with ONE deployed deviation: LH[128] in crush_ln_table.h is 0xffff00000000,
not the closed form's 2^48 (a rounding artifact of whatever script generated
the deployed table).  Entry 128 is reached whenever a straw2 16-bit draw is
0xFFFF, so bit-compatible placement requires the deployed value — it is
pinned below.  The LL half is pinned in _ll_table.py: the deployed table
deviates from its documented formula for most entries, and bit-compatible
placement requires the deployed values.
"""

from __future__ import annotations

import math

from ceph_tpu.crush._ll_table import LL_TBL


def _gen_rh_lh():
    rh, lh = [], []
    for k in range(129):
        rh.append(-(-(2**48 * 128) // (128 + k)))  # exact ceil
        lh.append(math.floor((2**48) * math.log2(1 + k / 128)))
    lh[128] = 0xFFFF00000000  # deployed-table deviation from the closed form
    return tuple(rh), tuple(lh)


RH_TBL, LH_TBL = _gen_rh_lh()


def crush_ln(xin: int) -> int:
    """Exact integer mirror of the reference crush_ln (mapper.c:248-290)."""
    x = (xin + 1) & 0xFFFFFFFF
    iexpon = 15
    if not (x & 0x18000):
        bits = 32 - (x & 0x1FFFF).bit_length() - 16
        x = (x << bits) & 0xFFFFFFFF
        iexpon = 15 - bits
    k = (x >> 8) - 128
    xl64 = (x * RH_TBL[k]) >> 48
    index2 = xl64 & 0xFF
    return (iexpon << 44) + ((LH_TBL[k] + LL_TBL[index2]) >> 4)
