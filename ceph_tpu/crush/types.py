"""CRUSH map data structures and builder.

Mirrors reference src/crush/crush.h (map/bucket/rule structs, :229-366) and
the builder API (src/crush/builder.c): buckets have negative ids, devices
non-negative; rules are step programs for the crush_do_rule VM.  Tunable
defaults are the reference's "optimal" (jewel) profile, which OSDMaps of the
reference era deploy.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

CRUSH_ITEM_NONE = 0x7FFFFFFF
CRUSH_ITEM_UNDEF = 0x7FFFFFFE

# rule step opcodes (reference crush.h:55-69)
RULE_NOOP = 0
RULE_TAKE = 1
RULE_CHOOSE_FIRSTN = 2
RULE_CHOOSE_INDEP = 3
RULE_EMIT = 4
RULE_CHOOSELEAF_FIRSTN = 6
RULE_CHOOSELEAF_INDEP = 7
RULE_SET_CHOOSE_TRIES = 8
RULE_SET_CHOOSELEAF_TRIES = 9
RULE_SET_CHOOSE_LOCAL_TRIES = 10
RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES = 11
RULE_SET_CHOOSELEAF_VARY_R = 12
RULE_SET_CHOOSELEAF_STABLE = 13

BUCKET_UNIFORM = 1
BUCKET_LIST = 2
BUCKET_TREE = 3
BUCKET_STRAW = 4
BUCKET_STRAW2 = 5

_ALG_NAMES = {
    "uniform": BUCKET_UNIFORM,
    "list": BUCKET_LIST,
    "tree": BUCKET_TREE,
    "straw": BUCKET_STRAW,
    "straw2": BUCKET_STRAW2,
}


@dataclass
class Tunables:
    """Reference 'optimal' (jewel) profile; crush_do_rule semantics at
    mapper.c:904-918."""

    choose_total_tries: int = 50
    choose_local_tries: int = 0
    choose_local_fallback_tries: int = 0
    chooseleaf_descend_once: int = 1
    chooseleaf_vary_r: int = 1
    chooseleaf_stable: int = 1

    @classmethod
    def legacy(cls) -> "Tunables":
        """crush_create() defaults (argonaut-era)."""
        return cls(
            choose_total_tries=19,
            choose_local_tries=2,
            choose_local_fallback_tries=5,
            chooseleaf_descend_once=0,
            chooseleaf_vary_r=0,
            chooseleaf_stable=0,
        )


@dataclass
class Bucket:
    id: int  # negative
    type: int  # 0 = device, >0 = bucket level
    alg: str = "straw2"
    hash: int = 0  # CRUSH_HASH_RJENKINS1
    items: List[int] = field(default_factory=list)
    weights: List[int] = field(default_factory=list)  # 16.16 fixed per item

    @property
    def size(self) -> int:
        return len(self.items)

    @property
    def weight(self) -> int:
        return sum(self.weights)


@dataclass
class Rule:
    steps: List[Tuple[int, int, int]]
    ruleset: int = 0
    type: int = 1  # pg_pool type: 1 replicated, 3 erasure
    min_size: int = 1
    max_size: int = 10


class CrushMap:
    def __init__(self, tunables: Optional[Tunables] = None):
        self.buckets: Dict[int, Bucket] = {}
        self.rules: List[Rule] = []
        self.max_devices = 0
        self.tunables = tunables or Tunables()
        self.type_names: Dict[int, str] = {0: "osd", 1: "host", 2: "rack", 3: "root"}
        self.item_names: Dict[int, str] = {}

    # -- builder (reference builder.c semantics) ---------------------------

    def add_bucket(self, bucket: Bucket, name: Optional[str] = None) -> int:
        if bucket.id >= 0:
            bucket.id = -1 - len(self.buckets)
        self.buckets[bucket.id] = bucket
        for item in bucket.items:
            if item >= 0:
                self.max_devices = max(self.max_devices, item + 1)
        if name:
            self.item_names[bucket.id] = name
        return bucket.id

    def make_straw2(
        self,
        type: int,
        items: List[int],
        weights: List[int],
        name: Optional[str] = None,
    ) -> int:
        return self.add_bucket(
            Bucket(id=0, type=type, alg="straw2", items=list(items),
                   weights=list(weights)),
            name,
        )

    def add_rule(self, rule: Rule) -> int:
        self.rules.append(rule)
        return len(self.rules) - 1

    def bucket(self, item_id: int) -> Bucket:
        return self.buckets[item_id]

    def max_depth(self) -> int:
        """Longest bucket chain (for bounding vectorized descents)."""

        def depth(bid: int) -> int:
            b = self.buckets[bid]
            best = 1
            for item in b.items:
                if item < 0:
                    best = max(best, 1 + depth(item))
            return best

        return max((depth(bid) for bid in self.buckets), default=0)


def build_three_level(
    n_racks: int,
    hosts_per_rack: int,
    osds_per_host: int,
    numrep: int = 3,
    weight: int = 0x10000,
) -> Tuple[CrushMap, int]:
    """root -> rack -> host -> osd map + chooseleaf-firstn rule (the
    deployment shape of large clusters; keeps bucket fanouts narrow)."""
    cmap = CrushMap()
    rack_ids, rack_w = [], []
    dev = 0
    for r in range(n_racks):
        host_ids, host_w = [], []
        for h in range(hosts_per_rack):
            items = list(range(dev, dev + osds_per_host))
            dev += osds_per_host
            weights = [weight] * osds_per_host
            hid = cmap.make_straw2(1, items, weights, name=f"host{r}-{h}")
            host_ids.append(hid)
            host_w.append(sum(weights))
        rid = cmap.make_straw2(2, host_ids, host_w, name=f"rack{r}")
        rack_ids.append(rid)
        rack_w.append(sum(host_w))
    root = cmap.make_straw2(3, rack_ids, rack_w, name="default")
    steps = [(RULE_TAKE, root, 0), (RULE_CHOOSELEAF_FIRSTN, numrep, 1),
             (RULE_EMIT, 0, 0)]
    ruleno = cmap.add_rule(Rule(steps=steps))
    return cmap, ruleno


def build_hierarchy(
    n_hosts: int,
    osds_per_host: int,
    numrep: int = 3,
    weight: int = 0x10000,
    chooseleaf: bool = True,
    firstn: bool = True,
) -> Tuple[CrushMap, int]:
    """Standard root->host->osd map + rule (the shape OSDMaps deploy)."""
    cmap = CrushMap()
    host_ids, host_weights = [], []
    dev = 0
    for h in range(n_hosts):
        items = list(range(dev, dev + osds_per_host))
        dev += osds_per_host
        weights = [weight] * osds_per_host
        hid = cmap.make_straw2(1, items, weights, name=f"host{h}")
        host_ids.append(hid)
        host_weights.append(sum(weights))
    root = cmap.make_straw2(3, host_ids, host_weights, name="default")
    if chooseleaf:
        op = RULE_CHOOSELEAF_FIRSTN if firstn else RULE_CHOOSELEAF_INDEP
        steps = [(RULE_TAKE, root, 0), (op, numrep, 1), (RULE_EMIT, 0, 0)]
    else:
        op = RULE_CHOOSE_FIRSTN if firstn else RULE_CHOOSE_INDEP
        steps = [(RULE_TAKE, root, 0), (op, numrep, 0), (RULE_EMIT, 0, 0)]
    ruleno = cmap.add_rule(Rule(steps=steps))
    return cmap, ruleno
