"""CRUSH map data structures and builder.

Mirrors reference src/crush/crush.h (map/bucket/rule structs, :229-366) and
the builder API (src/crush/builder.c): buckets have negative ids, devices
non-negative; rules are step programs for the crush_do_rule VM.  Tunable
defaults are the reference's "optimal" (jewel) profile, which OSDMaps of the
reference era deploy.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

CRUSH_ITEM_NONE = 0x7FFFFFFF
CRUSH_ITEM_UNDEF = 0x7FFFFFFE

# rule step opcodes (reference crush.h:55-69)
RULE_NOOP = 0
RULE_TAKE = 1
RULE_CHOOSE_FIRSTN = 2
RULE_CHOOSE_INDEP = 3
RULE_EMIT = 4
RULE_CHOOSELEAF_FIRSTN = 6
RULE_CHOOSELEAF_INDEP = 7
RULE_SET_CHOOSE_TRIES = 8
RULE_SET_CHOOSELEAF_TRIES = 9
RULE_SET_CHOOSE_LOCAL_TRIES = 10
RULE_SET_CHOOSE_LOCAL_FALLBACK_TRIES = 11
RULE_SET_CHOOSELEAF_VARY_R = 12
RULE_SET_CHOOSELEAF_STABLE = 13

BUCKET_UNIFORM = 1
BUCKET_LIST = 2
BUCKET_TREE = 3
BUCKET_STRAW = 4
BUCKET_STRAW2 = 5

_ALG_NAMES = {
    "uniform": BUCKET_UNIFORM,
    "list": BUCKET_LIST,
    "tree": BUCKET_TREE,
    "straw": BUCKET_STRAW,
    "straw2": BUCKET_STRAW2,
}


@dataclass
class Tunables:
    """Reference 'optimal' (jewel) profile; crush_do_rule semantics at
    mapper.c:904-918."""

    choose_total_tries: int = 50
    choose_local_tries: int = 0
    choose_local_fallback_tries: int = 0
    chooseleaf_descend_once: int = 1
    chooseleaf_vary_r: int = 1
    chooseleaf_stable: int = 1

    @classmethod
    def legacy(cls) -> "Tunables":
        """crush_create() defaults (argonaut-era)."""
        return cls(
            choose_total_tries=19,
            choose_local_tries=2,
            choose_local_fallback_tries=5,
            chooseleaf_descend_once=0,
            chooseleaf_vary_r=0,
            chooseleaf_stable=0,
        )


@dataclass
class Bucket:
    id: int  # negative
    type: int  # 0 = device, >0 = bucket level
    alg: str = "straw2"
    hash: int = 0  # CRUSH_HASH_RJENKINS1
    items: List[int] = field(default_factory=list)
    weights: List[int] = field(default_factory=list)  # 16.16 fixed per item

    @property
    def size(self) -> int:
        return len(self.items)

    @property
    def weight(self) -> int:
        return sum(self.weights)

    # -- derived per-alg data (reference builder.c constructions) ----------

    @property
    def sum_weights(self) -> List[int]:
        """List bucket prefix sums (crush_make_list_bucket,
        builder.c:259-272)."""
        out, w = [], 0
        for wi in self.weights:
            w += wi
            out.append(w)
        return out

    @property
    def tree_data(self):
        """(num_nodes, node_weights) for a tree bucket
        (crush_make_tree_bucket, builder.c:352-392): item i lives at node
        (i+1)*2-1; internal nodes sum their subtree weights."""
        size = self.size
        if size == 0:
            return 0, []
        depth = 1
        t = size - 1
        while t:
            t >>= 1
            depth += 1
        num_nodes = 1 << depth
        nw = [0] * num_nodes
        for i, wi in enumerate(self.weights):
            node = ((i + 1) << 1) - 1
            nw[node] = wi
            for _ in range(1, depth):
                node = _tree_parent(node)
                nw[node] += wi
        return num_nodes, nw

    def straws(self, straw_calc_version: int = 1) -> List[int]:
        """Classic straw scaling factors (crush_calc_straw,
        builder.c:427-540, both calc versions)."""
        size = self.size
        weights = self.weights
        # reverse sort by weight, stable insertion (builder.c:436-454)
        reverse = [0] if size else []
        for i in range(1, size):
            for j in range(i):
                if weights[i] < weights[reverse[j]]:
                    reverse.insert(j, i)
                    break
            else:
                reverse.append(i)
        straws = [0] * size
        numleft = size
        straw = 1.0
        wbelow = 0.0
        lastw = 0.0
        i = 0
        while i < size:
            if straw_calc_version == 0:
                if weights[reverse[i]] == 0:
                    straws[reverse[i]] = 0
                    i += 1
                    continue
                straws[reverse[i]] = int(straw * 0x10000)
                i += 1
                if i == size:
                    break
                if weights[reverse[i]] == weights[reverse[i - 1]]:
                    continue
                wbelow += (weights[reverse[i - 1]] - lastw) * numleft
                j = i
                while j < size:
                    if weights[reverse[j]] == weights[reverse[i]]:
                        numleft -= 1
                    else:
                        break
                    j += 1
                wnext = numleft * (weights[reverse[i]] -
                                   weights[reverse[i - 1]])
                pbelow = wbelow / (wbelow + wnext)
                straw *= (1.0 / pbelow) ** (1.0 / numleft)
                lastw = weights[reverse[i - 1]]
            else:
                if weights[reverse[i]] == 0:
                    straws[reverse[i]] = 0
                    i += 1
                    numleft -= 1
                    continue
                straws[reverse[i]] = int(straw * 0x10000)
                i += 1
                if i == size:
                    break
                wbelow += (weights[reverse[i - 1]] - lastw) * numleft
                numleft -= 1
                wnext = numleft * (weights[reverse[i]] -
                                   weights[reverse[i - 1]])
                pbelow = wbelow / (wbelow + wnext)
                straw *= (1.0 / pbelow) ** (1.0 / numleft)
                lastw = weights[reverse[i - 1]]
        return straws


def _tree_height(n: int) -> int:
    h = 0
    while (n & 1) == 0:
        h += 1
        n >>= 1
    return h


def _tree_parent(n: int) -> int:
    h = _tree_height(n)
    if n & (1 << (h + 1)):
        return n - (1 << h)
    return n + (1 << h)


@dataclass
class ChooseArg:
    """Per-bucket straw2 overrides (reference crush_choose_arg,
    crush.h:273-278): pg-upmap/balancer-era weight sets + id remaps."""

    ids: Optional[List[int]] = None
    weight_set: Optional[List[List[int]]] = None  # per-position weights


@dataclass
class Rule:
    steps: List[Tuple[int, int, int]]
    ruleset: int = 0
    type: int = 1  # pg_pool type: 1 replicated, 3 erasure
    min_size: int = 1
    max_size: int = 10


class CrushMap:
    def __init__(self, tunables: Optional[Tunables] = None):
        self.buckets: Dict[int, Bucket] = {}
        self.rules: List[Rule] = []
        self.max_devices = 0
        self.tunables = tunables or Tunables()
        self.type_names: Dict[int, str] = {0: "osd", 1: "host", 2: "rack", 3: "root"}
        self.item_names: Dict[int, str] = {}
        self.straw_calc_version = 1
        # named choose_args sets: name -> {bucket_id: ChooseArg}
        # (reference crush_choose_arg_map, CrushWrapper choose_args)
        self.choose_args: Dict[str, Dict[int, "ChooseArg"]] = {}
        # device classes (reference CrushWrapper class_map + shadow trees)
        self.device_class: Dict[int, str] = {}
        self._class_shadow: Dict[Tuple[int, str], int] = {}

    # -- builder (reference builder.c semantics) ---------------------------

    def add_bucket(self, bucket: Bucket, name: Optional[str] = None) -> int:
        if bucket.id >= 0:
            bucket.id = -1 - len(self.buckets)
        self.buckets[bucket.id] = bucket
        for item in bucket.items:
            if item >= 0:
                self.max_devices = max(self.max_devices, item + 1)
        if name:
            self.item_names[bucket.id] = name
        return bucket.id

    def make_straw2(
        self,
        type: int,
        items: List[int],
        weights: List[int],
        name: Optional[str] = None,
    ) -> int:
        return self.add_bucket(
            Bucket(id=0, type=type, alg="straw2", items=list(items),
                   weights=list(weights)),
            name,
        )

    # -- device classes (reference CrushWrapper device classes: shadow
    #    per-class hierarchies so rules can take "root~class") -------------

    def set_device_class(self, dev: int, cls: str) -> None:
        self.device_class[dev] = cls
        # class changes invalidate every shadow tree (reference rebuilds
        # them on map mutation); stale shadows would place data on the
        # wrong class silently.  Old shadow buckets stay in the map
        # (ids must remain dense) but are no longer reachable.
        self._class_shadow.clear()

    def class_root(self, root_id: int, cls: str) -> int:
        """Shadow bucket id for ``root~cls``: a copy of the subtree keeping
        only devices of the class, weights recomputed bottom-up (the
        reference's class shadow trees, CrushWrapper::populate_classes)."""
        key = (root_id, cls)
        cached = self._class_shadow.get(key)
        if cached is not None:
            return cached
        shadow = self._build_class_shadow(root_id, cls)
        if shadow is None:
            raise ValueError(f"no devices of class {cls!r} under {root_id}")
        self._class_shadow[key] = shadow
        return shadow

    def _build_class_shadow(self, bid: int, cls: str) -> Optional[int]:
        b = self.buckets[bid]
        items: List[int] = []
        weights: List[int] = []
        for item, w in zip(b.items, b.weights):
            if item >= 0:
                if self.device_class.get(item) == cls:
                    items.append(item)
                    weights.append(w)
            else:
                sub = self._build_class_shadow(item, cls)
                if sub is not None:
                    items.append(sub)
                    weights.append(self.buckets[sub].weight)
        if not items:
            return None
        name = self.item_names.get(bid)
        return self.add_bucket(
            Bucket(id=0, type=b.type, alg=b.alg, hash=b.hash,
                   items=items, weights=weights),
            name=f"{name}~{cls}" if name else None)

    def add_rule(self, rule: Rule) -> int:
        self.rules.append(rule)
        return len(self.rules) - 1

    # -- elastic mutation (reference CrushWrapper insert_item /
    #    remove_item: grow adds device-bearing host buckets under an
    #    existing root; drain unlinks a purged device and reweights the
    #    ancestor chain).  Bucket ids stay DENSE — nothing is ever
    #    deleted from ``buckets`` (the set_device_class shadow-tree
    #    rule), only unlinked — so the vectorized mapper's dense-id
    #    assumption survives every reshape.

    def parent_of(self, item: int) -> Optional[int]:
        for bid, b in self.buckets.items():
            if item in b.items:
                return bid
        return None

    def _reweight_item(self, parent: int, item: int, weight: int) -> None:
        b = self.buckets[parent]
        i = b.items.index(item)
        if b.weights[i] == weight:
            return
        b.weights[i] = weight
        gp = self.parent_of(parent)
        if gp is not None:
            self._reweight_item(gp, parent, b.weight)

    def add_host(self, name: str, devices: List[int],
                 weights: Optional[List[int]] = None,
                 root: str = "default") -> int:
        """Grow: a new host bucket holding ``devices``, linked under the
        named root with the ancestor weights bumped (CrushWrapper
        insert_item semantics: weights propagate to the top)."""
        weights = weights or [0x10000] * len(devices)
        root_id = next((bid for bid, n in self.item_names.items()
                        if n == root), None)
        if root_id is None:
            raise KeyError(f"no root bucket named {root!r}")
        hid = self.make_straw2(1, devices, weights, name=name)
        rb = self.buckets[root_id]
        rb.items.append(hid)
        rb.weights.append(self.buckets[hid].weight)
        gp = self.parent_of(root_id)
        if gp is not None:
            self._reweight_item(gp, root_id, rb.weight)
        self._class_shadow.clear()
        return hid

    def remove_device(self, dev: int) -> bool:
        """Drain: unlink a purged device from its holding bucket and
        reweight the chain above it; a host left empty is unlinked from
        its parent too (but stays in ``buckets`` — dense ids).  Returns
        whether anything was unlinked."""
        holder = self.parent_of(dev)
        if holder is None:
            return False
        b = self.buckets[holder]
        i = b.items.index(dev)
        del b.items[i]
        del b.weights[i]
        parent = self.parent_of(holder)
        if parent is not None:
            if b.items:
                self._reweight_item(parent, holder, b.weight)
            else:
                pb = self.buckets[parent]
                j = pb.items.index(holder)
                del pb.items[j]
                del pb.weights[j]
                gp = self.parent_of(parent)
                if gp is not None:
                    self._reweight_item(gp, parent, pb.weight)
        self.device_class.pop(dev, None)
        self._class_shadow.clear()
        return True

    def bucket(self, item_id: int) -> Bucket:
        return self.buckets[item_id]

    def max_depth(self) -> int:
        """Longest bucket chain (for bounding vectorized descents)."""

        def depth(bid: int) -> int:
            b = self.buckets[bid]
            best = 1
            for item in b.items:
                if item < 0:
                    best = max(best, 1 + depth(item))
            return best

        return max((depth(bid) for bid in self.buckets), default=0)


def build_three_level(
    n_racks: int,
    hosts_per_rack: int,
    osds_per_host: int,
    numrep: int = 3,
    weight: int = 0x10000,
) -> Tuple[CrushMap, int]:
    """root -> rack -> host -> osd map + chooseleaf-firstn rule (the
    deployment shape of large clusters; keeps bucket fanouts narrow)."""
    cmap = CrushMap()
    rack_ids, rack_w = [], []
    dev = 0
    for r in range(n_racks):
        host_ids, host_w = [], []
        for h in range(hosts_per_rack):
            items = list(range(dev, dev + osds_per_host))
            dev += osds_per_host
            weights = [weight] * osds_per_host
            hid = cmap.make_straw2(1, items, weights, name=f"host{r}-{h}")
            host_ids.append(hid)
            host_w.append(sum(weights))
        rid = cmap.make_straw2(2, host_ids, host_w, name=f"rack{r}")
        rack_ids.append(rid)
        rack_w.append(sum(host_w))
    root = cmap.make_straw2(3, rack_ids, rack_w, name="default")
    steps = [(RULE_TAKE, root, 0), (RULE_CHOOSELEAF_FIRSTN, numrep, 1),
             (RULE_EMIT, 0, 0)]
    ruleno = cmap.add_rule(Rule(steps=steps))
    return cmap, ruleno


def build_hierarchy(
    n_hosts: int,
    osds_per_host: int,
    numrep: int = 3,
    weight: int = 0x10000,
    chooseleaf: bool = True,
    firstn: bool = True,
) -> Tuple[CrushMap, int]:
    """Standard root->host->osd map + rule (the shape OSDMaps deploy)."""
    cmap = CrushMap()
    host_ids, host_weights = [], []
    dev = 0
    for h in range(n_hosts):
        items = list(range(dev, dev + osds_per_host))
        dev += osds_per_host
        weights = [weight] * osds_per_host
        hid = cmap.make_straw2(1, items, weights, name=f"host{h}")
        host_ids.append(hid)
        host_weights.append(sum(weights))
    root = cmap.make_straw2(3, host_ids, host_weights, name="default")
    if chooseleaf:
        op = RULE_CHOOSELEAF_FIRSTN if firstn else RULE_CHOOSELEAF_INDEP
        steps = [(RULE_TAKE, root, 0), (op, numrep, 1), (RULE_EMIT, 0, 0)]
    else:
        op = RULE_CHOOSE_FIRSTN if firstn else RULE_CHOOSE_INDEP
        steps = [(RULE_TAKE, root, 0), (op, numrep, 0), (RULE_EMIT, 0, 0)]
    ruleno = cmap.add_rule(Rule(steps=steps))
    return cmap, ruleno
