#!/usr/bin/env python3
"""balance: drive the graft-balance mgr subsystem on an ephemeral cluster.

Everything in this repo is in-process: there is no long-lived daemon to
connect to, so each subcommand boots a small vstart cluster with a mgr,
issues the corresponding ``balance *`` admin-socket command, and prints
the result.  The background loops stay OFF (``mgr_balancer_enabled=0``)
— the CLI is the explicit, pull-driven way to exercise the subsystem,
exactly like ``ceph balancer ...`` / ``ceph osd pool autoscale-status``
against a dev cluster.

    python scripts/balance.py status    [--osds N] [--json]
    python scripts/balance.py optimize  [--osds N] [--pg-num N] [--dry-run]
    python scripts/balance.py autoscale [--osds N] [--objects N] [--dry-run]
    python scripts/balance.py grow      --count N [--osds-per-host N]
    python scripts/balance.py drain     --osds 2,3 [--cluster-osds N]

Exit codes: 0 = command succeeded, 1 = operation failed (commit error,
reshape op stuck short of ``done``), 2 = usage error (bad arguments,
draining an OSD the cluster doesn't have).
"""

import argparse
import asyncio
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# how long a grow/drain reshape op may take to reach "done" before the
# CLI calls it stuck (small clusters settle in a few seconds; the
# margin absorbs first-JIT stalls)
RESHAPE_DEADLINE = 120.0


def _config():
    from ceph_tpu.cluster.vstart import _fast_config

    cfg = _fast_config()
    # loops off: every balancer/autoscaler/reshaper step below happens
    # because WE asked for it, so a run is deterministic and a disabled
    # subsystem provably does nothing in the background
    cfg.mgr_balancer_enabled = 0
    cfg.mgr_autoscale_enabled = 0
    return cfg


async def _boot(n_osds: int, osds_per_host: int = 1):
    from ceph_tpu.cluster.vstart import start_cluster

    cluster = await start_cluster(n_osds, osds_per_host=osds_per_host,
                                  config=_config(), with_mgr=True)
    client = await cluster.client()
    return cluster, client


async def _seed_pool(cluster, client, pg_num: int, objects: int = 0,
                     size: int = 3):
    pool = await client.pool_create("balance", "replicated",
                                    pg_num=pg_num, size=size)
    io = client.ioctx(pool)
    for i in range(objects):
        await io.write_full(f"obj{i}", f"balance-{i}".encode() * 8)
    # let the fresh pool finish peering: the balancer (correctly)
    # refuses to optimize through PG_RECOVERING, and a just-created
    # pool is briefly exactly that
    loop = asyncio.get_event_loop()
    deadline = loop.time() + 30.0
    while loop.time() < deadline:
        if cluster.mon._health_data()["status"] == "HEALTH_OK":
            break
        await asyncio.sleep(0.1)
    return pool


async def _reshape_done(cluster, op_id: int, on_phase=None) -> dict:
    """Poll ``balance status`` (the pull-driven advance) until the op
    reaches ``done`` or the deadline passes.  ``on_phase(op)`` runs on
    every poll — the drain flow uses it to play the operator's part
    (stopping daemons once the op says ``wait-down``)."""
    loop = asyncio.get_event_loop()
    deadline = loop.time() + RESHAPE_DEADLINE
    last = {}
    while loop.time() < deadline:
        status = await cluster.daemon_command("mgr", "balance status")
        for op in status.get("reshape_ops", []):
            if op.get("id") == op_id:
                last = op
        if last.get("phase") == "done":
            return last
        if on_phase is not None and last:
            await on_phase(last)
        await asyncio.sleep(0.25)
    return last


def _print(doc, as_json: bool) -> None:
    if as_json:
        print(json.dumps(doc, indent=2, sort_keys=True, default=str))
    else:
        for k in sorted(doc):
            print(f"{k:18s} {doc[k]}")


async def _cmd_status(args) -> int:
    cluster, client = await _boot(args.osds)
    try:
        await _seed_pool(cluster, client, args.pg_num)
        status = await cluster.daemon_command("mgr", "balance status")
        _print(status, args.json)
        return 0
    finally:
        await cluster.stop()


async def _cmd_optimize(args) -> int:
    cluster, client = await _boot(args.osds)
    try:
        await _seed_pool(cluster, client, args.pg_num)
        result = await cluster.daemon_command(
            "mgr", {"prefix": "balance optimize",
                    "dry_run": bool(args.dry_run)})
        _print(result, args.json)
        if "commit_error" in result:
            print(f"FAIL commit: {result['commit_error']}",
                  file=sys.stderr)
            return 1
        verdict = ("planned" if args.dry_run else "committed",
                   result.get("moves", 0), "moves")
        print("OK", *verdict)
        return 0
    finally:
        await cluster.stop()


async def _cmd_autoscale(args) -> int:
    cluster, client = await _boot(args.osds)
    try:
        await _seed_pool(cluster, client, args.pg_num,
                         objects=args.objects)
        result = await cluster.daemon_command(
            "mgr", {"prefix": "balance autoscale",
                    "dry_run": bool(args.dry_run)})
        _print(result, args.json)
        print("OK autoscale round complete")
        return 0
    finally:
        await cluster.stop()


async def _cmd_grow(args) -> int:
    cluster, client = await _boot(args.osds)
    try:
        await _seed_pool(cluster, client, args.pg_num, objects=8)
        op = await cluster.daemon_command(
            "mgr", {"prefix": "balance grow", "count": args.count,
                    "osds_per_host": args.osds_per_host})
        # the mon mints the ids + CRUSH hosts; booting the daemons is
        # the operator's job (vstart analog of racking new drives)
        new_ids = op.get("osds", [])
        await cluster.boot_osds(new_ids)
        final = await _reshape_done(cluster, op["id"])
        _print(final or op, args.json)
        if final.get("phase") != "done":
            print(f"FAIL grow op {op['id']} stuck in phase "
                  f"{final.get('phase')!r}", file=sys.stderr)
            return 1
        print(f"OK grew {args.osds} -> {args.osds + args.count} OSDs "
              f"(ids {new_ids})")
        return 0
    finally:
        await cluster.stop()


async def _cmd_drain(args, osd_ids) -> int:
    cluster, client = await _boot(args.cluster_osds)
    try:
        await _seed_pool(cluster, client, args.pg_num, objects=8)
        op = await cluster.daemon_command(
            "mgr", {"prefix": "balance drain", "osds": osd_ids})

        async def stop_when_drained(cur):
            # the operator's half of the handshake: once the op says
            # wait-down (data moved off), stop the retiring daemons so
            # the mon can mark them down and the op can purge them
            if cur.get("phase") != "wait-down":
                return
            for o in osd_ids:
                osd = cluster.osds.pop(o, None)
                if osd is not None:
                    await osd.stop()

        final = await _reshape_done(cluster, op["id"],
                                    on_phase=stop_when_drained)
        _print(final or op, args.json)
        if final.get("phase") != "done":
            print(f"FAIL drain op {op['id']} stuck in phase "
                  f"{final.get('phase')!r}", file=sys.stderr)
            return 1
        print(f"OK drained OSDs {osd_ids}")
        return 0
    finally:
        await cluster.stop()


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)
    for name in ("status", "optimize", "autoscale", "grow", "drain"):
        p = sub.add_parser(name)
        p.add_argument("--pg-num", type=int, default=32)
        p.add_argument("--json", action="store_true")
        if name == "drain":
            p.add_argument("--cluster-osds", type=int, default=5,
                           help="cluster size to boot (default 5)")
            p.add_argument("--osds", required=True,
                           help="comma-separated OSD ids to drain")
        else:
            p.add_argument("--osds", type=int, default=4,
                           help="cluster size to boot (default 4)")
        if name in ("optimize", "autoscale"):
            p.add_argument("--dry-run", action="store_true")
        if name == "autoscale":
            p.add_argument("--objects", type=int, default=64)
        if name == "grow":
            p.add_argument("--count", type=int, required=True)
            p.add_argument("--osds-per-host", type=int, default=1)
    args = ap.parse_args()

    if args.cmd == "grow" and args.count <= 0:
        print(f"grow --count must be positive (got {args.count})",
              file=sys.stderr)
        return 2
    if args.cmd == "drain":
        try:
            osd_ids = [int(o) for o in args.osds.split(",") if o.strip()]
        except ValueError:
            print(f"unparsable --osds {args.osds!r} "
                  "(want e.g. --osds 2,3)", file=sys.stderr)
            return 2
        bad = [o for o in osd_ids if o < 0 or o >= args.cluster_osds]
        if not osd_ids or bad:
            print(f"--osds {args.osds!r} names OSDs outside the "
                  f"{args.cluster_osds}-OSD cluster", file=sys.stderr)
            return 2
        if len(osd_ids) >= args.cluster_osds:
            print("refusing to drain every OSD in the cluster",
                  file=sys.stderr)
            return 2
        return asyncio.run(_cmd_drain(args, osd_ids))

    handler = {"status": _cmd_status, "optimize": _cmd_optimize,
               "autoscale": _cmd_autoscale, "grow": _cmd_grow}[args.cmd]
    return asyncio.run(handler(args))


if __name__ == "__main__":
    sys.exit(main())
