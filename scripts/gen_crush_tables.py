#!/usr/bin/env python
"""Regenerate ceph_tpu/crush/_ll_table.py from the reference checkout.

The straw2 draw uses a fixed-point log2 LUT (reference src/crush/
crush_ln_table.h).  The RH/LH halves follow exact closed forms
(RH[k] = ceil(2^48*128/(128+k)), LH[k] = floor(2^48*log2(1+k/128)) — verified
against every entry) and are generated at import time.  The LL half deviates
from its documented formula for most entries (generation artifacts in the
original table); those 256 values are therefore pinned here as protocol
constants — placements must match the deployed table bit-for-bit, not an
idealized one.

Usage: python scripts/gen_crush_tables.py [path-to-reference-checkout]
"""

import re
import sys

ref = sys.argv[1] if len(sys.argv) > 1 else "/root/reference"
src = open(f"{ref}/src/crush/crush_ln_table.h").read()
m = re.search(r"__LL_tbl\[256\]\s*=\s*\{(.*?)\};", src, re.S)
ll = [int(x, 16) for x in re.findall(r"0x([0-9a-fA-F]+)ull", m.group(1))]
assert len(ll) == 256

with open("ceph_tpu/crush/_ll_table.py", "w") as f:
    f.write('"""LL half of the straw2 log2 LUT — protocol constants.\n\n')
    f.write("Pinned from the reference crush_ln_table.h (see\n")
    f.write("scripts/gen_crush_tables.py); nominally 2^48*log2(1+k/2^15) but the\n")
    f.write("deployed table deviates from that formula for most entries, and\n")
    f.write('placement compatibility requires the deployed values.\n"""\n\n')
    f.write("LL_TBL = (\n")
    for i in range(0, 256, 4):
        f.write("    " + ", ".join(f"0x{v:012x}" for v in ll[i : i + 4]) + ",\n")
    f.write(")\n")
print("wrote ceph_tpu/crush/_ll_table.py")
