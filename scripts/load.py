#!/usr/bin/env python
"""graft-load CLI: seeded traffic windows, saturation ramps, soaks.

    python scripts/load.py list
    python scripts/load.py plan --spec smoke --seed 42
    python scripts/load.py run  --spec smoke --seed 42 [--json]
    python scripts/load.py ramp --spec ramp-ec --seed 42 [--out PATH]
    python scripts/load.py soak --scenario soak-mixed-crash --seed 42
    python scripts/load.py report [PATH]

``plan`` prints the resolved per-client op schedule's replay key (and
op counts) WITHOUT booting a cluster — two invocations with one seed
print identical output, the replay contract made cheap to eyeball.
``run`` drives one judged window: exit 0 when every SLO gate passes,
1 otherwise.  ``ramp`` sweeps the offered rate, writes a LOAD_r*.json
artifact beside the BENCH records, and exits 0 iff a knee was found
(at least one step passed every gate).  ``soak`` composes sustained
traffic with a seeded chaos fault schedule: exit 0 iff the durability/
frontier invariants hold.  ``--gate name=value`` overrides one SLO
threshold (e.g. ``--gate p99_ms=50`` to watch a gate fail).
"""

from __future__ import annotations

import argparse
import asyncio
import glob
import json
import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _gate_overrides(spec, pairs):
    if not pairs:
        return spec
    from dataclasses import replace

    gates = dict(spec.gates)
    for pair in pairs:
        name, _, value = pair.partition("=")
        if name not in gates:
            # a typo'd gate must not silently judge nothing
            print(f"unknown gate '{name}' "
                  f"(try: {', '.join(sorted(gates))})", file=sys.stderr)
            raise SystemExit(2)
        try:
            gates[name] = float(value)
        except ValueError:
            print(f"gate '{name}' needs a numeric threshold, got "
                  f"{value!r}", file=sys.stderr)
            raise SystemExit(2)
    return replace(spec, gates=tuple(sorted(gates.items())))


def _with_blackbox(spec, args):
    """Arm the graft-blackbox recorder for a CLI run (on by default:
    a failed judgment auto-produces a POSTMORTEM_*.json bundle in
    --postmortem DIR; --no-postmortem reverts to the library default
    of blackbox_enabled=0)."""
    if getattr(args, "no_postmortem", False):
        return spec
    from dataclasses import replace

    return replace(spec, config=tuple(spec.config) + (
        ("blackbox_enabled", 1),
        ("blackbox_dir", os.path.abspath(args.postmortem))))


def _with_tmpdir(spec_store, fn):
    tmpdir = None
    try:
        if spec_store != "mem":
            tmpdir = tempfile.mkdtemp(prefix="graft_load_")
        return fn(tmpdir)
    finally:
        if tmpdir is not None:
            import shutil

            shutil.rmtree(tmpdir, ignore_errors=True)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)
    sub.add_parser("list", help="list built-in load specs and soaks")
    for name in ("plan", "run", "ramp"):
        p = sub.add_parser(name)
        p.add_argument("--spec", required=True)
        p.add_argument("--seed", type=int, default=1)
        p.add_argument("--json", action="store_true")
        p.add_argument("--gate", action="append", default=[],
                       metavar="NAME=VALUE",
                       help="override one SLO gate threshold")
        if name in ("run", "ramp"):
            p.add_argument("--postmortem", default=".", metavar="DIR",
                           help="directory for triggered "
                                "POSTMORTEM_*.json bundles (default .)")
            p.add_argument("--no-postmortem", action="store_true",
                           help="disable the flight recorder / "
                                "postmortem bundles for this run")
        if name == "ramp":
            p.add_argument("--scales", default=None,
                           help="comma-separated rate multipliers "
                                "(default 1,2,4,8,16,32,64)")
            p.add_argument("--out", default=None,
                           help="artifact path (default LOAD_r<n>.json)")
    p = sub.add_parser("soak")
    p.add_argument("--scenario", required=True)
    p.add_argument("--seed", type=int, default=1)
    p.add_argument("--json", action="store_true")
    p.add_argument("--postmortem", default=".", metavar="DIR",
                   help="directory for triggered POSTMORTEM_*.json "
                        "bundles (default .)")
    p.add_argument("--no-postmortem", action="store_true",
                   help="disable the flight recorder / postmortem "
                        "bundles for this run")
    p = sub.add_parser("report")
    p.add_argument("path", nargs="?", default=None,
                   help="LOAD_r*.json (default: latest)")
    args = ap.parse_args()

    from ceph_tpu.load import ramp as rampmod
    from ceph_tpu.load.driver import build_plan, builtin_specs, plan_key, run_load
    from ceph_tpu.load.soak import builtin_soaks, run_soak

    specs = builtin_specs()
    soaks = builtin_soaks()
    if args.cmd == "list":
        for name, sp in sorted(specs.items()):
            print(f"{name:16s} clients={sp.clients} sessions={sp.sessions} "
                  f"rate={sp.rate}/client x {sp.duration}s "
                  f"pool={sp.pool_kind} verbs="
                  + ",".join(v for v, _ in sp.verbs))
        for name, sk in sorted(soaks.items()):
            print(f"{name:24s} [soak] rounds={sk.rounds} "
                  f"store={sk.load.store} "
                  f"invariants={','.join(sk.invariants)}")
        return 0

    if args.cmd == "soak":
        sk = soaks.get(args.scenario)
        if sk is None:
            print(f"unknown soak {args.scenario!r} "
                  f"(try: {', '.join(sorted(soaks))})", file=sys.stderr)
            return 2
        from dataclasses import replace as _replace

        sk = _replace(sk, load=_with_blackbox(sk.load, args))
        verdict = _with_tmpdir(sk.load.store, lambda tmpdir: asyncio.run(
            run_soak(sk, args.seed, tmpdir=tmpdir)))
        if args.json:
            print(json.dumps(verdict.as_dict(), indent=2))
        else:
            print(f"soak {verdict.name} seed={verdict.seed}: "
                  f"{'PASS' if verdict.passed else 'FAIL'} "
                  f"({verdict.acked_objects} tracked objects, "
                  f"faults={verdict.counters})")
            for f in verdict.failures:
                print(f"  FAIL {f}")
            if verdict.postmortem:
                print(f"  postmortem: {verdict.postmortem}")
        return 0 if verdict.passed else 1

    if args.cmd == "report":
        path = args.path
        if path is None:
            arts = sorted(glob.glob(os.path.join(REPO, "LOAD_r*.json")))
            if not arts:
                print("no LOAD_r*.json artifacts", file=sys.stderr)
                return 2
            path = arts[-1]
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            print(f"unreadable artifact {path}: {e}", file=sys.stderr)
            return 2
        print(rampmod.format_table(doc))
        return 0

    spec = specs.get(args.spec)
    if spec is None:
        print(f"unknown spec {args.spec!r} "
              f"(try: {', '.join(sorted(specs))})", file=sys.stderr)
        return 2
    spec = _gate_overrides(spec, args.gate)

    if args.cmd == "plan":
        plan = build_plan(spec, args.seed)
        doc = {"spec": spec.name, "seed": args.seed,
               "replay_key": plan_key(plan),
               "clients": len(plan),
               "offered_ops": sum(len(ops) for ops in plan),
               "verbs": {}}
        for ops in plan:
            for op in ops:
                doc["verbs"][op["verb"]] = \
                    doc["verbs"].get(op["verb"], 0) + 1
        print(json.dumps(doc, indent=2, sort_keys=True))
        return 0

    if args.cmd == "run":
        spec = _with_blackbox(spec, args)
        result, report = _with_tmpdir(
            spec.store, lambda tmpdir: asyncio.run(
                run_load(spec, args.seed, tmpdir=tmpdir)))
        if args.json:
            print(json.dumps({"result": result.as_dict(),
                              "gates": report.as_rows(),
                              "passed": report.passed,
                              "postmortem": report.postmortem},
                             indent=2))
        else:
            print(f"load {spec.name} seed={args.seed}: "
                  f"{'ALL GATES PASS' if report.passed else 'GATE FAIL'} "
                  f"({result.acked_ops}/{result.offered} acked, "
                  f"plan {result.plan_key[:12]})")
            for r in report.as_rows():
                mark = "PASS" if r["passed"] else "FAIL"
                print(f"  {mark} {r['gate']:8s} value={r['value']} "
                      f"threshold={r['threshold']} [{r['source']}]"
                      + (f" {r['note']}" if r["note"] else ""))
            if report.postmortem:
                print(f"  postmortem: {report.postmortem}")
        return 0 if report.passed else 1

    # ramp
    spec = _with_blackbox(spec, args)
    scales = tuple(float(s) for s in args.scales.split(",")) \
        if args.scales else rampmod.DEFAULT_SCALES
    doc = _with_tmpdir(spec.store, lambda tmpdir: asyncio.run(
        rampmod.ramp(spec, args.seed, scales=scales, tmpdir=tmpdir)))
    path = rampmod.write_artifact(doc, out=args.out)
    if args.json:
        print(json.dumps(doc, indent=2))
    else:
        print(rampmod.format_table(doc))
    # stderr: --json stdout must stay a parseable document
    print(f"wrote {path}", file=sys.stderr)
    return 0 if doc.get("knee") else 1


if __name__ == "__main__":
    sys.exit(main())
