#!/usr/bin/env python
"""graft-chaos CLI: run seeded fault-injection scenarios.

    python scripts/chaos.py list
    python scripts/chaos.py schedule --scenario smoke --seed 42
    python scripts/chaos.py run --scenario smoke --seed 42 [--json]

``run`` exits 0 when every invariant holds, 1 otherwise; ``schedule``
prints the resolved fault plan WITHOUT booting a cluster (two
invocations with the same seed print identical plans — the replay
contract, cheap to eyeball).  Scenarios with durable stores get a
temporary directory that is removed afterwards.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)
    sub.add_parser("list", help="list built-in scenarios")
    for name in ("schedule", "run"):
        p = sub.add_parser(name)
        p.add_argument("--scenario", required=True)
        p.add_argument("--seed", type=int, default=1)
        p.add_argument("--json", action="store_true")
        p.add_argument("--scale", type=float, default=1.0,
                       help="storm-scenario size factor: 1.0 = the "
                            "full acceptance shape (slow), small "
                            "fractions run the same code paths at "
                            "tier-1 size (e.g. --scale 0.06)")
        if name == "run":
            p.add_argument("--postmortem", default=".", metavar="DIR",
                           help="directory for triggered "
                                "POSTMORTEM_*.json bundles (default .)")
            p.add_argument("--no-postmortem", action="store_true",
                           help="disable the flight recorder / "
                                "postmortem bundles for this run")
    args = ap.parse_args()

    from ceph_tpu.chaos.balance import (
        ElasticScenario,
        build_elastic_plan,
        elastic_scenarios,
        run_elastic,
    )
    from ceph_tpu.chaos.frontdoor import (
        FrontdoorScenario,
        frontdoor_scenarios,
        run_frontdoor,
    )
    from ceph_tpu.chaos.integrity import (
        FillScenario,
        build_fill_plan,
        integrity_scenarios,
        run_fill_drain,
    )
    from ceph_tpu.chaos.scenario import (
        build_schedule,
        builtin_scenarios,
        run_scenario,
        storm_scenarios,
    )

    scenarios = builtin_scenarios()
    scenarios.update(frontdoor_scenarios(1.0))
    scenarios.update(integrity_scenarios(1.0))
    scenarios.update(elastic_scenarios(1.0))
    if getattr(args, "scale", 1.0) != 1.0:
        scenarios.update(storm_scenarios(args.scale))
        scenarios.update(frontdoor_scenarios(args.scale))
        scenarios.update(integrity_scenarios(args.scale))
        scenarios.update(elastic_scenarios(args.scale))
    if args.cmd == "list":
        for name, sc in sorted(scenarios.items()):
            print(f"{name:24s} osds={sc.osds} rounds={sc.rounds} "
                  f"store={sc.store} invariants={','.join(sc.invariants)}")
        return 0
    sc = scenarios.get(args.scenario)
    if sc is None:
        print(f"unknown scenario {args.scenario!r} "
              f"(try: {', '.join(sorted(scenarios))})", file=sys.stderr)
        return 2
    if args.cmd == "schedule":
        if isinstance(sc, FillScenario):
            print(json.dumps(build_fill_plan(sc, args.seed), indent=2))
        elif isinstance(sc, ElasticScenario):
            print(json.dumps(build_elastic_plan(sc, args.seed),
                             indent=2))
        else:
            print(json.dumps(build_schedule(sc, args.seed), indent=2))
        return 0
    if not args.no_postmortem:
        # graft-blackbox on by default for CLI runs: a conviction (or a
        # fired crash point / HEALTH_ERR edge) auto-produces a bundle
        from dataclasses import replace

        sc = replace(sc, config=tuple(sc.config) + (
            ("blackbox_enabled", 1),
            ("blackbox_dir", os.path.abspath(args.postmortem))))
    tmpdir = None
    try:
        if sc.store != "mem":
            tmpdir = tempfile.mkdtemp(prefix="graft_chaos_")
        if isinstance(sc, FrontdoorScenario):
            verdict = asyncio.run(run_frontdoor(sc, args.seed,
                                                tmpdir=tmpdir))
        elif isinstance(sc, FillScenario):
            verdict = asyncio.run(run_fill_drain(sc, args.seed,
                                                 tmpdir=tmpdir))
        elif isinstance(sc, ElasticScenario):
            verdict = asyncio.run(run_elastic(sc, args.seed,
                                              tmpdir=tmpdir))
        else:
            verdict = asyncio.run(run_scenario(sc, args.seed,
                                               tmpdir=tmpdir))
    finally:
        if tmpdir is not None:
            import shutil

            shutil.rmtree(tmpdir, ignore_errors=True)
    if args.json:
        print(json.dumps(verdict.as_dict(), indent=2))
    else:
        print(f"scenario {verdict.name} seed={verdict.seed}: "
              f"{'PASS' if verdict.passed else 'FAIL'} "
              f"({verdict.acked_objects} acked objects, "
              f"faults={verdict.counters})")
        for f in verdict.failures:
            print(f"  FAIL {f}")
        if getattr(verdict, "postmortem", None):
            print(f"  postmortem: {verdict.postmortem}")
    return 0 if verdict.passed else 1


if __name__ == "__main__":
    sys.exit(main())
