#!/usr/bin/env python
"""graft-blackbox CLI: render postmortem bundles.

    python scripts/blackbox.py report   [PATH]
    python scripts/blackbox.py key      [PATH]
    python scripts/blackbox.py perfetto [PATH] --out trace.json

``report`` reconstructs the breach window from a POSTMORTEM_*.json
bundle: the trigger + failing gates, the per-stage attribution of the
late/convicted ops (wall_coverage over the breach set), the
top-suspects table (daemon/PG/stage), and the skew-corrected merged
cluster timeline.  ``key`` prints the bundle's deterministic replay
key (bit-identical across two runs of one seed — the seeded-replay
witness).  ``perfetto`` exports the bundle's op timelines + flight
rings as a chrome://tracing / Perfetto JSON document.

PATH defaults to the newest POSTMORTEM_*.json in the current
directory.  Exit codes: 0 success, 1 bundle found but malformed for
the request, 2 usage / no bundle.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _resolve(path) -> str:
    if path:
        return path
    bundles = sorted(glob.glob("POSTMORTEM_*.json"),
                     key=os.path.getmtime)
    if not bundles:
        print("no POSTMORTEM_*.json bundle here (pass a path)",
              file=sys.stderr)
        raise SystemExit(2)
    return bundles[-1]


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)
    for name in ("report", "key", "perfetto"):
        p = sub.add_parser(name)
        p.add_argument("path", nargs="?", default=None,
                       help="POSTMORTEM_*.json (default: newest here)")
        if name == "report":
            p.add_argument("--json", action="store_true",
                           help="emit the breach report as JSON")
            p.add_argument("--tail", type=int, default=30,
                           help="timeline events to show (default 30)")
        if name == "perfetto":
            p.add_argument("--out", default=None,
                           help="output path (default <bundle>.trace.json)")
    args = ap.parse_args()

    from ceph_tpu.trace import postmortem as pm

    path = _resolve(args.path)
    try:
        bundle = pm.load_bundle(path)
    except (OSError, ValueError) as e:
        print(f"unreadable bundle {path}: {e}", file=sys.stderr)
        return 2

    if args.cmd == "key":
        print(pm.replay_key(bundle))
        return 0

    if args.cmd == "perfetto":
        from ceph_tpu.trace.perfetto import write

        out = args.out or f"{path[:-5]}.trace.json"
        try:
            doc = pm.chrome_trace(bundle)
        except (KeyError, TypeError, ValueError) as e:
            print(f"cannot export {path}: {e}", file=sys.stderr)
            return 1
        write(out, doc)
        print(f"wrote {out} ({len(doc['traceEvents'])} events)")
        return 0

    # report
    try:
        if args.json:
            print(json.dumps(
                {"trigger": bundle.get("trigger"),
                 "replay_key": pm.replay_key(bundle),
                 "breach": bundle.get("breach")
                 or pm.breach_report(bundle)},
                indent=2, sort_keys=True))
        else:
            print(pm.render_report(bundle, timeline_tail=args.tail))
    except (KeyError, TypeError, ValueError) as e:
        print(f"malformed bundle {path}: {e}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
