#!/bin/sh
# Build + run the measured CPU baseline suite and write the repo-root
# BASELINE_MEASURED.json that bench.py uses for vs_baseline denominators.
# See ec_baseline.c / crush_baseline.c / crc_baseline.c headers for what
# each measures and why it stands in for the reference binaries
# (empty submodules in this checkout).
set -e
cd "$(dirname "$0")"
REF=${REF:-/root/reference}

python dump_ops.py > baseline_ops.h
gcc -O3 -march=native -o ec_baseline ec_baseline.c
gcc -O3 -march=native -o crc_baseline crc_baseline.c
gcc -O3 -I. -I../gen_crush_golden -I"$REF/src/crush" -I"$REF/src" \
    -o crush_baseline crush_baseline.c \
    "$REF/src/crush/mapper.c" "$REF/src/crush/builder.c" \
    "$REF/src/crush/crush.c" "$REF/src/crush/hash.c" -lm

# run each binary to its own file first so a mid-run crash fails the
# script (set -e alone would miss a failure on the left of a pipe)
./ec_baseline    > ec.out
./crc_baseline   > crc.out
./crush_baseline > crush.out

{
  echo '{'
  echo '  "host": "'"$(grep -m1 'model name' /proc/cpuinfo | cut -d: -f2 | sed 's/^ //')"'",'
  echo '  "date": "'"$(date -u +%Y-%m-%dT%H:%M:%SZ)"'",'
  echo '  "results": ['
  sed 's/$/,/' ec.out crc.out
  cat crush.out
  echo '  ]'
  echo '}'
} > ../../BASELINE_MEASURED.json
rm -f ec.out crc.out crush.out
echo "wrote BASELINE_MEASURED.json"
