/* Measured CPU baseline for crc32c (Castagnoli), the reference's
 * hardware path (src/common/crc32c_intel_fast.c: SSE4.2 crc32
 * instruction, 3-way interleaved in the asm version).  This implements
 * the same scheme: split each buffer into 3 lanes, run the crc32q
 * instruction down each (breaking the 3-cycle latency chain), and merge
 * with a GF(2) shift-combine (the crc32_combine construction).  Times
 * the bench.py workload: 4096 buffers x 4096 bytes.
 *
 * Build: gcc -O3 -march=native -o crc_baseline crc_baseline.c
 */

#include <nmmintrin.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <time.h>

#define POLY 0x82f63b78u  /* reflected Castagnoli */

static double now_s(void) {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return ts.tv_sec + ts.tv_nsec * 1e-9;
}

/* GF(2) matrix ops for crc shift-combine (zlib crc32_combine scheme) */
static uint32_t gf2_times(const uint32_t *mat, uint32_t vec) {
    uint32_t sum = 0;
    while (vec) {
        if (vec & 1) sum ^= *mat;
        vec >>= 1;
        mat++;
    }
    return sum;
}

static void gf2_square(uint32_t *sq, const uint32_t *mat) {
    for (int n = 0; n < 32; n++) sq[n] = gf2_times(mat, mat[n]);
}

/* Build the 32x32 GF(2) operator advancing a crc by len zero bytes; the
 * asm path bakes the equivalent constants per stride, so the build cost
 * is setup, not per-buffer work. */
static void crc32c_shift_op(uint32_t *op, size_t len) {
    uint32_t even[32], odd[32];
    odd[0] = POLY;
    uint32_t row = 1;
    for (int n = 1; n < 32; n++) { odd[n] = row; row <<= 1; }
    gf2_square(even, odd);
    gf2_square(odd, even);
    for (int n = 0; n < 32; n++) op[n] = 1u << n;   /* identity */
    /* len stays in bytes: the first squared operator is an 8-bit shift */
    uint32_t *mats[2] = {even, odd};
    int which = 0;
    uint32_t tmp[32];
    while (len) {
        gf2_square(mats[which], mats[which ^ 1]);
        if (len & 1) {
            for (int n = 0; n < 32; n++)
                tmp[n] = gf2_times(mats[which], op[n]);
            for (int n = 0; n < 32; n++) op[n] = tmp[n];
        }
        len >>= 1;
        which ^= 1;
    }
}

static uint32_t shift_cached[32];
static size_t shift_cached_len = 0;

static uint32_t crc32c_shift(uint32_t crc, size_t len) {
    if (shift_cached_len != len) {
        crc32c_shift_op(shift_cached, len);
        shift_cached_len = len;
    }
    return gf2_times(shift_cached, crc);
}

static uint32_t crc32c_3way(uint32_t crc, const uint8_t *p, size_t n) {
    size_t third = (n / 24) * 8;
    if (third < 8)  {
        while (n--) crc = _mm_crc32_u8(crc, *p++);
        return crc;
    }
    const uint64_t *a = (const uint64_t *)p;
    const uint64_t *b = (const uint64_t *)(p + third);
    const uint64_t *c = (const uint64_t *)(p + 2 * third);
    uint64_t c0 = crc, c1 = 0, c2 = 0;
    for (size_t i = 0; i < third / 8; i++) {
        c0 = _mm_crc32_u64(c0, a[i]);
        c1 = _mm_crc32_u64(c1, b[i]);
        c2 = _mm_crc32_u64(c2, c[i]);
    }
    crc = crc32c_shift((uint32_t)c0, third) ^ (uint32_t)c1;
    crc = crc32c_shift(crc, third) ^ (uint32_t)c2;
    p += 3 * third;
    n -= 3 * third;
    while (n--) crc = _mm_crc32_u8(crc, *p++);
    return crc;
}

int main(void) {
    const int batch = 4096, length = 4096;
    uint8_t *buf = aligned_alloc(64, (size_t)batch * length);
    for (int i = 0; i < batch * length; i += 8)
        *(uint64_t *)(buf + i) = 0x9e3779b97f4a7c15ull * (i + 1);

    /* self-check: 3-way merge must equal the plain byte-serial crc */
    {
        uint32_t plain = ~0u;
        for (int i = 0; i < length; i++) plain = _mm_crc32_u8(plain, buf[i]);
        uint32_t fast = crc32c_3way(~0u, buf, length);
        if (plain != fast) {
            fprintf(stderr, "crc self-check failed: %08x != %08x\n",
                    plain, fast);
            return 1;
        }
    }
    double nbytes = (double)batch * length;
    volatile uint32_t sink = 0;
    double best = 0;
    for (int rep = 0; rep < 5; rep++) {
        int iters = 20;
        double t0 = now_s();
        for (int it = 0; it < iters; it++)
            for (int b = 0; b < batch; b++)
                sink ^= crc32c_3way(~0u, buf + (size_t)b * length, length);
        double dt = (now_s() - t0) / iters;
        double gbps = nbytes / dt / 1e9;
        if (gbps > best) best = gbps;
    }
    printf("{\"config\": \"crc32c_4096x4KiB\", \"gbps\": %.3f, "
           "\"sink\": %u}\n", best, (unsigned)sink);
    free(buf);
    return 0;
}
