/* Measured CPU baseline for the BASELINE.json EC configs.
 *
 * BASELINE.md's protocol calls for timing the reference's SIMD erasure
 * libraries (jerasure/gf-complete, ISA-L) on this host.  Those trees are
 * empty submodules in this checkout and the host ships no EC libraries,
 * so this file implements the same kernels those libraries dispatch to on
 * this CPU — GF(2^8) dot products over chunk buffers using
 * (a) the AVX-512 split-table technique (gf-complete SPLIT_TABLE(8,4),
 *     isa-l gf_vect_dot_prod's vpshufb core), and
 * (b) the GFNI affine path (vgf2p8affineqb), isa-l's fastest path on
 *     GFNI-capable parts like this Xeon,
 * takes the faster of the two per config, and reports GB/s of input data
 * with the reference tool's accounting (object bytes / seconds,
 * ceph_erasure_code_benchmark.cc:187).  The per-config coefficient
 * structure (including XOR-only rows and LRC/SHEC sparsity) is generated
 * from the package's own codecs by dump_ops.py, so CPU and TPU time the
 * identical math.
 *
 * Build:  gcc -O3 -march=native -o ec_baseline ec_baseline.c
 * Run:    ./ec_baseline            (one JSON line per config)
 */

#include <immintrin.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <time.h>

/* ---- GF(2^8), poly 0x11d (jerasure/isa-l representation) ---- */
static int gf_mul(int a, int b) {
    int r = 0;
    while (b) {
        if (b & 1) r ^= a;
        b >>= 1;
        a <<= 1;
        if (a & 0x100) a ^= 0x11d;
    }
    return r & 0xff;
}

#include "baseline_ops.h"

static double now_s(void) {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return ts.tv_sec + ts.tv_nsec * 1e-9;
}

/* ---- GFNI: verified affine-matrix packing for multiply-by-c ---- */
static uint64_t affine_qword(int c) {
    for (int rowrev = 0; rowrev < 2; rowrev++)
        for (int bitrev = 0; bitrev < 2; bitrev++) {
            uint64_t q = 0;
            for (int i = 0; i < 8; i++) {
                uint8_t row = 0;
                for (int j = 0; j < 8; j++)
                    if ((gf_mul(c, 1 << j) >> i) & 1)
                        row |= (uint8_t)(1u << (bitrev ? 7 - j : j));
                q |= (uint64_t)row << (8 * (rowrev ? 7 - i : i));
            }
            __m128i m = _mm_set1_epi64x((long long)q);
            int ok = 1;
            for (int v = 0; v < 256 && ok; v++) {
                __m128i x = _mm_set1_epi8((char)v);
                __m128i y = _mm_gf2p8affine_epi64_epi8(x, m, 0);
                uint8_t got = (uint8_t)_mm_extract_epi8(y, 0);
                if (got != gf_mul(c, v)) ok = 0;
            }
            if (ok) return q;
        }
    fprintf(stderr, "no affine packing for coeff %d\n", c);
    exit(1);
}

/* ---- split-table: lo/hi nibble product tables, vpshufb layout ---- */
static void mul_tables(int c, uint8_t lo[16], uint8_t hi[16]) {
    for (int n = 0; n < 16; n++) {
        lo[n] = (uint8_t)gf_mul(c, n);
        hi[n] = (uint8_t)gf_mul(c, n << 4);
    }
}

#define MAX_OPS 8
#define MAX_TERMS 64

struct kernel {
    int n_ops;
    int n_terms[MAX_OPS];
    int src[MAX_OPS][MAX_TERMS];
    int coeff[MAX_OPS][MAX_TERMS];
    __m512i aff[MAX_OPS][MAX_TERMS];      /* GFNI matrices */
    __m512i tlo[MAX_OPS][MAX_TERMS];      /* split tables  */
    __m512i thi[MAX_OPS][MAX_TERMS];
};

static void kernel_init(struct kernel *kn, const struct ec_config *cfg) {
    kn->n_ops = cfg->n_ops;
    for (int o = 0; o < cfg->n_ops; o++) {
        int s = cfg->start[o], e = cfg->start[o + 1];
        kn->n_terms[o] = e - s;
        for (int t = s; t < e; t++) {
            int i = t - s;
            kn->src[o][i] = cfg->src[t];
            kn->coeff[o][i] = cfg->coeff[t];
            kn->aff[o][i] = _mm512_set1_epi64(
                (long long)affine_qword(cfg->coeff[t]));
            uint8_t lo[16], hi[16];
            mul_tables(cfg->coeff[t], lo, hi);
            __m128i l = _mm_loadu_si128((const __m128i *)lo);
            __m128i h = _mm_loadu_si128((const __m128i *)hi);
            kn->tlo[o][i] = _mm512_broadcast_i32x4(l);
            kn->thi[o][i] = _mm512_broadcast_i32x4(h);
        }
    }
}

/* One object: inputs are chunk buffers, outputs one per op.  coeff==1
 * terms are pure XOR (as jerasure's matrix path and XOR codecs do). */
static void run_gfni(const struct kernel *kn, uint8_t **in, uint8_t **out,
                     int chunk) {
    for (int o = 0; o < kn->n_ops; o++) {
        uint8_t *dst = out[o];
        for (int p = 0; p < chunk; p += 64) {
            __m512i acc = _mm512_setzero_si512();
            for (int t = 0; t < kn->n_terms[o]; t++) {
                __m512i v = _mm512_loadu_si512(in[kn->src[o][t]] + p);
                if (kn->coeff[o][t] != 1)
                    v = _mm512_gf2p8affine_epi64_epi8(v, kn->aff[o][t], 0);
                acc = _mm512_xor_si512(acc, v);
            }
            _mm512_storeu_si512(dst + p, acc);
        }
    }
}

static void run_split(const struct kernel *kn, uint8_t **in, uint8_t **out,
                      int chunk) {
    const __m512i mask = _mm512_set1_epi8(0x0f);
    for (int o = 0; o < kn->n_ops; o++) {
        uint8_t *dst = out[o];
        for (int p = 0; p < chunk; p += 64) {
            __m512i acc = _mm512_setzero_si512();
            for (int t = 0; t < kn->n_terms[o]; t++) {
                __m512i v = _mm512_loadu_si512(in[kn->src[o][t]] + p);
                if (kn->coeff[o][t] != 1) {
                    __m512i ln = _mm512_and_si512(v, mask);
                    __m512i hn = _mm512_and_si512(
                        _mm512_srli_epi16(v, 4), mask);
                    v = _mm512_xor_si512(
                        _mm512_shuffle_epi8(kn->tlo[o][t], ln),
                        _mm512_shuffle_epi8(kn->thi[o][t], hn));
                }
                acc = _mm512_xor_si512(acc, v);
            }
            _mm512_storeu_si512(dst + p, acc);
        }
    }
}

static double bench_cfg(const struct ec_config *cfg, int use_gfni) {
    struct kernel kn;
    kernel_init(&kn, cfg);

    int n_in = 0;
    for (int o = 0; o < cfg->n_ops; o++)
        for (int t = cfg->start[o]; t < cfg->start[o + 1]; t++)
            if (cfg->src[t] + 1 > n_in) n_in = cfg->src[t] + 1;

    /* per-object buffers, randomized (input values don't affect timing) */
    int B = cfg->batch, S = cfg->chunk;
    uint8_t **bufs = malloc(sizeof(void *) * B * (n_in + cfg->n_ops));
    for (int i = 0; i < B * (n_in + cfg->n_ops); i++) {
        bufs[i] = aligned_alloc(64, S);
        for (int j = 0; j < S; j += 8)
            *(uint64_t *)(bufs[i] + j) = 0x9e3779b97f4a7c15ull * (i + j + 1);
    }

    double nbytes = (double)B * cfg->k * S;   /* reference accounting */
    double best = 0;
    for (int rep = 0; rep < 5; rep++) {
        /* size each window to ~0.25s of work */
        int iters = (int)(0.25 / (nbytes / 4e9)) + 1;
        double t0 = now_s();
        for (int it = 0; it < iters; it++)
            for (int b = 0; b < B; b++) {
                uint8_t **in = &bufs[b * (n_in + cfg->n_ops)];
                uint8_t **out = in + n_in;
                if (use_gfni)
                    run_gfni(&kn, in, out, S);
                else
                    run_split(&kn, in, out, S);
            }
        double dt = (now_s() - t0) / iters;
        double gbps = nbytes / dt / 1e9;
        if (gbps > best) best = gbps;   /* best-of: favor the baseline */
    }
    for (int i = 0; i < B * (n_in + cfg->n_ops); i++) free(bufs[i]);
    free(bufs);
    return best;
}

int main(void) {
    for (int c = 0; c < N_CONFIGS; c++) {
        const struct ec_config *cfg = CONFIGS[c];
        double g = bench_cfg(cfg, 1);
        double s = bench_cfg(cfg, 0);
        double v = g > s ? g : s;
        printf("{\"config\": \"%s\", \"gbps\": %.3f, "
               "\"gfni_gbps\": %.3f, \"split_gbps\": %.3f}\n",
               cfg->name, v, g, s);
        fflush(stdout);
    }
    return 0;
}
