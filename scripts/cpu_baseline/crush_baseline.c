/* Measured CPU baseline for CRUSH placement throughput.
 *
 * Links the reference's pure-C CRUSH core out-of-tree (same pattern as
 * ../gen_crush_golden/harness.c — no reference code enters the repo) and
 * times crush_do_rule over the bench topology bench.py uses
 * (ceph_tpu/crush/__init__.py bench_map): 40 racks x 16 hosts x 16 osds,
 * straw2 everywhere, jewel/optimal tunables, chooseleaf_firstn 3 (host),
 * 1M placements.  Output: one JSON line with mappings/s.
 *
 * Build: gcc -O3 -I$REF/src/crush -I. -o crush_baseline crush_baseline.c \
 *            $REF/src/crush/{mapper,builder,crush,hash}.c -lm
 */

#include <stdio.h>
#include <stdlib.h>
#include <time.h>
#include "builder.h"
#include "crush.h"
#include "hash.h"
#include "mapper.h"

static double now_s(void) {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return ts.tv_sec + ts.tv_nsec * 1e-9;
}

static int add_straw2(struct crush_map *m, int type, int n, int *items,
                      int *weights) {
    struct crush_bucket *b = crush_make_bucket(
        m, CRUSH_BUCKET_STRAW2, CRUSH_HASH_RJENKINS1, type, n, items, weights);
    int id;
    crush_add_bucket(m, 0, b, &id);
    return id;
}

int main(int argc, char **argv) {
    int n_racks = 40, hosts_per_rack = 16, osds_per_host = 16, numrep = 3;
    int n_pgs = argc > 1 ? atoi(argv[1]) : 1000000;

    struct crush_map *m = crush_create();
    m->choose_total_tries = 50;
    m->choose_local_tries = 0;
    m->choose_local_fallback_tries = 0;
    m->chooseleaf_descend_once = 1;
    m->chooseleaf_vary_r = 1;
    m->chooseleaf_stable = 1;

    int dev = 0;
    int *rack_ids = malloc(sizeof(int) * n_racks);
    int *rack_w = malloc(sizeof(int) * n_racks);
    for (int r = 0; r < n_racks; r++) {
        int host_ids[64], host_w[64];
        for (int h = 0; h < hosts_per_rack; h++) {
            int items[64], weights[64];
            for (int o = 0; o < osds_per_host; o++) {
                items[o] = dev++;
                weights[o] = 0x10000;
            }
            host_ids[h] = add_straw2(m, 1, osds_per_host, items, weights);
            host_w[h] = osds_per_host * 0x10000;
        }
        rack_ids[r] = add_straw2(m, 2, hosts_per_rack, host_ids, host_w);
        rack_w[r] = hosts_per_rack * osds_per_host * 0x10000;
    }
    int root = add_straw2(m, 3, n_racks, rack_ids, rack_w);

    struct crush_rule *rule = crush_make_rule(3, 0, 1, 1, 10);
    crush_rule_set_step(rule, 0, CRUSH_RULE_TAKE, root, 0);
    crush_rule_set_step(rule, 1, CRUSH_RULE_CHOOSELEAF_FIRSTN, numrep, 1);
    crush_rule_set_step(rule, 2, CRUSH_RULE_EMIT, 0, 0);
    int ruleno = crush_add_rule(m, rule, -1);
    crush_finalize(m);

    int nw = dev;
    __u32 *weights = malloc(sizeof(__u32) * nw);
    for (int i = 0; i < nw; i++) weights[i] = 0x10000;
    void *cw = malloc(m->working_size + 3 * numrep * sizeof(int));
    crush_init_workspace(m, cw);
    int result[8];

    /* warmup + 3 timed repeats, median-free best (favor the baseline) */
    double best = 0;
    long long sink = 0;
    for (int rep = 0; rep < 4; rep++) {
        double t0 = now_s();
        for (int x = 0; x < n_pgs; x++) {
            int len = crush_do_rule(m, ruleno, x, result, numrep,
                                    weights, nw, cw, NULL);
            sink += len ? result[0] : 0;
        }
        double dt = now_s() - t0;
        double rate = n_pgs / dt;
        if (rep > 0 && rate > best) best = rate;
    }
    printf("{\"config\": \"crush_10kosd_1Mpg\", \"mappings_per_s\": %.0f, "
           "\"sink\": %lld}\n", best, sink);
    return 0;
}
