#!/usr/bin/env python
"""Driver-invocable TPU validation hook (round 6).

Runs the real-device checks the CPU test suite cannot (the Pallas
bit-exactness assertions that ``tests/test_gf8.py`` skips without a TPU
backend, and the K-stacked planar kernel of the round-6 layout contract)
plus the backend-agnostic bit-planar round-trip/codec-equivalence checks,
and RECORDS the outcome as a JSON artifact alongside the BENCH_r*.json
trajectory so a bench number is never published without its
bit-exactness witness:

    python scripts/run_tpu_checks.py [--out TPU_CHECKS_rNN.json]

The default output name follows the highest existing BENCH round
(BENCH_r05.json -> TPU_CHECKS_r06.json).  Exit status is nonzero iff any
check FAILS; SKIP (no TPU attached) is not a failure — the artifact
records it honestly.
"""

import argparse
import glob
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _next_round() -> int:
    rounds = [0]
    for path in glob.glob(os.path.join(REPO, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if m:
            rounds.append(int(m.group(1)))
    return max(rounds) + 1


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None,
                    help="artifact path (default TPU_CHECKS_r<next>.json)")
    args = ap.parse_args()
    out_path = args.out or os.path.join(
        REPO, f"TPU_CHECKS_r{_next_round():02d}.json")

    import jax

    from scripts import tpu_checks

    backend = jax.default_backend()
    doc = {"backend": backend,
           "devices": [str(d) for d in jax.devices()],
           "checks": {}}
    failed = False
    for name, fn in tpu_checks.CHECKS:
        try:
            fn()
            # the pallas checks self-skip off-TPU; record that distinctly
            if name.startswith("pallas_"):
                from ceph_tpu.ops import gf8_pallas

                avail = (gf8_pallas.planar_available()
                         if name == "pallas_planar"
                         else gf8_pallas.available())
                doc["checks"][name] = "OK" if avail else "SKIP"
            else:
                doc["checks"][name] = "OK"
        except Exception as e:  # noqa: BLE001 — record, don't crash
            doc["checks"][name] = f"FAIL: {e!r}"
            failed = True
    doc["ok"] = not failed
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    print(json.dumps(doc))
    print(f"wrote {out_path}")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
