/* Independent EC golden-vector generator.
 *
 * Re-derives the coding matrices and encode byte layouts of the jerasure /
 * ISA-L codec families from their published algorithms, using from-scratch
 * GF(2^8) arithmetic (carryless shift-xor multiply mod 0x11d, inverse by
 * exhaustive search) — no lookup tables, no numpy, no code shared with the
 * Python package.  The emitted per-chunk FNV-1a fingerprints pin the
 * package's TPU encode output byte-for-byte (tests/test_ec_golden.py), the
 * same role ceph-erasure-code-corpus plays for the reference
 * (src/test/erasure-code/ceph_erasure_code_non_regression.cc:226).
 *
 * Build & run:  gcc -O2 -o gen gen.c && ./gen > ../../tests/golden/ec_golden.jsonl
 */

#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <stdint.h>

/* ---------------- GF(2^8), poly 0x11d, from first principles ----------- */

static int gf_mul(int a, int b) {
    int r = 0;
    a &= 0xff; b &= 0xff;
    while (b) {
        if (b & 1) r ^= a;
        b >>= 1;
        a <<= 1;
        if (a & 0x100) a ^= 0x11d;
    }
    return r & 0xff;
}

static int gf_inv(int a) {
    int x;
    for (x = 1; x < 256; x++)
        if (gf_mul(a, x) == 1) return x;
    fprintf(stderr, "gf_inv(0)\n");
    exit(1);
}

static int gf_div(int a, int b) { return gf_mul(a, gf_inv(b)); }

static int gf_pow(int a, int n) {
    int r = 1, i;
    for (i = 0; i < n; i++) r = gf_mul(r, a);
    return r;
}

/* ---------------- matrix builders -------------------------------------- */

/* jerasure reed_sol: extended Vandermonde (k+m, k), systematized by
 * elementary column operations, final parity-column normalization so the
 * first parity row is all ones. */
static void reed_sol_van_matrix(int k, int m, int *coding /* m*k */) {
    int rows = k + m, cols = k;
    int *v = calloc(rows * cols, sizeof(int));
    int i, j, x;
    v[0 * cols + 0] = 1;
    for (i = 1; i < rows - 1; i++)
        for (j = 0; j < cols; j++)
            v[i * cols + j] = gf_pow(i, j);
    v[(rows - 1) * cols + (cols - 1)] = 1;

    for (i = 0; i < cols; i++) {
        if (v[i * cols + i] == 0) {
            for (j = i + 1; j < cols; j++)
                if (v[i * cols + j] != 0) break;
            if (j == cols) { fprintf(stderr, "systematize failed\n"); exit(1); }
            for (x = 0; x < rows; x++) {
                int t = v[x * cols + i];
                v[x * cols + i] = v[x * cols + j];
                v[x * cols + j] = t;
            }
        }
        if (v[i * cols + i] != 1) {
            int inv = gf_inv(v[i * cols + i]);
            for (x = 0; x < rows; x++)
                v[x * cols + i] = gf_mul(v[x * cols + i], inv);
        }
        for (j = 0; j < cols; j++) {
            int f = v[i * cols + j];
            if (j != i && f != 0)
                for (x = 0; x < rows; x++)
                    v[x * cols + j] ^= gf_mul(f, v[x * cols + i]);
        }
    }
    /* normalization 1: first parity row becomes all ones (column scaling,
     * parity rows only) */
    for (j = 0; j < cols; j++) {
        int e = v[k * cols + j];
        if (e != 0 && e != 1)
            for (x = k; x < rows; x++)
                v[x * cols + j] = gf_div(v[x * cols + j], e);
    }
    /* normalization 2: first parity column becomes all ones (row scaling of
     * parity rows 1..m-1, jerasure reed_sol.c second normalization step) */
    for (x = k + 1; x < rows; x++) {
        int e = v[x * cols + 0];
        if (e != 0 && e != 1)
            for (j = 0; j < cols; j++)
                v[x * cols + j] = gf_div(v[x * cols + j], e);
    }
    for (i = 0; i < m; i++)
        for (j = 0; j < k; j++)
            coding[i * k + j] = v[(k + i) * cols + j];
    free(v);
}

/* jerasure reed_sol_r6: P = XOR row, Q = 2^j row */
static void reed_sol_r6_matrix(int k, int *coding /* 2*k */) {
    int j;
    for (j = 0; j < k; j++) {
        coding[0 * k + j] = 1;
        coding[1 * k + j] = gf_pow(2, j);
    }
}

/* jerasure cauchy_orig: 1 / (i ^ (m + j)) */
static void cauchy_orig_matrix(int k, int m, int *coding) {
    int i, j;
    for (i = 0; i < m; i++)
        for (j = 0; j < k; j++)
            coding[i * k + j] = gf_inv(i ^ (m + j));
}

/* number of ones in the 8x8 bit-matrix of multiply-by-a */
static int n_ones(int a) {
    int t, u, n = 0;
    for (u = 0; u < 8; u++) {
        int col = gf_mul(a, 1 << u);
        for (t = 0; t < 8; t++)
            if (col & (1 << t)) n++;
    }
    return n;
}

/* jerasure cauchy_good: scale columns so row 0 is ones, then scale each
 * later row by the divisor minimizing total bit-matrix ones */
static void cauchy_good_matrix(int k, int m, int *coding) {
    int i, j;
    cauchy_orig_matrix(k, m, coding);
    for (j = 0; j < k; j++)
        if (coding[0 * k + j] != 1) {
            int inv = gf_inv(coding[0 * k + j]);
            for (i = 0; i < m; i++)
                coding[i * k + j] = gf_mul(coding[i * k + j], inv);
        }
    for (i = 1; i < m; i++) {
        int best = 0, best_j = -1, total, jj;
        for (jj = 0; jj < k; jj++) best += n_ones(coding[i * k + jj]);
        for (j = 0; j < k; j++) {
            if (coding[i * k + j] == 1) continue;
            {
                int inv = gf_inv(coding[i * k + j]);
                total = 0;
                for (jj = 0; jj < k; jj++)
                    total += n_ones(gf_mul(coding[i * k + jj], inv));
                if (total < best) { best = total; best_j = j; }
            }
        }
        if (best_j != -1) {
            int inv = gf_inv(coding[i * k + best_j]);
            for (j = 0; j < k; j++)
                coding[i * k + j] = gf_mul(coding[i * k + j], inv);
        }
    }
}

/* ISA-L gf_gen_rs_matrix parity rows: row r = g^0..g^(k-1), g = 2^r */
static void isa_rs_matrix(int k, int m, int *coding) {
    int r, j, gen = 1;
    for (r = 0; r < m; r++) {
        int p = 1;
        for (j = 0; j < k; j++) {
            coding[r * k + j] = p;
            p = gf_mul(p, gen);
        }
        gen = gf_mul(gen, 2);
    }
}

/* ISA-L gf_gen_cauchy1_matrix parity rows: 1 / ((k + i) ^ j) */
static void isa_cauchy_matrix(int k, int m, int *coding) {
    int i, j;
    for (i = 0; i < m; i++)
        for (j = 0; j < k; j++)
            coding[i * k + j] = gf_inv((k + i) ^ j);
}

/* ---------------- wide fields GF(2^w), w in {16, 32} -------------------- */

static uint64_t gfw_poly(int w) {
    return w == 8 ? 0x11d : w == 16 ? 0x1100b : 0x100400007ULL;
}

static uint64_t gfw_mul(int w, uint64_t a, uint64_t b) {
    uint64_t poly = gfw_poly(w), mask = (w == 64) ? ~0ULL : ((1ULL << w) - 1);
    uint64_t r = 0;
    a &= mask; b &= mask;
    while (b) {
        if (b & 1) r ^= a;
        b >>= 1;
        a <<= 1;
        if (a >> w) a ^= poly;
    }
    return r & mask;
}

static uint64_t gfw_pow(int w, uint64_t a, uint64_t n) {
    uint64_t r = 1;
    while (n) {
        if (n & 1) r = gfw_mul(w, r, a);
        a = gfw_mul(w, a, a);
        n >>= 1;
    }
    return r;
}

static uint64_t gfw_inv(int w, uint64_t a) {
    /* a^(2^w - 2) */
    return gfw_pow(w, a, ((w == 32) ? 0xffffffffULL : ((1ULL << w) - 1)) - 1);
}

static uint64_t gfw_div(int w, uint64_t a, uint64_t b) {
    return gfw_mul(w, a, gfw_inv(w, b));
}

/* jerasure reed_sol over GF(2^w): same extended-Vandermonde systematization
 * + the two normalizations as reed_sol_van_matrix, word arithmetic */
static void reed_sol_van_matrix_w(int k, int m, int w, uint64_t *coding) {
    int rows = k + m, cols = k;
    uint64_t *v = calloc(rows * cols, sizeof(uint64_t));
    int i, j, x;
    v[0] = 1;
    for (i = 1; i < rows - 1; i++)
        for (j = 0; j < cols; j++)
            v[i * cols + j] = gfw_pow(w, i, j);
    v[(rows - 1) * cols + (cols - 1)] = 1;
    for (i = 0; i < cols; i++) {
        if (v[i * cols + i] == 0) {
            for (j = i + 1; j < cols; j++)
                if (v[i * cols + j] != 0) break;
            if (j == cols) { fprintf(stderr, "systematize failed\n"); exit(1); }
            for (x = 0; x < rows; x++) {
                uint64_t t = v[x * cols + i];
                v[x * cols + i] = v[x * cols + j];
                v[x * cols + j] = t;
            }
        }
        if (v[i * cols + i] != 1) {
            uint64_t inv = gfw_inv(w, v[i * cols + i]);
            for (x = 0; x < rows; x++)
                v[x * cols + i] = gfw_mul(w, v[x * cols + i], inv);
        }
        for (j = 0; j < cols; j++) {
            uint64_t f = v[i * cols + j];
            if (j != i && f != 0)
                for (x = 0; x < rows; x++)
                    v[x * cols + j] ^= gfw_mul(w, f, v[x * cols + i]);
        }
    }
    for (j = 0; j < cols; j++) {
        uint64_t e = v[k * cols + j];
        if (e != 0 && e != 1)
            for (x = k; x < rows; x++)
                v[x * cols + j] = gfw_div(w, v[x * cols + j], e);
    }
    for (x = k + 1; x < rows; x++) {
        uint64_t e = v[x * cols + 0];
        if (e != 0 && e != 1)
            for (j = 0; j < cols; j++)
                v[x * cols + j] = gfw_div(w, v[x * cols + j], e);
    }
    for (i = 0; i < m; i++)
        for (j = 0; j < k; j++)
            coding[i * k + j] = v[(k + i) * cols + j];
    free(v);
}

static void reed_sol_r6_matrix_w(int k, int w, uint64_t *coding) {
    int j;
    for (j = 0; j < k; j++) {
        coding[0 * k + j] = 1;
        coding[1 * k + j] = gfw_pow(w, 2, j);
    }
}

/* wide-field cauchy (jerasure cauchy.c over GF(2^w)) */
static void cauchy_orig_matrix_w(int k, int m, int w, uint64_t *coding) {
    int i, j;
    for (i = 0; i < m; i++)
        for (j = 0; j < k; j++)
            coding[i * k + j] = gfw_inv(w, (uint64_t)(i ^ (m + j)));
}

static int n_ones_w(int w, uint64_t a) {
    int u, t, n = 0;
    for (u = 0; u < w; u++) {
        uint64_t col = gfw_mul(w, a, (uint64_t)1 << u);
        for (t = 0; t < w; t++) n += (int)((col >> t) & 1);
    }
    return n;
}

static void cauchy_good_matrix_w(int k, int m, int w, uint64_t *coding) {
    int i, j;
    cauchy_orig_matrix_w(k, m, w, coding);
    for (j = 0; j < k; j++) {
        if (coding[0 * k + j] != 1) {
            uint64_t inv = gfw_inv(w, coding[0 * k + j]);
            for (i = 0; i < m; i++)
                coding[i * k + j] = gfw_mul(w, coding[i * k + j], inv);
        }
    }
    for (i = 1; i < m; i++) {
        int best = 0, best_j = -1;
        for (j = 0; j < k; j++) best += n_ones_w(w, coding[i * k + j]);
        for (j = 0; j < k; j++) {
            if (coding[i * k + j] != 1) {
                uint64_t inv = gfw_inv(w, coding[i * k + j]);
                int total = 0, jj;
                for (jj = 0; jj < k; jj++)
                    total += n_ones_w(
                        w, gfw_mul(w, coding[i * k + jj], inv));
                if (total < best) { best = total; best_j = j; }
            }
        }
        if (best_j != -1) {
            uint64_t inv = gfw_inv(w, coding[i * k + best_j]);
            for (j = 0; j < k; j++)
                coding[i * k + j] = gfw_mul(w, coding[i * k + j], inv);
        }
    }
}

/* packet-interleaved bit-matrix encode from a GF(2^w) word matrix */
static void bitmatrix_encode_ww(const uint64_t *mat, int k, int m, int w,
                                int ps, uint8_t **data, uint8_t **parity,
                                int size) {
    int sb = w * ps;
    int ns = size / sb;
    int i, t, j, u, s, b;
    for (i = 0; i < m; i++)
        for (t = 0; t < w; t++)
            for (s = 0; s < ns; s++) {
                uint8_t *out = parity[i] + s * sb + t * ps;
                memset(out, 0, ps);
                for (j = 0; j < k; j++)
                    for (u = 0; u < w; u++) {
                        uint64_t col = gfw_mul(w, mat[i * k + j],
                                               (uint64_t)1 << u);
                        if ((col >> t) & 1) {
                            const uint8_t *in = data[j] + s * sb + u * ps;
                            for (b = 0; b < ps; b++) out[b] ^= in[b];
                        }
                    }
            }
}

/* ---------------- native GF(2) bit-matrices (liberation family) --------- */

/* Plank's Liberation construction (w prime, k <= w, m=2): row 0 block =
 * [I..I]; row 1 block j = I cyclically shifted by j, plus for j>0 one
 * extra bit at (i, (i+j-1) mod w), i = (j*(w-1)/2) mod w. */
static void lib_bitmatrix(int k, int w, uint8_t *bm /* 2w x kw */) {
    int i, j, t;
    memset(bm, 0, 2 * w * k * w);
    for (t = 0; t < w; t++)
        for (j = 0; j < k; j++)
            bm[t * k * w + j * w + t] = 1;
    for (j = 0; j < k; j++) {
        for (i = 0; i < w; i++)
            bm[(w + i) * k * w + j * w + (j + i) % w] = 1;
        if (j > 0) {
            i = (j * ((w - 1) / 2)) % w;
            bm[(w + i) * k * w + j * w + (i + j - 1) % w] = 1;
        }
    }
}

/* Blaum-Roth over GF(2)[x]/M_p(x), p = w+1: row 1 block j = multiply by
 * x^j; column u of block j = x^(j+u) reduced mod M_p = 1 + x + ... + x^w */
static uint64_t br_reduce(uint64_t bits, int w) {
    uint64_t M = ((uint64_t)1 << (w + 1)) - 1;   /* 1 + x + ... + x^w */
    int d;
    for (d = 63; d >= w; d--)
        if ((bits >> d) & 1) bits ^= M << (d - w);
    return bits;
}

static void br_bitmatrix(int k, int w, uint8_t *bm) {
    int i, j, t, u;
    memset(bm, 0, 2 * w * k * w);
    for (t = 0; t < w; t++)
        for (j = 0; j < k; j++)
            bm[t * k * w + j * w + t] = 1;
    for (j = 0; j < k; j++)
        for (u = 0; u < w; u++) {
            uint64_t col = br_reduce((uint64_t)1 << (j + u), w);
            for (i = 0; i < w; i++)
                if ((col >> i) & 1)
                    bm[(w + i) * k * w + j * w + u] = 1;
        }
}

/* liber8tion-style (w=8, m=2): row 1 block j = GF(2^8) multiply-by-(2^j)
 * bit-matrix (deterministic stand-in for Plank's searched matrices; see
 * ceph_tpu/ec/liberation.py docstring) */
static void l8_bitmatrix(int k, uint8_t *bm) {
    int w = 8, i, j, t, u, g = 1;
    memset(bm, 0, 2 * w * k * w);
    for (t = 0; t < w; t++)
        for (j = 0; j < k; j++)
            bm[t * k * w + j * w + t] = 1;
    for (j = 0; j < k; j++) {
        for (u = 0; u < w; u++) {
            int col = gf_mul(g, 1 << u);
            for (t = 0; t < w; t++)
                if ((col >> t) & 1)
                    bm[(w + t) * k * w + j * w + u] = 1;
        }
        g = gf_mul(g, 2);
    }
}

/* ---------------- encodes ---------------------------------------------- */

/* bytewise matrix encode: parity[i][b] = XOR_j mat[i][j] * data[j][b] */
static void matrix_encode(const int *mat, int k, int m,
                          uint8_t **data, uint8_t **parity, int size) {
    int i, j, b;
    for (i = 0; i < m; i++)
        for (b = 0; b < size; b++) {
            int acc = 0;
            for (j = 0; j < k; j++)
                acc ^= gf_mul(mat[i * k + j], data[j][b]);
            parity[i][b] = (uint8_t)acc;
        }
}

/* jerasure bit-matrix schedule encode, w=8, packetsize ps.
 * Chunk layout: superblocks of w*ps bytes; packet row t of superblock s is
 * bytes [s*w*ps + t*ps, +ps).  Parity packet (i, t) = XOR over (j, u) with
 * bit t of (mat[i][j] * 2^u) set of data packet (j, u). */
static void bitmatrix_encode(const int *mat, int k, int m, int ps,
                             uint8_t **data, uint8_t **parity, int size) {
    int w = 8;
    int sb = w * ps;
    int ns = size / sb;
    int i, t, j, u, s, b;
    for (i = 0; i < m; i++)
        for (t = 0; t < w; t++)
            for (s = 0; s < ns; s++) {
                uint8_t *out = parity[i] + s * sb + t * ps;
                memset(out, 0, ps);
                for (j = 0; j < k; j++)
                    for (u = 0; u < w; u++) {
                        int col = gf_mul(mat[i * k + j], 1 << u);
                        if (col & (1 << t)) {
                            const uint8_t *in = data[j] + s * sb + u * ps;
                            for (b = 0; b < ps; b++) out[b] ^= in[b];
                        }
                    }
            }
}

/* wordwise matrix encode over GF(2^w), little-endian w-bit words */
static void matrix_encode_w(const uint64_t *mat, int k, int m, int w,
                            uint8_t **data, uint8_t **parity, int size) {
    int wb = w / 8, nw = size / wb, i, j, n, b;
    for (i = 0; i < m; i++)
        for (n = 0; n < nw; n++) {
            uint64_t acc = 0;
            for (j = 0; j < k; j++) {
                uint64_t v = 0;
                for (b = 0; b < wb; b++)
                    v |= (uint64_t)data[j][n * wb + b] << (8 * b);
                acc ^= gfw_mul(w, mat[i * k + j], v);
            }
            for (b = 0; b < wb; b++)
                parity[i][n * wb + b] = (acc >> (8 * b)) & 0xff;
        }
}

/* packet-interleaved encode from an explicit (mw x kw) 0/1 bit-matrix */
static void bitmatrix01_encode(const uint8_t *bm, int k, int m, int w, int ps,
                               uint8_t **data, uint8_t **parity, int size) {
    int sb = w * ps;
    int ns = size / sb;
    int i, t, j, u, s, b;
    for (i = 0; i < m; i++)
        for (t = 0; t < w; t++)
            for (s = 0; s < ns; s++) {
                uint8_t *out = parity[i] + s * sb + t * ps;
                memset(out, 0, ps);
                for (j = 0; j < k; j++)
                    for (u = 0; u < w; u++)
                        if (bm[(i * w + t) * (k * w) + j * w + u]) {
                            const uint8_t *in = data[j] + s * sb + u * ps;
                            for (b = 0; b < ps; b++) out[b] ^= in[b];
                        }
            }
}

/* ---------------- deterministic data + fingerprints -------------------- */

static uint32_t lcg_state;
static uint8_t lcg_next(void) {
    lcg_state = (1103515245u * lcg_state + 12345u) & 0x7fffffffu;
    return (uint8_t)((lcg_state >> 16) & 0xff);
}

static uint64_t fnv1a(const uint8_t *p, int n) {
    uint64_t h = 1469598103934665603ull;
    int i;
    for (i = 0; i < n; i++) {
        h ^= p[i];
        h *= 1099511628211ull;
    }
    return h;
}

static void hex16(const uint8_t *p, char *out) {
    int i;
    for (i = 0; i < 16; i++) sprintf(out + 2 * i, "%02x", p[i]);
    out[32] = 0;
}

/* ---------------- SHEC shingled matrix --------------------------------- */
/* Vandermonde RS matrix with shingle-patterned zeros; the (m1, c1) split
 * minimizes the recovery-efficiency metric (independent re-derivation of
 * the SHEC construction for the oracle; same algorithm as the published
 * SHEC paper / reference ErasureCodeShec.cc:415-524). */

static double shec_eff1(int k, int m1, int m2, int c1, int c2) {
    int r_eff_k[64];
    double r_e1 = 0.0;
    int i, rr, cc, first;
    if (m1 < c1 || m2 < c2) return -1.0;
    if ((m1 == 0 && c1 != 0) || (m2 == 0 && c2 != 0)) return -1.0;
    for (i = 0; i < k; i++) r_eff_k[i] = 100000000;
    for (rr = 0; rr < m1; rr++) {
        int start = ((rr * k) / m1) % k;
        int end = (((rr + c1) * k) / m1) % k;
        int span = ((rr + c1) * k) / m1 - (rr * k) / m1;
        cc = start; first = 1;
        while (first || cc != end) {
            first = 0;
            if (span < r_eff_k[cc]) r_eff_k[cc] = span;
            cc = (cc + 1) % k;
        }
        r_e1 += span;
    }
    for (rr = 0; rr < m2; rr++) {
        int start = ((rr * k) / m2) % k;
        int end = (((rr + c2) * k) / m2) % k;
        int span = ((rr + c2) * k) / m2 - (rr * k) / m2;
        cc = start; first = 1;
        while (first || cc != end) {
            first = 0;
            if (span < r_eff_k[cc]) r_eff_k[cc] = span;
            cc = (cc + 1) % k;
        }
        r_e1 += span;
    }
    for (i = 0; i < k; i++) r_e1 += r_eff_k[i];
    return r_e1 / (k + m1 + m2);
}

static void shec_matrix_w(int k, int m, int c, int w, int single,
                          uint64_t *matw) {
    int c1, m1, c2, m2, rr, cc, end, start;
    int c1_best = -1, m1_best = -1;
    double min_r_e1 = 100.0;
    if (single) {
        m1 = 0; c1 = 0; m2 = m; c2 = c;
    } else {
        for (c1 = 0; c1 <= c / 2; c1++) {
            for (m1 = 0; m1 <= m; m1++) {
                double r_e1;
                c2 = c - c1; m2 = m - m1;
                if (m1 < c1 || m2 < c2) continue;
                if ((m1 == 0 && c1 != 0) || (m2 == 0 && c2 != 0)) continue;
                if ((m1 != 0 && c1 == 0) || (m2 != 0 && c2 == 0)) continue;
                r_e1 = shec_eff1(k, m1, m2, c1, c2);
                if (min_r_e1 - r_e1 > 2.220446049250313e-16 &&
                    r_e1 < min_r_e1) {
                    min_r_e1 = r_e1;
                    c1_best = c1; m1_best = m1;
                }
            }
        }
        m1 = m1_best; c1 = c1_best;
        m2 = m - m1_best; c2 = c - c1_best;
    }
    if (w == 8) {
        int *m8 = calloc(m * k, sizeof(int));
        int i;
        reed_sol_van_matrix(k, m, m8);
        for (i = 0; i < m * k; i++) matw[i] = (uint64_t)m8[i];
        free(m8);
    } else {
        reed_sol_van_matrix_w(k, m, w, matw);
    }
    for (rr = 0; rr < m1; rr++) {
        end = ((rr * k) / m1) % k;
        start = (((rr + c1) * k) / m1) % k;
        cc = start;
        while (cc != end) {
            matw[rr * k + cc] = 0;
            cc = (cc + 1) % k;
        }
    }
    for (rr = 0; rr < m2; rr++) {
        end = ((rr * k) / m2) % k;
        start = (((rr + c2) * k) / m2) % k;
        cc = start;
        while (cc != end) {
            matw[(rr + m1) * k + cc] = 0;
            cc = (cc + 1) % k;
        }
    }
}

/* ---------------- config table + main ---------------------------------- */

typedef struct {
    const char *plugin;
    const char *technique;
    int k, m, w, packetsize;
    int object_size;   /* chosen pre-aligned: no padding ambiguity */
    int seed;
    int c;             /* shec only */
} Cfg;

static const Cfg CONFIGS[] = {
    {"jerasure", "reed_sol_van", 4, 2, 8, 0, 4096, 1},
    {"jerasure", "reed_sol_van", 8, 4, 8, 0, 8192, 2},
    {"jerasure", "reed_sol_van", 6, 3, 8, 0, 6144, 3},
    {"jerasure", "reed_sol_r6_op", 4, 2, 8, 0, 4096, 4},
    {"jerasure", "cauchy_orig", 3, 2, 8, 8, 2304, 5},
    {"jerasure", "cauchy_good", 4, 2, 8, 8, 4096, 6},
    {"jerasure", "cauchy_good", 5, 3, 8, 8, 6400, 7},
    {"isa", "reed_sol_van", 8, 4, 8, 0, 8192, 8},
    {"isa", "reed_sol_van", 4, 2, 8, 0, 4096, 9},
    {"isa", "cauchy", 8, 4, 8, 0, 8192, 10},
    /* wide fields */
    {"jerasure", "reed_sol_van", 4, 2, 16, 0, 8192, 11},
    {"jerasure", "reed_sol_van", 4, 2, 32, 0, 8192, 12},
    {"jerasure", "reed_sol_r6_op", 4, 2, 16, 0, 8192, 13},
    /* liberation family (native bit-matrices) */
    {"jerasure", "liberation", 4, 2, 7, 4, 896, 14},
    {"jerasure", "blaum_roth", 4, 2, 6, 4, 1152, 15},
    {"jerasure", "liber8tion", 5, 2, 8, 4, 1920, 16},
    /* wide-field cauchy (round 4: w-coverage parity with reed_sol) */
    {"jerasure", "cauchy_orig", 4, 2, 16, 4, 4096, 17},
    {"jerasure", "cauchy_good", 4, 2, 16, 4, 4096, 18},
    {"jerasure", "cauchy_good", 4, 2, 32, 4, 8192, 19},
    /* shec shingled codes (round 5: w in {8, 16, 32}) */
    {"shec", "multiple", 6, 4, 8, 0, 3072, 20, 3},
    {"shec", "multiple", 6, 4, 16, 0, 6144, 21, 3},
    {"shec", "multiple", 6, 4, 32, 0, 12288, 22, 3},
    {"shec", "single", 4, 3, 16, 0, 4096, 23, 2},
    {"shec", "multiple", 8, 4, 32, 0, 16384, 24, 2},
};

static int is_native_bitmatrix(const Cfg *c) {
    return !strcmp(c->technique, "liberation") ||
           !strcmp(c->technique, "blaum_roth") ||
           !strcmp(c->technique, "liber8tion");
}

int main(void) {
    unsigned ci;
    for (ci = 0; ci < sizeof(CONFIGS) / sizeof(CONFIGS[0]); ci++) {
        const Cfg *c = &CONFIGS[ci];
        int k = c->k, m = c->m, w = c->w;
        int chunk = c->object_size / k;
        int *mat = calloc(m * k, sizeof(int));
        uint64_t *matw = calloc(m * k, sizeof(uint64_t));
        uint8_t *bm = calloc(m * w * k * w, 1);
        uint8_t **data = calloc(k, sizeof(uint8_t *));
        uint8_t **parity = calloc(m, sizeof(uint8_t *));
        int i, j;
        char hexbuf[40];

        lcg_state = (uint32_t)c->seed;
        for (i = 0; i < k; i++) {
            data[i] = malloc(chunk);
            for (j = 0; j < chunk; j++) data[i][j] = lcg_next();
        }
        for (i = 0; i < m; i++) parity[i] = malloc(chunk);

        if (!strcmp(c->plugin, "shec")) {
            shec_matrix_w(k, m, c->c, w, !strcmp(c->technique, "single"),
                          matw);
            if (w == 8) {
                for (i = 0; i < m * k; i++) mat[i] = (int)matw[i];
                matrix_encode(mat, k, m, data, parity, chunk);
            } else {
                matrix_encode_w(matw, k, m, w, data, parity, chunk);
            }
        } else if (is_native_bitmatrix(c)) {
            if (!strcmp(c->technique, "liberation")) lib_bitmatrix(k, w, bm);
            else if (!strcmp(c->technique, "blaum_roth")) br_bitmatrix(k, w, bm);
            else l8_bitmatrix(k, bm);
            bitmatrix01_encode(bm, k, m, w, c->packetsize, data, parity, chunk);
        } else if (w != 8) {
            if (!strcmp(c->technique, "reed_sol_van")) {
                reed_sol_van_matrix_w(k, m, w, matw);
                matrix_encode_w(matw, k, m, w, data, parity, chunk);
            } else if (!strcmp(c->technique, "reed_sol_r6_op")) {
                reed_sol_r6_matrix_w(k, w, matw);
                matrix_encode_w(matw, k, m, w, data, parity, chunk);
            } else {
                /* wide-field cauchy: packet bit-matrix encode */
                if (!strcmp(c->technique, "cauchy_orig"))
                    cauchy_orig_matrix_w(k, m, w, matw);
                else cauchy_good_matrix_w(k, m, w, matw);
                bitmatrix_encode_ww(matw, k, m, w, c->packetsize,
                                    data, parity, chunk);
            }
        } else {
            if (!strcmp(c->plugin, "jerasure")) {
                if (!strcmp(c->technique, "reed_sol_van")) reed_sol_van_matrix(k, m, mat);
                else if (!strcmp(c->technique, "reed_sol_r6_op")) reed_sol_r6_matrix(k, mat);
                else if (!strcmp(c->technique, "cauchy_orig")) cauchy_orig_matrix(k, m, mat);
                else if (!strcmp(c->technique, "cauchy_good")) cauchy_good_matrix(k, m, mat);
            } else {
                if (!strcmp(c->technique, "cauchy")) isa_cauchy_matrix(k, m, mat);
                else isa_rs_matrix(k, m, mat);
            }
            if (c->packetsize)
                bitmatrix_encode(mat, k, m, c->packetsize, data, parity, chunk);
            else
                matrix_encode(mat, k, m, data, parity, chunk);
        }

        printf("{\"plugin\": \"%s\", \"technique\": \"%s\", \"k\": %d, "
               "\"m\": %d, \"w\": %d, \"packetsize\": %d, \"object_size\": %d, "
               "\"seed\": %d, \"chunk_size\": %d, ",
               c->plugin, c->technique, k, m, w, c->packetsize,
               c->object_size, c->seed, chunk);
        if (c->c) printf("\"c\": %d, ", c->c);
        if (!strcmp(c->plugin, "shec")) {
            printf("\"matrix\": [");
            for (i = 0; i < m * k; i++)
                printf("%s%llu", i ? ", " : "",
                       (unsigned long long)matw[i]);
        } else if (is_native_bitmatrix(c)) {
            printf("\"bitmatrix\": [");
            for (i = 0; i < m * w * k * w; i++)
                printf("%s%d", i ? ", " : "", bm[i]);
        } else if (w != 8) {
            printf("\"matrix\": [");
            for (i = 0; i < m * k; i++)
                printf("%s%llu", i ? ", " : "",
                       (unsigned long long)matw[i]);
        } else {
            printf("\"matrix\": [");
            for (i = 0; i < m * k; i++)
                printf("%s%d", i ? ", " : "", mat[i]);
        }
        printf("], \"chunks\": [");
        for (i = 0; i < k + m; i++) {
            const uint8_t *p = i < k ? data[i] : parity[i - k];
            hex16(p, hexbuf);
            printf("%s{\"fnv1a64\": \"%016llx\", \"head\": \"%s\"}",
                   i ? ", " : "", (unsigned long long)fnv1a(p, chunk), hexbuf);
        }
        printf("]}\n");

        for (i = 0; i < k; i++) free(data[i]);
        for (i = 0; i < m; i++) free(parity[i]);
        free(data); free(parity); free(mat); free(matw); free(bm);
    }
    return 0;
}
