/* Independent EC golden-vector generator.
 *
 * Re-derives the coding matrices and encode byte layouts of the jerasure /
 * ISA-L codec families from their published algorithms, using from-scratch
 * GF(2^8) arithmetic (carryless shift-xor multiply mod 0x11d, inverse by
 * exhaustive search) — no lookup tables, no numpy, no code shared with the
 * Python package.  The emitted per-chunk FNV-1a fingerprints pin the
 * package's TPU encode output byte-for-byte (tests/test_ec_golden.py), the
 * same role ceph-erasure-code-corpus plays for the reference
 * (src/test/erasure-code/ceph_erasure_code_non_regression.cc:226).
 *
 * Build & run:  gcc -O2 -o gen gen.c && ./gen > ../../tests/golden/ec_golden.jsonl
 */

#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <stdint.h>

/* ---------------- GF(2^8), poly 0x11d, from first principles ----------- */

static int gf_mul(int a, int b) {
    int r = 0;
    a &= 0xff; b &= 0xff;
    while (b) {
        if (b & 1) r ^= a;
        b >>= 1;
        a <<= 1;
        if (a & 0x100) a ^= 0x11d;
    }
    return r & 0xff;
}

static int gf_inv(int a) {
    int x;
    for (x = 1; x < 256; x++)
        if (gf_mul(a, x) == 1) return x;
    fprintf(stderr, "gf_inv(0)\n");
    exit(1);
}

static int gf_div(int a, int b) { return gf_mul(a, gf_inv(b)); }

static int gf_pow(int a, int n) {
    int r = 1, i;
    for (i = 0; i < n; i++) r = gf_mul(r, a);
    return r;
}

/* ---------------- matrix builders -------------------------------------- */

/* jerasure reed_sol: extended Vandermonde (k+m, k), systematized by
 * elementary column operations, final parity-column normalization so the
 * first parity row is all ones. */
static void reed_sol_van_matrix(int k, int m, int *coding /* m*k */) {
    int rows = k + m, cols = k;
    int *v = calloc(rows * cols, sizeof(int));
    int i, j, x;
    v[0 * cols + 0] = 1;
    for (i = 1; i < rows - 1; i++)
        for (j = 0; j < cols; j++)
            v[i * cols + j] = gf_pow(i, j);
    v[(rows - 1) * cols + (cols - 1)] = 1;

    for (i = 0; i < cols; i++) {
        if (v[i * cols + i] == 0) {
            for (j = i + 1; j < cols; j++)
                if (v[i * cols + j] != 0) break;
            if (j == cols) { fprintf(stderr, "systematize failed\n"); exit(1); }
            for (x = 0; x < rows; x++) {
                int t = v[x * cols + i];
                v[x * cols + i] = v[x * cols + j];
                v[x * cols + j] = t;
            }
        }
        if (v[i * cols + i] != 1) {
            int inv = gf_inv(v[i * cols + i]);
            for (x = 0; x < rows; x++)
                v[x * cols + i] = gf_mul(v[x * cols + i], inv);
        }
        for (j = 0; j < cols; j++) {
            int f = v[i * cols + j];
            if (j != i && f != 0)
                for (x = 0; x < rows; x++)
                    v[x * cols + j] ^= gf_mul(f, v[x * cols + i]);
        }
    }
    /* normalization 1: first parity row becomes all ones (column scaling,
     * parity rows only) */
    for (j = 0; j < cols; j++) {
        int e = v[k * cols + j];
        if (e != 0 && e != 1)
            for (x = k; x < rows; x++)
                v[x * cols + j] = gf_div(v[x * cols + j], e);
    }
    /* normalization 2: first parity column becomes all ones (row scaling of
     * parity rows 1..m-1, jerasure reed_sol.c second normalization step) */
    for (x = k + 1; x < rows; x++) {
        int e = v[x * cols + 0];
        if (e != 0 && e != 1)
            for (j = 0; j < cols; j++)
                v[x * cols + j] = gf_div(v[x * cols + j], e);
    }
    for (i = 0; i < m; i++)
        for (j = 0; j < k; j++)
            coding[i * k + j] = v[(k + i) * cols + j];
    free(v);
}

/* jerasure reed_sol_r6: P = XOR row, Q = 2^j row */
static void reed_sol_r6_matrix(int k, int *coding /* 2*k */) {
    int j;
    for (j = 0; j < k; j++) {
        coding[0 * k + j] = 1;
        coding[1 * k + j] = gf_pow(2, j);
    }
}

/* jerasure cauchy_orig: 1 / (i ^ (m + j)) */
static void cauchy_orig_matrix(int k, int m, int *coding) {
    int i, j;
    for (i = 0; i < m; i++)
        for (j = 0; j < k; j++)
            coding[i * k + j] = gf_inv(i ^ (m + j));
}

/* number of ones in the 8x8 bit-matrix of multiply-by-a */
static int n_ones(int a) {
    int t, u, n = 0;
    for (u = 0; u < 8; u++) {
        int col = gf_mul(a, 1 << u);
        for (t = 0; t < 8; t++)
            if (col & (1 << t)) n++;
    }
    return n;
}

/* jerasure cauchy_good: scale columns so row 0 is ones, then scale each
 * later row by the divisor minimizing total bit-matrix ones */
static void cauchy_good_matrix(int k, int m, int *coding) {
    int i, j;
    cauchy_orig_matrix(k, m, coding);
    for (j = 0; j < k; j++)
        if (coding[0 * k + j] != 1) {
            int inv = gf_inv(coding[0 * k + j]);
            for (i = 0; i < m; i++)
                coding[i * k + j] = gf_mul(coding[i * k + j], inv);
        }
    for (i = 1; i < m; i++) {
        int best = 0, best_j = -1, total, jj;
        for (jj = 0; jj < k; jj++) best += n_ones(coding[i * k + jj]);
        for (j = 0; j < k; j++) {
            if (coding[i * k + j] == 1) continue;
            {
                int inv = gf_inv(coding[i * k + j]);
                total = 0;
                for (jj = 0; jj < k; jj++)
                    total += n_ones(gf_mul(coding[i * k + jj], inv));
                if (total < best) { best = total; best_j = j; }
            }
        }
        if (best_j != -1) {
            int inv = gf_inv(coding[i * k + best_j]);
            for (j = 0; j < k; j++)
                coding[i * k + j] = gf_mul(coding[i * k + j], inv);
        }
    }
}

/* ISA-L gf_gen_rs_matrix parity rows: row r = g^0..g^(k-1), g = 2^r */
static void isa_rs_matrix(int k, int m, int *coding) {
    int r, j, gen = 1;
    for (r = 0; r < m; r++) {
        int p = 1;
        for (j = 0; j < k; j++) {
            coding[r * k + j] = p;
            p = gf_mul(p, gen);
        }
        gen = gf_mul(gen, 2);
    }
}

/* ISA-L gf_gen_cauchy1_matrix parity rows: 1 / ((k + i) ^ j) */
static void isa_cauchy_matrix(int k, int m, int *coding) {
    int i, j;
    for (i = 0; i < m; i++)
        for (j = 0; j < k; j++)
            coding[i * k + j] = gf_inv((k + i) ^ j);
}

/* ---------------- encodes ---------------------------------------------- */

/* bytewise matrix encode: parity[i][b] = XOR_j mat[i][j] * data[j][b] */
static void matrix_encode(const int *mat, int k, int m,
                          uint8_t **data, uint8_t **parity, int size) {
    int i, j, b;
    for (i = 0; i < m; i++)
        for (b = 0; b < size; b++) {
            int acc = 0;
            for (j = 0; j < k; j++)
                acc ^= gf_mul(mat[i * k + j], data[j][b]);
            parity[i][b] = (uint8_t)acc;
        }
}

/* jerasure bit-matrix schedule encode, w=8, packetsize ps.
 * Chunk layout: superblocks of w*ps bytes; packet row t of superblock s is
 * bytes [s*w*ps + t*ps, +ps).  Parity packet (i, t) = XOR over (j, u) with
 * bit t of (mat[i][j] * 2^u) set of data packet (j, u). */
static void bitmatrix_encode(const int *mat, int k, int m, int ps,
                             uint8_t **data, uint8_t **parity, int size) {
    int w = 8;
    int sb = w * ps;
    int ns = size / sb;
    int i, t, j, u, s, b;
    for (i = 0; i < m; i++)
        for (t = 0; t < w; t++)
            for (s = 0; s < ns; s++) {
                uint8_t *out = parity[i] + s * sb + t * ps;
                memset(out, 0, ps);
                for (j = 0; j < k; j++)
                    for (u = 0; u < w; u++) {
                        int col = gf_mul(mat[i * k + j], 1 << u);
                        if (col & (1 << t)) {
                            const uint8_t *in = data[j] + s * sb + u * ps;
                            for (b = 0; b < ps; b++) out[b] ^= in[b];
                        }
                    }
            }
}

/* ---------------- deterministic data + fingerprints -------------------- */

static uint32_t lcg_state;
static uint8_t lcg_next(void) {
    lcg_state = (1103515245u * lcg_state + 12345u) & 0x7fffffffu;
    return (uint8_t)((lcg_state >> 16) & 0xff);
}

static uint64_t fnv1a(const uint8_t *p, int n) {
    uint64_t h = 1469598103934665603ull;
    int i;
    for (i = 0; i < n; i++) {
        h ^= p[i];
        h *= 1099511628211ull;
    }
    return h;
}

static void hex16(const uint8_t *p, char *out) {
    int i;
    for (i = 0; i < 16; i++) sprintf(out + 2 * i, "%02x", p[i]);
    out[32] = 0;
}

/* ---------------- config table + main ---------------------------------- */

typedef struct {
    const char *plugin;
    const char *technique;
    int k, m, packetsize;
    int object_size;   /* chosen pre-aligned: no padding ambiguity */
    int seed;
} Cfg;

static const Cfg CONFIGS[] = {
    {"jerasure", "reed_sol_van", 4, 2, 0, 4096, 1},
    {"jerasure", "reed_sol_van", 8, 4, 0, 8192, 2},
    {"jerasure", "reed_sol_van", 6, 3, 0, 6144, 3},
    {"jerasure", "reed_sol_r6_op", 4, 2, 0, 4096, 4},
    {"jerasure", "cauchy_orig", 3, 2, 8, 2304, 5},
    {"jerasure", "cauchy_good", 4, 2, 8, 4096, 6},
    {"jerasure", "cauchy_good", 5, 3, 8, 6400, 7},
    {"isa", "reed_sol_van", 8, 4, 0, 8192, 8},
    {"isa", "reed_sol_van", 4, 2, 0, 4096, 9},
    {"isa", "cauchy", 8, 4, 0, 8192, 10},
};

int main(void) {
    unsigned ci;
    for (ci = 0; ci < sizeof(CONFIGS) / sizeof(CONFIGS[0]); ci++) {
        const Cfg *c = &CONFIGS[ci];
        int k = c->k, m = c->m;
        int chunk = c->object_size / k;
        int *mat = calloc(m * k, sizeof(int));
        uint8_t **data = calloc(k, sizeof(uint8_t *));
        uint8_t **parity = calloc(m, sizeof(uint8_t *));
        int i, j;
        char hexbuf[40];

        if (!strcmp(c->plugin, "jerasure")) {
            if (!strcmp(c->technique, "reed_sol_van")) reed_sol_van_matrix(k, m, mat);
            else if (!strcmp(c->technique, "reed_sol_r6_op")) reed_sol_r6_matrix(k, mat);
            else if (!strcmp(c->technique, "cauchy_orig")) cauchy_orig_matrix(k, m, mat);
            else if (!strcmp(c->technique, "cauchy_good")) cauchy_good_matrix(k, m, mat);
        } else {
            if (!strcmp(c->technique, "cauchy")) isa_cauchy_matrix(k, m, mat);
            else isa_rs_matrix(k, m, mat);
        }

        lcg_state = (uint32_t)c->seed;
        for (i = 0; i < k; i++) {
            data[i] = malloc(chunk);
            for (j = 0; j < chunk; j++) data[i][j] = lcg_next();
        }
        for (i = 0; i < m; i++) parity[i] = malloc(chunk);

        if (c->packetsize)
            bitmatrix_encode(mat, k, m, c->packetsize, data, parity, chunk);
        else
            matrix_encode(mat, k, m, data, parity, chunk);

        printf("{\"plugin\": \"%s\", \"technique\": \"%s\", \"k\": %d, "
               "\"m\": %d, \"packetsize\": %d, \"object_size\": %d, "
               "\"seed\": %d, \"chunk_size\": %d, \"matrix\": [",
               c->plugin, c->technique, k, m, c->packetsize,
               c->object_size, c->seed, chunk);
        for (i = 0; i < m * k; i++)
            printf("%s%d", i ? ", " : "", mat[i]);
        printf("], \"chunks\": [");
        for (i = 0; i < k + m; i++) {
            const uint8_t *p = i < k ? data[i] : parity[i - k];
            hex16(p, hexbuf);
            printf("%s{\"fnv1a64\": \"%016llx\", \"head\": \"%s\"}",
                   i ? ", " : "", (unsigned long long)fnv1a(p, chunk), hexbuf);
        }
        printf("]}\n");

        for (i = 0; i < k; i++) free(data[i]);
        for (i = 0; i < m; i++) free(parity[i]);
        free(data); free(parity); free(mat);
    }
    return 0;
}
