/* Golden-vector harness over the reference CRUSH C core (built out-of-tree;
 * generates test vectors only — no reference code enters the new repo). */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include "builder.h"
#include "crush.h"
#include "mapper.h"
#include "hash.h"

#define NX 400

static struct crush_map *new_map(int total, int local, int fallback,
                                 int descend_once, int vary_r, int stable) {
  struct crush_map *m = crush_create();
  m->choose_total_tries = total;
  m->choose_local_tries = local;
  m->choose_local_fallback_tries = fallback;
  m->chooseleaf_descend_once = descend_once;
  m->chooseleaf_vary_r = vary_r;
  m->chooseleaf_stable = stable;
  return m;
}

static int add_alg(struct crush_map *m, int alg, int type, int n, int *items, int *weights) {
  struct crush_bucket *b = crush_make_bucket(m, alg,
                                             CRUSH_HASH_RJENKINS1, type, n, items, weights);
  int id;
  crush_add_bucket(m, 0, b, &id);
  return id;
}

static int add_straw2(struct crush_map *m, int type, int n, int *items, int *weights) {
  return add_alg(m, CRUSH_BUCKET_STRAW2, type, n, items, weights);
}

static const char *alg_name(int alg) {
  switch (alg) {
  case CRUSH_BUCKET_UNIFORM: return "uniform";
  case CRUSH_BUCKET_LIST: return "list";
  case CRUSH_BUCKET_TREE: return "tree";
  case CRUSH_BUCKET_STRAW: return "straw";
  default: return "straw2";
  }
}

static void print_bucket(struct crush_map *m, int id, int first) {
  struct crush_bucket *b = m->buckets[-1-id];
  int i;
  if (!first) printf(",");
  printf("{\"id\":%d,\"type\":%d,\"alg\":\"%s\",\"weight\":%u,\"items\":[",
         id, b->type, alg_name(b->alg), b->weight);
  for (i = 0; i < b->size; i++) printf("%s%d", i?",":"", b->items[i]);
  printf("],\"weights\":[");
  for (i = 0; i < b->size; i++) printf("%s%u", i?",":"", crush_get_bucket_item_weight(b, i));
  printf("]");
  /* derived builder data: lets the python side verify ITS builder math */
  if (b->alg == CRUSH_BUCKET_LIST) {
    struct crush_bucket_list *lb = (struct crush_bucket_list *)b;
    printf(",\"sum_weights\":[");
    for (i = 0; i < b->size; i++) printf("%s%u", i?",":"", lb->sum_weights[i]);
    printf("]");
  } else if (b->alg == CRUSH_BUCKET_TREE) {
    struct crush_bucket_tree *tb = (struct crush_bucket_tree *)b;
    printf(",\"num_nodes\":%u,\"node_weights\":[", tb->num_nodes);
    for (i = 0; i < (int)tb->num_nodes; i++) printf("%s%u", i?",":"", tb->node_weights[i]);
    printf("]");
  } else if (b->alg == CRUSH_BUCKET_STRAW) {
    struct crush_bucket_straw *sb = (struct crush_bucket_straw *)b;
    printf(",\"straws\":[");
    for (i = 0; i < b->size; i++) printf("%s%u", i?",":"", sb->straws[i]);
    printf("]");
  }
  printf("}");
}

static void run_scenario_args(const char *name, struct crush_map *m, int root,
                              struct crush_rule *rule, __u32 *reweight, int nw,
                              int result_max,
                              struct crush_choose_arg *cargs, int carg_bucket) {
  int ruleno = crush_add_rule(m, rule, -1);
  crush_finalize(m);
  void *cw = malloc(m->working_size + 3 * result_max * sizeof(int));
  int result[16];
  int x, i, b, nb = 0;
  printf("{\"scenario\":\"%s\",\"root\":%d,\"result_max\":%d,", name, root, result_max);
  printf("\"tunables\":{\"total\":%d,\"local\":%d,\"fallback\":%d,\"descend_once\":%d,\"vary_r\":%d,\"stable\":%d,\"straw_calc\":%d},",
         m->choose_total_tries, m->choose_local_tries, m->choose_local_fallback_tries,
         m->chooseleaf_descend_once, m->chooseleaf_vary_r, m->chooseleaf_stable,
         m->straw_calc_version);
  printf("\"steps\":[");
  for (i = 0; i < rule->len; i++)
    printf("%s[%d,%d,%d]", i?",":"", rule->steps[i].op, rule->steps[i].arg1, rule->steps[i].arg2);
  printf("],\"weights\":[");
  for (i = 0; i < nw; i++) printf("%s%u", i?",":"", reweight[i]);
  printf("],\"buckets\":[");
  for (b = 0; b < m->max_buckets; b++)
    if (m->buckets[b]) { print_bucket(m, -1-b, nb==0); nb++; }
  printf("]");
  if (cargs) {
    struct crush_choose_arg *a = &cargs[-1-carg_bucket];
    printf(",\"choose_args\":{\"%d\":{", carg_bucket);
    if (a->ids) {
      printf("\"ids\":[");
      for (i = 0; i < (int)a->ids_size; i++) printf("%s%d", i?",":"", a->ids[i]);
      printf("],");
    }
    printf("\"weight_set\":[");
    for (b = 0; b < (int)a->weight_set_size; b++) {
      printf("%s[", b?",":"");
      for (i = 0; i < (int)a->weight_set[b].size; i++)
        printf("%s%u", i?",":"", a->weight_set[b].weights[i]);
      printf("]");
    }
    printf("]}}");
  }
  printf(",\"results\":[");
  for (x = 0; x < NX; x++) {
    crush_init_workspace(m, cw);
    int len = crush_do_rule(m, ruleno, x, result, result_max, reweight, nw, cw, cargs);
    printf("%s[", x?",":"");
    for (i = 0; i < len; i++) printf("%s%d", i?",":"", result[i]);
    printf("]");
  }
  printf("]}\n");
  free(cw);
}

static void run_scenario(const char *name, struct crush_map *m, int root,
                         struct crush_rule *rule, __u32 *reweight, int nw,
                         int result_max) {
  run_scenario_args(name, m, root, rule, reweight, nw, result_max, NULL, 0);
}

static struct crush_rule *mk_rule(int type, int op1, int n1, int t1,
                                  int op2, int n2, int t2) {
  int len = (op2 >= 0) ? 4 : 3;
  struct crush_rule *r = crush_make_rule(len, 0, type, 1, 10);
  int p = 0;
  crush_rule_set_step(r, p++, CRUSH_RULE_TAKE, -1, 0);  /* root id patched below */
  crush_rule_set_step(r, p++, op1, n1, t1);
  if (op2 >= 0) crush_rule_set_step(r, p++, op2, n2, t2);
  crush_rule_set_step(r, p++, CRUSH_RULE_EMIT, 0, 0);
  return r;
}

int main(void) {
  int i, h, rck;

  /* ---- scenario 1: flat straw2, choose_firstn ---- */
  {
    struct crush_map *m = new_map(50, 0, 0, 1, 1, 1);
    int items[32], weights[32];
    __u32 rw[32];
    for (i = 0; i < 32; i++) { items[i] = i; weights[i] = 0x10000 * (1 + i % 3); }
    weights[7] = 0; weights[20] = 0;
    int root = add_straw2(m, 3, 32, items, weights);
    for (i = 0; i < 32; i++) rw[i] = 0x10000;
    rw[3] = 0x4000; rw[11] = 0;
    struct crush_rule *r = mk_rule(1, CRUSH_RULE_CHOOSE_FIRSTN, 3, 0, -1, 0, 0);
    r->steps[0].arg1 = root;
    run_scenario("flat_firstn", m, root, r, rw, 32, 3);
    crush_destroy(m);
  }

  /* ---- scenario 2/3/5: 8 hosts x 4 devices ---- */
  for (int variant = 0; variant < 3; variant++) {
    struct crush_map *m = (variant == 2) ? new_map(19, 2, 5, 0, 0, 0)
                                         : new_map(50, 0, 0, 1, 1, 1);
    int hostid[8];
    for (h = 0; h < 8; h++) {
      int items[4], weights[4];
      for (i = 0; i < 4; i++) { items[i] = h * 4 + i; weights[i] = 0x10000 * (1 + ((h + i) % 2)); }
      if (h == 2) weights[1] = 0;
      hostid[h] = add_straw2(m, 1, 4, items, weights);
    }
    int ritems[8], rweights[8];
    for (h = 0; h < 8; h++) { ritems[h] = hostid[h]; rweights[h] = m->buckets[-1-hostid[h]]->weight; }
    int root = add_straw2(m, 3, 8, ritems, rweights);
    __u32 rw[32];
    for (i = 0; i < 32; i++) rw[i] = 0x10000;
    rw[5] = 0x8000; rw[13] = 0; rw[28] = 0x2000;
    struct crush_rule *r;
    const char *name;
    int rmax = 3;
    if (variant == 0) { r = mk_rule(1, CRUSH_RULE_CHOOSELEAF_FIRSTN, 0, 1, -1, 0, 0); name = "host_chooseleaf_firstn"; }
    else if (variant == 1) { r = mk_rule(3, CRUSH_RULE_CHOOSELEAF_INDEP, 0, 1, -1, 0, 0); name = "host_chooseleaf_indep"; rmax = 4; }
    else { r = mk_rule(1, CRUSH_RULE_CHOOSELEAF_FIRSTN, 0, 1, -1, 0, 0); name = "host_chooseleaf_firstn_legacy"; }
    r->steps[0].arg1 = root;
    run_scenario(name, m, root, r, rw, 32, rmax);
    crush_destroy(m);
  }

  /* ---- scenario 4: racks -> hosts -> devices, two choose steps ---- */
  {
    struct crush_map *m = new_map(50, 0, 0, 1, 1, 1);
    int rackid[2];
    int dev = 0;
    for (rck = 0; rck < 2; rck++) {
      int hitems[4], hweights[4];
      for (h = 0; h < 4; h++) {
        int items[4], weights[4];
        for (i = 0; i < 4; i++) { items[i] = dev++; weights[i] = 0x10000 * (1 + (i % 3)); }
        int hid = add_straw2(m, 1, 4, items, weights);
        hitems[h] = hid; hweights[h] = m->buckets[-1-hid]->weight;
      }
      rackid[rck] = add_straw2(m, 2, 4, hitems, hweights);
    }
    int ritems[2] = { rackid[0], rackid[1] };
    int rweights[2] = { (int)m->buckets[-1-rackid[0]]->weight, (int)m->buckets[-1-rackid[1]]->weight };
    int root = add_straw2(m, 3, 2, ritems, rweights);
    __u32 rw[32];
    for (i = 0; i < 32; i++) rw[i] = 0x10000;
    rw[9] = 0;
    struct crush_rule *r = mk_rule(1, CRUSH_RULE_CHOOSE_FIRSTN, 2, 2,
                                   CRUSH_RULE_CHOOSELEAF_FIRSTN, 2, 1);
    r->steps[0].arg1 = root;
    run_scenario("racks_two_step", m, root, r, rw, 32, 4);
    crush_destroy(m);
  }

  /* ---- scenario 6: flat indep ---- */
  {
    struct crush_map *m = new_map(50, 0, 0, 1, 1, 1);
    int items[32], weights[32];
    __u32 rw[32];
    for (i = 0; i < 32; i++) { items[i] = i; weights[i] = 0x10000 * (1 + i % 3); }
    weights[7] = 0;
    int root = add_straw2(m, 3, 32, items, weights);
    for (i = 0; i < 32; i++) rw[i] = 0x10000;
    rw[2] = 0;
    struct crush_rule *r = mk_rule(3, CRUSH_RULE_CHOOSE_INDEP, 3, 0, -1, 0, 0);
    r->steps[0].arg1 = root;
    run_scenario("flat_indep", m, root, r, rw, 32, 3);
    crush_destroy(m);
  }

  /* ---- scenarios 7-9: flat list / tree / straw buckets ---- */
  {
    int algs[3] = { CRUSH_BUCKET_LIST, CRUSH_BUCKET_TREE, CRUSH_BUCKET_STRAW };
    const char *names[3] = { "flat_list_firstn", "flat_tree_firstn",
                             "flat_straw_firstn" };
    int a;
    for (a = 0; a < 3; a++) {
      struct crush_map *m = new_map(50, 0, 0, 1, 1, 1);
      m->straw_calc_version = 1;
      int items[16], weights[16];
      __u32 rw[16];
      for (i = 0; i < 16; i++) { items[i] = i; weights[i] = 0x10000 * (1 + i % 4); }
      weights[5] = 0;
      int root = add_alg(m, algs[a], 3, 16, items, weights);
      for (i = 0; i < 16; i++) rw[i] = 0x10000;
      rw[2] = 0x8000; rw[9] = 0;
      struct crush_rule *r = mk_rule(1, CRUSH_RULE_CHOOSE_FIRSTN, 3, 0, -1, 0, 0);
      r->steps[0].arg1 = root;
      run_scenario(names[a], m, root, r, rw, 16, 3);
      crush_destroy(m);
    }
  }

  /* ---- scenario 10: straw2 root over list-bucket hosts, chooseleaf ---- */
  {
    struct crush_map *m = new_map(50, 0, 0, 1, 1, 1);
    int hostid[4];
    for (h = 0; h < 4; h++) {
      int items[4], weights[4];
      for (i = 0; i < 4; i++) { items[i] = h * 4 + i; weights[i] = 0x10000 * (1 + ((h + i) % 3)); }
      hostid[h] = add_alg(m, CRUSH_BUCKET_LIST, 1, 4, items, weights);
    }
    int ritems[4], rweights[4];
    for (h = 0; h < 4; h++) { ritems[h] = hostid[h]; rweights[h] = m->buckets[-1-hostid[h]]->weight; }
    int root = add_straw2(m, 3, 4, ritems, rweights);
    __u32 rw[16];
    for (i = 0; i < 16; i++) rw[i] = 0x10000;
    rw[6] = 0;
    struct crush_rule *r = mk_rule(1, CRUSH_RULE_CHOOSELEAF_FIRSTN, 3, 1, -1, 0, 0);
    r->steps[0].arg1 = root;
    run_scenario("list_hosts_chooseleaf", m, root, r, rw, 16, 3);
    crush_destroy(m);
  }

  /* ---- scenario 10b: classic straw with calc version 0 ---- */
  {
    struct crush_map *m = new_map(50, 0, 0, 1, 1, 1);
    m->straw_calc_version = 0;
    int items[10], weights[10];
    __u32 rw[10];
    for (i = 0; i < 10; i++) { items[i] = i; weights[i] = 0x10000 * (1 + i % 3); }
    weights[3] = 0;
    int root = add_alg(m, CRUSH_BUCKET_STRAW, 3, 10, items, weights);
    for (i = 0; i < 10; i++) rw[i] = 0x10000;
    struct crush_rule *r = mk_rule(1, CRUSH_RULE_CHOOSE_FIRSTN, 3, 0, -1, 0, 0);
    r->steps[0].arg1 = root;
    run_scenario("flat_straw_v0_firstn", m, root, r, rw, 10, 3);
    crush_destroy(m);
  }

  /* ---- scenario 11: tree indep ---- */
  {
    struct crush_map *m = new_map(50, 0, 0, 1, 1, 1);
    int items[12], weights[12];
    __u32 rw[12];
    for (i = 0; i < 12; i++) { items[i] = i; weights[i] = 0x10000 * (1 + i % 2); }
    int root = add_alg(m, CRUSH_BUCKET_TREE, 3, 12, items, weights);
    for (i = 0; i < 12; i++) rw[i] = 0x10000;
    rw[4] = 0;
    struct crush_rule *r = mk_rule(3, CRUSH_RULE_CHOOSE_INDEP, 3, 0, -1, 0, 0);
    r->steps[0].arg1 = root;
    run_scenario("flat_tree_indep", m, root, r, rw, 12, 3);
    crush_destroy(m);
  }

  /* ---- scenario 12: straw2 with choose_args (weight_set + ids) ---- */
  {
    struct crush_map *m = new_map(50, 0, 0, 1, 1, 1);
    int items[16], weights[16];
    __u32 rw[16];
    for (i = 0; i < 16; i++) { items[i] = i; weights[i] = 0x10000 * (1 + i % 3); }
    int root = add_straw2(m, 3, 16, items, weights);
    for (i = 0; i < 16; i++) rw[i] = 0x10000;
    crush_finalize(m);
    /* choose_args indexed by -1-id over max_buckets */
    struct crush_choose_arg *cargs = calloc(m->max_buckets, sizeof(*cargs));
    static __u32 ws0[16], ws1[16];
    static __s32 aids[16];
    static struct crush_weight_set wsets[2];
    for (i = 0; i < 16; i++) {
      ws0[i] = 0x8000 * (1 + (i % 5));      /* balancer-style reweights */
      ws1[i] = 0x10000 * (1 + ((i + 2) % 4));
      aids[i] = i * 7 + 1;                  /* id remap perturbs the hash */
    }
    wsets[0].weights = ws0; wsets[0].size = 16;
    wsets[1].weights = ws1; wsets[1].size = 16;
    cargs[-1-root].ids = aids;
    cargs[-1-root].ids_size = 16;
    cargs[-1-root].weight_set = wsets;
    cargs[-1-root].weight_set_size = 2;
    struct crush_rule *r = mk_rule(1, CRUSH_RULE_CHOOSE_FIRSTN, 3, 0, -1, 0, 0);
    r->steps[0].arg1 = root;
    run_scenario_args("straw2_choose_args", m, root, r, rw, 16, 3, cargs, root);
    free(cargs);
    crush_destroy(m);
  }

  /* hash vectors */
  {
    printf("{\"scenario\":\"hash\",\"h1\":[");
    for (i = 0; i < 64; i++) printf("%s%u", i?",":"", crush_hash32(0, i * 2654435761u + 17));
    printf("],\"h2\":[");
    for (i = 0; i < 64; i++) printf("%s%u", i?",":"", crush_hash32_2(0, i, i * 40503u + 3));
    printf("],\"h3\":[");
    for (i = 0; i < 64; i++) printf("%s%u", i?",":"", crush_hash32_3(0, i, i + 1, i * 7));
    printf("],\"h5\":[");
    for (i = 0; i < 64; i++) printf("%s%u", i?",":"", crush_hash32_5(0, i, 2*i, 3*i, 5*i, 7*i));
    printf("]}\n");
  }
  return 0;
}
