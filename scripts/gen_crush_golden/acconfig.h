/* minimal config for out-of-tree crush build */
#define HAVE_SYS_TYPES_H 1
#define HAVE_STDINT_H 1
#define HAVE_LINUX_TYPES_H 1
