#!/usr/bin/env python
"""graft-trace CLI: inspect, attribute, and export op traces.

    python scripts/trace.py convert dump.json -o trace.json
    python scripts/trace.py demo [--osds 3] [--json] [--perfetto out.json]
    python scripts/trace.py attribute [--secs 2.0] [--json]

``convert`` turns a saved ``dump_historic_ops`` payload (one daemon's
dict, or ``{daemon: payload}``) into Chrome-trace/Perfetto JSON with no
cluster and no jax in sight.  ``demo`` boots a 3-OSD vstart cluster
with tracing enabled, drives one EC write + read, and prints the op's
cross-daemon span tree and stage attribution.  ``attribute`` runs a
short EC write burst and prints the aggregated per-stage breakdown —
the instrument behind ``bench.py --attribute``.

Exit codes (tested like scripts/chaos.py): 0 success, 1 bad/missing
input or an incomplete trace, 2 usage error (argparse).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _render_tree(nodes, indent=0, out=None):
    out = out if out is not None else []
    for n in nodes:
        dur = f"{n['dur'] * 1e3:.2f}ms" if n.get("dur") is not None \
            else "open"
        out.append(f"{'  ' * indent}{n['daemon']} {n['name']} [{dur}]")
        _render_tree(n["children"], indent + 1, out)
    return out


def cmd_convert(args) -> int:
    from ceph_tpu.trace.perfetto import chrome_trace_from_dumps, write

    try:
        with open(args.dump, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        print(f"cannot read {args.dump}: {e}", file=sys.stderr)
        return 1
    if not isinstance(doc, dict):
        print(f"{args.dump}: expected a dump_historic_ops payload "
              f"(dict), got {type(doc).__name__}", file=sys.stderr)
        return 1
    # accept one daemon's payload or a {daemon: payload} map
    dumps = doc if doc and all(isinstance(v, dict) and "ops" in v
                               for v in doc.values()) \
        else {"daemon": doc}
    if not all(isinstance(d.get("ops"), list) for d in dumps.values()):
        print(f"{args.dump}: no 'ops' list found", file=sys.stderr)
        return 1
    if not any(d.get("ops") for d in dumps.values()):
        print("no ops in dump", file=sys.stderr)
        return 1
    trace = chrome_trace_from_dumps(dumps)
    write(args.out, trace)
    print(f"wrote {len(trace['traceEvents'])} events -> {args.out}")
    return 0


async def _demo_cluster(n_osds: int):
    from ceph_tpu.cluster.vstart import _fast_config, start_cluster

    config = _fast_config()
    config.trace_enabled = 1
    config.osd_op_history_size = 200
    cluster = await start_cluster(n_osds, config=config)
    client = await cluster.client()
    pool = await client.pool_create(
        "trace_ec", "erasure", pg_num=4,
        ec_profile={"plugin": "jerasure", "technique": "reed_sol_van",
                    "k": "2", "m": "1"})
    return cluster, client, pool


async def _demo(args) -> int:
    from ceph_tpu.trace.attribution import attribute_events
    from ceph_tpu.trace.perfetto import chrome_trace_from_spans, write
    from ceph_tpu.trace.span import assemble_tree

    cluster, client, pool = await _demo_cluster(args.osds)
    try:
        io = client.ioctx(pool)
        await io.write_full("traced", b"\xa5" * 65536)
        assert await io.read("traced") == b"\xa5" * 65536
        # the newest client trace is the read; take the write's id
        tracer = client.objecter.tracer
        tids = list(tracer._traces)
        if not tids:
            print("no client trace recorded", file=sys.stderr)
            return 1
        tid = tids[-2] if len(tids) >= 2 else tids[-1]
        spans = tracer.dump_trace(tid)
        for oid in cluster.osds:
            spans += await cluster.daemon_command(
                f"osd.{oid}", {"prefix": "trace dump",
                               "args": {"trace_id": tid}})
        tree = assemble_tree(spans)
        # the traced op's stage attribution from the primary's tracker
        stages = None
        for oid in cluster.osds:
            hist = await cluster.daemon_command(
                f"osd.{oid}", "dump_historic_ops")
            for op in hist["ops"]:
                if op.get("trace_id") == tid:
                    evs = [(e["time"], e["event"])
                           for e in op["type_data"]["events"]]
                    stages = attribute_events(evs)[0]
        if args.json:
            print(json.dumps({"trace_id": tid, "tree": tree,
                              "stages": stages}, indent=2, default=str))
        else:
            print(f"trace {tid}:")
            print("\n".join(_render_tree(tree)))
            if stages:
                print("stage attribution:")
                for stage, s in sorted(stages.items(),
                                       key=lambda kv: -kv[1]):
                    print(f"  {stage:<24} {s * 1e3:8.3f}ms")
        if args.perfetto:
            write(args.perfetto, chrome_trace_from_spans(spans))
            print(f"perfetto trace -> {args.perfetto}")
        if not tree or not spans:
            print("trace incomplete", file=sys.stderr)
            return 1
        return 0
    finally:
        await cluster.stop()


async def _attribute(args) -> int:
    import time

    cluster, client, pool = await _demo_cluster(3)
    try:
        from ceph_tpu.trace.attribution import flush_op_history

        io = client.ioctx(pool)
        blob = b"\xa5" * 65536
        await io.write_full("warm", blob)
        await flush_op_history(cluster, 200)
        lats, deadline = [], time.perf_counter() + args.secs
        i = 0
        while time.perf_counter() < deadline:
            t0 = time.perf_counter()
            await io.write_full(f"attr_{i % 32}", blob)
            lats.append(time.perf_counter() - t0)
            i += 1
        wall = sum(lats) / len(lats)
        from ceph_tpu.trace.attribution import merge_reports

        reports = []
        for oid in cluster.osds:
            reports.append(await cluster.daemon_command(
                "osd.%d" % oid,
                {"prefix": "dump_op_attribution",
                 "args": {"match": "write_full"}}))
        merged = merge_reports(reports, measured_wall_s=wall)
        if not merged.get("ops"):
            print("no attributed ops", file=sys.stderr)
            return 1
        if args.json:
            print(json.dumps(merged, indent=2))
        else:
            print(f"{merged['ops']} ops, wall_coverage="
                  f"{merged.get('wall_coverage')}")
            for stage, row in merged["stages"].items():
                print(f"  {stage:<24} {row['s'] * 1e3:9.3f}ms "
                      f"{row['frac'] * 100:5.1f}%")
        return 0
    finally:
        await cluster.stop()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)
    p = sub.add_parser("convert",
                       help="dump_historic_ops JSON -> chrome trace")
    p.add_argument("dump")
    p.add_argument("-o", "--out", default="trace.json")
    p = sub.add_parser("demo", help="one traced op through vstart")
    p.add_argument("--osds", type=int, default=3)
    p.add_argument("--json", action="store_true")
    p.add_argument("--perfetto", help="write chrome trace JSON here")
    p = sub.add_parser("attribute", help="stage breakdown of a write burst")
    p.add_argument("--secs", type=float, default=2.0)
    p.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)
    if args.cmd == "convert":
        return cmd_convert(args)
    if args.cmd == "demo":
        return asyncio.run(_demo(args))
    return asyncio.run(_attribute(args))


if __name__ == "__main__":
    sys.exit(main())
