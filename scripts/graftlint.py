#!/usr/bin/env python
"""graftlint CLI: whole-repo static analysis gate.

    python scripts/graftlint.py                 # lint the repo, text report
    python scripts/graftlint.py --json          # machine-readable report
    python scripts/graftlint.py --dot locks.dot # export the lock graph
    python scripts/graftlint.py --write-baseline  # accept current findings
    python scripts/graftlint.py --runtime-edges dump.json  # merge a live
        cluster's `lockdep dump` edges into the static lock graph

Exit status: 0 when every finding is baselined (or none fire), 1
otherwise — tier-1 runs this over the repo and fails on anything new.

Pure AST analysis: no jax import, no device, safe under
JAX_PLATFORMS=cpu and on machines with no accelerator at all.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ceph_tpu.analysis import baseline as baseline_mod  # noqa: E402
from ceph_tpu.analysis import engine  # noqa: E402
from ceph_tpu.analysis import lockgraph  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="ceph_tpu static analysis")
    ap.add_argument("paths", nargs="*",
                    help="files to lint (default: the whole repo)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the full report as JSON")
    ap.add_argument("--baseline",
                    default=baseline_mod.default_baseline_path(),
                    help="suppression baseline file (default: "
                         "GRAFTLINT_BASELINE.json at the repo root)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline: report every finding")
    ap.add_argument("--write-baseline", action="store_true",
                    help="accept every current finding into --baseline "
                         "and exit 0")
    ap.add_argument("--runtime-edges", metavar="JSON",
                    help="a `lockdep dump` JSON file (or raw edges "
                         "mapping) to merge into the static lock graph")
    ap.add_argument("--dot", metavar="FILE",
                    help="write the merged lock-order graph as DOT")
    args = ap.parse_args(argv)

    runtime_edges = None
    if args.runtime_edges:
        with open(args.runtime_edges, encoding="utf-8") as f:
            doc = json.load(f)
        runtime_edges = doc.get("edges", doc)

    baseline = set()
    if not args.no_baseline and not args.write_baseline:
        baseline = baseline_mod.load_baseline(args.baseline)

    report = engine.run_lint(paths=args.paths or None,
                             baseline=baseline,
                             runtime_edges=runtime_edges)

    if args.dot:
        # the lockgraph rule already extracted the edges during run_lint
        static_edges = report.static_edges_raw or {}
        cycle = (report.lock_graph or {}).get("cycle")
        with open(args.dot, "w", encoding="utf-8") as f:
            f.write(lockgraph.to_dot(static_edges, runtime_edges or {},
                                     cycle))
            f.write("\n")
        print(f"lock graph written to {args.dot}", file=sys.stderr)

    if args.write_baseline:
        n = baseline_mod.write_baseline(args.baseline, report.findings)
        print(f"baseline written: {n} suppression(s) -> {args.baseline}")
        return 0

    if args.as_json:
        print(engine.dump_report_json(report))
    else:
        print(report.render_text())
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
