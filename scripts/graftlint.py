#!/usr/bin/env python
"""graftlint CLI: whole-repo static analysis gate.

    python scripts/graftlint.py                 # lint the repo, text report
    python scripts/graftlint.py --json          # machine-readable report
    python scripts/graftlint.py --dot locks.dot # export the lock graph
    python scripts/graftlint.py --write-baseline  # accept current findings
    python scripts/graftlint.py --runtime-edges dump.json  # merge a live
        cluster's `lockdep dump` edges into the static lock graph
    python scripts/graftlint.py --race batch-smoke --seeds 1,2,3
        # dynamic half: run the scenario under the seeded
        # schedule-perturbation loop with the write-after-read tracker
        # armed, once per seed

Exit status: 0 when every finding is baselined (or none fire), 1
otherwise — tier-1 runs this over the repo and fails on anything new.
``--race`` keeps the same contract (0 clean / 1 convictions or
scenario failures) and adds 2 for usage errors (unknown scenario,
unparsable seed list), so CI can tell "found a race" from "asked
wrong".

The static modes are pure AST analysis: no jax import, no device, safe
under JAX_PLATFORMS=cpu and on machines with no accelerator at all.
``--race`` boots real (in-process) clusters and takes seconds per seed.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ceph_tpu.analysis import baseline as baseline_mod  # noqa: E402
from ceph_tpu.analysis import engine  # noqa: E402
from ceph_tpu.analysis import lockgraph  # noqa: E402


def _race_main(args) -> int:
    """The --race driver: seeds x one scenario through racecheck.race_run.

    2 = usage error (bad seed list, unknown scenario), 1 = any seed's
    verdict failed or the tracker convicted, 0 = all seeds clean."""
    from ceph_tpu.analysis import racecheck

    try:
        seeds = [int(s) for s in args.seeds.split(",") if s.strip()]
        if not seeds:
            raise ValueError("empty seed list")
    except ValueError as e:
        print(f"graftlint --race: bad --seeds {args.seeds!r}: {e}",
              file=sys.stderr)
        return 2
    runs = []
    for seed in seeds:
        try:
            verdict, report, digest = racecheck.race_run(
                args.race, seed, shrink=not args.full_scale)
        except KeyError:
            print(f"graftlint --race: unknown scenario {args.race!r} "
                  "(scripts/chaos.py list)", file=sys.stderr)
            return 2
        runs.append({"seed": seed, "passed": verdict.passed,
                     "failures": list(verdict.failures),
                     "race": report, "trace_digest": digest})
    bad = sum(1 for r in runs
              if not r["passed"] or r["race"]["findings"])
    if args.as_json:
        print(json.dumps({"scenario": args.race, "runs": runs,
                          "ok": bad == 0}, indent=2, default=str))
    else:
        for r in runs:
            nf = len(r["race"]["findings"])
            status = "ok" if r["passed"] and not nf else "FAIL"
            print(f"{args.race} seed={r['seed']}: {status} "
                  f"(findings={nf}, ticks={r['race']['ticks']}, "
                  f"reads={r['race']['reads']}, "
                  f"writes={r['race']['writes']})")
            for f in r["failures"]:
                print(f"    invariant: {f}")
            for f in r["race"]["findings"]:
                print(f"    race: {f['message']}")
    return 1 if bad else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="ceph_tpu static analysis")
    ap.add_argument("paths", nargs="*",
                    help="files to lint (default: the whole repo)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the full report as JSON")
    ap.add_argument("--baseline",
                    default=baseline_mod.default_baseline_path(),
                    help="suppression baseline file (default: "
                         "GRAFTLINT_BASELINE.json at the repo root)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline: report every finding")
    ap.add_argument("--write-baseline", action="store_true",
                    help="accept every current finding into --baseline "
                         "and exit 0")
    ap.add_argument("--runtime-edges", metavar="JSON",
                    help="a `lockdep dump` JSON file (or raw edges "
                         "mapping) to merge into the static lock graph")
    ap.add_argument("--dot", metavar="FILE",
                    help="write the merged lock-order graph as DOT")
    ap.add_argument("--race", metavar="SCENARIO",
                    help="dynamic mode: run SCENARIO under the seeded "
                         "schedule-perturbation loop with the "
                         "write-after-read tracker armed (once per "
                         "--seeds entry); skips the static lint")
    ap.add_argument("--seeds", default="1,2,3",
                    help="comma-separated seeds for --race "
                         "(default: 1,2,3)")
    ap.add_argument("--full-scale", action="store_true",
                    help="--race at the scenario's full workload scale "
                         "(default: the shrunk smoke scale)")
    args = ap.parse_args(argv)

    if args.race:
        return _race_main(args)

    runtime_edges = None
    if args.runtime_edges:
        with open(args.runtime_edges, encoding="utf-8") as f:
            doc = json.load(f)
        runtime_edges = doc.get("edges", doc)

    baseline = set()
    if not args.no_baseline and not args.write_baseline:
        baseline = baseline_mod.load_baseline(args.baseline)

    report = engine.run_lint(paths=args.paths or None,
                             baseline=baseline,
                             runtime_edges=runtime_edges)

    if args.dot:
        # the lockgraph rule already extracted the edges during run_lint
        static_edges = report.static_edges_raw or {}
        cycle = (report.lock_graph or {}).get("cycle")
        with open(args.dot, "w", encoding="utf-8") as f:
            f.write(lockgraph.to_dot(static_edges, runtime_edges or {},
                                     cycle))
            f.write("\n")
        print(f"lock graph written to {args.dot}", file=sys.stderr)

    if args.write_baseline:
        n = baseline_mod.write_baseline(args.baseline, report.findings)
        print(f"baseline written: {n} suppression(s) -> {args.baseline}")
        return 0

    if args.as_json:
        print(engine.dump_report_json(report))
    else:
        print(report.render_text())
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
