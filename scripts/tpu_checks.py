#!/usr/bin/env python
"""Real-device validations the CPU test suite cannot run (VERDICT r4
weak #4: the Pallas kernel's bit-exactness check skips on CPU).

Run on a host with the TPU attached (NOT under the test conftest):

    python scripts/tpu_checks.py

Exits nonzero on any failure; prints one OK line per check.
"""

import os
import sys

import numpy as np

# runnable from anywhere: the repo root is this file's parent's parent
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def check_pallas_gf8():
    import jax.numpy as jnp

    from ceph_tpu.ec import matrices
    from ceph_tpu.ops import gf8, gf8_pallas

    if not gf8_pallas.available():
        print("SKIP pallas_gf8 (no TPU backend)")
        return
    rng = np.random.default_rng(7)
    for (k, m, n) in [(8, 4, 16384 * 3), (8, 4, 16384 * 2 + 1000),
                      (4, 2, 5000), (10, 4, 16384)]:
        bm = np.asarray(gf8.expand_bitmatrix(matrices.isa_rs_matrix(k, m)))
        data = jnp.asarray(rng.integers(0, 256, (k, n), dtype=np.uint8))
        got = np.asarray(gf8_pallas.bitmatrix_matmul(bm, data))
        want = np.asarray(gf8.bitmatrix_matmul(bm, data))
        assert np.array_equal(got, want), (k, m, n)
    print("OK pallas_gf8 bit-exact vs XLA path")


def check_pallas_planar():
    """The K-stacked planar Pallas kernel must be bit-exact vs the XLA
    planar path (round 6; this is the headline-encode production path)."""
    import jax.numpy as jnp

    from ceph_tpu.ec import matrices
    from ceph_tpu.ops import gf8, gf8_pallas

    if not gf8_pallas.planar_available():
        print("SKIP pallas_planar (no TPU backend)")
        return
    rng = np.random.default_rng(11)
    for (k, m, nb) in [(8, 4, 2048 * 3), (8, 4, 2048 * 2 + 100),
                       (4, 2, 5000), (10, 4, 2048), (2, 1, 2048)]:
        bm = np.asarray(gf8.expand_bitmatrix(matrices.isa_rs_matrix(k, m)))
        planes = jnp.asarray(
            rng.integers(0, 256, (k * 8, nb), dtype=np.uint8))
        got = np.asarray(gf8_pallas.planar_matmul(bm, planes))
        want = np.asarray(gf8.planar_matmul_xla(jnp.asarray(bm), planes))
        assert np.array_equal(got, want), (k, m, nb)
    print("OK pallas_planar stacked kernel bit-exact vs XLA planar path")


def check_planar_roundtrip():
    """Layout-contract check (any backend): byte -> bit-planar -> byte is
    the identity for every w, and the planar matmul matches the byte-path
    GF math bit-for-bit."""
    import jax.numpy as jnp

    from ceph_tpu.ops import gf8, gfw

    rng = np.random.default_rng(5)
    for w in (8, 16, 32):
        d = rng.integers(0, 256, (5, 16 * w), dtype=np.uint8)
        p = gfw.bytes_to_planar_w(jnp.asarray(d), w)
        assert np.array_equal(np.asarray(gfw.planar_to_bytes_w(p, w)), d), w
    mat = rng.integers(0, 256, (4, 8), dtype=np.uint8)
    data = rng.integers(0, 256, (8, 4096), dtype=np.uint8)
    bm = jnp.asarray(gf8.expand_bitmatrix(mat))
    got = np.asarray(gf8.planar_to_bytes(
        gf8.planar_matmul(bm, gf8.bytes_to_planar(jnp.asarray(data)))))
    assert np.array_equal(got, gf8.gf_matmul_ref(mat, data))
    print("OK planar round-trip + planar matmul bit-exact")


def check_planar_codec_paths():
    """encode_planar/decode_planar must agree with the byte batch paths
    on every codec family (runs on any backend)."""
    import jax.numpy as jnp

    from ceph_tpu.ec import factory

    rng = np.random.default_rng(9)
    for profile, s, erasures in (
        ({"plugin": "isa", "k": "8", "m": "4"}, 512, (2,)),
        ({"plugin": "jerasure", "technique": "reed_sol_van",
          "k": "4", "m": "2", "w": "16"}, 256, (0, 5)),
        ({"plugin": "lrc", "k": "4", "m": "2", "l": "3"}, 256, (1,)),
        ({"plugin": "shec", "k": "6", "m": "4", "c": "3"}, 256, (0, 3, 7)),
    ):
        codec = factory(dict(profile))
        k, n = codec.get_data_chunk_count(), codec.get_chunk_count()
        data = rng.integers(0, 256, (8, k, s), dtype=np.uint8)
        want = np.asarray(codec.encode_batch(jnp.asarray(data)))
        pb = codec.to_planar(data)
        got = np.asarray(codec.encode_planar(pb).to_batch())
        assert np.array_equal(got, want), ("encode", profile)
        full = np.concatenate([data, want], axis=1)
        zeroed = full.copy()
        for e in erasures:
            zeroed[:, e] = 0
        wd = np.asarray(codec.decode_batch(tuple(erasures), zeroed))
        gd = np.asarray(codec.decode_planar(
            tuple(erasures), codec.to_planar(zeroed)).to_batch())
        assert np.array_equal(gd, wd), ("decode", profile)
    print("OK planar codec paths match byte paths on all families")


def check_codec_roundtrip():
    from ceph_tpu.ec import factory

    rng = np.random.default_rng(1)
    for profile in (
        {"plugin": "isa", "k": "8", "m": "4"},
        {"plugin": "jerasure", "technique": "reed_sol_van",
         "k": "4", "m": "2", "w": "16"},
        {"plugin": "shec", "k": "6", "m": "4", "c": "3", "w": "32"},
        {"plugin": "lrc", "k": "4", "m": "2", "l": "3"},
    ):
        codec = factory(dict(profile))
        n = codec.get_chunk_count()
        obj = rng.integers(0, 256, codec.get_chunk_size(1 << 16) *
                           codec.get_data_chunk_count(),
                           dtype=np.uint8).tobytes()
        chunks = codec.encode(range(n), obj)
        drop = {0, n - 1}
        avail = {i: c for i, c in chunks.items() if i not in drop}
        assert codec.decode_concat(avail)[:len(obj)] == obj, profile
    print("OK codec encode/decode roundtrips on device")


CHECKS = (
    ("pallas_gf8", check_pallas_gf8),
    ("pallas_planar", check_pallas_planar),
    ("planar_roundtrip", check_planar_roundtrip),
    ("planar_codec_paths", check_planar_codec_paths),
    ("codec_roundtrip", check_codec_roundtrip),
)


def main() -> int:
    import jax

    print(f"backend: {jax.default_backend()}  devices: {jax.devices()}")
    for _name, fn in CHECKS:
        fn()
    print("ALL TPU CHECKS PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
