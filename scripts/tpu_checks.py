#!/usr/bin/env python
"""Real-device validations the CPU test suite cannot run (VERDICT r4
weak #4: the Pallas kernel's bit-exactness check skips on CPU).

Run on a host with the TPU attached (NOT under the test conftest):

    python scripts/tpu_checks.py

Exits nonzero on any failure; prints one OK line per check.
"""

import os
import sys

import numpy as np

# runnable from anywhere: the repo root is this file's parent's parent
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def check_pallas_gf8():
    import jax.numpy as jnp

    from ceph_tpu.ec import matrices
    from ceph_tpu.ops import gf8, gf8_pallas

    if not gf8_pallas.available():
        print("SKIP pallas_gf8 (no TPU backend)")
        return
    rng = np.random.default_rng(7)
    for (k, m, n) in [(8, 4, 16384 * 3), (8, 4, 16384 * 2 + 1000),
                      (4, 2, 5000), (10, 4, 16384)]:
        bm = np.asarray(gf8.expand_bitmatrix(matrices.isa_rs_matrix(k, m)))
        data = jnp.asarray(rng.integers(0, 256, (k, n), dtype=np.uint8))
        got = np.asarray(gf8_pallas.bitmatrix_matmul(bm, data))
        want = np.asarray(gf8.bitmatrix_matmul(bm, data))
        assert np.array_equal(got, want), (k, m, n)
    print("OK pallas_gf8 bit-exact vs XLA path")


def check_codec_roundtrip():
    from ceph_tpu.ec import factory

    rng = np.random.default_rng(1)
    for profile in (
        {"plugin": "isa", "k": "8", "m": "4"},
        {"plugin": "jerasure", "technique": "reed_sol_van",
         "k": "4", "m": "2", "w": "16"},
        {"plugin": "shec", "k": "6", "m": "4", "c": "3", "w": "32"},
        {"plugin": "lrc", "k": "4", "m": "2", "l": "3"},
    ):
        codec = factory(dict(profile))
        n = codec.get_chunk_count()
        obj = rng.integers(0, 256, codec.get_chunk_size(1 << 16) *
                           codec.get_data_chunk_count(),
                           dtype=np.uint8).tobytes()
        chunks = codec.encode(range(n), obj)
        drop = {0, n - 1}
        avail = {i: c for i, c in chunks.items() if i not in drop}
        assert codec.decode_concat(avail)[:len(obj)] == obj, profile
    print("OK codec encode/decode roundtrips on device")


def main() -> int:
    import jax

    print(f"backend: {jax.default_backend()}  devices: {jax.devices()}")
    check_pallas_gf8()
    check_codec_roundtrip()
    print("ALL TPU CHECKS PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
