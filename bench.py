#!/usr/bin/env python
"""Benchmark harness mirroring the reference's ceph_erasure_code_benchmark.

The reference tool (src/test/erasure-code/ceph_erasure_code_benchmark.cc)
times plugin encode/decode over an object of --size for --iterations and
prints seconds + KiB.  This harness runs the same configs (BASELINE.json)
against the TPU batch engine and prints one JSON line per metric; the LAST
line is always the headline (north-star) metric:

    {"metric": ..., "value": GB/s, "unit": "GB/s", "vs_baseline": x}

Measurement methodology (round 3, after the r01->r02 "regression"):
each repeat enqueues `iters` dispatches back-to-back and blocks ONCE at the
end — JAX async dispatch pipelines them, so the figure is sustained device
throughput.  The old harness blocked per call, so it measured host<->device
round-trip latency over the axon tunnel; that latency is environment-noisy
(r01 408 vs r02 264 GB/s on an identical code path — both were samples of
tunnel latency, not codec speed).  We take the median of `repeats` repeats
and report min/max spread so an outlier can never silently become the
number of record again.

Baseline constant: the reference publishes no numbers (BASELINE.md); ISA-L
single-socket RS(8,4) encode measures in the ~5 GB/s range on contemporary
x86 cores, which BASELINE.md designates as the to-beat figure until a
locally-measured reference binary exists.
"""

import argparse
import json
import statistics
import sys
import time

import numpy as np

BASELINE_GBPS = 5.0


def _bench(fn, args, iters, repeats=5, warmup=2):
    """Median seconds-per-call over `repeats` pipelined timing windows.

    Returns (median, min, max) of the per-call time.  Each window enqueues
    `iters` async dispatches and blocks once, so per-call dispatch latency
    is amortized and the device queue stays full (sustained throughput,
    which is what the reference tool's bytes/seconds accounting reports for
    a hot CPU loop, ceph_erasure_code_benchmark.cc:180-187).
    """
    import jax

    for _ in range(warmup):
        jax.block_until_ready(fn(*args))
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = None
        for _ in range(iters):
            out = fn(*args)
        jax.block_until_ready(out)
        times.append((time.perf_counter() - t0) / iters)
    return statistics.median(times), min(times), max(times)


def bench_ec(profile, batch, chunk, workload="encode", erasures=(0,), iters=20,
             repeats=5):
    """Returns (median, min, max) GB/s of input data processed (matching the
    reference tool's accounting: object bytes per iteration / seconds,
    ceph_erasure_code_benchmark.cc:187)."""
    import jax.numpy as jnp

    from ceph_tpu.ec import factory

    codec = factory(dict(profile))
    k = codec.get_data_chunk_count()
    rng = np.random.default_rng(0)
    data = jnp.asarray(rng.integers(0, 256, (batch, k, chunk), dtype=np.uint8))
    nbytes = batch * k * chunk
    if workload == "encode":
        med, lo, hi = _bench(codec.encode_batch, (data,), iters, repeats)
    else:
        parity = codec.encode_batch(data)
        full = jnp.concatenate([data, jnp.asarray(parity)], axis=1)
        med, lo, hi = _bench(
            codec.decode_batch, (tuple(erasures), full), iters, repeats)
    return nbytes / med / 1e9, nbytes / hi / 1e9, nbytes / lo / 1e9


def bench_crush(n_osds=10_000, n_pgs=1_000_000, iters=3):
    """Whole-map PG->OSD placement throughput (mappings/s)."""
    from ceph_tpu.crush import bench_map

    return bench_map(n_osds=n_osds, n_pgs=n_pgs, iters=iters)


def bench_crc32c(batch=4096, length=4096, iters=20, repeats=5):
    """Batched device crc32c GB/s (reference src/common/crc32c.cc asm path)."""
    import jax.numpy as jnp

    from ceph_tpu.ops.crc32c import crc32c_batch

    rng = np.random.default_rng(0)
    data = jnp.asarray(rng.integers(0, 256, (batch, length), dtype=np.uint8))
    med, lo, hi = _bench(crc32c_batch, (data,), iters, repeats)
    nbytes = batch * length
    return nbytes / med / 1e9, nbytes / hi / 1e9, nbytes / lo / 1e9


EC_CONFIGS = [
    # (name, profile, kwargs) — BASELINE.md metric table configs.
    ("ec_encode_jerasure_rsvan_k4m2_1M",
     {"plugin": "jerasure", "technique": "reed_sol_van", "k": "4", "m": "2"},
     dict(batch=16, chunk=262144, workload="encode")),
    ("ec_decode_jerasure_rsvan_k4m2_1M_e2",
     {"plugin": "jerasure", "technique": "reed_sol_van", "k": "4", "m": "2"},
     dict(batch=16, chunk=262144, workload="decode", erasures=(0, 5))),
    ("ec_encode_lrc_k4m2l3",
     {"plugin": "lrc", "k": "4", "m": "2", "l": "3"},
     dict(batch=1024, chunk=4096, workload="encode")),
    ("ec_decode_lrc_k4m2l3_e1",
     {"plugin": "lrc", "k": "4", "m": "2", "l": "3"},
     dict(batch=1024, chunk=4096, workload="decode", erasures=(1,))),
    ("ec_decode_shec_643_e3",
     {"plugin": "shec", "k": "6", "m": "4", "c": "3"},
     dict(batch=1024, chunk=4096, workload="decode", erasures=(0, 3, 7))),
    ("ec_decode_isa_k8m4_4k_e1",
     {"plugin": "isa", "k": "8", "m": "4"},
     dict(batch=4096, chunk=512, workload="decode", erasures=(2,))),
]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--all", action="store_true",
                    help="compat alias: the full metric set is the default now")
    ap.add_argument("--headline-only", action="store_true",
                    help="skip the full metric set, print only the headline")
    ap.add_argument("--iterations", type=int, default=20)
    ap.add_argument("--repeats", type=int, default=5)
    args = ap.parse_args()

    results = []
    if not args.headline_only:
        for name, profile, kw in EC_CONFIGS:
            try:
                med, lo, hi = bench_ec(profile, iters=args.iterations,
                                       repeats=args.repeats, **kw)
            except Exception as e:
                print(json.dumps({"metric": name, "error": repr(e)}),
                      file=sys.stderr)
                continue
            results.append({
                "metric": name, "value": round(med, 3), "unit": "GB/s",
                "vs_baseline": round(med / BASELINE_GBPS, 3),
                "min": round(lo, 3), "max": round(hi, 3)})
        try:
            med, lo, hi = bench_crc32c(iters=args.iterations,
                                       repeats=args.repeats)
            results.append({
                "metric": "crc32c_batch_4096x4KiB", "value": round(med, 3),
                "unit": "GB/s", "vs_baseline": None,
                "min": round(lo, 3), "max": round(hi, 3)})
        except Exception as e:
            print(json.dumps({"metric": "crc32c_batch_4096x4KiB",
                              "error": repr(e)}), file=sys.stderr)
        try:
            pg_per_s = bench_crush()
            results.append({
                "metric": "crush_map_10kosd_1Mpg", "value": round(pg_per_s),
                "unit": "mappings/s", "vs_baseline": None})
        except Exception as e:
            print(json.dumps({"metric": "crush_map_10kosd_1Mpg",
                              "error": repr(e)}), file=sys.stderr)
        for r in results:
            print(json.dumps(r))

    # headline metric (always the LAST line): north-star encode config
    med, lo, hi = bench_ec({"plugin": "isa", "k": "8", "m": "4"},
                           batch=4096, chunk=512, workload="encode",
                           iters=args.iterations, repeats=args.repeats)
    print(json.dumps({
        "metric": "ec_encode_isa_k8m4_4KiB_stripe_batch4096",
        "value": round(med, 3),
        "unit": "GB/s",
        "vs_baseline": round(med / BASELINE_GBPS, 3),
        "min": round(lo, 3), "max": round(hi, 3),
    }))


if __name__ == "__main__":
    main()
