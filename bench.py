#!/usr/bin/env python
"""Benchmark harness mirroring the reference's ceph_erasure_code_benchmark.

The reference tool (src/test/erasure-code/ceph_erasure_code_benchmark.cc)
times plugin encode/decode over an object of --size for --iterations and
prints seconds + KiB.  This harness runs the same configs (BASELINE.json)
against the TPU batch engine and prints one JSON line per metric; the LAST
line is always the headline (north-star) metric:

    {"metric": ..., "value": GB/s, "unit": "GB/s", "vs_baseline": x}

Measurement methodology (round 3, after the r01->r02 "regression"):
each repeat enqueues `iters` dispatches back-to-back and blocks ONCE at the
end — JAX async dispatch pipelines them, so the figure is sustained device
throughput.  The old harness blocked per call, so it measured host<->device
round-trip latency over the axon tunnel; that latency is environment-noisy
(r01 408 vs r02 264 GB/s on an identical code path — both were samples of
tunnel latency, not codec speed).  We take the median of `repeats` repeats
and report min/max spread so an outlier can never silently become the
number of record again.

Baselines (round 4): vs_baseline denominators are MEASURED on this host —
scripts/cpu_baseline/ implements the reference's SIMD EC kernels
(gf-complete split-table + isa-l GFNI paths, best-of), its 3-way hardware
crc32c, and times the reference's own CRUSH C core linked out-of-tree;
run.sh writes BASELINE_MEASURED.json, loaded here per config.  The old
BASELINE_GBPS = 5.0 literature constant remains only as a fallback when
that file is absent.
"""

import argparse
import json
import os
import statistics
import sys
import time

import numpy as np

BASELINE_GBPS = 5.0  # fallback only; see BASELINE_MEASURED.json

_MEASURED_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "BASELINE_MEASURED.json")


def _measured_baselines():
    """config-name -> measured denominator (GB/s, or mappings/s for crush)."""
    out = {}
    try:
        with open(_MEASURED_PATH) as f:
            doc = json.load(f)
        for row in doc.get("results", []):
            val = row.get("gbps") or row.get("mappings_per_s")
            if val:
                out[row["config"]] = float(val)
    except (OSError, ValueError, KeyError, TypeError, AttributeError):
        return {}
    return out


MEASURED = _measured_baselines()


def _vs(value, config_key, fallback=BASELINE_GBPS):
    """(vs_baseline, baseline_row_fields): ratio against the measured
    denominator, with explicit provenance so a fallback ratio can never
    masquerade as a measured one.  fallback=None -> no ratio at all when
    unmeasured (used for non-GB/s metrics where 5.0 is meaningless)."""
    base = MEASURED.get(config_key)
    if base:
        return round(value / base, 3), {"baseline": base,
                                        "baseline_src": "measured"}
    if fallback is None:
        return None, {"baseline": None, "baseline_src": "unmeasured"}
    return round(value / fallback, 3), {"baseline": fallback,
                                        "baseline_src": "fallback_constant"}


def _bench(fn, args, iters, repeats=5, warmup=2):
    """Median seconds-per-call over `repeats` pipelined timing windows.

    Returns (median, min, max) of the per-call time.  Each window enqueues
    `iters` async dispatches and blocks once, so per-call dispatch latency
    is amortized and the device queue stays full (sustained throughput,
    which is what the reference tool's bytes/seconds accounting reports for
    a hot CPU loop, ceph_erasure_code_benchmark.cc:180-187).
    """
    import jax

    for _ in range(warmup):
        jax.block_until_ready(fn(*args))
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = None
        for _ in range(iters):
            out = fn(*args)
        jax.block_until_ready(out)
        times.append((time.perf_counter() - t0) / iters)
    return statistics.median(times), min(times), max(times)


def bench_ec(profile, batch, chunk, workload="encode", erasures=(0,), iters=20,
             repeats=5):
    """Returns (median, min, max) GB/s of input data processed (matching the
    reference tool's accounting: object bytes per iteration / seconds,
    ceph_erasure_code_benchmark.cc:187)."""
    import jax.numpy as jnp

    from ceph_tpu.ec import factory

    codec = factory(dict(profile))
    k = codec.get_data_chunk_count()
    rng = np.random.default_rng(0)
    data = jnp.asarray(rng.integers(0, 256, (batch, k, chunk), dtype=np.uint8))
    nbytes = batch * k * chunk
    if workload == "encode":
        med, lo, hi = _bench(codec.encode_batch, (data,), iters, repeats)
    else:
        parity = codec.encode_batch(data)
        full = jnp.concatenate([data, jnp.asarray(parity)], axis=1)
        med, lo, hi = _bench(
            codec.decode_batch, (tuple(erasures), full), iters, repeats)
    return nbytes / med / 1e9, nbytes / hi / 1e9, nbytes / lo / 1e9


def bench_crush(n_osds=10_000, n_pgs=1_000_000, iters=3):
    """Whole-map PG->OSD placement throughput (mappings/s)."""
    from ceph_tpu.crush import bench_map

    return bench_map(n_osds=n_osds, n_pgs=n_pgs, iters=iters)


def bench_crc32c(batch=4096, length=4096, iters=20, repeats=5):
    """Batched device crc32c GB/s (reference src/common/crc32c.cc asm path)."""
    import jax.numpy as jnp

    from ceph_tpu.ops.crc32c import crc32c_batch

    rng = np.random.default_rng(0)
    data = jnp.asarray(rng.integers(0, 256, (batch, length), dtype=np.uint8))
    med, lo, hi = _bench(crc32c_batch, (data,), iters, repeats)
    nbytes = batch * length
    return nbytes / med / 1e9, nbytes / hi / 1e9, nbytes / lo / 1e9


EC_CONFIGS = [
    # (name, baseline_key, profile, kwargs) — BASELINE.md metric table
    # configs; baseline_key indexes BASELINE_MEASURED.json.
    ("ec_encode_jerasure_rsvan_k4m2_1M", "jer_rsvan_k4m2_encode",
     {"plugin": "jerasure", "technique": "reed_sol_van", "k": "4", "m": "2"},
     dict(batch=16, chunk=262144, workload="encode")),
    ("ec_decode_jerasure_rsvan_k4m2_1M_e2", "jer_rsvan_k4m2_decode_e05",
     {"plugin": "jerasure", "technique": "reed_sol_van", "k": "4", "m": "2"},
     dict(batch=16, chunk=262144, workload="decode", erasures=(0, 5))),
    ("ec_encode_lrc_k4m2l3", "lrc_k4m2l3_encode",
     {"plugin": "lrc", "k": "4", "m": "2", "l": "3"},
     dict(batch=1024, chunk=4096, workload="encode")),
    ("ec_decode_lrc_k4m2l3_e1", "lrc_k4m2l3_decode_e1",
     {"plugin": "lrc", "k": "4", "m": "2", "l": "3"},
     dict(batch=1024, chunk=4096, workload="decode", erasures=(1,))),
    ("ec_decode_shec_643_e3", "shec_643_decode_e037",
     {"plugin": "shec", "k": "6", "m": "4", "c": "3"},
     dict(batch=1024, chunk=4096, workload="decode", erasures=(0, 3, 7))),
    ("ec_decode_isa_k8m4_4k_e1", "isa_k8m4_decode_e2",
     {"plugin": "isa", "k": "8", "m": "4"},
     dict(batch=4096, chunk=512, workload="decode", erasures=(2,))),
]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--all", action="store_true",
                    help="compat alias: the full metric set is the default now")
    ap.add_argument("--headline-only", action="store_true",
                    help="skip the full metric set, print only the headline")
    ap.add_argument("--iterations", type=int, default=20)
    ap.add_argument("--repeats", type=int, default=5)
    args = ap.parse_args()

    results = []
    if not args.headline_only:
        for name, base_key, profile, kw in EC_CONFIGS:
            try:
                med, lo, hi = bench_ec(profile, iters=args.iterations,
                                       repeats=args.repeats, **kw)
            except Exception as e:
                print(json.dumps({"metric": name, "error": repr(e)}),
                      file=sys.stderr)
                continue
            ratio, prov = _vs(med, base_key)
            results.append({
                "metric": name, "value": round(med, 3), "unit": "GB/s",
                "vs_baseline": ratio, **prov,
                "min": round(lo, 3), "max": round(hi, 3)})
        try:
            med, lo, hi = bench_crc32c(iters=args.iterations,
                                       repeats=args.repeats)
            ratio, prov = _vs(med, "crc32c_4096x4KiB", fallback=None)
            results.append({
                "metric": "crc32c_batch_4096x4KiB", "value": round(med, 3),
                "unit": "GB/s", "vs_baseline": ratio, **prov,
                "min": round(lo, 3), "max": round(hi, 3)})
        except Exception as e:
            print(json.dumps({"metric": "crc32c_batch_4096x4KiB",
                              "error": repr(e)}), file=sys.stderr)
        try:
            pg_per_s = bench_crush()
            ratio, prov = _vs(pg_per_s, "crush_10kosd_1Mpg", fallback=None)
            results.append({
                "metric": "crush_map_10kosd_1Mpg", "value": round(pg_per_s),
                "unit": "mappings/s", "vs_baseline": ratio, **prov})
        except Exception as e:
            print(json.dumps({"metric": "crush_map_10kosd_1Mpg",
                              "error": repr(e)}), file=sys.stderr)
        for r in results:
            print(json.dumps(r))

    # headline metric (always the LAST line): north-star encode config
    med, lo, hi = bench_ec({"plugin": "isa", "k": "8", "m": "4"},
                           batch=4096, chunk=512, workload="encode",
                           iters=args.iterations, repeats=args.repeats)
    ratio, prov = _vs(med, "isa_k8m4_encode")
    print(json.dumps({
        "metric": "ec_encode_isa_k8m4_4KiB_stripe_batch4096",
        "value": round(med, 3),
        "unit": "GB/s",
        "vs_baseline": ratio, **prov,
        "min": round(lo, 3), "max": round(hi, 3),
    }))


if __name__ == "__main__":
    main()
